"""Host-side tree model + LightGBM-compatible text serialization.

Re-design of the reference Tree (/root/reference/include/LightGBM/tree.h,
src/io/tree.cpp) and the model text format
(src/boosting/gbdt_model_text.cpp:410 SaveModelToString / :421
LoadModelFromString). Trees are plain numpy arrays on the host; for batch
prediction a whole forest is stacked into a few device tensors
(ops/predict.py StackedTrees).

decision_type byte layout (tree.h kCategoricalMask/kDefaultLeftMask):
  bit0 = categorical split, bit1 = default_left, bits2-3 = missing_type
  (0 = none, 1 = zero, 2 = nan).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..ops.binning import BinMapper, BinType, MissingType

__all__ = ["Tree", "tree_from_arrays"]

_MISSING_CODE = {MissingType.NONE: 0, MissingType.ZERO: 1, MissingType.NAN: 2}
_MISSING_NAME = {0: MissingType.NONE, 1: MissingType.ZERO, 2: MissingType.NAN}

CAT_MASK = 1
DEFAULT_LEFT_MASK = 2


@dataclasses.dataclass
class Tree:
    num_leaves: int
    split_feature: np.ndarray       # [L-1] i32
    split_gain: np.ndarray          # [L-1] f32
    threshold: np.ndarray           # [L-1] f64 (real-valued)
    threshold_bin: np.ndarray       # [L-1] i32 (bin-space; -1 if unknown)
    decision_type: np.ndarray       # [L-1] u8
    left_child: np.ndarray          # [L-1] i32
    right_child: np.ndarray         # [L-1] i32
    leaf_value: np.ndarray          # [L] f64
    leaf_weight: np.ndarray         # [L] f64
    leaf_count: np.ndarray          # [L] i64
    internal_value: np.ndarray      # [L-1] f64
    internal_weight: np.ndarray     # [L-1] f64
    internal_count: np.ndarray      # [L-1] i64
    shrinkage: float = 1.0
    # categorical splits: threshold_bin indexes into cat_threshold via
    # cat_boundaries (bitset spans), like tree.h cat_boundaries_
    num_cat: int = 0
    cat_boundaries: Optional[np.ndarray] = None
    cat_threshold: Optional[np.ndarray] = None
    # linear leaves (tree.h leaf_const_/leaf_coeff_/leaf_features_)
    is_linear: bool = False
    leaf_const: Optional[np.ndarray] = None      # [L] f64
    leaf_features: Optional[list] = None         # per-leaf real feature ids
    leaf_coeff: Optional[list] = None            # per-leaf coefficients

    @property
    def num_nodes(self) -> int:
        return max(self.num_leaves - 1, 0)

    def is_categorical_node(self, i: int) -> bool:
        return bool(self.decision_type[i] & CAT_MASK)

    def default_left(self, i: int) -> bool:
        return bool(self.decision_type[i] & DEFAULT_LEFT_MASK)

    def missing_type(self, i: int) -> int:
        return (int(self.decision_type[i]) >> 2) & 3

    def apply_shrinkage(self, rate: float) -> None:
        """Tree::Shrinkage (tree.h:188; scales linear leaves too,
        tree.h:192-206)."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        self.shrinkage *= rate
        if self.is_linear and self.leaf_const is not None:
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [[c * rate for c in cs]
                               for cs in (self.leaf_coeff or [])]

    def num_leaves_actual(self) -> int:
        return self.num_leaves

    # -- single-row host predict (reference: tree.h:134) ------------------
    def predict_row(self, x: np.ndarray) -> float:
        leaf = self.predict_leaf_row(x)
        if self.is_linear and self.leaf_const is not None:
            out = float(self.leaf_const[leaf])
            feats = self.leaf_features[leaf] if self.leaf_features else []
            for f, c in zip(feats, self.leaf_coeff[leaf]):
                v = x[f]
                if np.isnan(v):
                    return float(self.leaf_value[leaf])
                out += c * v
            return out
        return float(self.leaf_value[leaf])

    def predict_leaf_row(self, x: np.ndarray) -> int:
        if self.num_leaves == 1:
            return 0
        node = 0
        while node >= 0:
            f = self.split_feature[node]
            v = x[f]
            if self.is_categorical_node(node):
                go_left = self._cat_decision(node, v)
            else:
                go_left = self._num_decision(node, v)
            node = self.left_child[node] if go_left else self.right_child[node]
        return ~node

    def _num_decision(self, node: int, v: float) -> bool:
        mt = self.missing_type(node)
        if np.isnan(v) and mt != 2:
            v = 0.0
        if mt == 2 and np.isnan(v):
            return self.default_left(node)
        if mt == 1 and (abs(v) <= 1e-35):
            return self.default_left(node)
        return v <= self.threshold[node]

    def _cat_decision(self, node: int, v: float) -> bool:
        # NaN routes right here but maps to bin 0 (the most frequent
        # category) during binned training/scoring — this asymmetry is
        # reference semantics, not a bug (tree.h:374-383 CategoricalDecision
        # vs bin.h:612 ValueToBin's `isnan -> return 0` for categoricals).
        if np.isnan(v) or v < 0:
            return False
        iv = int(v)
        cat_idx = int(self.threshold[node])
        lo = self.cat_boundaries[cat_idx]
        hi = self.cat_boundaries[cat_idx + 1]
        word = iv // 32
        if word >= hi - lo:
            return False
        return bool((int(self.cat_threshold[lo + word]) >> (iv % 32)) & 1)

    # -- text format ------------------------------------------------------
    def to_string(self, index: int) -> str:
        def fmt(arr, f):
            return " ".join(f % x for x in arr)

        L = self.num_leaves
        lines = [f"Tree={index}", f"num_leaves={L}",
                 f"num_cat={self.num_cat}"]
        if L > 1:
            lines += [
                "split_feature=" + fmt(self.split_feature, "%d"),
                "split_gain=" + fmt(self.split_gain, "%g"),
                "threshold=" + fmt(self.threshold, "%.17g"),
                "decision_type=" + fmt(self.decision_type, "%d"),
                "left_child=" + fmt(self.left_child, "%d"),
                "right_child=" + fmt(self.right_child, "%d"),
                "leaf_value=" + fmt(self.leaf_value, "%.17g"),
                "leaf_weight=" + fmt(self.leaf_weight, "%g"),
                "leaf_count=" + fmt(self.leaf_count, "%d"),
                "internal_value=" + fmt(self.internal_value, "%g"),
                "internal_weight=" + fmt(self.internal_weight, "%g"),
                "internal_count=" + fmt(self.internal_count, "%d"),
            ]
            if self.num_cat > 0:
                lines += [
                    "cat_boundaries=" + fmt(self.cat_boundaries, "%d"),
                    "cat_threshold=" + fmt(self.cat_threshold, "%d"),
                ]
        else:
            lines += ["leaf_value=" + fmt(self.leaf_value[:1], "%.17g")]
        lines += [f"is_linear={int(self.is_linear)}"]
        if self.is_linear and self.leaf_const is not None:
            L = self.num_leaves
            nf = [len(self.leaf_features[i]) if self.leaf_features else 0
                  for i in range(L)]
            lines += ["leaf_const=" + fmt(self.leaf_const[:L], "%.17g"),
                      "num_features=" + fmt(nf, "%d")]
            feat_toks, coef_toks = [], []
            for i in range(L):
                if nf[i]:
                    feat_toks += ["%d" % f for f in self.leaf_features[i]]
                    coef_toks += ["%.17g" % c for c in self.leaf_coeff[i]]
            lines += ["leaf_features=" + " ".join(feat_toks),
                      "leaf_coeff=" + " ".join(coef_toks)]
        lines += [f"shrinkage={self.shrinkage:g}"]
        return "\n".join(lines) + "\n\n"

    @classmethod
    def from_lines(cls, kv: Dict[str, str]) -> "Tree":
        L = int(kv["num_leaves"])
        num_cat = int(kv.get("num_cat", "0"))

        def arr(key, dtype, size, default=0):
            if key not in kv or size == 0:
                return np.full(size, default, dtype)
            vals = kv[key].split()
            return np.asarray(vals, dtype=dtype)

        n_nodes = max(L - 1, 0)
        t = cls(
            num_leaves=L,
            split_feature=arr("split_feature", np.int32, n_nodes),
            split_gain=arr("split_gain", np.float64, n_nodes),
            threshold=arr("threshold", np.float64, n_nodes),
            threshold_bin=np.full(n_nodes, -1, np.int32),
            decision_type=arr("decision_type", np.uint8, n_nodes),
            left_child=arr("left_child", np.int32, n_nodes),
            right_child=arr("right_child", np.int32, n_nodes),
            leaf_value=arr("leaf_value", np.float64, L),
            leaf_weight=arr("leaf_weight", np.float64, L),
            leaf_count=arr("leaf_count", np.int64, L),
            internal_value=arr("internal_value", np.float64, n_nodes),
            internal_weight=arr("internal_weight", np.float64, n_nodes),
            internal_count=arr("internal_count", np.int64, n_nodes),
            num_cat=num_cat,
            shrinkage=float(kv.get("shrinkage", "1")),
            is_linear=bool(int(kv.get("is_linear", "0"))),
        )
        # the batched predictor sweeps nodes in index order and relies
        # on internal children having LARGER indices than their parent
        # (ops/predict.py _traverse; Tree::Split numbering guarantees
        # this for every model LightGBM or this package writes) —
        # reject third-party model strings that violate it rather than
        # silently mispredicting
        for i in range(n_nodes):
            for c in (int(t.left_child[i]), int(t.right_child[i])):
                if 0 <= c <= i:
                    raise ValueError(
                        f"model tree node {i} has internal child {c} "
                        "<= its own index; node numbering must be "
                        "topological (parent before child)")
        if num_cat > 0:
            t.cat_boundaries = np.asarray(kv["cat_boundaries"].split(),
                                          np.int64)
            t.cat_threshold = np.asarray(kv["cat_threshold"].split(),
                                         np.uint32)
        if t.is_linear and "leaf_const" in kv:
            t.leaf_const = np.asarray(kv["leaf_const"].split(), np.float64)
            nf = np.asarray(kv.get("num_features", "").split() or [0] * L,
                            np.int64)
            feat_toks = kv.get("leaf_features", "").split()
            coef_toks = kv.get("leaf_coeff", "").split()
            t.leaf_features, t.leaf_coeff = [], []
            pos = 0
            for i in range(L):
                k = int(nf[i]) if i < len(nf) else 0
                t.leaf_features.append(
                    [int(v) for v in feat_toks[pos: pos + k]])
                t.leaf_coeff.append(
                    [float(v) for v in coef_toks[pos: pos + k]])
                pos += k
        return t


@jax.jit
def pack_tree_device(t):
    """Everything except the categorical bitmask as ONE f32 vector
    (i32 fields are < 2^24 so the cast is lossless): a tree crosses
    device->host in two transfers instead of one per field."""
    import jax.numpy as jnp
    parts = [getattr(t, f) for f in t._fields if f != "split_cat_mask"]
    vec = jnp.concatenate(
        [jnp.ravel(p).astype(jnp.float32) for p in parts])
    return vec, t.split_cat_mask


def unpack_tree_host(vec, cmask, proto):
    """Inverse of pack_tree_device; ``proto`` supplies shapes/dtypes."""
    vec = np.asarray(vec)
    fields = {}
    off = 0
    for f in proto._fields:
        if f == "split_cat_mask":
            continue
        arr = getattr(proto, f)
        sz = int(np.prod(arr.shape)) if arr.shape else 1
        piece = vec[off:off + sz].astype(arr.dtype)
        fields[f] = piece.reshape(arr.shape) if arr.shape else piece[0]
        off += sz
    fields["split_cat_mask"] = np.asarray(cmask)
    return type(proto)(**fields)


def _fetch_tree_host(dev_tree):
    """Device TreeArrays -> host TreeArrays in two transfers."""
    if isinstance(getattr(dev_tree, "split_feature", None), np.ndarray):
        return dev_tree
    vec, cmask = jax.device_get(pack_tree_device(dev_tree))
    return unpack_tree_host(vec, cmask, dev_tree)


def tree_from_arrays(dev_tree, mappers: Sequence[BinMapper],
                     used_features: Optional[np.ndarray] = None) -> Tree:
    """Convert device TreeArrays (ops/grow.py) to a host Tree, realizing
    bin-space thresholds as real values via the BinMappers."""
    # ONE device->host fetch for the whole tree: everything except the
    # categorical bitmask is packed into a single f32 vector on device
    # (i32 fields are < 2^24 so the cast is lossless); per-field
    # np.asarray would pay a device round-trip per array (a dozen
    # pipeline stalls per boosting iteration)
    dev_tree = _fetch_tree_host(dev_tree)
    L = int(np.asarray(dev_tree.num_leaves))
    nn = max(L - 1, 0)
    inner_sf = np.asarray(dev_tree.split_feature)[:nn].astype(np.int32)
    if used_features is not None:
        sf = used_features[inner_sf].astype(np.int32)
    else:
        sf = inner_sf
    tb = np.asarray(dev_tree.threshold_bin)[:nn].astype(np.int32)
    dl = np.asarray(dev_tree.default_left)[:nn]
    is_cat_node = np.asarray(dev_tree.split_is_cat)[:nn]
    cat_masks = np.asarray(dev_tree.split_cat_mask)[:nn]
    thr = np.zeros(nn, np.float64)
    dtypes = np.zeros(nn, np.uint8)
    cat_boundaries = [0]
    cat_threshold: List[int] = []
    num_cat = 0
    for i in range(nn):
        # mappers are one-per-used-feature: index by the inner id
        m = mappers[inner_sf[i]]
        code = _MISSING_CODE[m.missing_type] << 2
        if m.bin_type == BinType.CATEGORICAL:
            # Realize the bin-membership mask from the split search as a
            # bitset over raw category values (tree.h SplitCategorical
            # layout: threshold = index into cat_boundaries_).
            if is_cat_node[i]:
                member = np.where(cat_masks[i][: len(m.bin_to_cat)])[0]
            else:  # legacy prefix split "bin <= t"
                member = np.arange(min(int(tb[i]) + 1, len(m.bin_to_cat)))
            cats = np.asarray(m.bin_to_cat, np.int64)[member]
            nwords = (int(cats.max()) // 32 + 1) if len(cats) else 1
            words = np.zeros(nwords, np.uint32)
            for c in cats:
                words[c // 32] |= np.uint32(1) << np.uint32(c % 32)
            thr[i] = float(num_cat)
            code |= CAT_MASK
            cat_threshold.extend(int(x) for x in words)
            cat_boundaries.append(len(cat_threshold))
            num_cat += 1
        else:
            thr[i] = m.bin_upper_bound(int(tb[i]))
            if dl[i]:
                code |= DEFAULT_LEFT_MASK
        dtypes[i] = code
    return Tree(
        num_cat=num_cat,
        cat_boundaries=np.asarray(cat_boundaries, np.int64)
        if num_cat else None,
        cat_threshold=np.asarray(cat_threshold, np.uint32)
        if num_cat else None,
        num_leaves=L,
        split_feature=sf,
        split_gain=np.asarray(dev_tree.split_gain)[:nn].astype(np.float64),
        threshold=thr,
        threshold_bin=tb,
        decision_type=dtypes,
        left_child=np.asarray(dev_tree.left_child)[:nn].astype(np.int32),
        right_child=np.asarray(dev_tree.right_child)[:nn].astype(np.int32),
        leaf_value=np.asarray(dev_tree.leaf_value)[:L].astype(np.float64),
        leaf_weight=np.asarray(dev_tree.leaf_weight)[:L].astype(np.float64),
        leaf_count=np.asarray(dev_tree.leaf_count)[:L].astype(np.int64),
        internal_value=np.asarray(
            dev_tree.internal_value)[:nn].astype(np.float64),
        internal_weight=np.asarray(
            dev_tree.internal_weight)[:nn].astype(np.float64),
        internal_count=np.asarray(
            dev_tree.internal_count)[:nn].astype(np.int64),
    )
