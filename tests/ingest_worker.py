"""Worker for the distributed streaming-ingestion tests
(tests/test_data_ingest.py): one rank of a 2-process world that
ingests ITS shard through a chunk source.

All wiring comes from the environment (LIGHTGBM_TPU_COORDINATOR /
NUM_PROCS / RANK picked up by a bare ``init_distributed()``, plus the
fault/watchdog variables) — so it runs both spawned directly by a test
and under ``python -m lightgbm_tpu launch``.

Each rank builds the SAME global dataset twice through
``spmd.distributed_dataset``:

- eager: the raw shard array (mapper sync + re-bin + allgather),
- streaming: an ``ArrayChunkSource`` over the shard (pass-1 mapper
  sync inside the construct, binned-shard allgather, no raw matrix).

It asserts bins/mappers/labels identical in-process, prints
``INGEST_PARITY_OK``, trains both and asserts the models agree; rank 0
writes ``model_stream.txt`` / ``model_eager.txt``. A LightGBMError (a
watchdog abort — e.g. ``rank_kill@-1`` killing the peer before the
pass-1 mapper sync) prints ``WORKER ABORT: <msg>`` and hard-exits 13.

Usage: python ingest_worker.py <outdir> [num_rounds]
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

outdir = sys.argv[1]
num_rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 6

from lightgbm_tpu.parallel.distributed import init_distributed  # noqa: E402

init_distributed()   # supervisor env (or single-process no-op)

import jax  # noqa: E402
import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.basic import LightGBMError  # noqa: E402
from lightgbm_tpu.data import ArrayChunkSource  # noqa: E402
from lightgbm_tpu.parallel import spmd  # noqa: E402

rank = jax.process_index()
nproc = jax.process_count()

rs = np.random.RandomState(17)
n, f = 800, 6
X = rs.randn(n, f)
y = (X @ rs.randn(f) > 0).astype(np.float64)
shard = n // max(nproc, 1)
lo, hi = rank * shard, (rank + 1) * shard
params = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
          "min_data_in_leaf": 5, "seed": 3, "verbosity": -1}

try:
    ds_stream = spmd.distributed_dataset(
        ArrayChunkSource(X[lo:hi], label=y[lo:hi], chunk_rows=128),
        params=dict(params))
    ds_eager = spmd.distributed_dataset(
        X[lo:hi], label=y[lo:hi], params=dict(params))

    assert ds_stream.num_data() == ds_eager.num_data() == n
    assert [m.to_dict() for m in ds_stream.mappers] == \
        [m.to_dict() for m in ds_eager.mappers], "mapper divergence"
    np.testing.assert_array_equal(ds_stream.host_bins(),
                                  ds_eager.host_bins())
    np.testing.assert_array_equal(np.asarray(ds_stream.get_label()),
                                  np.asarray(ds_eager.get_label()))
    print(f"rank {rank} INGEST_PARITY_OK", flush=True)

    bst_s = lgb.train(dict(params), ds_stream,
                      num_boost_round=num_rounds)
    bst_e = lgb.train(dict(params), ds_eager,
                      num_boost_round=num_rounds)
    assert bst_s.model_to_string() == bst_e.model_to_string(), \
        "trained models diverge between ingestion modes"
    # final barrier: rank 0 is the coordination-service leader, and an
    # early exit would kill the peer mid-training with a fatal
    # distributed-client error
    from lightgbm_tpu.parallel.hostsync import host_allgather
    host_allgather(np.asarray([rank], np.int64), "test/ingest_done")
except LightGBMError as e:
    print(f"WORKER ABORT: {e}", flush=True)
    os._exit(13)

if rank == 0:
    bst_s.save_model(os.path.join(outdir, "model_stream.txt"))
    bst_e.save_model(os.path.join(outdir, "model_eager.txt"))
print(f"rank {rank} DONE iterations={bst_s.current_iteration()}",
      flush=True)
# skip jax.distributed atexit teardown: with peers already dead it can
# block on the coordination service instead of exiting
sys.stdout.flush()
os._exit(0)
