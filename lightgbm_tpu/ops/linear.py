"""Linear-leaf fitting (linear_tree).

Re-design of LinearTreeLearner::CalculateLinear
(/root/reference/src/treelearner/linear_tree_learner.cpp:180-375) for TPU:
per-leaf coefficients  beta = -(X^T H X + lambda I)^-1 X^T g  where X is
[leaf branch numerical features | 1].  Instead of per-thread accumulation
into triangular buffers, the normal equations for ALL leaves are built in
one batched segment-reduction over rows and solved with one batched
jnp.linalg.solve — the whole fit is three fused device passes.

Reference semantics kept:
- rows with NaN in any of the leaf's features are excluded from the fit
  and fall back to the piecewise-constant leaf value at prediction
  (tree.cpp:134-148);
- leaves with fewer valid rows than coefficients keep the constant model
  (linear_tree_learner.cpp:330-341);
- lambda is added to feature diagonals only, not the bias.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .gather import gather_small

__all__ = ["branch_features_per_leaf", "fit_leaf_linear",
           "linear_leaf_values"]


def linear_leaf_values(const: jnp.ndarray, coef: jnp.ndarray,
                       feats: jnp.ndarray, nfeat: jnp.ndarray,
                       fallback: jnp.ndarray, X: jnp.ndarray,
                       leaves: jnp.ndarray) -> jnp.ndarray:
    """Per-row output of linear leaves with NaN fallback to the constant
    leaf value (tree.cpp:120-150 PredictionFunLinear). Shared by training
    score updates, binned valid scoring and raw batch prediction.

    Args:
      const: ``[L]`` fitted constants. coef: ``[L, km]``. feats: ``[L,
        km]`` feature column ids into X. nfeat: ``[L]`` active counts.
      fallback: ``[L]`` piecewise-constant leaf values.
      X: ``[n, F]`` feature values (NaN preserved). leaves: ``[n]`` i32.
    """
    km = feats.shape[1]
    if km == 0:
        return gather_small(const, leaves)
    # gather_small for every [n]-sized leaf lookup: TPU small-table
    # gathers run ~1 elt/cycle (benchmarks/PROFILE.md)
    fr = gather_small(feats, leaves)                       # [n, km]
    act = jnp.arange(km)[None, :] < gather_small(nfeat, leaves)[:, None]
    x = jnp.take_along_axis(X, fr, axis=1)
    nanrow = jnp.any(jnp.isnan(x) & act, axis=1)
    lin = gather_small(const, leaves) + jnp.sum(
        jnp.where(act, jnp.nan_to_num(x) * gather_small(coef, leaves),
                  0.0), axis=1)
    return jnp.where(nanrow, gather_small(fallback, leaves), lin)


def branch_features_per_leaf(split_feature: np.ndarray,
                             left_child: np.ndarray,
                             right_child: np.ndarray,
                             leaf_parent: np.ndarray,
                             num_leaves: int,
                             is_numerical) -> list:
    """Per-leaf sorted unique numerical features on the root->leaf path
    (Tree::branch_features analog; host-side, trees are tiny)."""
    nn = max(num_leaves - 1, 0)
    parent_of_node = np.full(nn, -1, np.int64)
    for i in range(nn):
        for c in (left_child[i], right_child[i]):
            if c >= 0:
                parent_of_node[c] = i
    out = []
    for leaf in range(num_leaves):
        feats = set()
        node = int(leaf_parent[leaf])
        while node >= 0:
            f = int(split_feature[node])
            if is_numerical(f):
                feats.add(f)
            node = int(parent_of_node[node])
        out.append(sorted(feats))
    return out


def fit_leaf_linear(raw: jnp.ndarray,
                    row_leaf: jnp.ndarray,
                    grad: jnp.ndarray,
                    hess: jnp.ndarray,
                    row_weight: jnp.ndarray,
                    leaf_feats: jnp.ndarray,
                    leaf_nfeat: jnp.ndarray,
                    leaf_value: jnp.ndarray,
                    linear_lambda: float
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fit every leaf's linear model in one batched pass.

    Args:
      raw: ``[n, F]`` float32 raw feature values (NaN preserved).
      row_leaf: ``[n]`` i32 leaf assignment.
      grad, hess: ``[n]`` float gradients/hessians.
      row_weight: ``[n]`` bagging/GOSS weight (0 = out of bag — excluded
        from the fit, like the reference's leaf_map_[i] == -1 skip).
      leaf_feats: ``[L, kmax]`` i32 per-leaf feature ids (0-padded).
      leaf_nfeat: ``[L]`` i32 number of active features per leaf.
      leaf_value: ``[L]`` float piecewise-constant outputs (fallback).
      linear_lambda: L2 regularization on coefficients.

    Returns:
      (leaf_const [L], leaf_coeff [L, kmax], train_pred [n]).
    """
    n, F = raw.shape
    L, kmax = leaf_feats.shape
    dtype = grad.dtype
    k1 = kmax + 1
    w = row_weight.astype(dtype)

    feats_row = leaf_feats[row_leaf]                       # [n, kmax]
    active_row = jnp.arange(kmax)[None, :] < leaf_nfeat[row_leaf][:, None]
    x = jnp.take_along_axis(raw, feats_row, axis=1)        # [n, kmax]
    row_ok = ~jnp.any(jnp.isnan(x) & active_row, axis=1)
    x = jnp.where(active_row & row_ok[:, None],
                  jnp.nan_to_num(x.astype(dtype)), 0.0)
    xa = jnp.concatenate([x, jnp.ones((n, 1), dtype)], axis=1)
    in_fit = row_ok & (w > 0)
    xa = xa * in_fit[:, None].astype(dtype)                # [n, k1]
    grad = grad * w
    hess = hess * w

    outer = xa[:, :, None] * (xa * hess[:, None])[:, None, :]
    XtHX = jax.ops.segment_sum(outer.reshape(n, k1 * k1), row_leaf,
                               num_segments=L).reshape(L, k1, k1)
    Xtg = jax.ops.segment_sum(xa * grad[:, None], row_leaf, num_segments=L)
    cnt_ok = jax.ops.segment_sum(in_fit.astype(dtype), row_leaf,
                                 num_segments=L)

    active_col = jnp.arange(kmax)[None, :] < leaf_nfeat[:, None]  # [L,kmax]
    act1 = jnp.concatenate([active_col, jnp.ones((L, 1), bool)], axis=1)
    pair_act = act1[:, :, None] & act1[:, None, :]
    eye = jnp.eye(k1, dtype=dtype)
    # diagonal additions: lambda on active feature entries, 0 on the bias,
    # and 1 on inactive (padded) entries so the batched solve stays
    # non-singular
    lam_vec = jnp.concatenate(
        [jnp.full((kmax,), linear_lambda, dtype), jnp.zeros((1,), dtype)])
    diag_add = jnp.where(act1, lam_vec[None, :], 1.0)     # [L, k1]
    A = jnp.where(pair_act, XtHX, 0.0) + eye[None] * diag_add[:, None, :]
    b = jnp.where(act1, Xtg, 0.0)
    coef = -jnp.linalg.solve(A, b[..., None])[..., 0]      # [L, k1]

    finite = jnp.all(jnp.isfinite(coef), axis=1)
    ok_leaf = (cnt_ok >= (leaf_nfeat + 1).astype(dtype)) & finite
    const = jnp.where(ok_leaf, coef[:, -1], leaf_value)
    coeffs = jnp.where(ok_leaf[:, None] & active_col, coef[:, :kmax], 0.0)

    pred_lin = gather_small(const, row_leaf) + jnp.sum(
        gather_small(coeffs, row_leaf) * x, axis=1)
    pred = jnp.where(row_ok, pred_lin, gather_small(leaf_value, row_leaf))
    return const, coeffs, pred
