# tpulint fixture: TPL006 positive — lock held across a collective in
# the resilience layer. The watchdog's contract is copy-under-lock,
# dispatch-outside: a bookkeeping lock held across a collective would
# hang the abort path that exists to break hangs.
import threading

import jax
import jax.numpy as jnp

_lock = threading.Lock()
_heartbeat = {"t": 0.0}


def guarded_sync(values):
    with _lock:
        # EXPECT: TPL006
        total = jnp.sum(values)      # collective while holding _lock
        _heartbeat["t"] = float(total)


class Watchdog:
    def __init__(self):
        self._lock = threading.RLock()
        self.last = None

    def run(self, x):
        with self._lock:
            # EXPECT: TPL006
            y = jax.device_put(x)
            self.last = y
