"""Distributed tracing plane: spans across train -> publish -> serve.

The fleet metrics plane (obs/export.py, obs/registry.py) says how
much; this module says WHERE THE TIME GOES. One span is one named,
timed section of the lifecycle — a boosting iteration, a model
publication, a watcher's validate->load->swap, one served request —
emitted as ``{"event": "span"}`` JSONL lines through the exact same
recorder/daemon drain machinery every other telemetry event rides
(docs/OBSERVABILITY.md "Tracing").

Span model
----------
- ``trace_id`` groups spans into one causal story (a retrain
  generation, a client request); ``span_id`` names the span;
  ``parent_id`` is the causing span (or null for roots).
- Every span carries a PAIRED wall clock (``wall``, ``time.time`` at
  span start) and monotonic clock (``mono``, ``time.perf_counter`` at
  span start) plus ``dur`` seconds. Monotonic clocks have arbitrary
  per-process origins; the wall/mono pair lets the ``trace`` CLI
  estimate each process's offset (median of ``wall - mono`` over its
  spans) and place all processes on ONE corrected timeline — wall
  clocks alone would inherit NTP skew jitter per event, monotonic
  clocks alone cannot be merged at all.
- Context propagates explicitly: the pipeline supervisor seeds a
  generation trace through the ``LIGHTGBM_TPU_TRACE_CTX`` env var
  (``trace_id:span_id``), the publisher stamps its context into the
  manifest (``manifest["trace"]``) so the serve watcher's swap spans
  correlate to the publishing generation, and the serve protocol
  carries an optional ``trace`` field end to end.

Cost contract: recording a span is one clock pair + one locked list
append, sampled/aggregated per iteration or per request — NEVER per
row, and nothing here is called from ``# tpulint: hot`` drivers (the
per-iteration spans are derived in the telemetry recorder from
``Timer.snapshot()`` deltas the hot path already pays for).

Threading contract (tpulint TPL008 over obs/): the span buffer is
appended from trainer/handler/watcher threads and drained from
recorder/daemon threads — every touch of ``_spans`` and the current
trace context goes through ``_spans_lock``, mirroring the
locked snapshot-and-clear drains of ``resilience/faults.py`` and
``obs/cost.py``.

This module never imports jax (not even lazily): the ``trace`` CLI,
the pipeline supervisor and the publisher all consume it on jax-free
paths.
"""

from __future__ import annotations

import json
import os
import re
import statistics
import sys
import threading
import time
import uuid
from collections import defaultdict
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["SPAN_EVENT_KEYS", "FUSED_SCAN_PHASE", "BLOCKING_PHASES",
           "TRACE_CTX_ENV", "new_trace_id", "new_span_id",
           "make_span", "record_span", "span", "drain_span_events",
           "span_events_snapshot", "current_context",
           "set_current_trace", "format_context",
           "record_iteration_spans", "load_spans",
           "correct_clock_skew", "chrome_trace", "critical_paths",
           "render_critical_paths", "main"]

#: the JSONL schema contract of every ``{"event": "span"}`` line —
#: derived from the single-source registry (obs/schemas.py EVENTS,
#: the TPL015 contract) and re-exported here for the span emitters
#: and tests that historically import it from this module
from .schemas import required_keys as _required_keys  # noqa: E402

SPAN_EVENT_KEYS = _required_keys("span")

#: the Timer phase that blocks INSIDE a fused-scan window's
#: train_one_iter call (the window-boundary batched fetch,
#: models/gbdt.py _dispatch_scan_window). Defined here — the jax-free
#: layer every consumer can import — and used by gbdt.py itself, the
#: fused-iteration bench and the per-iteration host-gap derivation
#: below: in-call wall minus these phases is the host driver gap the
#: ``fused_scan_iters auto`` flip gate requires to be ~0.
FUSED_SCAN_PHASE = "boosting/fused_scan"
BLOCKING_PHASES = (FUSED_SCAN_PHASE,)

#: env var carrying the current trace context into spawned workers
#: (``trace_id:span_id``) — the pipeline supervisor exports it per
#: generation so the train worker's iteration spans and the
#: publisher's publish span join the generation's trace
TRACE_CTX_ENV = "LIGHTGBM_TPU_TRACE_CTX"

#: span-buffer cap, same shape as obs/cost.py's event cap: a consumer
#: that never drains must not grow memory forever (the newest spans
#: win nothing — appends beyond the cap are dropped, drains restart it)
_SPANS_CAP = 4096

_spans_lock = threading.Lock()
# ---- guarded by _spans_lock ----
_spans: List[Dict[str, Any]] = []
_spans_dropped = 0
# (trace_id, span_id) of the process-current trace; False = env not
# parsed yet, None = parsed and absent
_current: Any = False


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def _proc_label() -> str:
    # derived per span, not cached: spans land per iteration/request
    # (never per row), and a cache would be one more thread-shared
    # field to guard across the pipeline's fork tree
    rank = os.environ.get("LIGHTGBM_TPU_RANK") or ""
    return f"pid{os.getpid()}" + (f".rank{rank}" if rank else "")


def format_context(trace_id: str, span_id: str) -> str:
    """The ``LIGHTGBM_TPU_TRACE_CTX`` wire form."""
    return f"{trace_id}:{span_id}"


def _parse_context(raw: str) -> Optional[Tuple[str, str]]:
    parts = (raw or "").split(":")
    if len(parts) == 2 and all(parts):
        return (parts[0], parts[1])
    return None


def current_context() -> Optional[Dict[str, str]]:
    """The process-current trace context (``{"trace_id", "span_id"}``)
    — set explicitly via :func:`set_current_trace` or inherited from
    the ``LIGHTGBM_TPU_TRACE_CTX`` env var on first use; None when
    neither exists."""
    global _current
    with _spans_lock:
        if _current is False:
            _current = _parse_context(
                os.environ.get(TRACE_CTX_ENV, ""))
        ctx = _current
    if ctx is None:
        return None
    return {"trace_id": ctx[0], "span_id": ctx[1]}


def set_current_trace(trace_id: Optional[str],
                      span_id: Optional[str] = None) -> None:
    """Set (or with ``None`` clear) the process-current trace."""
    global _current
    with _spans_lock:
        _current = None if trace_id is None \
            else (trace_id, span_id or new_span_id())


def make_span(name: str, t_start: float,
              t_end: Optional[float] = None, *,
              trace_id: Optional[str] = None,
              span_id: Optional[str] = None,
              parent_id: Optional[str] = None,
              attrs: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
    """Build one span event dict WITHOUT buffering it (the load
    generator writes its spans straight to its own event log).

    ``t_start``/``t_end`` are ``time.perf_counter()`` readings;
    ``t_end`` defaults to now. The paired wall timestamp is derived
    from the current clock pair so spans whose start lies in the past
    still carry a consistent (wall, mono) anchor."""
    now_m = time.perf_counter()
    if t_end is None:
        t_end = now_m
    return {
        "event": "span",
        "name": str(name),
        "trace_id": trace_id or new_trace_id(),
        "span_id": span_id or new_span_id(),
        "parent_id": parent_id,
        "wall": time.time() - (now_m - t_start),
        "mono": float(t_start),
        "dur": max(0.0, float(t_end) - float(t_start)),
        "proc": _proc_label(),
        "attrs": dict(attrs) if attrs else {},
    }


def record_span(name: str, t_start: float,
                t_end: Optional[float] = None, *,
                trace_id: Optional[str] = None,
                span_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                attrs: Optional[Dict[str, Any]] = None) -> str:
    """Record one finished span into the process buffer; returns its
    span id. The buffer is drained into the JSONL stream by the
    telemetry recorder / serve daemon (locked snapshot-and-clear)."""
    global _spans_dropped
    ev = make_span(name, t_start, t_end, trace_id=trace_id,
                   span_id=span_id, parent_id=parent_id, attrs=attrs)
    with _spans_lock:
        if len(_spans) < _SPANS_CAP:
            _spans.append(ev)
        else:
            _spans_dropped += 1
    return ev["span_id"]


class _SpanHandle:
    """What :func:`span` yields: the ids children parent to, plus a
    mutable ``attrs`` dict stamped onto the span when it closes."""

    __slots__ = ("trace_id", "span_id", "parent_id", "attrs")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}


@contextmanager
def span(name: str, *, trace_id: Optional[str] = None,
         parent_id: Optional[str] = None,
         attrs: Optional[Dict[str, Any]] = None
         ) -> Iterator[_SpanHandle]:
    """Record the enclosed section as a span. Without an explicit
    ``trace_id`` the process-current context supplies trace and
    parent; with neither, the span roots a fresh trace."""
    if trace_id is None:
        ctx = current_context()
        if ctx is not None:
            trace_id = ctx["trace_id"]
            if parent_id is None:
                parent_id = ctx["span_id"]
    handle = _SpanHandle(trace_id or new_trace_id(), new_span_id(),
                         parent_id, attrs)
    t0 = time.perf_counter()
    try:
        yield handle
    finally:
        record_span(name, t0, trace_id=handle.trace_id,
                    span_id=handle.span_id,
                    parent_id=handle.parent_id, attrs=handle.attrs)


def drain_span_events() -> List[Dict[str, Any]]:
    """Locked snapshot-and-clear of the span buffer (the same drain
    contract as ``resilience.faults.drain_events`` — a span recorded
    from another thread between a bare copy and clear would be lost
    forever)."""
    global _spans, _spans_dropped
    with _spans_lock:
        if not _spans:
            return []
        out, _spans = _spans, []
        _spans_dropped = 0
    return out


def span_events_snapshot() -> List[Dict[str, Any]]:
    """Copy of the pending (undrained) spans, for tests/inspection."""
    with _spans_lock:
        return list(_spans)


def record_iteration_spans(event: Dict[str, Any], t_start: float,
                           t_end: float) -> None:
    """Derive the per-iteration spans from one telemetry iteration
    event (obs/recorder.py): a ``train/iteration`` parent covering
    [t_start, t_end] plus one ``phase/<label>`` child per Timer phase
    delta, laid out sequentially (phase clocks are per-label
    accumulators, not timestamps — relative placement inside the
    iteration is synthetic, the durations are real).

    On fused-scan iterations the parent also carries the dispatch-gap
    decomposition: ``host_gap_s`` = iteration wall minus the blocking
    ``boosting/fused_scan`` phase — the host driver time the
    ``fused_scan_iters auto`` flip gate requires to be ~0 inside a
    window (an upper bound off-chip, where per-iteration programs
    execute synchronously inside the dispatch call).

    Costs one clock pair + a handful of locked appends per ITERATION
    — nothing here runs inside the hot-marked drivers."""
    ctx = current_context()
    if ctx is None:
        # a bare train() run still groups its iterations in one trace
        set_current_trace(new_trace_id())
        ctx = current_context()
    attrs: Dict[str, Any] = {"iteration": event.get("iteration")}
    scan = event.get("scan")
    phases = event.get("phases") or {}

    def _total(v: Dict[str, Any]) -> float:
        # single-process deltas carry total; SPMD-aggregated carry
        # mean (per-process) + min/max
        return float(v.get("total", v.get("mean", 0.0)))

    if scan:
        blocking = sum(_total(phases[lb]) for lb in BLOCKING_PHASES
                       if lb in phases)
        attrs["scan"] = scan
        attrs["host_gap_s"] = round(
            max((t_end - t_start) - blocking, 0.0), 6)
    parent = record_span("train/iteration", t_start, t_end,
                         trace_id=ctx["trace_id"],
                         parent_id=ctx["span_id"], attrs=attrs)
    cursor = t_start
    for label in sorted(phases):
        dur = _total(phases[label])
        if dur <= 0.0:
            continue
        record_span(f"phase/{label}", cursor, cursor + dur,
                    trace_id=ctx["trace_id"], parent_id=parent,
                    attrs={"count": int(phases[label]
                                        .get("count", 0))})
        cursor += dur


# ---------------------------------------------------------------------
# the `python -m lightgbm_tpu trace <dir>` CLI: merge per-process
# streams, correct clock skew, reconstruct critical paths, export
# Chrome trace-event JSON (Perfetto-loadable)
# ---------------------------------------------------------------------

#: matches the fleet's stream names (x.jsonl, x.jsonl.rankN,
#: x.jsonl.fleet) — kept identical to obs/recorder._STREAM_NAME_RE so
#: `trace` and `stats --fleet` always walk the same files
_STREAM_NAME_RE = re.compile(r"\.jsonl(\.rank\d+|\.fleet)?$")


def load_spans(directory: str) -> List[Dict[str, Any]]:
    """Every ``{"event": "span"}`` line under ``directory``
    (recursive), each stamped with its stream's relative path under
    ``"_stream"``. A truncated FINAL line per stream is tolerated (a
    SIGKILLed replica lands mid-write); garbage before the last line
    raises — that is corruption, not a crash artifact."""
    from .recorder import _stream_lines

    spans: List[Dict[str, Any]] = []
    for root, _dirs, names in sorted(os.walk(directory)):
        for name in sorted(names):
            if not _STREAM_NAME_RE.search(name):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, directory)

            def _parse(line: str, is_last: bool) -> Optional[dict]:
                try:
                    ev = json.loads(line)
                except ValueError:
                    if is_last:
                        return None        # mid-write crash artifact
                    raise ValueError(
                        f"{path}: malformed telemetry line "
                        f"{line[:80]!r}")
                return ev if isinstance(ev, dict) else None

            for ev in _stream_lines(path, _parse):
                if ev.get("event") != "span":
                    continue
                ev["_stream"] = rel
                spans.append(ev)
    return spans


def _proc_key(s: Dict[str, Any]) -> Tuple[str, str]:
    # (stream, proc): pids recycle across elastic restarts and hosts,
    # the stream they wrote into disambiguates the clock domain
    return (str(s.get("_stream", "")), str(s.get("proc", "?")))


def correct_clock_skew(spans: List[Dict[str, Any]]
                       ) -> Dict[Tuple[str, str], float]:
    """Place every span on one corrected timeline: per process, the
    offset between its monotonic clock and the shared wall clock is
    the median of ``wall - mono`` over its spans (the median rejects
    the occasional NTP step mid-run), and each span gains absolute
    ``t0``/``t1`` seconds = ``mono + offset``. Returns the per-process
    offsets (for the CLI's provenance print)."""
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = \
        defaultdict(list)
    for s in spans:
        groups[_proc_key(s)].append(s)
    offsets: Dict[Tuple[str, str], float] = {}
    for key, group in groups.items():
        offsets[key] = statistics.median(
            float(s["wall"]) - float(s["mono"]) for s in group)
    for s in spans:
        t0 = float(s["mono"]) + offsets[_proc_key(s)]
        s["t0"] = t0
        s["t1"] = t0 + float(s.get("dur", 0.0))
    return offsets


def chrome_trace(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the ``traceEvents`` array of complete
    ``ph: "X"`` events in microseconds, plus ``process_name``
    metadata) over skew-corrected spans — loadable in Perfetto /
    chrome://tracing. Timestamps are relative to the earliest span so
    the viewer opens at t=0."""
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    procs = sorted({_proc_key(s) for s in spans})
    pid_of = {key: i + 1 for i, key in enumerate(procs)}
    base = min(float(s["t0"]) for s in spans)
    events: List[Dict[str, Any]] = []
    for (stream, proc), pid in sorted(pid_of.items(),
                                      key=lambda kv: kv[1]):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"{proc} ({stream})"}})
    for s in spans:
        args = {"trace_id": s.get("trace_id"),
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                **(s.get("attrs") or {})}
        events.append({
            "name": str(s.get("name", "?")),
            "ph": "X",
            "ts": round((float(s["t0"]) - base) * 1e6, 3),
            "dur": round(float(s.get("dur", 0.0)) * 1e6, 3),
            "pid": pid_of[_proc_key(s)],
            "tid": 0,
            "cat": str(s.get("name", "?")).split("/", 1)[0],
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


#: watcher swap phases in causal order (serve/daemon.py poll_once)
_SWAP_STEPS = ("swap/validate", "swap/load", "swap/stage",
               "swap/apply")


def critical_paths(spans: List[Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """Reconstruct the named lifecycle critical paths from
    skew-corrected spans: for each trace that published a model,

        last train/iteration -> publish/model -> swap/validate ->
        load -> stage -> apply -> first serve/request answered by
        the swapped model

    The final hop joins ACROSS traces: request spans ride the
    client's trace, so the first request served by the new model is
    found by model id + corrected time (earliest ``serve/request``
    whose ``attrs.model`` matches the applied forest and whose start
    is at/after the swap's end)."""
    by_trace: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    requests: List[Dict[str, Any]] = []
    for s in spans:
        by_trace[str(s.get("trace_id"))].append(s)
        if s.get("name") == "serve/request":
            requests.append(s)
    requests.sort(key=lambda s: s["t0"])
    paths: List[Dict[str, Any]] = []
    for tid, group in by_trace.items():
        pubs = [s for s in group if s.get("name") == "publish/model"]
        if not pubs:
            continue
        pub = max(pubs, key=lambda s: s["t1"])
        steps: List[Dict[str, Any]] = []

        def _push(s: Dict[str, Any], label: Optional[str] = None
                  ) -> None:
            if steps and s["t0"] > steps[-1]["t1"]:
                steps.append({"name": "(wait)",
                              "t0": steps[-1]["t1"], "t1": s["t0"],
                              "dur_s": s["t0"] - steps[-1]["t1"],
                              "gap": True})
            steps.append({"name": label or str(s["name"]),
                          "t0": s["t0"], "t1": s["t1"],
                          "dur_s": float(s.get("dur", 0.0)),
                          "gap": False})

        iters = [s for s in group
                 if s.get("name") == "train/iteration"]
        if iters:
            last = max(iters, key=lambda s: (
                (s.get("attrs") or {}).get("iteration") or 0,
                s["t1"]))
            it_no = (last.get("attrs") or {}).get("iteration")
            _push(last, f"train/iteration #{it_no}")
        _push(pub)
        model = None
        swap_end = None
        # several replicas may swap; follow the EARLIEST completed
        # apply (the first replica able to answer from the new model)
        applies = sorted(
            (s for s in group if s.get("name") == "swap/apply"),
            key=lambda s: s["t1"])
        if applies:
            apply_proc = _proc_key(applies[0])
            for name in _SWAP_STEPS:
                cands = [s for s in group if s.get("name") == name
                         and _proc_key(s) == apply_proc]
                if cands:
                    _push(min(cands, key=lambda s: s["t0"]))
            model = (applies[0].get("attrs") or {}).get("model")
            swap_end = applies[0]["t1"]
        served = None
        if model is not None and swap_end is not None:
            for req in requests:
                if (req.get("attrs") or {}).get("model") == model \
                        and req["t0"] >= swap_end:
                    served = req
                    break
            if served is not None:
                _push(served, f"serve/request (model {model})")
        paths.append({
            "trace_id": tid,
            "generation": (pub.get("attrs") or {}).get("generation"),
            "model": model,
            "complete": bool(iters and applies and served),
            "steps": steps,
            "total_s": (steps[-1]["t1"] - steps[0]["t0"])
            if steps else 0.0,
        })
    paths.sort(key=lambda p: (p["generation"] is None,
                              p["generation"], p["trace_id"]))
    return paths


def render_critical_paths(paths: List[Dict[str, Any]]) -> str:
    lines: List[str] = []
    for p in paths:
        gen = p["generation"]
        head = f"critical path · generation " \
               f"{'?' if gen is None else gen} · trace " \
               f"{p['trace_id']}" \
               f"{'' if p['complete'] else ' · INCOMPLETE'}"
        lines.append(head)
        t_base = p["steps"][0]["t0"] if p["steps"] else 0.0
        for st in p["steps"]:
            at = st["t0"] - t_base
            lines.append(f"  {st['name']:44s} +{at:9.3f}s  "
                         f"{st['dur_s'] * 1e3:10.2f} ms")
        lines.append(f"  {'TOTAL iteration -> first-served':44s} "
                     f"{'':10s} {p['total_s'] * 1e3:10.2f} ms")
        lines.append("")
    return "\n".join(lines).rstrip()


_TRACE_HELP = """\
usage: python -m lightgbm_tpu trace <telemetry-dir> [--out FILE]

Merge every telemetry stream under the directory (x.jsonl plus the
fleet's .rankN / .fleet suffixes, recursively), collect the
{"event": "span"} lines, correct cross-process clock skew from each
span's paired wall/monotonic timestamps, and:

- write Chrome trace-event JSON (default <dir>/trace.json) — open it
  at https://ui.perfetto.dev or chrome://tracing,
- print the reconstructed lifecycle critical paths: last trained
  iteration -> publish -> manifest-validated swap -> first request
  served by the new model, with clock-corrected latencies.

Span schema, propagation map and the Perfetto workflow:
docs/OBSERVABILITY.md "Tracing". This command never imports jax.

exit codes:
  0  spans merged and exported (even if no complete critical path)
  1  no span events found, unreadable directory, or corrupt stream
"""


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(_TRACE_HELP)
        return 0
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        if i + 1 >= len(argv):
            print("trace: --out needs a file argument",
                  file=sys.stderr)
            return 1
        out_path = argv[i + 1]
        del argv[i:i + 2]
    if len(argv) != 1:
        print("usage: python -m lightgbm_tpu trace <telemetry-dir> "
              "[--out FILE]", file=sys.stderr)
        return 1
    directory = argv[0]
    if not os.path.isdir(directory):
        print(f"[LightGBM-TPU] [Fatal] not a directory: {directory}",
              file=sys.stderr)
        return 1
    try:
        spans = load_spans(directory)
    except OSError as e:
        print(f"[LightGBM-TPU] [Fatal] cannot read {directory}: {e}",
              file=sys.stderr)
        return 1
    except ValueError as e:
        print(f"[LightGBM-TPU] [Fatal] corrupt telemetry: {e}",
              file=sys.stderr)
        return 1
    if not spans:
        print(f"no span events in any *.jsonl under {directory}",
              file=sys.stderr)
        return 1
    offsets = correct_clock_skew(spans)
    doc = chrome_trace(spans)
    out_path = out_path or os.path.join(directory, "trace.json")
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
    except OSError as e:
        print(f"[LightGBM-TPU] [Fatal] cannot write {out_path}: {e}",
              file=sys.stderr)
        return 1
    print(f"{len(spans)} span(s) from {len(offsets)} process(es) -> "
          f"{out_path} (Perfetto/chrome://tracing)")
    if len(offsets) > 1:
        monos = sorted(offsets.values())
        print(f"clock-skew correction: per-process mono->wall "
              f"offsets spread over {monos[-1] - monos[0]:.3f} s")
    paths = critical_paths(spans)
    if paths:
        print()
        print(render_critical_paths(paths))
    else:
        print("no publish spans: critical paths need a traced "
              "publish -> swap -> serve lifecycle (run the pipeline "
              "with tracing on)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
