"""Quantized histogram collectives + the payload-adaptive mode chooser.

Data-parallel growth allreduces the full ``[F, B, 2]`` f32 histogram at
every split (MULTICHIP_r04: 2048 elems at F=64, B=16 — at an
Allstate-like F=4228, B=255 that is ~2M f32 elems, ~8.6 MB per
reduction), which dominates at pod scale and wide feature spaces. This
module provides the two answers named by ROADMAP open item 2:

1. **Block-quantized allreduce** (:func:`hist_allreduce`) in the
   EQuARX mold (arXiv:2506.17615): the histogram is flattened into
   256-element blocks, each block is quantized to int8/int16 with one
   f32 scale, and only the integer payload (plus the tiny scale
   vector) crosses the interconnect. Two wire strategies:

   - ``exchange`` (default for histogram-sized payloads): a two-phase
     reduce-scatter/all-gather built from ``lax.all_to_all`` +
     ``lax.all_gather`` whose wire dtype really is int8/int16 — each
     device receives every peer's quantized chunk, dequantizes and
     sums in f32, REquantizes its reduced chunk with fresh scales, and
     all-gathers the result. Per-device wire bytes drop from ~2x4xN
     (f32 ring allreduce) to ~2x1xN (int8) — the ~4x the EQuARX paper
     measures, visible to the dryrun payload audit because the
     collective operands ARE int8/int16.
   - ``psum`` (vmap-safe; used where the call site sits under
     ``jax.vmap``, e.g. the voting growers' elected-feature buffer):
     block amax is ``lax.pmax``-shared so every rank quantizes with
     the same scale, then the int values ride one ``lax.psum`` in an
     int32 accumulator (no overflow for any world size <= 2^16). The
     transport dtype stays int32, so this strategy models the wire
     saving rather than realizing it — acceptable for the small
     voting payloads; the dominant data-parallel path uses
     ``exchange``.

   **Determinism argument**: the reduced result every rank consumes is
   the output of ``all_gather`` (exchange) or ``psum`` (psum strategy)
   of integer payloads — bit-identical on every rank by construction
   (integer addition is associative-commutative-exact; all_gather is a
   broadcast of identical bytes). Split decisions derived from it are
   therefore replicated, exactly like the f32 psum they replace.

   **Error feedback** (the EF-SGD compressor-feedback loop): each rank
   keeps a local residual buffer ``ef`` the same shape as the
   histogram. Quantization consumes ``x + ef`` and the new residual is
   ``(x + ef) - dequant(sent)`` (plus, on the exchange path, the
   phase-2 requantization error of the chunk this rank owns). The
   per-round sent payloads then telescope:

       sum_k sent_k = sum_k x_k + ef_0 - ef_K

   so the ACCUMULATED dequantized error after any number of
   reductions is bounded by the final residual — one round's
   quantization step — instead of growing linearly with depth/trees.
   The growers thread ``ef`` through their loop carries
   (:mod:`lightgbm_tpu.ops.grow`).

2. **Payload-adaptive parallelism choice**
   (:func:`choose_parallel_mode`): the reference's tree_learner choice
   is a static user flag (docs/Parallel-Learning-Guide.rst: feature-
   parallel for small data, data-parallel for large data + few
   features, voting for both large); ``tree_learner=auto`` replaces it
   with a decision from the measured payload model — the same
   dtype-aware byte accounting ``__graft_entry__.dryrun_multichip``
   emits (:func:`payload_elems` / :func:`payload_bytes` seed both), in
   the spirit of automatic cross-replica sharding (arXiv:2004.13336).

Scalar/count psums (root tuples, exact child counts, SplitInfo
allreduce) stay f32: they are O(1)-to-O(B) bytes and feed count
thresholds where quantization buys nothing.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "BLOCK", "QMAX", "WIRE_ITEMSIZE", "hist_allreduce",
    "hist_reduce_scatter", "make_hist_psum_ef",
    "resolve_hist_comm", "payload_elems", "payload_bytes",
    "splitinfo_elems", "post_reduction_elems", "post_reduction_bytes",
    "choose_parallel_mode", "collective_payloads",
    "jaxpr_collective_payloads", "collective_summary",
]

#: quantization block size: one f32 scale per BLOCK elements (1.6%
#: overhead at int8). 256 keeps blocks lane-aligned on TPU.
BLOCK = 256

QMAX = {"int8": 127, "int16": 32767}
_WIRE_DTYPE = {"int8": jnp.int8, "int16": jnp.int16}

#: wire bytes per histogram element per hist_comm mode
WIRE_ITEMSIZE = {"f32": 4, "int16": 2, "int8": 1}

#: floor on block scales so an all-zero block quantizes to zeros
#: instead of NaNs
_TINY = 1e-30

#: auto hist_comm: quantize once the per-reduction f32 payload crosses
#: this many bytes (narrow histograms gain nothing and keep exact f32)
AUTO_QUANT_BYTES = 1 << 20

#: auto tree_learner: replicate rows (feature-parallel) only below this
#: many global rows — above it the one-time replication (and per-device
#: memory) dwarfs the histogram traffic it saves
FEATURE_MAX_ROWS = 1 << 16

#: auto tree_learner: stay data-parallel while one histogram reduction
#: is at most this many bytes; beyond it voting's O(2k*B) exchange wins
DATA_MAX_BYTES = 1 << 20


def _axis_size(name) -> int:
    """Static mapped-axis size (jax 0.4.37: ``lax.axis_size`` does not
    exist yet; ``core.axis_frame`` returns the int size under
    shard_map)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(name)
    return jax.core.axis_frame(name)


# ---------------------------------------------------------------------
# the quantized-allreduce primitive
# ---------------------------------------------------------------------

def _quantize(blocks: jnp.ndarray, qmax: int, wire_dtype):
    """Per-block symmetric quantization: ``[nblk, BLOCK] -> (q, scale)``
    with ``scale = amax / qmax`` so dequantization is ``q * scale``."""
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.maximum(amax, _TINY) / qmax
    q = jnp.clip(jnp.round(blocks / scale[:, None]), -qmax, qmax)
    return q.astype(wire_dtype), scale


def _pack_scales(q, scale, wire_dtype):
    """Append each block's f32 scale, bitcast into wire-dtype lanes, to
    its int payload: ``[nblk, BLOCK] + [nblk] -> [nblk, BLOCK + s]``.
    One homogeneous integer buffer then rides ONE collective — scales
    never travel as a separate (concurrently-rendezvousing) f32 op,
    and the wire really is pure int8/int16."""
    s = 4 // jnp.dtype(wire_dtype).itemsize          # lanes per f32
    sw = lax.bitcast_convert_type(scale[:, None], wire_dtype)
    return jnp.concatenate([q, sw.reshape(q.shape[0], s)], axis=1)


def _unpack_scales(packed, wire_dtype):
    """Inverse of :func:`_pack_scales` -> (q [.., BLOCK], scale [..])."""
    s = 4 // jnp.dtype(wire_dtype).itemsize
    q = packed[..., :BLOCK]
    scale = lax.bitcast_convert_type(
        packed[..., BLOCK:].reshape(packed.shape[:-1] + (1, s)),
        jnp.float32)
    return q, scale.reshape(packed.shape[:-1])


def _allreduce_exchange(blocks, scale_q, axis_name, qmax, wire_dtype,
                        D, dtype):
    """Two-phase quantized allreduce of pre-quantized blocks.

    Phase 1 (reduce-scatter shape): ``all_to_all`` routes chunk ``i``
    of every rank's int payload (scales packed into the same integer
    buffer) to rank ``i``, which dequantizes and sums in f32. Phase 2:
    the owner requantizes its reduced chunk with fresh scales and
    ``all_gather`` broadcasts the packed int result. Exactly TWO
    collectives per reduction, each consuming the previous one's
    output — the strict data dependence keeps every rank's collective
    sequence in lockstep (jaxlib 0.4.37's in-process CPU rendezvous
    is racy when independent collectives are in flight together).
    Returns ``(reduced [nblk, BLOCK] f32, phase2_err [cb*BLOCK] f32)``
    — the requantization error this rank introduced on its owned
    chunk (for error feedback)."""
    nblk = blocks.shape[0]
    cb = nblk // D                                   # blocks per chunk
    pk = _pack_scales(blocks, scale_q, wire_dtype)   # [nblk, BLOCK+s]
    px = lax.all_to_all(pk.reshape(D, cb, pk.shape[1]), axis_name,
                        split_axis=0, concat_axis=0)  # [D, cb, BLOCK+s]
    qx, sx = _unpack_scales(px, wire_dtype)
    red = jnp.sum(qx.astype(dtype) * sx[..., None], axis=0)
    q2, scale2 = _quantize(red, qmax, wire_dtype)
    deq2 = q2.astype(dtype) * scale2[:, None]            # [cb, BLOCK]
    err2 = (red - deq2).reshape(-1)
    pk2 = _pack_scales(q2, scale2, wire_dtype)
    pg = lax.all_gather(pk2, axis_name, axis=0)      # [D, cb, BLOCK+s]
    qg, sg = _unpack_scales(pg, wire_dtype)
    out = qg.reshape(nblk, BLOCK).astype(dtype) \
        * sg.reshape(nblk)[:, None]
    return out, err2


def _allreduce_shared_psum(blocks, axis_name, qmax, wire_dtype, dtype):
    """Shared-scale quantized allreduce: pmax the block amax so every
    rank quantizes with the SAME scale, then ``sum_r q_r * scale =
    scale * psum(q_r)`` holds exactly. int32 transport (headroom for
    any world <= 2^16 at int16); batches under jax.vmap, unlike
    all_to_all. Returns (reduced [nblk, BLOCK], sent-dequant
    [nblk, BLOCK]) — the latter is this rank's contribution as the
    wire saw it (for error feedback)."""
    amax = lax.pmax(jnp.max(jnp.abs(blocks), axis=-1), axis_name)
    scale = jnp.maximum(amax, _TINY) / qmax
    q = jnp.clip(jnp.round(blocks / scale[..., None]), -qmax, qmax)
    q = q.astype(wire_dtype)
    total = lax.psum(q.astype(jnp.int32), axis_name)
    out = total.astype(dtype) * scale[..., None]
    sent = q.astype(dtype) * scale[..., None]
    return out, sent


def hist_allreduce(x: jnp.ndarray, axis_name, mode: str = "f32",
                   error_feedback: Optional[jnp.ndarray] = None,
                   strategy: str = "auto"):
    """Allreduce a histogram across ``axis_name`` under ``mode``.

    ``mode="f32"`` (or a non-floating ``x``, e.g. the exact int32
    histograms of quantized-gradient training) is a plain
    ``lax.psum``. ``"int16"``/``"int8"`` run the block-quantized
    reduction described in the module docstring. With
    ``error_feedback`` (a buffer of ``x``'s shape) the return is
    ``(reduced, new_error_feedback)``; without it, just ``reduced``.

    ``strategy="auto"`` resolves (at trace time) to ``"exchange"`` —
    the int-wire all_to_all/all_gather pair — on TPU, and to the
    shared-scale ``"psum"`` transport on CPU: jaxlib 0.4.37's
    in-process CPU collective rendezvous stalls 5s+ (and, before the
    scales were packed into the int payload, deadlocked outright)
    when all_to_all/all_gather pairs cycle in a tight loop, while the
    pmax->psum chain is the pattern every existing multi-device test
    exercises. ``LIGHTGBM_TPU_COMM_EXCHANGE=1`` forces the exchange
    path for wire-level audits on CPU.

    The result is replicated — bit-identical on every rank — for every
    mode/strategy (see the determinism argument above), so split
    decisions computed from it never diverge.
    """
    has_ef = error_feedback is not None

    def ret(y, ef):
        return (y, ef) if has_ef else y

    if axis_name is None:
        return ret(x, error_feedback)
    if mode not in ("int8", "int16") \
            or not jnp.issubdtype(x.dtype, jnp.floating):
        return ret(lax.psum(x, axis_name), error_feedback)
    D = _axis_size(axis_name)
    if D == 1:
        return ret(x, error_feedback)
    if strategy == "auto":
        import os
        if jax.default_backend() == "tpu" \
                or os.environ.get("LIGHTGBM_TPU_COMM_EXCHANGE") == "1":
            strategy = "exchange"
        else:
            strategy = "psum"

    qmax = QMAX[mode]
    wire_dtype = _WIRE_DTYPE[mode]
    dtype = x.dtype
    shape = x.shape
    n = x.size
    xe = x if not has_ef else x + error_feedback

    if strategy == "psum":
        pad = (-n) % BLOCK
        blocks = jnp.pad(xe.reshape(-1), (0, pad)) \
            .reshape((n + pad) // BLOCK, BLOCK)
        out_b, sent_b = _allreduce_shared_psum(blocks, axis_name, qmax,
                                               wire_dtype, dtype)
        y = out_b.reshape(-1)[:n].reshape(shape)
        new_ef = None
        if has_ef:
            new_ef = xe - sent_b.reshape(-1)[:n].reshape(shape)
        return ret(y, new_ef)

    # exchange strategy: flatten, pad to a D*BLOCK multiple
    step = D * BLOCK
    np_ = -(-n // step) * step
    flat = jnp.pad(xe.reshape(-1), (0, np_ - n))
    nblk = np_ // BLOCK
    blocks = flat.reshape(nblk, BLOCK)
    q, scale = _quantize(blocks, qmax, wire_dtype)
    out_b, err2 = _allreduce_exchange(q, scale, axis_name, qmax,
                                      wire_dtype, D, dtype)
    y = out_b.reshape(-1)[:n].reshape(shape)
    if not has_ef:
        return y
    sent = q.astype(dtype) * scale[:, None]
    ef_flat = (blocks - sent).reshape(-1)                # [np_]
    # fold the phase-2 requantization error of the chunk THIS rank
    # owns into its residual (the owner introduced it)
    cbe = np_ // D
    off = lax.axis_index(axis_name) * cbe
    cur = lax.dynamic_slice(ef_flat, (off,), (cbe,))
    ef_flat = lax.dynamic_update_slice(ef_flat, cur + err2, (off,))
    new_ef = ef_flat[:n].reshape(shape)
    return y, new_ef


def make_hist_psum_ef(axis_name, hist_comm: str, quantize: bool = True):
    """The one wire-mode decision every grower shares: resolve the
    histogram wire format and build the EF-threaded reduction closure
    whose residual the growers carry through their loops
    (ops/grow.py). ``quantize=False`` pins the wire to exact f32
    regardless of ``hist_comm`` — the compact grower passes it for
    feature/voting-parallel (no full-histogram reduction) and
    quantized-gradient training (exact int32 histograms already).

    Returns ``(qm, use_ef, hist_psum_ef)``: the resolved wire mode,
    whether an error-feedback buffer must be allocated/carried, and
    ``hist_psum_ef(x, ef) -> (reduced, new_ef)`` — identity on a
    single device, exact ``lax.psum`` (``ef`` untouched) at f32 wire,
    the quantized :func:`hist_allreduce` otherwise."""
    qm = hist_comm if (axis_name is not None and quantize
                       and hist_comm in ("int8", "int16")) else "f32"
    use_ef = qm != "f32"

    def hist_psum_ef(x, ef):
        if axis_name is None:
            return x, ef
        if not use_ef:
            return lax.psum(x, axis_name), ef
        return hist_allreduce(x, axis_name, qm, ef)

    return qm, use_ef, hist_psum_ef


# ---------------------------------------------------------------------
# the reduce-scatter primitive (sharded split search)
# ---------------------------------------------------------------------

def hist_reduce_scatter(x: jnp.ndarray, axis_name, mode: str = "f32",
                        error_feedback: Optional[jnp.ndarray] = None,
                        scatter_axis: int = 0):
    """Reduce ``x`` across ``axis_name`` and return only THIS device's
    chunk of ``scatter_axis`` — the reference data-parallel learner's
    ``ReduceScatter`` (network.h) as a first-class wire primitive for
    ``split_search="sharded"``: each device then searches its owned
    ``F/D`` feature chunk instead of the full gathered histogram, and
    only the tiny winning SplitInfo records travel afterwards.

    ``x.shape[scatter_axis]`` must be ``D * chunk``.

    - ``mode="f32"`` (and any non-floating ``x``, e.g. exact int32
      quantized-gradient histograms): ``lax.psum_scatter`` — its chunk
      is bit-identical to the matching slice of ``lax.psum`` (on CPU by
      construction of the ordered reduction; on TPU the ring allreduce
      IS reduce-scatter + all-gather), which is what makes
      sharded-search split decisions byte-identical to the gathered
      path's.
    - ``"int8"``/``"int16"``: the int-wire exchange's phase 1
      (all_to_all of per-block-quantized payloads, scales packed into
      the same integer buffer) followed by the owner REQUANTIZING its
      reduced chunk and consuming the dequantized result — the same
      bytes the gathered exchange's phase-2 all_gather would have
      broadcast, minus the broadcast. Blocks are laid out per device
      chunk (each chunk padded to a BLOCK multiple independently), so
      chunk ownership aligns with ``scatter_axis`` slices exactly.

    With ``error_feedback`` (full ``x`` shape) the return is
    ``(chunk, new_error_feedback)`` — the residual covers the whole
    local histogram plus this rank's phase-2 requantization error on
    its owned chunk, telescoping like :func:`hist_allreduce`'s.
    Replication: every device's chunk is a pure function of the
    globally-reduced histogram, and downstream SplitInfo combines are
    allreduces — so split decisions stay identical on every rank.
    """
    has_ef = error_feedback is not None

    def ret(y, ef):
        return (y, ef) if has_ef else y

    if axis_name is None:
        return ret(x, error_feedback)
    if mode not in ("int8", "int16") \
            or not jnp.issubdtype(x.dtype, jnp.floating):
        chunk = lax.psum_scatter(x, axis_name,
                                 scatter_dimension=scatter_axis,
                                 tiled=True)
        return ret(chunk, error_feedback)
    D = _axis_size(axis_name)
    if D == 1:
        return ret(x, error_feedback)

    qmax = QMAX[mode]
    wire_dtype = _WIRE_DTYPE[mode]
    dtype = x.dtype
    xe = x if not has_ef else x + error_feedback
    xm = jnp.moveaxis(xe, scatter_axis, 0)
    cs = xm.shape[0] // D                    # chunk rows
    per = xm.size // D                       # elements per chunk
    flat = xm.reshape(D, per)
    pad = (-per) % BLOCK
    fl = jnp.pad(flat, ((0, 0), (0, pad)))   # [D, per + pad]
    cb = (per + pad) // BLOCK
    blocks = fl.reshape(D * cb, BLOCK)
    q, scale = _quantize(blocks, qmax, wire_dtype)
    pk = _pack_scales(q, scale, wire_dtype)  # [D*cb, BLOCK+s]
    px = lax.all_to_all(pk.reshape(D, cb, pk.shape[1]), axis_name,
                        split_axis=0, concat_axis=0)  # [D, cb, BLOCK+s]
    qx, sx = _unpack_scales(px, wire_dtype)
    red = jnp.sum(qx.astype(dtype) * sx[..., None], axis=0)  # [cb, BLOCK]
    q2, scale2 = _quantize(red, qmax, wire_dtype)
    deq2 = q2.astype(dtype) * scale2[:, None]
    chunk = deq2.reshape(-1)[:per].reshape((cs,) + xm.shape[1:])
    chunk = jnp.moveaxis(chunk, 0, scatter_axis)
    if not has_ef:
        return chunk
    sent = q.astype(dtype) * scale[:, None]          # [D*cb, BLOCK]
    ef_full = (blocks - sent).reshape(D, per + pad)[:, :per]
    err2 = (red - deq2).reshape(-1)[:per]            # own-chunk requant
    own = lax.axis_index(axis_name)
    cur = lax.dynamic_index_in_dim(ef_full, own, keepdims=False)
    ef_full = lax.dynamic_update_index_in_dim(ef_full, cur + err2, own,
                                              axis=0)
    new_ef = jnp.moveaxis(ef_full.reshape(xm.shape), 0, scatter_axis)
    return chunk, new_ef


# ---------------------------------------------------------------------
# payload model (seeds dryrun_multichip's accounting AND the auto
# tree_learner choice)
# ---------------------------------------------------------------------

def payload_elems(mode: str, F: int, B: int, top_k: int = 20) -> int:
    """Largest per-reduction collective payload (ELEMENTS) of one
    split search under parallelism ``mode`` — the quantity
    ``dryrun_multichip`` measures in the lowered StableHLO
    (MULTICHIP_r04 at F=64, B=16, k=3: data 2048 >> voting 384 >>
    feature 32).

    - ``data``: the full ``[F, B, 2]`` histogram psum.
    - ``voting``: the elected ``[k2, B, 2]`` buffer, x2 because both
      children's searches fuse into one vmapped collective
      (CopyLocalHistogram, parallel_tree_learner.h:153-161).
    - ``feature``: the SplitInfo allreduce only — scalars plus one
      ``[B]`` categorical mask, bounded by ``2B``.
    """
    if mode == "data":
        return F * B * 2
    if mode == "voting":
        return 2 * min(2 * top_k, F) * B * 2
    if mode == "feature":
        return 2 * B
    raise ValueError(f"unknown parallel mode: {mode}")


def payload_bytes(mode: str, F: int, B: int, hist_comm: str = "f32",
                  top_k: int = 20) -> int:
    """Dtype-aware wire BYTES of :func:`payload_elems`, including the
    per-block f32 scale overhead of the quantized modes. Histogram
    payloads (data/voting) scale with ``hist_comm``; the feature-mode
    SplitInfo stays f32 by design."""
    elems = payload_elems(mode, F, B, top_k)
    if mode == "feature" or hist_comm not in ("int8", "int16"):
        return elems * 4
    scales = -(-elems // BLOCK) * 4
    return elems * WIRE_ITEMSIZE[hist_comm] + scales


def splitinfo_elems(B: int) -> int:
    """Elements of ONE SplitInfo allreduce record: the scalar fields
    plus the ``[B]`` categorical membership mask — the same ``2B``
    bound the feature-parallel payload model uses."""
    return 2 * B


def post_reduction_elems(mode: str, F: int, B: int, D: int = 1,
                         split_search: str = "gathered",
                         top_k: int = 20) -> int:
    """POST-reduction split-search payload per device (ELEMENTS): what
    each device RECEIVES after the reduce phase, per split search.

    - ``gathered`` data-parallel: the full ``[F, B, 2]`` reduced
      histogram is broadcast back to every device (the all-gather arm
      of the ring allreduce).
    - ``sharded`` data-parallel (``split_search="sharded"``): each
      device receives only its owned ``ceil(F/D)`` feature chunk from
      the reduce-scatter, plus the ``O(D)`` per-device best-SplitInfo
      records of the combine.
    - other modes: unchanged from :func:`payload_elems` (voting's
      elected buffer / feature's SplitInfo are already small).
    """
    if mode == "data" and split_search == "sharded" and D > 1:
        return -(-F // D) * B * 2 + D * splitinfo_elems(B)
    return payload_elems(mode, F, B, top_k)


def post_reduction_bytes(mode: str, F: int, B: int, D: int = 1,
                         split_search: str = "gathered",
                         hist_comm: str = "f32", top_k: int = 20) -> int:
    """Dtype-aware wire BYTES of :func:`post_reduction_elems`. The
    histogram part scales with ``hist_comm`` (chunk or full broadcast);
    SplitInfo records stay f32 by design."""
    if mode == "data" and split_search == "sharded" and D > 1:
        chunk = -(-F // D) * B * 2
        if hist_comm in ("int8", "int16"):
            scales = -(-chunk // BLOCK) * 4
            hist_b = chunk * WIRE_ITEMSIZE[hist_comm] + scales
        else:
            hist_b = chunk * 4
        return hist_b + D * splitinfo_elems(B) * 4
    return payload_bytes(mode, F, B, hist_comm, top_k)


def resolve_hist_comm(hist_comm: str, F: int, B: int,
                      parallel_mode: str = "data",
                      top_k: int = 20) -> str:
    """Concrete wire mode for ``hist_comm="auto"``: quantize to int16
    once one f32 histogram reduction OF THE ACTIVE PARALLELISM MODE
    crosses ``AUTO_QUANT_BYTES`` (voting's elected buffer is far
    smaller than the full data-parallel histogram, so auto under
    voting stays exact until the elected payload itself is heavy;
    int16 keeps eval parity within tolerance — int8 stays opt-in
    until the on-chip quant_bench comms arm records its verdict);
    narrow histograms keep exact f32."""
    if hist_comm != "auto":
        return hist_comm
    wire_f32 = payload_bytes(parallel_mode, F, B, "f32", top_k)
    return "int16" if wire_f32 >= AUTO_QUANT_BYTES else "f32"


def choose_parallel_mode(F: int, B: int, rows: int, world: int,
                         hist_comm: str = "f32",
                         top_k: int = 20) -> str:
    """Pick data|voting|feature parallelism from the payload model —
    the ``tree_learner=auto`` decision.

    The reference's Parallel-Learning-Guide decision table (small data
    -> feature; large data + narrow -> data; large + wide -> voting),
    re-derived from measured bytes instead of adjectives:

    - ``feature`` when the dataset is small enough to replicate
      (``rows <= FEATURE_MAX_ROWS``): per-split traffic collapses to
      the SplitInfo allreduce and each device still does 1/D of the
      histogram work over its feature shard.
    - ``data`` while one histogram reduction, at the chosen wire
      dtype, stays under ``DATA_MAX_BYTES`` (or when voting cannot
      elect fewer features than exist, ``F <= 2*top_k``): exact
      reductions, no voting approximation.
    - ``voting`` otherwise: the exchange drops to the elected
      ``O(2k*B)`` buffer regardless of F (PV-Tree).
    """
    if world <= 1:
        return "data"
    if rows <= FEATURE_MAX_ROWS:
        return "feature"
    if F <= 2 * top_k:
        return "data"
    wire = resolve_hist_comm(hist_comm, F, B)
    if payload_bytes("data", F, B, wire, top_k) <= DATA_MAX_BYTES:
        return "data"
    return "voting"


# ---------------------------------------------------------------------
# jaxpr payload audit (dryrun_multichip + tests)
# ---------------------------------------------------------------------

#: collective primitives whose operands count as wire payload
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "psum_invariant",
    # jax>=0.8 varying-manual-axes (check_vma=True) names
    "psum2",
})


def collective_payloads(fn, *args):
    """Trace ``fn(*args)`` and return one record per collective operand
    in the jaxpr: ``{"prim", "elems", "itemsize", "bytes"}`` —
    dtype-aware, so a quantized allreduce's int8 operands report 1/4
    the bytes of the f32 psum they replace."""
    return jaxpr_collective_payloads(jax.make_jaxpr(fn)(*args))


def jaxpr_collective_payloads(closed):
    """:func:`collective_payloads` over an already-traced ClosedJaxpr
    (so callers needing the jaxpr for other audits trace once)."""
    records = []

    def _sub(val):
        import jax.extend.core as jcore
        if isinstance(val, jcore.ClosedJaxpr):
            yield val.jaxpr
        elif isinstance(val, jcore.Jaxpr):
            yield val
        elif isinstance(val, (tuple, list)):
            for v in val:
                yield from _sub(v)

    eqn_seq = [0]

    def _walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in COLLECTIVE_PRIMS:
                eqn_seq[0] += 1
                # output side too: a psum RETURNS the full reduced
                # operand where a psum_scatter returns 1/D of it — the
                # out bytes are the post-reduction payload the sharded
                # split search exists to shrink
                out_elems = out_bytes = 0
                for v in eqn.outvars:
                    aval = getattr(v, "aval", None)
                    if aval is None or not hasattr(aval, "size"):
                        continue
                    out_elems += int(aval.size)
                    out_bytes += int(aval.size) \
                        * int(jnp.dtype(aval.dtype).itemsize)
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    if aval is None or not hasattr(aval, "size"):
                        continue
                    itemsize = jnp.dtype(aval.dtype).itemsize
                    records.append({
                        "prim": eqn.primitive.name,
                        "eqn": eqn_seq[0],
                        "elems": int(aval.size),
                        "itemsize": int(itemsize),
                        "bytes": int(aval.size) * int(itemsize),
                        "elems_out": out_elems,
                        "bytes_out": out_bytes,
                    })
            for val in eqn.params.values():
                for sub in _sub(val):
                    _walk(sub)

    _walk(closed.jaxpr)
    return records


def collective_summary(closed) -> dict:
    """Budget view of a traced program's collectives — the numbers
    ``lint --ir`` (TPL012, analysis/ircheck.py) diffs against the
    committed ``tools/ir_budgets.json``:

    - ``wire_bytes``: total operand bytes entering collectives (the
      payload the int8/int16 hist wire shrinks 4x/2x),
    - ``post_reduction_bytes``: total bytes the collectives RETURN
      (the payload ``split_search=sharded``'s psum_scatter cuts ~D x
      vs a full psum),
    - ``n_collectives`` / ``prims``: the collective census.

    Out-bytes are counted once per collective *equation* (a
    multi-operand psum contributes one output, not one per operand)."""
    records = jaxpr_collective_payloads(closed)
    out_by_eqn = {}
    for r in records:
        out_by_eqn[r["eqn"]] = r["bytes_out"]
    return {
        "n_collectives": len(out_by_eqn),
        "prims": sorted({r["prim"] for r in records}),
        "wire_bytes": sum(r["bytes"] for r in records),
        "post_reduction_bytes": sum(out_by_eqn.values()),
    }
