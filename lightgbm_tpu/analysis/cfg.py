"""Per-function control-flow graphs + path-sensitive dataflow.

The statement-level rules (TPL001-TPL006) answer "does this call occur
in this function"; the distributed-safety rules (TPL007/TPL008) need
"on *which paths* does it occur, and what is guaranteed to hold there".
This module builds one small CFG per function definition and solves two
forward dataflow problems over it:

**Guard pins** — for every statement, the set of branch decisions
``(test_expr, polarity)`` that hold on *every* path from the function
entry to it (meet = intersection over incoming edges). Because the meet
runs over the CFG rather than the lexical nesting, an early exit
propagates its condition onto the code *after* the branch::

    if process_index() == 0:
        return                    # this arm always diverts
    host_allgather(...)           # pins: (process_index()==0, False)

while a fall-through arm correctly contributes nothing::

    if process_index() == 0:
        payload = serialize()     # falls through
    host_broadcast_bytes(payload) # pins: {} — every rank reaches it

which is exactly the distinction between a rank-divergent collective
(deadlock) and the idiomatic rank-dependent *argument* (fine). ``for``
loops pin their body on the iterable (a rank-dependent iterable means a
rank-dependent trip count — every extra iteration is an extra
collective some ranks never join).

**Held locks** — for every statement, the set of lock expressions
guaranteed held there: lexical ``with lock:`` scopes plus a forward
``.acquire()``/``.release()`` dataflow (meet = intersection), so
TPL008's "write and read share a common lock" check is a CFG question,
not a syntactic one.

Each statement also carries its exception context (``in_except`` /
``in_finally``): code in handlers runs only on ranks that hit the
exception — a collective there is rank-divergent by construction.

Pure stdlib; importing this never imports jax.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .astscan import dotted_of

__all__ = ["FunctionCFG", "UnitInfo", "Pin"]

#: one guaranteed branch decision: (test expression, polarity). For
#: ``for`` bodies the "test" is the iterable and polarity is True.
Pin = Tuple[ast.expr, bool]

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def looks_like_lock(expr: ast.expr) -> Optional[str]:
    """The dotted name of a lock-ish context/target expression
    (``self._lock``, ``_state_lock``, ``threading.Lock()``), else
    None. Shared by the CFG lock dataflow and TPL006/TPL008."""
    d = dotted_of(expr)
    if d is None:
        if isinstance(expr, ast.Call):
            f = dotted_of(expr.func) or ""
            if f.rsplit(".", 1)[-1] in _LOCK_CTORS:
                return f  # anonymous with Lock(): — named by ctor
        return None
    last = d.rsplit(".", 1)[-1].lower()
    if "lock" in last or "mutex" in last:
        return d
    return None


@dataclass
class UnitInfo:
    """Everything the flow rules need to know about one statement."""
    stmt: ast.stmt
    pins: List[Pin]
    in_except: bool
    in_finally: bool
    held_locks: FrozenSet[str]
    reachable: bool = True


@dataclass
class _Block:
    bid: int
    units: List[int] = field(default_factory=list)
    # (succ block id, optional pin added on this edge)
    succs: List[Tuple[int, Optional[Tuple[int, bool]]]] = \
        field(default_factory=list)
    in_except: bool = False
    in_finally: bool = False
    with_locks: FrozenSet[str] = frozenset()


class FunctionCFG:
    """CFG + solved dataflow for one ``ast.FunctionDef`` body. Nested
    function/class definitions are opaque single statements (each
    nested def gets its own FunctionCFG from the rule)."""

    def __init__(self, fn_node: ast.AST):
        self.fn_node = fn_node
        self._blocks: List[_Block] = []
        self._units: List[Tuple[ast.stmt, int]] = []  # (stmt, block id)
        self._node_unit: Dict[int, int] = {}          # id(node) -> uid
        self._pin_nodes: Dict[int, ast.expr] = {}     # id -> test expr
        entry = self._new_block()
        self.entry = entry.bid
        exit_block = self._new_block()
        self.exit = exit_block.bid
        body = getattr(fn_node, "body", [])
        ctx = _Ctx(loop_header=None, loop_exit=None,
                   in_except=False, in_finally=False,
                   with_locks=frozenset())
        tail = self._build_body(body, entry.bid, ctx)
        if tail is not None:
            self._edge(tail, self.exit)
        self._guards_in = self._solve_guards()
        self._locks_in = self._solve_locks()
        # per-unit precision: a lock.acquire() earlier in the SAME
        # block counts as held for the statements after it
        self._unit_locks: Dict[int, FrozenSet[str]] = {}
        for b in self._blocks:
            cur = self._locks_in[b.bid] or frozenset()
            for uid in b.units:
                self._unit_locks[uid] = cur
                cur = self._transfer_locks_one(uid, cur)

    # -- construction --------------------------------------------------
    def _new_block(self, *, in_except=False, in_finally=False,
                   with_locks: FrozenSet[str] = frozenset()) -> _Block:
        b = _Block(bid=len(self._blocks), in_except=in_except,
                   in_finally=in_finally, with_locks=with_locks)
        self._blocks.append(b)
        return b

    def _edge(self, src: int, dst: int,
              pin: Optional[Pin] = None) -> None:
        key = None
        if pin is not None:
            key = (id(pin[0]), pin[1])
            self._pin_nodes[id(pin[0])] = pin[0]
        self._blocks[src].succs.append((dst, key))

    def _add_unit(self, block: int, stmt: ast.stmt,
                  index_nodes: Optional[List[ast.AST]] = None) -> int:
        uid = len(self._units)
        self._units.append((stmt, block))
        self._blocks[block].units.append(uid)
        for root in (index_nodes if index_nodes is not None
                     else [stmt]):
            for sub in ast.walk(root):
                self._node_unit.setdefault(id(sub), uid)
        return uid

    def _spawn(self, ctx: "_Ctx", **over) -> _Block:
        return self._new_block(
            in_except=over.get("in_except", ctx.in_except),
            in_finally=over.get("in_finally", ctx.in_finally),
            with_locks=over.get("with_locks", ctx.with_locks))

    def _build_body(self, stmts, cur: Optional[int],
                    ctx: "_Ctx") -> Optional[int]:
        """Append ``stmts`` to block ``cur``; return the open block at
        the end, or None when every path diverted (return/raise/...)."""
        for stmt in stmts:
            if cur is None:
                # unreachable code after a divert: still index it (the
                # rules must be able to look any node up) in a fresh,
                # edgeless block

                cur = self._spawn(ctx).bid
            cur = self._build_stmt(stmt, cur, ctx)
        return cur

    def _build_stmt(self, stmt: ast.stmt, cur: int,
                    ctx: "_Ctx") -> Optional[int]:
        if isinstance(stmt, ast.If):
            self._add_unit(cur, stmt, [stmt.test])
            after = self._spawn(ctx)
            body_b = self._spawn(ctx)
            self._edge(cur, body_b.bid, (stmt.test, True))
            body_tail = self._build_body(stmt.body, body_b.bid, ctx)
            if body_tail is not None:
                self._edge(body_tail, after.bid)
            if stmt.orelse:
                else_b = self._spawn(ctx)
                self._edge(cur, else_b.bid, (stmt.test, False))
                else_tail = self._build_body(stmt.orelse, else_b.bid,
                                             ctx)
                if else_tail is not None:
                    self._edge(else_tail, after.bid)
            else:
                self._edge(cur, after.bid, (stmt.test, False))
            return after.bid if self._blocks[after.bid].succs or \
                self._has_preds(after.bid) else None
        if isinstance(stmt, ast.While):
            header = self._spawn(ctx)
            self._edge(cur, header.bid)
            self._add_unit(header.bid, stmt, [stmt.test])
            after = self._spawn(ctx)
            body_b = self._spawn(ctx)
            self._edge(header.bid, body_b.bid, (stmt.test, True))
            # the else clause runs ONLY on normal exhaustion, never on
            # break — it needs its own block off the header's false
            # edge, with break paths joining after it
            exhausted = after
            if stmt.orelse:
                exhausted = self._spawn(ctx)
            self._edge(header.bid, exhausted.bid, (stmt.test, False))
            inner = ctx.replace(loop_header=header.bid,
                                loop_exit=after.bid)
            body_tail = self._build_body(stmt.body, body_b.bid, inner)
            if body_tail is not None:
                self._edge(body_tail, header.bid)
            if stmt.orelse:
                else_tail = self._build_body(stmt.orelse,
                                             exhausted.bid, ctx)
                if else_tail is not None:
                    self._edge(else_tail, after.bid)
            return after.bid
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._add_unit(cur, stmt, [stmt.target, stmt.iter])
            header = self._spawn(ctx)
            self._edge(cur, header.bid)
            after = self._spawn(ctx)
            body_b = self._spawn(ctx)
            # body executes a data-dependent number of times: pin it on
            # the iterable (rank-dependent iterable = rank-dependent
            # collective count). The after-block is unpinned — the loop
            # may run zero times but the exit is always reached.
            self._edge(header.bid, body_b.bid, (stmt.iter, True))
            exhausted = after
            if stmt.orelse:
                exhausted = self._spawn(ctx)
            self._edge(header.bid, exhausted.bid)
            inner = ctx.replace(loop_header=header.bid,
                                loop_exit=after.bid)
            body_tail = self._build_body(stmt.body, body_b.bid, inner)
            if body_tail is not None:
                self._edge(body_tail, header.bid)
            if stmt.orelse:
                else_tail = self._build_body(stmt.orelse,
                                             exhausted.bid, ctx)
                if else_tail is not None:
                    self._edge(else_tail, after.bid)
            return after.bid
        if isinstance(stmt, ast.Try):
            handlers = []
            for h in stmt.handlers:
                hb = self._spawn(ctx, in_except=True)
                # an exception can fire at any point of the try body;
                # the guaranteed state there is the state at try entry
                self._edge(cur, hb.bid)
                handlers.append((h, hb))
            after = self._spawn(ctx)
            body_b = self._spawn(ctx)
            self._edge(cur, body_b.bid)
            inner = ctx
            body_tail = self._build_body(stmt.body, body_b.bid, inner)
            if stmt.orelse and body_tail is not None:
                body_tail = self._build_body(stmt.orelse, body_tail,
                                             inner)
            exits = []
            if body_tail is not None:
                exits.append(body_tail)
            for h, hb in handlers:
                hctx = ctx.replace(in_except=True)
                htail = self._build_body(h.body, hb.bid, hctx)
                if htail is not None:
                    exits.append(htail)
            if stmt.finalbody:
                fin = self._spawn(ctx, in_finally=True)
                for e in exits:
                    self._edge(e, fin.bid)
                if not exits:
                    # every path raised/returned: the finally still
                    # runs on the way out
                    self._edge(cur, fin.bid)
                fctx = ctx.replace(in_finally=True)
                ftail = self._build_body(stmt.finalbody, fin.bid, fctx)
                if ftail is not None:
                    self._edge(ftail, after.bid)
            else:
                for e in exits:
                    self._edge(e, after.bid)
                if not exits:
                    return None
            return after.bid
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._add_unit(cur, stmt, list(stmt.items))
            locks = set(ctx.with_locks)
            for item in stmt.items:
                name = looks_like_lock(item.context_expr)
                if name:
                    locks.add(name)
            wctx = ctx.replace(with_locks=frozenset(locks))
            body_b = self._spawn(wctx)
            self._edge(cur, body_b.bid)
            tail = self._build_body(stmt.body, body_b.bid, wctx)
            if tail is None:
                return None
            after = self._spawn(ctx)
            self._edge(tail, after.bid)
            return after.bid
        # -- simple / opaque statements --------------------------------
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # nested defs are their own CFGs; index only the header
            self._add_unit(cur, stmt, [ast.Expr(value=d)
                                       for d in stmt.decorator_list]
                           or [ast.Pass()])
            return cur
        self._add_unit(cur, stmt)
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._edge(cur, self.exit)
            return None
        if isinstance(stmt, ast.Break):
            if ctx.loop_exit is not None:
                self._edge(cur, ctx.loop_exit)
            return None
        if isinstance(stmt, ast.Continue):
            if ctx.loop_header is not None:
                self._edge(cur, ctx.loop_header)
            return None
        return cur

    def _has_preds(self, bid: int) -> bool:
        return any(s == bid for b in self._blocks
                   for (s, _) in b.succs)

    # -- dataflow ------------------------------------------------------
    def _solve_guards(self) -> List[Optional[FrozenSet]]:
        """in[b] = ∩ over incoming edges of (out[pred] ∪ edge pin);
        out == in (statements never add pins). Meet over the CFG, so
        pins shrink to what holds on *every* path."""
        n = len(self._blocks)
        state: List[Optional[FrozenSet]] = [None] * n
        state[self.entry] = frozenset()
        preds: Dict[int, List[Tuple[int, Optional[Tuple[int, bool]]]]] \
            = {i: [] for i in range(n)}
        for b in self._blocks:
            for (succ, pin) in b.succs:
                preds[succ].append((b.bid, pin))
        for _ in range(n + 2):  # pins only shrink: converges fast
            changed = False
            for bid in range(n):
                if bid == self.entry:
                    continue
                contribs = []
                for (p, pin) in preds[bid]:
                    if state[p] is None:
                        continue
                    s = state[p]
                    if pin is not None:
                        s = s | {pin}
                    contribs.append(s)
                if not contribs:
                    continue
                new = frozenset.intersection(*contribs)
                if state[bid] is None or new != state[bid]:
                    state[bid] = new
                    changed = True
            if not changed:
                break
        return state

    def _solve_locks(self) -> List[Optional[FrozenSet[str]]]:
        """Forward ``.acquire()``/``.release()`` dataflow (meet = ∩).
        Lexical ``with lock:`` scopes are carried on the blocks
        themselves and unioned in at query time."""
        n = len(self._blocks)
        state: List[Optional[FrozenSet[str]]] = [None] * n
        state[self.entry] = frozenset()
        preds: Dict[int, List[int]] = {i: [] for i in range(n)}
        for b in self._blocks:
            for (succ, _) in b.succs:
                preds[succ].append(b.bid)
        outs: List[Optional[FrozenSet[str]]] = [None] * n
        for _ in range(2 * n + 2):
            changed = False
            for bid in range(n):
                known = [outs[p] for p in preds[bid]
                         if outs[p] is not None]
                if bid == self.entry:
                    inset: FrozenSet[str] = frozenset()
                elif known:
                    inset = frozenset.intersection(*known)
                else:
                    continue
                out = self._transfer_locks(bid, inset)
                if state[bid] != inset or outs[bid] != out:
                    state[bid] = inset
                    outs[bid] = out
                    changed = True
            if not changed:
                break
        return state

    def _transfer_locks(self, bid: int,
                        held: FrozenSet[str]) -> FrozenSet[str]:
        cur = held
        for uid in self._blocks[bid].units:
            cur = self._transfer_locks_one(uid, cur)
        return cur

    @staticmethod
    def _unit_expr_roots(stmt: ast.stmt) -> List[ast.AST]:
        """The expressions a unit itself evaluates. For compound
        statements that is the HEADER only — body statements live in
        their own blocks, and walking the whole subtree would
        attribute a branch-internal acquire()/release() to the header
        block and leak it down paths that never execute it."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.target, stmt.iter]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return list(stmt.items)
        if isinstance(stmt, (ast.Try, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            return []
        return [stmt]

    def _transfer_locks_one(self, uid: int,
                            held: FrozenSet[str]) -> FrozenSet[str]:
        cur = set(held)
        stmt, _ = self._units[uid]
        for root in self._unit_expr_roots(stmt):
            for sub in ast.walk(root):
                if not isinstance(sub, ast.Call) or \
                        not isinstance(sub.func, ast.Attribute):
                    continue
                name = looks_like_lock(sub.func.value)
                if name is None:
                    continue
                if sub.func.attr == "acquire":
                    cur.add(name)
                elif sub.func.attr == "release":
                    cur.discard(name)
        return frozenset(cur)

    # -- queries -------------------------------------------------------
    def info(self, node: ast.AST) -> Optional[UnitInfo]:
        """Flow facts for the statement containing ``node`` (any
        expression node inside it). None for nodes this CFG does not
        own (e.g. bodies of nested defs)."""
        uid = self._node_unit.get(id(node))
        if uid is None:
            return None
        stmt, bid = self._units[uid]
        block = self._blocks[bid]
        pins_raw = self._guards_in[bid]
        pins: List[Pin] = []
        if pins_raw:
            for (nid, pol) in sorted(pins_raw,
                                     key=lambda p: (self._pin_lineno(p),
                                                    p[1])):
                pins.append((self._pin_nodes[nid], pol))
        locks = self._unit_locks.get(uid, frozenset()) \
            | block.with_locks
        return UnitInfo(stmt=stmt, pins=pins,
                        in_except=block.in_except,
                        in_finally=block.in_finally,
                        held_locks=locks,
                        reachable=self._guards_in[bid] is not None)

    def _pin_lineno(self, pin) -> int:
        node = self._pin_nodes.get(pin[0])
        return getattr(node, "lineno", 0)

    def held_locks(self, node: ast.AST) -> FrozenSet[str]:
        got = self.info(node)
        return got.held_locks if got is not None else frozenset()


@dataclass(frozen=True)
class _Ctx:
    loop_header: Optional[int]
    loop_exit: Optional[int]
    in_except: bool
    in_finally: bool
    with_locks: FrozenSet[str]

    def replace(self, **kw) -> "_Ctx":
        data = dict(loop_header=self.loop_header,
                    loop_exit=self.loop_exit,
                    in_except=self.in_except,
                    in_finally=self.in_finally,
                    with_locks=self.with_locks)
        data.update(kw)
        return _Ctx(**data)
