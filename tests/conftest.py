"""Test configuration: force an 8-virtual-device CPU mesh.

Mirrors the reference's localhost-cluster test pattern
(tests/distributed/_test_distributed.py): multi-node is simulated on one
host — here via XLA's host-platform device partitioning instead of
loopback TCP sockets.
"""

import os

# Force CPU: the ambient environment may point JAX_PLATFORMS at a remote
# TPU tunnel, which would run every test over per-op RTT. The tunnel's
# sitecustomize re-registers its platform and overrides the jax_platforms
# config at interpreter start, so an env var alone is not enough — the
# config must be re-overridden after importing jax (backends are not
# initialized yet at conftest-import time, so this takes effect).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _isolate_process_fault_log():
    """Tier-1 order independence: the PROCESS-LEVEL fault-event log
    (resilience.faults.FAULT_EVENTS) is drained by whichever telemetry
    recorder runs next, so a test that provokes watchdog timeouts /
    injected faults without attaching a recorder (the
    test_distributed_resilience in-process chaos tests) used to leak
    its events into an unrelated later test's JSONL stream —
    test_jsonl_schema_one_valid_event_per_iteration counted 15 lines
    for 5 iterations whenever the distributed module ran first.
    Snapshot-and-clear after every test so each starts with an empty
    process log; tests that assert on these events consume them
    inside the test body."""
    yield
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS, drain_events
    if FAULT_EVENTS:
        drain_events(FAULT_EVENTS)
    # same contract for the process-level XLA compile-event queue
    # (obs/cost.py): a test that compiles jitted entry points without
    # draining would otherwise leak {"event": "compile"} lines into an
    # unrelated later test's JSONL stream
    from lightgbm_tpu.obs.cost import drain_compile_events
    drain_compile_events()
    # and for the process-level span buffer + current trace context
    # (obs/trace.py): spans recorded without an attached recorder must
    # not leak into a later test's stream, and a test that calls
    # set_current_trace must not re-parent spans of the next test
    from lightgbm_tpu.obs.trace import drain_span_events, set_current_trace
    drain_span_events()
    set_current_trace(None)


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(42)


def make_synthetic_binary(n=2000, f=10, seed=7):
    """Linearly-separable-ish binary task with noise."""
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    coef = rs.randn(f)
    logits = X @ coef + 0.5 * rs.randn(n)
    y = (logits > 0).astype(np.float64)
    return X, y


def make_synthetic_regression(n=2000, f=10, seed=7):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    coef = rs.randn(f)
    y = X @ coef + 0.1 * rs.randn(n)
    return X, y
