"""Phase timing — the USE_TIMETAG subsystem re-imagined for JAX.

The reference compiles a global ``Common::Timer`` + RAII ``FunctionTimer``
into every hot-path phase and logs a sorted per-label wall-time table at
process exit (/root/reference/include/LightGBM/utils/common.h:973-1057,
instrumentation points listed in SURVEY.md §5). On TPU the device runs
asynchronously from Python, so two complementary mechanisms are provided:

- ``Timer`` / ``timed(label)``: host wall-clock aggregation per label.
  Because dispatch is async, a label's time only reflects device work if
  the section itself synchronizes (the train loop's per-iteration sync
  points do). Enabled with env ``LIGHTGBM_TPU_TIMETAG=1`` or
  ``Timer.enable()``; ``Timer.log_summary()`` prints the sorted table and
  ``Timer.snapshot()`` returns it machine-readable (the telemetry
  recorder diffs consecutive snapshots into per-iteration phase times).
- inside an active ``trace_to`` capture, every timed section also enters
  a ``jax.profiler.TraceAnnotation`` so the phases show up as named
  spans in the tensorboard/xplane view even when host timing is off.

When neither timing nor tracing is active, ``timed`` yields immediately:
no jax import, no TraceAnnotation construction, no clock reads — the
instrumented loop must cost nothing with telemetry off.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager, nullcontext
from typing import Dict, Iterator

from .log import log_info

__all__ = ["Timer", "timed", "trace_to", "EnvCapture",
           "parse_xprof_spec"]

# number of live trace_to() captures; touched under Timer._lock
_tracing = 0


class Timer:
    """Process-global label -> accumulated wall seconds."""

    _acc: Dict[str, float] = defaultdict(float)
    _cnt: Dict[str, int] = defaultdict(int)
    _enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")
    # callbacks can fire from user threads and the recorder snapshots
    # concurrently with additions
    _lock = threading.Lock()

    @classmethod
    def enable(cls, on: bool = True) -> None:
        cls._enabled = on

    @classmethod
    def enabled(cls) -> bool:
        return cls._enabled

    @classmethod
    def add(cls, label: str, seconds: float) -> None:
        with cls._lock:
            cls._acc[label] += seconds
            cls._cnt[label] += 1

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._acc.clear()
            cls._cnt.clear()

    @classmethod
    def summary(cls) -> Dict[str, float]:
        with cls._lock:
            return dict(cls._acc)

    @classmethod
    def snapshot(cls) -> Dict[str, Dict[str, float]]:
        """Consistent ``{label: {"total": seconds, "count": n}}`` copy."""
        with cls._lock:
            return {label: {"total": sec, "count": cls._cnt[label]}
                    for label, sec in cls._acc.items()}

    @classmethod
    def log_summary(cls) -> None:
        snap = cls.snapshot()
        if not snap:
            return
        grand = sum(v["total"] for v in snap.values()) or 1.0
        log_info("lightgbm_tpu phase timings (host wall):")
        log_info(f"  {'label':32s} {'total s':>10s} {'count':>8s} "
                 f"{'mean ms':>10s} {'%':>6s}")
        for label, v in sorted(snap.items(), key=lambda kv: -kv[1]["total"]):
            sec, cnt = v["total"], int(v["count"])
            mean_ms = sec / cnt * 1e3 if cnt else 0.0
            log_info(f"  {label:32s} {sec:10.3f} {cnt:8d} "
                     f"{mean_ms:10.3f} {100.0 * sec / grand:6.1f}")


# shared no-op context: the disabled cost of a timed() section is one
# flag check + returning this singleton, against the seed's per-call
# jax import + TraceAnnotation + generator frame
_NULL = nullcontext()

# jax resolved once on first active use — not at module import (utils
# load before the backend is configured) and not per call
_jax = None


def _get_jax():
    global _jax
    if _jax is None:
        import jax
        _jax = jax
    return _jax


@contextmanager
def _timed_active(label: str) -> Iterator[None]:
    jax = _get_jax()

    with jax.profiler.TraceAnnotation(label):
        if not Timer._enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            Timer.add(label, time.perf_counter() - t0)


# resolved lazily: the jax profiler's session slot, so timed() also
# annotates traces started OUTSIDE trace_to() via the Python API
# (jax.profiler.start_trace / jax.profiler.trace). Captures triggered
# against jax.profiler.start_server happen in C++ and are NOT visible
# here — use trace_to() or LIGHTGBM_TPU_TIMETAG=1 for those. False-y
# sentinel until jax is imported; None forever if the private attr
# moved (degrade to library-only detection, never break).
_profile_state = False


def _external_trace_active() -> bool:
    global _profile_state
    if _profile_state is False:
        import sys
        if "jax" not in sys.modules:
            return False
        try:
            from jax._src.profiler import _profile_state as st
            _profile_state = st
        except Exception:
            _profile_state = None
    if _profile_state is None:
        return False
    try:
        return _profile_state.profile_session is not None
    except Exception:
        return False


def timed(label: str):
    """Time a phase and, inside a trace capture (ours or an externally
    started jax profiler session), annotate it. A strict no-op (shared
    null context) when neither timing nor tracing is active."""
    if not Timer._enabled and not _tracing \
            and not _external_trace_active():
        return _NULL
    return _timed_active(label)


@contextmanager
def trace_to(log_dir: str) -> Iterator[None]:
    """Capture a full device trace (jax.profiler.trace wrapper) — view
    with tensorboard's profile plugin, or any xplane.pb reader. While a
    capture is live, ``timed`` sections emit TraceAnnotation spans even
    with host timing off."""
    global _tracing
    jax = _get_jax()

    with Timer._lock:
        _tracing += 1
    try:
        with jax.profiler.trace(log_dir):
            yield
    finally:
        with Timer._lock:
            _tracing -= 1


def parse_xprof_spec(spec: str):
    """Parse ``LIGHTGBM_TPU_XPROF=<dir>:iters=A-B`` (or ``:iters=A``
    for a one-iteration window) into ``(log_dir, first, last)``.
    Raises ValueError on a malformed spec — a silently ignored typo
    would cost an on-chip session its capture."""
    if ":iters=" not in spec:
        raise ValueError(
            f"LIGHTGBM_TPU_XPROF expects <dir>:iters=A-B, got "
            f"{spec!r}")
    log_dir, window = spec.rsplit(":iters=", 1)
    lo, _, hi = window.partition("-")
    try:
        first = int(lo)
        last = int(hi) if hi else first
    except ValueError:
        raise ValueError(
            f"LIGHTGBM_TPU_XPROF iteration window {window!r} is not "
            "A-B integers") from None
    if not log_dir or first < 0 or last < first:
        raise ValueError(
            f"LIGHTGBM_TPU_XPROF window {spec!r} needs a directory "
            "and 0 <= A <= B")
    return log_dir, first, last


class EnvCapture:
    """Env-driven device captures for the train loop (engine.py):

    - ``LIGHTGBM_TPU_TRACE_TO=<dir>`` wraps the WHOLE iteration loop
      in one :func:`trace_to` capture — device profiles reachable
      without any API calls,
    - ``LIGHTGBM_TPU_XPROF=<dir>:iters=A-B`` captures only iterations
      A..B (engine-absolute): the programmatic window that makes a
      steady-state fused-scan iteration inspectable without paying a
      whole-run xplane file.

    The engine calls ``before_iteration(i)`` / ``after_iteration(i)``
    per iteration and ``close()`` in its finally; every call is a
    no-op (two integer compares) outside the configured windows, and
    :meth:`from_env` returns None when neither knob is set, so an
    untraced run never even takes the per-iteration calls."""

    def __init__(self, trace_dir=None, xprof=None, _tracer=None):
        self._trace_dir = trace_dir
        self._xprof = xprof                     # (dir, first, last)
        self._tracer = _tracer or trace_to
        self._whole = None
        self._window = None

    @classmethod
    def from_env(cls, env=None):
        env = os.environ if env is None else env
        trace_dir = env.get("LIGHTGBM_TPU_TRACE_TO") or None
        spec = env.get("LIGHTGBM_TPU_XPROF") or None
        xprof = parse_xprof_spec(spec) if spec else None
        if trace_dir is None and xprof is None:
            return None
        return cls(trace_dir=trace_dir, xprof=xprof)

    def _enter(self, log_dir):
        cm = self._tracer(log_dir)
        cm.__enter__()
        return cm

    def before_iteration(self, i: int) -> None:
        if self._trace_dir is not None and self._whole is None:
            self._whole = self._enter(self._trace_dir)
        if self._xprof is not None and self._window is None \
                and i == self._xprof[1]:
            self._window = self._enter(self._xprof[0])

    def after_iteration(self, i: int) -> None:
        if self._window is not None and i >= self._xprof[2]:
            cm, self._window = self._window, None
            self._xprof = None     # one window per run, never re-armed
            cm.__exit__(None, None, None)

    def close(self) -> None:
        """Idempotent; runs on the engine's finally so an exception
        mid-window still finalizes the capture files."""
        for attr in ("_window", "_whole"):
            cm = getattr(self, attr)
            if cm is not None:
                setattr(self, attr, None)
                try:
                    cm.__exit__(None, None, None)
                except Exception:
                    pass
