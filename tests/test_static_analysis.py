"""tpulint (lightgbm_tpu/analysis/) — the tier-1 static-analysis gate.

Four layers, all jax-free and fast (<10 s over the whole package):

1. The package itself must lint clean against the checked-in baseline
   (tools/tpulint_baseline.txt), every baseline entry must carry a
   justification, and no entry may be stale.
2. The derived jit-reachable set must cover the entry points the old
   hand-maintained ``KNOWN_JITTED`` allowlist tracked — renaming
   ``_grow_masked_impl`` (or breaking its jit wrapping) fails here, so
   the allowlist is now computed, not maintained.
3. Per-rule fixtures (tests/analysis_fixtures/): one positive and one
   negative file per rule, asserted by finding id and line number via
   ``# EXPECT: TPLNNN`` markers (the marker pins the line after it).
4. CLI contract: ``python -m lightgbm_tpu lint`` runs WITHOUT importing
   jax, honors --rule/--format/--baseline, and exits 0/1 as documented.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "lightgbm_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")
BASELINE = os.path.join(REPO, "tools", "tpulint_baseline.txt")

sys.path.insert(0, REPO)

from lightgbm_tpu.analysis import build_callgraph, run_lint  # noqa: E402
from lightgbm_tpu.analysis.baseline import load_baseline  # noqa: E402

import functools  # noqa: E402


# tests/test_hot_path_lint.py re-exports several of these tests (thin
# compat wrapper), so pytest runs them twice per tier-1 pass; cache the
# package-wide analyses so the duplicates cost ~0 instead of ~2 s each
@functools.lru_cache(maxsize=None)
def _cached_graph():
    return build_callgraph(PKG)


@functools.lru_cache(maxsize=None)
def _cached_lint(rules=None):
    return run_lint(root=PKG, rules=list(rules) if rules else None,
                    baseline_path=BASELINE)


# ---------------------------------------------------------------------
# 1. the shipped tree is clean
# ---------------------------------------------------------------------

def test_package_lints_clean_against_baseline():
    res = _cached_lint()
    assert not res.findings, (
        "new tpulint findings (fix them, or baseline WITH a "
        "justification — see docs/STATIC_ANALYSIS.md):\n  "
        + "\n  ".join(f"{f.fid} @ {f.relpath}:{f.lineno}"
                      for f in res.findings))
    assert not res.stale_baseline, (
        "stale baseline entries (the finding no longer occurs — "
        "delete them from tools/tpulint_baseline.txt):\n  "
        + "\n  ".join(e.fid for e in res.stale_baseline))
    assert res.elapsed < 10.0, (
        f"analyzer took {res.elapsed:.1f}s over the package; the "
        "review-time budget is 10s")


def test_baseline_entries_all_justified():
    entries = load_baseline(BASELINE)
    assert entries, "baseline file missing or empty (expected at "\
        f"{BASELINE})"
    unjustified = [e.fid for e in entries if not e.justification]
    assert not unjustified, (
        "baseline entries without an inline justification comment: "
        + ", ".join(unjustified))


# ---------------------------------------------------------------------
# 2. KNOWN_JITTED, migrated: the allowlist is now DERIVED
# ---------------------------------------------------------------------

# The old tests/test_hot_path_lint.py allowlist (minus the stale
# `predict_forest_raw` entry, which tpulint exposed as a dead eager
# loop nothing ever jitted — removed in the same change), plus the
# wider lax-loop-bearing entry points the call graph proves. If any of
# these leaves the derived set (renamed, de-jitted, newly referenced
# from eager code), this fails and names it.
KNOWN_JITTED = {
    ("ops/gather.py", "_gather_small"),
    ("ops/grow.py", "_grow_masked_impl"),
    ("ops/grow.py", "_grow_compact_impl"),
    ("ops/grow.py", "_grow_level_impl"),
    ("ops/grow.py", "grow_tree_impl"),
    ("ops/histogram.py", "_hist_from_rows_impl"),
    ("ops/histogram.py", "_hist_scatter"),
    ("ops/histogram.py", "build_histogram"),
    ("ops/pallas_hist.py", "hist_from_rows_pallas"),
    ("ops/pallas_hist.py", "_hist_tiles"),
    ("ops/predict.py", "_traverse"),
    ("ops/predict.py", "predict_leaf_binned"),
    ("ops/predict.py", "predict_leaf_raw"),
    ("ranking.py", "_lambdarank_grads"),
    ("models/gbdt.py", "GBDTBooster._get_fused_fn.step"),
    # the shared one-iteration body and the multi-iteration scan
    # program built over it (docs/FUSED.md) — de-jitting any of these
    # silently re-opens the per-iteration dispatch hole
    ("models/gbdt.py", "_fused_iter_step"),
    ("models/gbdt.py", "GBDTBooster._get_scan_fn.scan_fn"),
    ("models/gbdt.py", "GBDTBooster._get_scan_fn.scan_fn.body"),
}


def test_known_jitted_covered_by_derived_set():
    graph = _cached_graph()
    missing = KNOWN_JITTED - graph.jit_reachable
    assert not missing, (
        "functions expected to be jit-only left the DERIVED "
        "jit-reachable set (renamed? de-jitted? now referenced from "
        f"eager code?): {sorted(missing)}")


def test_known_jitted_entries_exist():
    """A renamed/deleted function must be pruned here — stale entries
    would silently stop guarding anything (the failure mode that let
    the old allowlist carry `predict_forest_raw` for a dead
    function)."""
    graph = _cached_graph()
    live = {(p, q) for (p, q) in graph.funcs}
    stale = KNOWN_JITTED - live
    assert not stale, f"prune stale KNOWN_JITTED entries: {sorted(stale)}"


def test_every_hot_path_lax_loop_is_jit_reachable():
    """The old test's core property, generalized from models/gbdt.py +
    ops/ to the full rule scope: zero non-baselined TPL001s."""
    res = _cached_lint(("TPL001",))
    assert not res.findings, (
        "eager-dispatch risk (PROFILE.md 530 ms/iter class):\n  "
        + "\n  ".join(f"{f.relpath}:{f.lineno}: {f.fid}"
                      for f in res.findings))


# ---------------------------------------------------------------------
# 3. per-rule fixtures, asserted by id + line
# ---------------------------------------------------------------------

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(TPL\d{3})\s*$")


def _expected_findings(path: str):
    """(rule, lineno) pairs pinned by `# EXPECT: TPLNNN` markers — the
    marker names the line that FOLLOWS it."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                out.append((m.group(1), i + 1))
    return sorted(out)


_FIXTURES = [
    "tpl001_pos.py", "tpl001_neg.py",
    "tpl002_pos.py", "tpl002_neg.py",
    "tpl003_pos.py", "tpl003_neg.py",
    "tpl004_pos.py", "tpl004_neg.py",
    "tpl005_pos.py", "tpl005_neg.py",
    "obs/tpl006_pos.py", "obs/tpl006_neg.py",
    "resilience/tpl006_pos.py", "resilience/tpl006_neg.py",
    "tpl007_pos.py", "tpl007_neg.py",
    "tpl007_placement_pos.py", "tpl007_placement_neg.py",
    "data/tpl007_pos.py", "data/tpl007_neg.py",
    "obs/tpl008_pos.py", "obs/tpl008_neg.py",
    "obs/tpl008_pragma.py",
    "obs/tpl008_export_pos.py", "obs/tpl008_export_neg.py",
    "obs/tpl008_trace_pos.py", "obs/tpl008_trace_neg.py",
    "serve/tpl008_pos.py", "serve/tpl008_neg.py",
    "resilience/tpl008_pos.py", "resilience/tpl008_neg.py",
    "pipeline/tpl006_pos.py", "pipeline/tpl006_neg.py",
    "pipeline/tpl008_pos.py", "pipeline/tpl008_neg.py",
    "tpl009_pos.py", "tpl009_neg.py",
    "tpl010_pos.py", "tpl010_neg.py",
    "tpl010_comms_pos.py", "tpl010_comms_neg.py",
]

# cross-module fixture: must be linted TOGETHER with the module whose
# helper it imports (the package-wide basename fallback resolves the
# helper through the shared call graph)
_FIXTURE_GROUPS = [
    (("tpl010_import_helper.py", "tpl010_pos.py"),
     "tpl010_import_helper.py"),
]

# contract-pass fixtures (TPL015-TPL018): each pos/neg file is linted
# together with the mini registry at contract/obs/schemas.py — the
# contract rules literal-eval the SCANNED tree's registry, and no-op
# on trees without one (which keeps the single-file fixtures above
# clean). The agg group's target is the registry itself: its
# declared-but-never-used entries anchor whole-tree findings there.
_CONTRACT_SCHEMAS = "contract/obs/schemas.py"
_FIXTURE_GROUPS += [
    ((_CONTRACT_SCHEMAS, rel), rel) for rel in (
        "contract/tpl015_pos.py", "contract/tpl015_neg.py",
        "contract/tpl016_pos.py", "contract/tpl016_neg.py",
        "contract/tpl017_pos.py", "contract/tpl017_neg.py",
        "contract/tpl018_pos.py", "contract/tpl018_neg.py",
    )
] + [
    (("contract/agg/obs/schemas.py", "contract/agg/site.py"),
     "contract/agg/obs/schemas.py"),
]


@pytest.mark.parametrize("relpath", _FIXTURES)
def test_rule_fixture(relpath):
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=[relpath], baseline_path="")
    got = sorted((f.rule, f.lineno) for f in res.findings)
    expected = _expected_findings(os.path.join(FIXTURES, relpath))
    assert got == expected, (
        f"{relpath}: findings diverge from # EXPECT markers\n"
        f"  expected: {expected}\n  got:      {got}\n  "
        + "\n  ".join(f"{f.fid} @ {f.lineno}: {f.message[:100]}"
                      for f in res.findings))


@pytest.mark.parametrize("files,target",
                         _FIXTURE_GROUPS,
                         ids=[g[1] for g in _FIXTURE_GROUPS])
def test_cross_module_fixture(files, target):
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=list(files), baseline_path="")
    got = sorted((f.rule, f.lineno) for f in res.findings
                 if f.relpath == target)
    expected = _expected_findings(os.path.join(FIXTURES, target))
    assert got == expected, (
        f"{target}: findings diverge from # EXPECT markers\n"
        f"  expected: {expected}\n  got:      {got}")


def test_fixture_positive_files_have_expectations():
    for rel in _FIXTURES:
        expected = _expected_findings(os.path.join(FIXTURES, rel))
        if "_pos" in rel:
            assert expected, f"{rel} has no # EXPECT markers"
        else:
            assert not expected, f"{rel} is a negative fixture but " \
                                 "carries # EXPECT markers"


def test_every_rule_has_fixture_coverage():
    from lightgbm_tpu.analysis import ALL_RULES
    covered = set()
    targets = list(_FIXTURES) + [g[1] for g in _FIXTURE_GROUPS]
    for rel in targets:
        for rule, _ in _expected_findings(os.path.join(FIXTURES, rel)):
            covered.add(rule)
    missing = {r.id for r in ALL_RULES} - covered
    assert not missing, f"rules without a positive fixture: {missing}"


# ---------------------------------------------------------------------
# 4. CLI contract (and the no-jax guarantee)
# ---------------------------------------------------------------------

def test_cli_lint_runs_without_jax():
    """`python -m lightgbm_tpu lint` must complete without importing
    jax anywhere on its path (review-time tooling runs where no
    backend can initialize). Proved in a subprocess: after a full lint
    run, 'jax' must be absent from sys.modules."""
    code = (
        "import sys\n"
        "from lightgbm_tpu.analysis.cli import main\n"
        "rc = main(['--format', 'json'])\n"
        # the --ir flag family must also parse (and reject misuse)
        # without dragging jax in: only an actual --ir run may import
        # it
        "assert main(['--ir-entry', 'parallel/dp_grow']) == 2\n"
        "assert main(['--rule', 'TPL011']) == 2\n"
        "assert 'jax' not in sys.modules, 'lint imported jax!'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["jit_reachable"], "empty derived jit-reachable set"


def test_cli_rule_filter_and_exit_code():
    # a fresh finding (no baseline) must exit 1 and honor --rule
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=["tpl001_pos.py"], rules=["TPL001"],
                   baseline_path="")
    assert res.findings and all(f.rule == "TPL001" for f in res.findings)
    res2 = run_lint(root=FIXTURES, package="tpulint_fixtures",
                    files=["tpl001_pos.py"], rules=["TPL004"],
                    baseline_path="")
    assert not res2.findings  # rule filter excludes the TPL001 hits
    with pytest.raises(ValueError):
        run_lint(root=FIXTURES, package="tpulint_fixtures",
                 files=["tpl001_pos.py"], rules=["TPL999"])


def test_cli_help_mentions_exit_codes():
    from lightgbm_tpu.analysis.cli import EXIT_CODES, build_parser
    text = build_parser().format_help()
    assert "exit codes:" in text
    assert "--rule" in text and "--baseline" in text
    assert "--ir" in text and "--ir-entry" in text
    assert EXIT_CODES.strip().splitlines()[1].strip().startswith("0")


def test_finding_ids_are_line_number_free():
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=["tpl001_pos.py"], baseline_path="")
    for f in res.findings:
        assert f.fid == f"{f.rule}:{f.relpath}:{f.func}:{f.symbol}#" \
            + f.fid.rsplit("#", 1)[1]
        assert str(f.lineno) not in f.fid.rsplit("#", 1)[0].replace(
            f.relpath, "")


# ---------------------------------------------------------------------
# carried over from the old test_hot_path_lint.py: the resilience-guard
# placement contract (docs/RESILIENCE.md) — still a plain-ast check
# ---------------------------------------------------------------------

def _function_node(tree, qualpath):
    nodes = [tree]
    for name in qualpath:
        found = None
        for node in nodes:
            for child in ast.walk(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and child.name == name:
                    found = child
                    break
            if found is not None:
                break
        assert found is not None, \
            f"function {'.'.join(qualpath)} not found"
        nodes = [found]
    return nodes[0]


def test_nonfinite_guard_stays_inside_jitted_step():
    """The resilience guard contract: the non-finite check on
    gradients/hessians/leaf values must live INSIDE the fused jitted
    step (one fused reduction), and the fused iteration wrapper must
    not grow an eager per-iteration host fetch — TPL002 enforces the
    latter through the `# tpulint: hot` marker, re-asserted here."""
    path = os.path.join(PKG, "models", "gbdt.py")
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)

    guard_helpers = {"_gh_flag_clamp", "_leaf_value_guard"}

    def _calls(fn_node):
        names = set()
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute):
                    names.add(n.func.attr)
                elif isinstance(n.func, ast.Name):
                    names.add(n.func.id)
        return names

    # the guard lives in the shared one-iteration body
    # (_fused_iter_step) that BOTH fused entry points trace: the
    # per-iteration jit wrapper (_get_fused_fn.step) and the
    # multi-iteration scan body (_get_scan_fn.scan_fn.body)
    body = _function_node(tree, ["_fused_iter_step"])
    body_calls = _calls(body)
    assert "isfinite" in body_calls or (body_calls & guard_helpers), (
        "the non-finite guard left the fused iteration body: "
        "_fused_iter_step must trace jnp.isfinite (directly or via "
        "_gh_flag_clamp/_leaf_value_guard), not check eagerly")
    for helper in guard_helpers & body_calls:
        node = _function_node(tree, [helper])
        assert "isfinite" in _calls(node), (
            f"{helper} no longer reduces via jnp.isfinite — the fused "
            "guard is gone")
    for entry in (["_get_fused_fn", "step"],
                  ["_get_scan_fn", "scan_fn", "body"]):
        node = _function_node(tree, entry)
        assert "_fused_iter_step" in _calls(node), (
            f"{'.'.join(entry)} no longer traces _fused_iter_step — "
            "the two fused paths have diverged from the one shared "
            "iteration body")

    # (2) no host materialization in the fused iteration driver —
    # now the analyzer's job: _train_one_iter_fused is hot-marked and
    # models/gbdt.py TPL002 findings are limited to the baseline
    res = _cached_lint(("TPL002",))
    fused = [f for f in res.findings
             if f.func.endswith("_train_one_iter_fused")]
    assert not fused, (
        "eager host fetch in _train_one_iter_fused (guard/fault flags "
        "must ride the async _push_guard_flags queue):\n  "
        + "\n  ".join(f"line {f.lineno}: {f.symbol}" for f in fused))
    scan = res.graph.scans["models/gbdt.py"]
    hot = {q for q, i in scan.funcs.items() if i.is_hot}
    assert "GBDTBooster._train_one_iter_fused" in hot, (
        "_train_one_iter_fused lost its '# tpulint: hot' marker — "
        "TPL002 no longer guards the fused driver")
    # the scan drivers must stay hot-marked too: the window-boundary
    # batched fetch in _dispatch_scan_window is the ONE baselined sync
    # of the scan pipeline (docs/FUSED.md), and TPL002 only watches it
    # — and the pure-host _pop_scan_iter — through these markers
    for fn in ("GBDTBooster._dispatch_scan_window",
               "GBDTBooster._pop_scan_iter"):
        assert fn in hot, (
            f"{fn} lost its '# tpulint: hot' marker — TPL002 no "
            "longer guards the scan-window drivers")


def test_scan_body_device_get_mutation_fails(tmp_path):
    """The acceptance mutation (ISSUE 11): a per-iteration
    ``jax.device_get`` sneaking INSIDE the traced scan body — the
    exact per-iteration sync the window exists to delete — must fail
    lint with the expected stable id."""
    anchor = ("                new_score, outs, flags = "
              "_fused_iter_step(")
    res = _lint_mutated(
        "models/gbdt.py",
        lambda src: src.replace(
            anchor,
            "                jax.device_get(score)\n" + anchor),
        ["TPL002"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL002:models/gbdt.py:GBDTBooster._get_scan_fn.scan_fn"
            ".body:jax.device_get#1") in fids, fids


def test_pop_scan_iter_host_fetch_mutation_fails(tmp_path):
    """A blocking per-pop device read in the hot scan driver (e.g.
    re-fetching the pack slice per iteration) re-opens the dispatch
    gap; the hot marker must surface it."""
    anchor = "        self._push_guard_flags(it, p[\"flags\"][j])"
    res = _lint_mutated(
        "models/gbdt.py",
        lambda src: src.replace(
            anchor,
            "        jax.device_get(self.score)\n" + anchor),
        ["TPL002"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL002:models/gbdt.py:GBDTBooster._pop_scan_iter:"
            "jax.device_get#1") in fids, fids


# ---------------------------------------------------------------------
# 5. CFG rules (TPL007-TPL009) against the REAL distributed layer:
#    the shipped tree is clean, and the exact mutations the acceptance
#    criteria name re-surface the expected finding ids
# ---------------------------------------------------------------------

def _lint_mutated(relpath, transform, rules, tmp_path):
    """Apply a source-text ``transform`` to one real package file and
    lint the mutated copy in isolation."""
    with open(os.path.join(PKG, relpath), encoding="utf-8") as fh:
        src = fh.read()
    mutated = transform(src)
    assert mutated != src, f"mutation did not apply to {relpath}"
    dst = tmp_path / relpath
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(mutated, encoding="utf-8")
    return run_lint(root=str(tmp_path), package="lightgbm_tpu",
                    files=[relpath], baseline_path="",
                    rules=list(rules))


def test_distributed_layer_is_collective_order_clean():
    res = _cached_lint(("TPL007",))
    assert not res.findings, (
        "rank-divergent collective order in the shipped tree:\n  "
        + "\n  ".join(f"{f.fid} @ {f.relpath}:{f.lineno}"
                      for f in res.findings))


def test_reordering_a_collective_behind_a_rank_guard_fails(tmp_path):
    """The acceptance mutation: gate spmd.verify_step_consistency's
    allgather behind a process_index() early return -> TPL007 with the
    expected stable id."""
    anchor = ("    local = np.asarray([int(iteration), "
              "int(num_trees)], np.int64)")
    res = _lint_mutated(
        "parallel/spmd.py",
        lambda src: src.replace(
            anchor,
            "    if jax.process_index() != 0:\n        return\n"
            + anchor),
        ["TPL007"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL007:parallel/spmd.py:verify_step_consistency:"
            "collective:host_allgather#1") in fids, fids


def test_collective_in_except_handler_fails(tmp_path):
    """Wrapping sync_bin_mappers' broadcast into an error-recovery
    handler -> TPL007 (only some ranks run recovery paths)."""
    anchor = '    buf = host_broadcast_bytes(payload, "spmd/sync_bin_mappers")'
    replacement = (
        "    try:\n"
        "        raise RuntimeError()\n"
        "    except RuntimeError:\n"
        "        buf = host_broadcast_bytes(payload, "
        '"spmd/sync_bin_mappers")')
    res = _lint_mutated(
        "parallel/spmd.py",
        lambda src: src.replace(anchor, replacement),
        ["TPL007"], tmp_path)
    assert any(f.rule == "TPL007"
               and f.symbol == "collective:host_broadcast_bytes"
               and f.func == "sync_bin_mappers"
               for f in res.findings), [f.fid for f in res.findings]


def test_deleting_the_pending_delete_lock_fails(tmp_path):
    """The acceptance mutation: strip the _pending_lock guards from
    hostsync's kv bookkeeping -> TPL008 names the shared list (it is
    mutated from the watchdog's worker threads)."""
    def strip_locks(src):
        src = src.replace(
            "            with _pending_lock:\n"
            "                doomed, _pending_delete[:] = "
            "list(_pending_delete), []",
            "            doomed, _pending_delete[:] = "
            "list(_pending_delete), []")
        src = src.replace(
            "        with _pending_lock:\n"
            "            _pending_delete.append(f\"{prefix}/{me}\")",
            "        _pending_delete.append(f\"{prefix}/{me}\")")
        return src

    res = _lint_mutated("parallel/hostsync.py", strip_locks,
                        ["TPL008"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL008:parallel/hostsync.py:_kv_exchange:"
            "shared:_pending_delete#1") in fids, fids


def test_stripping_the_watchdog_threadsafe_pragma_fails(tmp_path):
    """watchdog.guarded's box handshake is Event-ordered and carries
    the pragma saying why; without the pragma TPL008 must flag both
    worker-side writes."""
    pragma = ("    # tpulint: threadsafe Event handshake "
              "(write, set, wait, read)\n")
    res = _lint_mutated(
        "resilience/watchdog.py",
        lambda src: src.replace(pragma, ""),
        ["TPL008"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL008:resilience/watchdog.py:guarded._run:"
            "shared:box#1") in fids, fids
    assert ("TPL008:resilience/watchdog.py:guarded._run:"
            "shared:box#2") in fids, fids


def test_stripping_the_batcher_lock_fails(tmp_path):
    """Serving acceptance mutation: strip the lock around the batcher
    worker's queue bookkeeping (serve/batcher.py _run_batch) ->
    TPL008 names the shared counters submit()/stats() read
    concurrently."""
    anchor = ("        with self._lock:\n"
              "            self._pending_rows -= X.shape[0]\n")
    res = _lint_mutated(
        "serve/batcher.py",
        lambda src: src.replace(
            anchor,
            "        if True:\n"
            "            self._pending_rows -= X.shape[0]\n"),
        ["TPL008"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL008:serve/batcher.py:MicroBatcher._run_batch:"
            "shared:self._pending_rows#1") in fids, fids


def test_stripping_the_loadgen_lock_fails(tmp_path):
    """Lifecycle acceptance mutation (ISSUE 13): strip the lock around
    the pipeline load generator's outcome bookkeeping
    (pipeline.py LoadGenerator._note) -> TPL008 names the shared
    counters the supervisor's snapshot() reads concurrently."""
    anchor = ("        now = time.monotonic()\n"
              "        with self._lock:\n"
              "            self._counts[\"attempts\"] += 1")
    res = _lint_mutated(
        "pipeline.py",
        lambda src: src.replace(
            anchor,
            "        now = time.monotonic()\n"
            "        if True:\n"
            "            self._counts[\"attempts\"] += 1"),
        ["TPL008"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL008:pipeline.py:LoadGenerator._note:"
            "shared:self._counts#1") in fids, fids
    assert ("TPL008:pipeline.py:LoadGenerator._note:"
            "shared:self._latencies#1") in fids, fids


def test_stripping_the_export_lock_fails(tmp_path):
    """Fleet-metrics acceptance mutation (ISSUE 15): strip the lock
    around the /metrics endpoint's scrape bookkeeping
    (obs/export.py _Handler.do_GET) -> TPL008 names the module-global
    counter the handler threads mutate and scrape_count() reads
    concurrently. The seeding is the request-handler-thread rule:
    ThreadingHTTPServer runs do_GET on per-connection threads no
    Thread(target=...) spawn reveals."""
    anchor = ("                with _scrape_lock:\n"
              "                    count = _scrape_counts.get("
              "exporter.port, 0) + 1\n")
    res = _lint_mutated(
        "obs/export.py",
        lambda src: src.replace(
            anchor,
            "                if True:\n"
            "                    count = _scrape_counts.get("
            "exporter.port, 0) + 1\n"),
        ["TPL008"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL008:obs/export.py:"
            "MetricsHTTPServer.__init__._Handler.do_GET:"
            "shared:_scrape_counts#1") in fids, fids


def test_stripping_the_span_buffer_lock_fails(tmp_path):
    """Tracing-plane acceptance mutation (ISSUE 16): strip
    ``_spans_lock`` from the span recorder's buffered append
    (obs/trace.py record_span) -> TPL008 names the buffer. The
    mutated copy is linted TOGETHER with the unmodified serve daemon,
    whose request-handler and hot-swap watcher threads put
    record_span on the thread side of the call graph."""
    import shutil
    anchor = ("    with _spans_lock:\n"
              "        if len(_spans) < _SPANS_CAP:\n")
    with open(os.path.join(PKG, "obs", "trace.py"),
              encoding="utf-8") as fh:
        src = fh.read()
    mutated = src.replace(
        anchor, "    if True:\n        if len(_spans) < _SPANS_CAP:\n")
    assert mutated != src, "mutation did not apply to obs/trace.py"
    for rel in ("serve/daemon.py", "serve/batcher.py"):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(PKG, rel), dst)
    dst = tmp_path / "obs" / "trace.py"
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(mutated, encoding="utf-8")
    res = run_lint(root=str(tmp_path), package="lightgbm_tpu",
                   files=["obs/trace.py", "serve/daemon.py",
                          "serve/batcher.py"],
                   baseline_path="", rules=["TPL008"])
    fids = [f.fid for f in res.findings]
    assert ("TPL008:obs/trace.py:record_span:shared:_spans#1"
            in fids), fids
    assert ("TPL008:obs/trace.py:record_span:shared:"
            "_spans_dropped#1" in fids), fids


def test_tracing_plane_is_thread_and_lock_clean():
    """The shipped tracing plane lints clean for the thread/lock
    rules: every touch of the span buffer and the current-trace cell
    rides _spans_lock, and the span-recording daemon/watcher paths
    carry their own guards."""
    res = run_lint(root=PKG, rules=["TPL006", "TPL008"],
                   baseline_path=BASELINE,
                   files=["obs/trace.py", "obs/recorder.py",
                          "serve/daemon.py", "serve/batcher.py"])
    assert not res.findings, [f.fid for f in res.findings]


def test_hot_drivers_stay_clock_free_with_tracing_on():
    """TPL002 (host syncs/clock reads in hot-marked drivers) must
    stay clean with the tracing plane wired in: per-iteration spans
    are derived in the telemetry recorder from Timer deltas the hot
    path already pays for — never from clock reads inside the
    hot-marked iteration drivers."""
    res = run_lint(root=PKG, rules=["TPL002"], baseline_path=BASELINE,
                   files=["models/gbdt.py", "engine.py",
                          "obs/trace.py"])
    assert not res.findings, [f.fid for f in res.findings]


def test_metrics_plane_is_thread_and_lock_clean():
    """The shipped fleet-metrics modules (obs/export.py, obs/cost.py)
    lint clean for the lock-across-dispatch and thread-shared-state
    rules — the new scrape/capture paths carry their locks."""
    res = run_lint(root=PKG, rules=["TPL006", "TPL008"],
                   baseline_path=BASELINE,
                   files=["obs/export.py", "obs/cost.py",
                          "obs/recorder.py", "obs/jit_tracker.py"])
    assert not res.findings, [f.fid for f in res.findings]


def test_pipeline_and_publisher_are_thread_clean():
    """The shipped lifecycle modules (pipeline.py, the publisher /
    store / autoscaler under resilience/) lint clean for the
    thread/lock rules."""
    res = run_lint(root=PKG, rules=["TPL006", "TPL008"],
                   baseline_path=BASELINE,
                   files=["pipeline.py", "resilience/publisher.py",
                          "resilience/elastic.py",
                          "resilience/store.py",
                          "resilience/autoscale.py"])
    assert not res.findings, [f.fid for f in res.findings]


def test_stripping_the_autoscaler_lock_fails(tmp_path):
    """Self-healing-fleet acceptance mutation (ISSUE 17): strip the
    lock from the autoscaling policy's scrape-side ingest
    (resilience/autoscale.py AutoscalePolicy.observe) -> TPL008 names
    the shared observation fields decide() consumes on the supervision
    loop. The mutated copy is linted TOGETHER with the unmodified
    fleet supervisor, whose scrape thread puts observe() on the
    thread side of the call graph."""
    import shutil
    anchor = ("        with self._lock:\n"
              "            shed_delta = 0.0\n")
    with open(os.path.join(PKG, "resilience", "autoscale.py"),
              encoding="utf-8") as fh:
        src = fh.read()
    mutated = src.replace(
        anchor, "        if True:\n            shed_delta = 0.0\n")
    assert mutated != src, "mutation did not apply to autoscale.py"
    for rel in ("resilience/elastic.py",):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(PKG, rel), dst)
    dst = tmp_path / "resilience" / "autoscale.py"
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(mutated, encoding="utf-8")
    res = run_lint(root=str(tmp_path), package="lightgbm_tpu",
                   files=["resilience/autoscale.py",
                          "resilience/elastic.py"],
                   baseline_path="", rules=["TPL008"])
    fids = [f.fid for f in res.findings]
    assert ("TPL008:resilience/autoscale.py:AutoscalePolicy.observe:"
            "shared:self._shed_delta#1" in fids), fids
    assert ("TPL008:resilience/autoscale.py:AutoscalePolicy.observe:"
            "shared:self._seq#1" in fids), fids


def test_grow_collective_conds_are_justified():
    """The shipped tree's psum-under-cond sites (histogram-pool reads,
    masked/forced-split gating) all carry replicated-cond whys."""
    res = _cached_lint(("TPL010",))
    assert not res.findings, (
        "unjustified device collective under a traced cond:\n  "
        + "\n  ".join(f"{f.fid} @ {f.relpath}:{f.lineno}"
                      for f in res.findings))


def test_stripping_the_pool_replicated_cond_pragma_fails(tmp_path):
    """The ADVICE r4 _research_leafwise site: the pool-miss branch runs
    window_hist -> hist_psum inside lax.cond. Without the pragma
    documenting the replicated-predicate invariant, TPL010 must flag
    it with the expected stable id."""
    pragma = ("                # tpulint: replicated-cond leaf2slot is "
              "pool state derived only from the replicated "
              "tree/argmax sequence\n")
    res = _lint_mutated(
        "ops/grow.py",
        lambda src: src.replace(pragma, ""),
        ["TPL010"], tmp_path)
    fids = [f.fid for f in res.findings]
    # since ISSUE 9 the pool-miss branch's reduction is the
    # parallel/comms.py quantized-allreduce wrapper, and the rule
    # names THAT collective (proof the wrapper recognizer, not the
    # lax.psum closure, carries the detection in a single-file lint)
    assert ("TPL010:ops/grow.py:"
            "_grow_compact_impl._research_leafwise.body:"
            "cond-collective:hist_allreduce#1") in fids, fids


def test_stripping_the_comms_recognizer_blinds_tpl010():
    """The ISSUE 9 recognizer mutation: with the parallel/comms.py
    wrapper entry stripped from TPL010, the quantized-allreduce
    fixture's direct-call hazards go UNDETECTED — proving the
    ``_COMMS_WRAPPERS`` entry (not an accident of the callgraph
    closure) is what keeps wrapped collectives visible when comms.py
    is outside the linted set."""
    from lightgbm_tpu.analysis.rules_flow import CollectiveUnderTracedCond

    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=["tpl010_comms_pos.py"], baseline_path="")
    assert len(res.findings) == 3, [f.fid for f in res.findings]
    saved = CollectiveUnderTracedCond._COMMS_WRAPPERS
    try:
        CollectiveUnderTracedCond._COMMS_WRAPPERS = frozenset()
        mutated = run_lint(root=FIXTURES, package="tpulint_fixtures",
                           files=["tpl010_comms_pos.py"],
                           baseline_path="")
    finally:
        CollectiveUnderTracedCond._COMMS_WRAPPERS = saved
    assert not mutated.findings, (
        "a stripped recognizer must miss the wrapped collectives "
        "(otherwise the entry is dead weight)",
        [f.fid for f in mutated.findings])


def test_stripping_the_comms_recognizer_blinds_tpl007():
    """Same mutation for TPL007's host-order recognizer: a
    comms.hist_allreduce dispatched from an `except` handler (an
    untraced host path) must flag — and stop flagging when the
    wrapper entry is removed from the collective set."""
    from lightgbm_tpu.analysis.rules_flow import CollectiveOrder

    src = (
        "from lightgbm_tpu.parallel import comms\n\n\n"
        "def retry_reduce(hist, axis):\n"
        "    try:\n"
        "        return comms.hist_allreduce(hist, axis, 'int8')\n"
        "    except RuntimeError:\n"
        "        return comms.hist_allreduce(hist, axis, 'f32')\n")
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "comms_host.py")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)
        res = run_lint(root=td, package="tpulint_fixtures",
                       files=["comms_host.py"], baseline_path="",
                       rules=["TPL007"])
        assert any(f.rule == "TPL007"
                   and f.symbol == "collective:hist_allreduce"
                   for f in res.findings), [f.fid for f in res.findings]
        saved = CollectiveOrder._COLLECTIVES
        try:
            CollectiveOrder._COLLECTIVES = \
                saved - CollectiveOrder._COMMS_WRAPPERS
            mutated = run_lint(root=td, package="tpulint_fixtures",
                               files=["comms_host.py"],
                               baseline_path="", rules=["TPL007"])
        finally:
            CollectiveOrder._COLLECTIVES = saved
        assert not any(f.symbol == "collective:hist_allreduce"
                       for f in mutated.findings), (
            [f.fid for f in mutated.findings])


def test_rank_guarding_the_placement_barrier_fails(tmp_path):
    """The ISSUE 10 acceptance mutation: gate placement.upload_barrier's
    world join behind a process_index() early return -> TPL007 with
    the expected stable id (a rank that skips the barrier deadlocks
    the post-placement world at the first training collective)."""
    anchor = ('    host_allgather(np.asarray([_process_index()], '
              'np.int64), what)')
    res = _lint_mutated(
        "parallel/placement.py",
        lambda src: src.replace(
            anchor,
            "    if jax.process_index() != 0:\n        return\n"
            + anchor),
        ["TPL007"], tmp_path)
    fids = [f.fid for f in res.findings]
    assert ("TPL007:parallel/placement.py:upload_barrier:"
            "collective:host_allgather#1") in fids, fids


def test_rank_gating_the_checkpoint_gather_fails(tmp_path):
    """Moving the sharded-score assembly BELOW the callback's rank-0
    gate (the deadlock the hoist in Checkpoint.__call__ exists to
    avoid) -> TPL007 on the fetch_global call site."""
    anchor = "            score_host = placement.fetch_global(eng.score)"
    res = _lint_mutated(
        "resilience/checkpoint.py",
        lambda src: src.replace(
            anchor,
            "            if rank != 0:\n                return\n"
            + anchor),
        ["TPL007"], tmp_path)
    assert any(f.rule == "TPL007"
               and f.symbol == "collective:fetch_global"
               for f in res.findings), [f.fid for f in res.findings]


def test_stripping_the_placement_recognizer_blinds_tpl007(tmp_path):
    """The placement wrapper entries must be load-bearing: with
    _PLACEMENT_WRAPPERS stripped from the collective set, the
    rank-guarded barrier mutation above goes dark at the wrapper call
    site (upload_barrier taken as a plain local call)."""
    from lightgbm_tpu.analysis.rules_flow import CollectiveOrder

    src = (
        "import jax\n\n"
        "from lightgbm_tpu.parallel.placement import upload_barrier\n"
        "\n\n"
        "def gated(shards):\n"
        "    if jax.process_index() == 0:\n"
        "        upload_barrier('bad/gated')\n"
        "    return shards\n")
    path = tmp_path / "placement_host.py"
    path.write_text(src, encoding="utf-8")
    res = run_lint(root=str(tmp_path), package="tpulint_fixtures",
                   files=["placement_host.py"], baseline_path="",
                   rules=["TPL007"])
    assert any(f.symbol == "collective:upload_barrier"
               for f in res.findings), [f.fid for f in res.findings]
    saved = CollectiveOrder._COLLECTIVES
    try:
        CollectiveOrder._COLLECTIVES = \
            saved - CollectiveOrder._PLACEMENT_WRAPPERS
        mutated = run_lint(root=str(tmp_path),
                           package="tpulint_fixtures",
                           files=["placement_host.py"],
                           baseline_path="", rules=["TPL007"])
    finally:
        CollectiveOrder._COLLECTIVES = saved
    assert not any(f.symbol == "collective:upload_barrier"
                   for f in mutated.findings), (
        [f.fid for f in mutated.findings])


def test_threadsafe_pragma_requires_a_reason():
    """`# tpulint: threadsafe` with no why must NOT suppress (the
    obs/tpl008_pos.py fixture carries exactly that case); with a why it
    must (obs/tpl008_pragma.py)."""
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=["obs/tpl008_pos.py"], baseline_path="")
    bare = [f for f in res.findings
            if "_pragma_without_reason" in f.func]
    assert bare, "bare threadsafe pragma suppressed a finding"
    res2 = run_lint(root=FIXTURES, package="tpulint_fixtures",
                    files=["obs/tpl008_pragma.py"], baseline_path="")
    assert not res2.findings


# ---------------------------------------------------------------------
# 6. CI wiring, --changed mode, SARIF
# ---------------------------------------------------------------------

def test_lint_sh_strict_is_clean_and_fast():
    """tools/lint.sh (the CI one-shot) must pass --strict with
    TPL007-TPL009 enabled, within the 10 s review-time budget."""
    import time as _time
    t0 = _time.perf_counter()
    proc = subprocess.run(
        ["sh", os.path.join(REPO, "tools", "lint.sh")], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    elapsed = _time.perf_counter() - t0
    assert proc.returncode == 0, (
        f"tools/lint.sh --strict failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    assert elapsed < 10.0, f"lint.sh took {elapsed:.1f}s (budget 10s)"
    from lightgbm_tpu.analysis import ALL_RULES
    assert {"TPL007", "TPL008", "TPL009"} <= {r.id for r in ALL_RULES}


def _git(cwd, *args):
    proc = subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, capture_output=True, text=True, timeout=30)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


def _throwaway_repo(tmp_path):
    """A git repo holding a tiny lightgbm_tpu package with one
    committed in-scope module."""
    repo = tmp_path / "repo"
    pkg = repo / "lightgbm_tpu"
    (pkg / "models").mkdir(parents=True)
    (pkg / "models" / "clean.py").write_text("X = 1\n")
    (pkg / "utils.py").write_text("Y = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    return repo, pkg


def test_changed_mode_fast_path_and_findings(tmp_path):
    from lightgbm_tpu.analysis.cli import changed_relpaths, main

    repo, pkg = _throwaway_repo(tmp_path)
    # nothing changed: the fast path answers without building the
    # analyzer at all
    assert changed_relpaths(str(pkg), "HEAD") == set()
    assert main(["--changed", "--root", str(pkg)]) == 0

    # an out-of-scope change still takes the fast path
    (pkg / "utils.py").write_text("Y = 2\n")
    assert changed_relpaths(str(pkg), "HEAD") == {"utils.py"}
    assert main(["--changed", "--root", str(pkg)]) == 0

    # an in-scope change with a fresh TPL001 makes --changed fail
    (pkg / "models" / "clean.py").write_text(
        "from jax import lax\n\n\n"
        "def eager(xs):\n"
        "    def body(i, acc):\n"
        "        return acc + xs[i]\n"
        "    return lax.fori_loop(0, 3, body, 0.0)\n")
    assert changed_relpaths(str(pkg), "HEAD") == \
        {"models/clean.py", "utils.py"}
    assert main(["--changed", "--root", str(pkg),
                 "--baseline", ""]) == 1

    # untracked new files count as changed too
    (pkg / "models" / "new.py").write_text("Z = 1\n")
    assert "models/new.py" in changed_relpaths(str(pkg), "HEAD")


def test_changed_mode_does_not_report_out_of_scope_stale_entries():
    """--changed restricted to files without baseline entries must not
    call the models/gbdt.py acceptances stale (staleness is only
    decidable where rules ran)."""
    res = run_lint(root=PKG, scope={"parallel/hostsync.py"},
                   baseline_path=BASELINE)
    assert not res.findings
    assert not res.stale_baseline, [e.fid for e in res.stale_baseline]


def test_sarif_output_schema_shape():
    from lightgbm_tpu.analysis.report import render_sarif

    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=["tpl001_pos.py"], baseline_path="")
    payload = json.loads(render_sarif(res))
    assert payload["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in payload["$schema"]
    run = payload["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpulint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert {"TPL001", "TPL007", "TPL008", "TPL009"} <= rule_ids
    assert run["results"], "a positive fixture must produce results"
    r0 = run["results"][0]
    assert r0["ruleId"] == "TPL001"
    assert r0["level"] == "warning"
    assert r0["message"]["text"]
    loc = r0["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == \
        "tpulint_fixtures/tpl001_pos.py"
    assert loc["region"]["startLine"] > 0
    assert loc["region"]["startColumn"] > 0
    assert r0["partialFingerprints"]["tpulintFindingId/v1"].startswith(
        "TPL001:")


def test_sarif_cli_and_baselined_suppressions():
    """`lint --format sarif` on the real package: exit 0, valid JSON,
    and the baselined findings ride along as suppressed results."""
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "lint",
         "--format", "sarif"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    results = payload["runs"][0]["results"]
    suppressed = [r for r in results if r.get("suppressions")]
    assert len(suppressed) == len(results), \
        "a clean tree must only carry baselined (suppressed) results"
    assert suppressed, "the 3 baseline acceptances should be present"


# ---------------------------------------------------------------------
# 7. CFG/dataflow precision regressions (review findings)
# ---------------------------------------------------------------------

def _cfg_of(src, fn_name):
    from lightgbm_tpu.analysis.cfg import FunctionCFG
    tree = ast.parse(src)
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef) and n.name == fn_name)
    return FunctionCFG(fn), fn


def test_cfg_branch_local_acquire_does_not_leak_past_the_branch():
    """An acquire() inside ONE arm of a branch must not count as held
    on the join (the meet over both paths), and never on the other
    arm — the lock transfer walks compound-statement headers only."""
    cfg, fn = _cfg_of(
        "def f(cond):\n"
        "    if cond:\n"
        "        _lock.acquire()\n"
        "        a = 1\n"
        "    else:\n"
        "        b = 1\n"
        "    shared.append(1)\n",
        "f")
    nodes = {n.targets[0].id if isinstance(n, ast.Assign) else "append":
             n for n in ast.walk(fn)
             if isinstance(n, ast.Assign)
             or (isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr == "append")}
    assert "_lock" in cfg.held_locks(nodes["a"])     # after acquire
    assert not cfg.held_locks(nodes["b"])            # other arm
    assert not cfg.held_locks(nodes["append"])       # join: meet = {}


def test_cfg_release_in_branch_does_not_unlock_the_other_path():
    cfg, fn = _cfg_of(
        "def f(cond):\n"
        "    _lock.acquire()\n"
        "    if cond:\n"
        "        _lock.release()\n"
        "        return\n"
        "    shared.append(1)\n",
        "f")
    append = next(n for n in ast.walk(fn)
                  if isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)
                  and n.func.attr == "append")
    assert "_lock" in cfg.held_locks(append)


def test_cfg_loop_else_runs_only_on_exhaustion_not_break():
    """The while/for else body must keep the exhausted-edge pins: a
    break path wired INTO the else block would intersect them away
    (and hide rank-gated collectives placed in loop-else clauses)."""
    cfg, fn = _cfg_of(
        "def f(flag, rank):\n"
        "    while flag:\n"
        "        if rank != 0:\n"
        "            break\n"
        "    else:\n"
        "        in_else = 1\n"
        "    after = 1\n",
        "f")
    assigns = {n.targets[0].id: n for n in ast.walk(fn)
               if isinstance(n, ast.Assign)}
    else_info = cfg.info(assigns["in_else"])
    # else runs only on normal exhaustion: the (flag, False) pin
    # survives; a break edge into this block would wash it out to []
    assert [(ast.unparse(t), pol) for (t, pol) in else_info.pins] == \
        [("flag", False)], else_info.pins
    after_info = cfg.info(assigns["after"])
    assert after_info.pins == []  # join of else + break paths


def test_full_run_reports_stale_entry_for_deleted_file(tmp_path):
    """--strict must keep catching rotted acceptances whose FILE is
    gone: a full run applies no scope path-filter to staleness."""
    pkg = tmp_path / "lightgbm_tpu"
    (pkg / "models").mkdir(parents=True)
    (pkg / "models" / "live.py").write_text("X = 1\n")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "TPL001:models/deleted.py:gone:lax.scan#1  # justified once\n")
    res = run_lint(root=str(pkg), package="lightgbm_tpu",
                   baseline_path=str(baseline))
    assert [e.fid for e in res.stale_baseline] == \
        ["TPL001:models/deleted.py:gone:lax.scan#1"]
    # ...but a narrowed (--changed-style) run stays silent about it
    res2 = run_lint(root=str(pkg), package="lightgbm_tpu",
                    scope={"models/live.py"},
                    baseline_path=str(baseline))
    assert not res2.stale_baseline


def test_changed_relpaths_with_package_below_repo_root(tmp_path):
    """git diff prints toplevel-relative paths; --relative keeps the
    pre-commit gate working when the package is nested (repo/src/pkg),
    instead of silently matching nothing."""
    from lightgbm_tpu.analysis.cli import changed_relpaths

    repo = tmp_path / "repo"
    pkg = repo / "src" / "lightgbm_tpu"
    (pkg / "models").mkdir(parents=True)
    (pkg / "models" / "m.py").write_text("A = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (pkg / "models" / "m.py").write_text("A = 2\n")
    assert changed_relpaths(str(pkg), "HEAD") == {"models/m.py"}


# ---------------------------------------------------------------------
# 10. Contract pass (TPL015-TPL018) against the REAL tree: the shipped
#     registries and their call sites agree, and the exact drift
#     mutations the acceptance criteria name re-surface stable ids
# ---------------------------------------------------------------------

def _lint_mutated_contract(tmp_path, mutations, extra=()):
    """Copy the real ``obs/schemas.py`` registry plus the named package
    files into a tmp tree, applying the per-file ``mutations``
    transforms, and run only the contract rules.  The registry must
    ride along: the contract pass no-ops when obs/schemas.py is absent
    from the scanned tree."""
    relpaths = dict.fromkeys(
        ["obs/schemas.py", *mutations, *extra])
    for relpath in relpaths:
        with open(os.path.join(PKG, relpath), encoding="utf-8") as fh:
            src = fh.read()
        transform = mutations.get(relpath)
        if transform is not None:
            mutated = transform(src)
            assert mutated != src, f"mutation did not apply to {relpath}"
            src = mutated
        dst = tmp_path / relpath
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src, encoding="utf-8")
    return run_lint(root=str(tmp_path), package="lightgbm_tpu",
                    files=list(relpaths), baseline_path="",
                    rules=["TPL015", "TPL016", "TPL017", "TPL018"])


def test_renaming_an_emitted_event_key_fails(tmp_path):
    """The acceptance mutation: renaming ``wall_time`` inside the
    iteration event literal drifts the wire format from the EVENTS
    registry -> TPL015 flags both the undeclared key and the missing
    required one, at the emitting function."""
    res = _lint_mutated_contract(tmp_path, {
        "obs/recorder.py": lambda src: src.replace(
            '"wall_time": now_mono - self._t0,',
            '"walltime": now_mono - self._t0,')})
    fids = [f.fid for f in res.findings]
    assert ("TPL015:obs/recorder.py:TelemetryRecorder.record_iteration:"
            "event:iteration:keys#1") in fids, fids
    assert ("TPL015:obs/recorder.py:TelemetryRecorder.record_iteration:"
            "event:iteration:missing#1") in fids, fids


def test_stripping_a_declared_env_default_fails(tmp_path):
    """The acceptance mutation: dropping the declared default for
    LIGHTGBM_TPU_INIT_RETRIES out of the ENV_VARS registry leaves the
    distributed layer's ``.get(..., "10")`` claiming a default the
    registry no longer records -> TPL017 at the reading site."""
    res = _lint_mutated_contract(tmp_path, {
        "obs/schemas.py": lambda src: src.replace(
            '"LIGHTGBM_TPU_INIT_RETRIES": {\n        "default": "10",',
            '"LIGHTGBM_TPU_INIT_RETRIES": {\n        "default": None,')},
        extra=("parallel/distributed.py",))
    fids = [f.fid for f in res.findings]
    assert ("TPL017:parallel/distributed.py:_initialize_with_retry:"
            "env:LIGHTGBM_TPU_INIT_RETRIES:default#1") in fids, fids


def test_recording_an_undeclared_fault_kind_fails(tmp_path):
    """The acceptance mutation: a typo'd kind in the publisher's
    poison-event writer is invisible to every fault-log consumer
    keyed on the registry -> TPL018 at the writing function."""
    res = _lint_mutated_contract(tmp_path, {
        "resilience/publisher.py": lambda src: src.replace(
            'record_fault_event(\n                "publish_poison",',
            'record_fault_event(\n                "publish_poizon",')})
    fids = [f.fid for f in res.findings]
    assert ("TPL018:resilience/publisher.py:publish_model:"
            "fault-kind:publish_poizon#1") in fids, fids


def test_cli_contract_rules_run_without_jax():
    """The contract pass stays on the jax-free default path: a
    --rule-filtered TPL015-TPL018 run over the real tree completes
    clean in a subprocess with 'jax' absent from sys.modules."""
    code = (
        "import sys\n"
        "from lightgbm_tpu.analysis.cli import main\n"
        "rc = main(['--rule', 'TPL015', '--rule', 'TPL016',\n"
        "           '--rule', 'TPL017', '--rule', 'TPL018',\n"
        "           '--format', 'json'])\n"
        "assert 'jax' not in sys.modules, 'contract lint imported jax!'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    payload = json.loads(proc.stdout)
    assert payload["findings"] == [], payload["findings"]


def test_sarif_covers_contract_findings():
    from lightgbm_tpu.analysis.report import render_sarif

    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=[_CONTRACT_SCHEMAS, "contract/tpl015_pos.py"],
                   baseline_path="",
                   rules=["TPL015", "TPL016", "TPL017", "TPL018"])
    payload = json.loads(render_sarif(res))
    run = payload["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"TPL015", "TPL016", "TPL017", "TPL018"} <= rule_ids
    hits = [r for r in run["results"] if r["ruleId"] == "TPL015"]
    assert hits, "the TPL015 positive fixture must surface in SARIF"
    for r in hits:
        assert r["partialFingerprints"]["tpulintFindingId/v1"] \
            .startswith("TPL015:")
        assert r["message"]["text"]
