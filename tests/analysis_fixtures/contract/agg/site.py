"""Exercises exactly half of the agg mini registry, so the unused
half surfaces as aggregate findings anchored in obs/schemas.py."""

import os


def emit(log, registry):
    log.append({"event": "beep", "n": 1})
    registry.counter("beeps").inc()
    return os.environ.get("LIGHTGBM_TPU_BEEP", "5")
