"""Histogram construction: the GBDT hot loop, TPU-style.

Re-design of the reference's histogram kernels
(/root/reference/src/io/dense_bin.hpp:99 ``ConstructHistogramInner``,
src/treelearner/cuda/cuda_histogram_constructor.cu:18): per-row (grad, hess)
scatter-add into ``[num_features, num_bins, 2]`` accumulators.

Design notes (TPU-first):
- Histogram entries are (sum_grad, sum_hess) pairs ONLY — exactly like the
  reference (``kHistEntrySize = 2 * sizeof(hist_t)``, bin.h:39). Per-bin
  data counts are *estimated* downstream from the hessian ratio
  ``cnt = RoundInt(hess * num_data / sum_hessian)``
  (feature_histogram.hpp:528,543), so no count channel is accumulated.
- The bin matrix is stored transposed ``[F, n]`` (column-major, like the
  reference's DenseBin) so one feature's bins are a contiguous vector.
- The fast path is the *nibble decomposition*: a bin index b = 16*hi + lo
  turns the histogram into HI^T @ (LO * payload) — dense batched matmuls
  that ride the MXU instead of scatter hardware (which XLA serializes on
  TPU). With the 2-channel payload an 8-feature pack is a [128, S] x
  [S, 256] matmul — both dims exact multiples of the 128-lane MXU tile.
- Precision: the default float path runs single-pass bf16-input/f32-accum
  matmuls (the MXU's native mode). The reference's GPU learner documents
  AUC parity with single-precision histograms at 255 bins
  (docs/GPU-Performance.rst:134-158); ``precision="high"|"highest"``
  (3/6-pass emulation) are available for stricter accumulation.
- Quantized int8 payloads are EXACT: int8 values are exactly
  representable in bf16, products against a {0,1} one-hot are exact, and
  f32 accumulation of a <=8192-row block is exact (|sum| <= 8192*127 <
  2^24); each block is converted to int32 before the cross-block sum, so
  the result equals true int32 accumulation at full MXU speed.
- There is no most-frequent-bin omission / ``FixHistogram`` reconstruction
  (dataset.h:760): every bin is accumulated directly, which on TPU costs
  nothing extra and removes a cross-rank reconstruction step.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["build_histogram", "subtract_histogram", "hist_from_rows",
           "hist_from_rows_int", "PACK"]

PACK = 4           # features per MXU pack. The matmul computes all
                   # PACK x PACK cross-feature blocks and keeps the
                   # diagonal, so FLOPs per feature scale with PACK —
                   # while the materialized one-hot bytes per feature
                   # (s_hi + s_lo*C values) don't depend on it.
                   # Measured on v5e (benchmarks/PROFILE.md): PACK=4
                   # beats 8 (half the FLOPs) and 2 (whose M=16 matmul
                   # streams the MXU poorly).
S_LO = 16          # bins per low-digit group: b = S_LO*hi + lo. With
                   # PACK=4 the 16x16 split keeps the matmul N dim at
                   # PACK*S_LO*C = 128 — exactly the MXU's output lanes
                   # — and sits at the one-hot byte optimum
                   # min(s_hi + s_lo*C) s.t. s_hi*s_lo >= num_bins.
ROW_BLOCK = 16384  # rows per accumulation block (bounds one-hot residency
                   # AND keeps int-as-bf16 block sums exact:
                   # 16384*127 = 2.1M < 2^24; sized to the compact
                   # grower's chunk so a chunk histogram is ONE block)

_PRECISIONS = {
    "default": None,
    "high": lax.Precision.HIGH,
    "highest": lax.Precision.HIGHEST,
}


def _nibble_hist_block(rows: jnp.ndarray, payload: jnp.ndarray,
                       s_hi: int, precision, int_exact: bool) -> jnp.ndarray:
    """One row-block of the nibble-decomposed MXU histogram.

    ``hist[f, b] = sum_r [bins[r,f]==b] * payload[r]`` with
    ``b = S_LO*hi + lo`` factors into
    ``sum_r HI[r, f*s_hi+hi] * LO[r, f*S_LO+lo] * payload[r]``:
    a dense [PACK*s_hi, S] x [S, PACK*S_LO*C] matmul per PACK-feature
    group — the MXU replacement for the CUDA shared-memory scatter-add
    (/root/reference/src/treelearner/cuda/cuda_histogram_constructor.cu:18).
    Cross-feature (p != q) blocks of the product are computed and
    discarded; the MXU does them for free within the 128-lane tile.

    Args:
      rows: ``[S, npacks, PACK]`` native-width (u8/u16) bin values —
        kept narrow so the materialized compare operands stay small.
      payload: ``[S, C]`` float or int8 channels (grad, hess).
    Returns:
      ``[npacks, PACK, s_hi * S_LO, C]`` partial histograms, f32 (exact
      integers when ``int_exact``).
    """
    S, npacks, P = rows.shape
    C = payload.shape[-1]
    # bf16 one-hots whenever the TPU matmul runs in single-pass mode:
    # the MXU truncates DEFAULT-precision f32 inputs to bf16 anyway,
    # and {0,1} masks commute with truncation (LOC is pay-or-zero), so
    # the result is bit-identical on TPU while the materialized
    # one-hot traffic — the measured cost center of the whole
    # histogram (xplane, benchmarks/PROFILE.md) — halves. Multi-pass
    # "high"/"highest" emulation needs true f32 operands, and CPU
    # matmuls don't truncate, so both keep the payload dtype there.
    bf16_pass = int_exact or (precision is None
                              and jax.default_backend() == "tpu")
    onehot_dtype = jnp.bfloat16 if bf16_pass else payload.dtype
    if int_exact:
        precision = None
    if bf16_pass:
        payload = payload.astype(jnp.bfloat16)
    rdt = rows.dtype
    hi = rows // rdt.type(S_LO)
    lo = rows & rdt.type(S_LO - 1)
    HI = (hi[..., None] == jnp.arange(s_hi, dtype=rdt)) \
        .astype(onehot_dtype)
    LO = (lo[..., None] == jnp.arange(S_LO, dtype=rdt)) \
        .astype(onehot_dtype)
    LOC = LO[..., None] * payload[:, None, None, None, :]  # [S,np,P,sl,C]
    out = jnp.einsum(
        "snx,snyc->nxyc",
        HI.reshape(S, npacks, P * s_hi),
        LOC.reshape(S, npacks, P * S_LO, C),
        preferred_element_type=jnp.float32,
        precision=precision)
    d = jnp.diagonal(out.reshape(npacks, P, s_hi, P, S_LO, C),
                     axis1=1, axis2=3)                    # [np,hi,sl,C,P]
    return d.transpose(0, 4, 1, 2, 3).reshape(npacks, P, s_hi * S_LO, C)


def _hist_from_rows_impl(rows: jnp.ndarray, payload: jnp.ndarray,
                         num_bins: int, method: str,
                         accum_dtype, precision) -> jnp.ndarray:
    if method == "scatter":
        return _hist_scatter(rows.T, payload.astype(accum_dtype), num_bins)
    int_exact = jnp.issubdtype(accum_dtype, jnp.integer)
    if method == "pallas":
        # VMEM-resident one-hot kernel (ops/pallas_hist.py). Always
        # f32-accumulated (int8 payloads: exact int32) — the
        # hist_precision multi-pass emulation is an MXU-path knob.
        from .pallas_hist import hist_from_rows_pallas
        return hist_from_rows_pallas(rows, payload, num_bins,
                                     int_exact=int_exact)
    S, F = rows.shape
    C = payload.shape[-1]
    s_hi = -(-num_bins // S_LO)
    f_pad = (-F) % PACK
    if f_pad:
        rows = jnp.pad(rows, ((0, 0), (0, f_pad)))
    Fp = F + f_pad
    npacks = Fp // PACK
    if not jnp.issubdtype(rows.dtype, jnp.unsignedinteger):
        rows = rows.astype(jnp.uint32)
    rows = rows.reshape(S, npacks, PACK)

    def finish(block):
        return block.astype(accum_dtype) if int_exact else block

    if S <= ROW_BLOCK:
        h = finish(_nibble_hist_block(rows, payload, s_hi, precision,
                                      int_exact))
    else:
        nblk = -(-S // ROW_BLOCK)
        pad = nblk * ROW_BLOCK - S
        if pad:
            rows = jnp.pad(rows, ((0, pad), (0, 0), (0, 0)))
            payload = jnp.pad(payload, ((0, pad), (0, 0)))
        rows_b = rows.reshape(nblk, ROW_BLOCK, npacks, PACK)
        pay_b = payload.reshape(nblk, ROW_BLOCK, C)

        def body(acc, xs):
            r, p = xs
            blk = _nibble_hist_block(r, p, s_hi, precision, int_exact)
            return acc + finish(blk), None

        init = jnp.zeros((npacks, PACK, s_hi * S_LO, C), accum_dtype)
        h, _ = lax.scan(body, init, (rows_b, pay_b))
    h = h.reshape(Fp, s_hi * S_LO, C)
    return h[:F, :num_bins, :]


def hist_from_rows(rows: jnp.ndarray, payload: jnp.ndarray,
                   num_bins: int, method: str = "mxu",
                   precision: str = "default") -> jnp.ndarray:
    """Float histogram over a row-block matrix.

    Args:
      rows: ``[S, F]`` integer bin matrix (row-major).
      payload: ``[S, C]`` float per-row channels (grad, hess).
      num_bins: B.
      method: "mxu" (nibble matmul), "pallas" (VMEM-resident one-hot
        kernel, ops/pallas_hist.py) or "scatter" (CPU-friendly).
      precision: matmul pass count — "default" (1-pass bf16/f32-accum),
        "high" (3-pass), "highest" (6-pass); mxu path only.
    Returns:
      ``[F, B, C]`` histograms (padding features report zeros only if the
      caller masked their payload; callers crop to the true F).
    """
    acc = jnp.promote_types(payload.dtype, jnp.float32)
    return _hist_from_rows_impl(rows, payload, num_bins, method,
                                acc, _PRECISIONS[precision])


def hist_from_rows_int(rows: jnp.ndarray, payload: jnp.ndarray,
                       num_bins: int, method: str = "mxu") -> jnp.ndarray:
    """Quantized histogram: int8 payload, exact int32 result
    (subtraction-safe) via bf16 MXU passes with per-block conversion."""
    return _hist_from_rows_impl(rows, payload, num_bins, method, jnp.int32,
                                None)


def _hist_scatter(bins_T: jnp.ndarray, gh: jnp.ndarray, num_bins: int,
                  unroll: int = 1) -> jnp.ndarray:
    """Scatter-add path: lax.scan over features, one scatter per feature."""

    def body(carry, bins_f):
        hist = jnp.zeros((num_bins, gh.shape[-1]), dtype=gh.dtype)
        hist = hist.at[bins_f].add(gh, mode="drop")
        return carry, hist

    _, hists = lax.scan(body, None, bins_T, unroll=unroll)
    return hists


def build_histogram(bins_T: jnp.ndarray,
                    grad: jnp.ndarray,
                    hess: jnp.ndarray,
                    row_weight: jnp.ndarray,
                    mask: jnp.ndarray,
                    num_bins: int,
                    method: str = "scatter",
                    precision: str = "default") -> jnp.ndarray:
    """Build per-feature histograms for the rows selected by ``mask``.

    Args:
      bins_T: ``[F, n]`` integer bin matrix (feature-major).
      grad, hess: ``[n]`` float gradients/hessians.
      row_weight: ``[n]`` sampling weight (bagging mask / GOSS
        amplification); scales the payload.
      mask: ``[n]`` bool leaf-membership mask.
      num_bins: global max number of bins B.

    Returns:
      ``[F, B, 2]`` float array of (sum_grad, sum_hess).
    """
    m = mask.astype(grad.dtype) * row_weight.astype(grad.dtype)
    gh = jnp.stack([grad * m, hess * m], axis=-1)  # [n, 2]
    if method in ("mxu", "pallas"):
        return hist_from_rows(bins_T.T, gh, num_bins, method, precision)
    return _hist_scatter(bins_T, gh, num_bins)


def subtract_histogram(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """The histogram-subtraction trick: sibling = parent - child
    (serial_tree_learner.cpp:473-520)."""
    return parent - child
