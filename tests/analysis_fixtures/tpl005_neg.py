# tpulint fixture: TPL005 negative — deterministic iteration only.
import jax
import jax.numpy as jnp


def reduce_shards(shards):
    total = jnp.float32(0.0)
    names = {s.name for s in shards}
    for name in sorted(names):        # sorted(): total deterministic order
        total = total + jax.lax.psum(shards[name], "x")
    return total


def list_order(parts, keys):
    ordered = [k for k in keys]       # list in, list out
    return jnp.stack([parts[k] for k in ordered])


def membership_only(callbacks):
    before = {c for c in callbacks if c.enabled}
    # set MEMBERSHIP is order-free — only iteration is hazardous
    rest = [c for c in callbacks if c not in before]
    for c in rest:
        c(jnp.zeros(()))
    return rest


def host_only_set(tags):
    # set iteration with no jax dispatch anywhere near it: out of
    # TPL005's blast radius (pure host bookkeeping)
    seen = set(tags)
    return {t: len(t) for t in sorted(seen)}
