"""Small-table row gathers that compile well on TPU.

``table[idx]`` with a million-row ``idx`` and a tiny table lowers to an
XLA gather that TPUs execute one element at a time (~8.6 ms per million
rows measured — benchmarks/PROFILE.md). The boosting loop needs exactly
this shape in several places (leaf value -> row score contribution, the
reference's ScoreUpdater::AddScore walk, score_updater.hpp:58): a [n]
index vector into an [L <= a few hundred] table. ``gather_small``
replaces it with L sequential full-width selects — O(L * n / lanes)
vector work, ~30x faster at L=255 — while keeping exact dtype semantics
(values are moved bit-for-bit, never re-rounded).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["gather_small"]


def gather_small(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``table[idx]`` via a fori_loop of vector selects.

    Args:
      table: ``[L, ...]`` values (any dtype); L is static and small.
        Trailing dims (e.g. per-leaf coefficient rows) are supported.
      idx: ``[n]`` int indices into the table.
    Returns:
      ``[n, ...]`` array of ``table.dtype``.

    Out-of-range semantics DIVERGE from ``table[idx]`` under jit: XLA
    clamps indices to [0, L), so ``table[-1]`` returns ``table[0]``;
    this returns **0** for any out-of-range index. All current callers
    (score updates, valid scoring, linear-leaf eval) pass leaf ids that
    are in-range by construction; a caller introducing sentinel indices
    (e.g. -1 for an unrouted row) must mask them explicitly rather than
    rely on either behavior. Set ``LIGHTGBM_TPU_DEBUG_GATHER=1`` to
    assert in-range eagerly (host round-trip — debug only).
    """
    if os.environ.get("LIGHTGBM_TPU_DEBUG_GATHER") and not isinstance(
            idx, jax.core.Tracer):
        lo = int(jnp.min(idx))
        hi = int(jnp.max(idx))
        if lo < 0 or hi >= table.shape[0]:
            raise ValueError(
                f"gather_small: index range [{lo}, {hi}] outside "
                f"table [0, {table.shape[0]})")
    return _gather_small(table, idx)


@jax.jit
def _gather_small(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    L = table.shape[0]
    init = jnp.zeros(idx.shape + table.shape[1:], table.dtype)
    idx_b = idx.reshape(idx.shape + (1,) * (table.ndim - 1))

    def body(l, acc):
        return jnp.where(idx_b == l, table[l], acc)

    return lax.fori_loop(0, L, body, init)
