"""Microbenchmark of histogram-construction strategies on the TPU.

The GBDT hot loop is a (g, h, count) scatter-add over per-feature bins
(reference: dense_bin.hpp ConstructHistogramInner). TPUs have no scatter
hardware, so the right strategy is an empirical question. This measures:

  scan_scatter   - lax.scan over features, one .at[].add per feature
  flat_scatter   - ONE scatter of n*F updates into a flat [F*B*3] buffer
  onehot         - one-hot einsum riding the MXU
  segsum         - jax.ops.segment_sum with combined (f, bin) segment ids
  packed_scatter - quantized (g,h) packed into one int32 channel, flat scatter
  pallas         - hand-tiled VMEM-resident one-hot kernel
                   (lightgbm_tpu/ops/pallas_hist.py; the hist_method=
                   "pallas" production path). Its time against `onehot`
                   is the first half of the auto-flip gate; the binding
                   number is fused_iter_bench.py's pallas arm.

Run on the tunneled TPU:  python benchmarks/hist_micro.py
Env: HM_ROWS, HM_FEATURES, HM_BINS.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = int(os.environ.get("HM_ROWS", 1_000_000))
F = int(os.environ.get("HM_FEATURES", 28))
B = int(os.environ.get("HM_BINS", 256))


def timeit(fn, *args, warmup=2, iters=5):
    for _ in range(warmup):
        r = fn(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / iters


if __name__ == "__main__":
    import jax
    import jax.numpy as jnp
    from jax import lax

    print(f"backend={jax.default_backend()} n={N} F={F} B={B}", flush=True)
    rs = np.random.RandomState(0)
    bins_T = jnp.asarray(rs.randint(0, B, size=(F, N)).astype(np.uint8))
    grad = jnp.asarray(rs.randn(N).astype(np.float32))
    hess = jnp.asarray(np.abs(rs.randn(N)).astype(np.float32))
    w = jnp.ones((N,), jnp.float32)

    @jax.jit
    def scan_scatter(bins_T, g, h, w):
        gh = jnp.stack([g * w, h * w, w], axis=-1)

        def body(carry, bins_f):
            hist = jnp.zeros((B, 3), jnp.float32).at[bins_f].add(
                gh, mode="drop")
            return carry, hist

        _, hists = lax.scan(body, None, bins_T)
        return hists

    @jax.jit
    def flat_scatter(bins_T, g, h, w):
        gh = jnp.stack([g * w, h * w, w], axis=-1)          # [n, 3]
        idx = (jnp.arange(F, dtype=jnp.int32)[:, None] * B
               + bins_T.astype(jnp.int32))                   # [F, n]
        flat = jnp.zeros((F * B, 3), jnp.float32)
        flat = flat.at[idx.reshape(-1)].add(
            jnp.tile(gh, (F, 1)), mode="drop")
        return flat.reshape(F, B, 3)

    @jax.jit
    def segsum(bins_T, g, h, w):
        gh = jnp.stack([g * w, h * w, w], axis=-1)
        idx = (jnp.arange(F, dtype=jnp.int32)[:, None] * B
               + bins_T.astype(jnp.int32)).reshape(-1)
        out = jax.ops.segment_sum(jnp.tile(gh, (F, 1)), idx,
                                  num_segments=F * B)
        return out.reshape(F, B, 3)

    @jax.jit
    def onehot(bins_T, g, h, w, block=32768):
        gh = jnp.stack([g * w, h * w, w], axis=-1)
        pad = (-N) % block
        if pad:
            bins_T = jnp.pad(bins_T, ((0, 0), (0, pad)))
            gh = jnp.pad(gh, ((0, pad), (0, 0)))
        nblk = bins_T.shape[1] // block
        bins_blk = bins_T.reshape(F, nblk, block).transpose(1, 0, 2)
        gh_blk = gh.reshape(nblk, block, 3)

        def body(acc, xs):
            b, ghb = xs
            oh = jax.nn.one_hot(b, B, dtype=jnp.bfloat16)
            acc = acc + jnp.einsum("frb,rc->fbc", oh,
                                   ghb.astype(jnp.bfloat16),
                                   preferred_element_type=jnp.float32)
            return acc, None

        init = jnp.zeros((F, B, 3), jnp.float32)
        hists, _ = lax.scan(body, init, (bins_blk, gh_blk))
        return hists

    @jax.jit
    def packed_scatter(bins_T, g, h, w):
        # int16 quantized (g,h) packed into one int32; count via a
        # separate int32 scatter of packed (1<<16 | 1)-style trick is
        # skipped - just g,h packed + count from per-leaf totals.
        gs = jnp.clip(g * w * 32767.0 / 4.0, -32767, 32767).astype(jnp.int32)
        hs = jnp.clip(h * w * 32767.0 / 4.0, 0, 65535).astype(jnp.int32)
        packed = (gs << 16) | hs
        idx = (jnp.arange(F, dtype=jnp.int32)[:, None] * B
               + bins_T.astype(jnp.int32))
        flat = jnp.zeros((F * B,), jnp.int32)
        flat = flat.at[idx.reshape(-1)].add(
            jnp.tile(packed, (F,)), mode="drop")
        return flat.reshape(F, B)

    arms = [("scan_scatter", scan_scatter),
            ("flat_scatter", flat_scatter),
            ("segsum", segsum),
            ("onehot", onehot),
            ("packed_scatter", packed_scatter)]

    from lightgbm_tpu.ops.pallas_hist import (hist_from_rows_pallas,
                                              pallas_available)
    if pallas_available():
        @jax.jit
        def pallas_arm(bins_T, g, h, w):
            gh = jnp.stack([g * w, h * w, w], axis=-1)
            return hist_from_rows_pallas(bins_T.T, gh, B)

        arms.append(("pallas", pallas_arm))
    else:
        print("pallas           SKIPPED (unavailable)", flush=True)

    results = {}
    for name, fn in arms:
        try:
            dt = timeit(fn, bins_T, grad, hess, w)
            gbs = (N * F * 1 + N * 12) / dt / 1e9
            results[name] = dt
            print(f"{name:16s} {dt*1e3:9.2f} ms   ({gbs:6.1f} GB/s eff)",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{name:16s} FAILED: {type(e).__name__}: {e}", flush=True)
    if results:
        best = min(results, key=results.get)
        print(f"best: {best} ({results[best]*1e3:.2f} ms)", flush=True)
