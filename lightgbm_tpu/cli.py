"""Command-line application.

Re-design of the reference CLI (/root/reference/src/application/
application.cpp:31-285, src/main.cpp): ``key=value`` arguments plus an
optional ``config=<file>`` configuration file, dispatching the tasks
train / predict / convert_model / refit / save_binary.

Usage:
    python -m lightgbm_tpu config=train.conf [key=value ...]
    python -m lightgbm_tpu task=train data=train.csv objective=binary
    python -m lightgbm_tpu stats run.jsonl     # summarize telemetry
    python -m lightgbm_tpu stats telemetry/ --fleet   # merged fleet view
    python -m lightgbm_tpu checkpoints <dir>   # inspect snapshots
    python -m lightgbm_tpu lint [--help]       # tpulint static analyzer
    python -m lightgbm_tpu launch 4 -- <cmd>   # elastic restart supervisor
    python -m lightgbm_tpu serve model.txt     # inference daemon
    python -m lightgbm_tpu trace telemetry/    # merge spans -> Perfetto

Config-file syntax matches the reference (application.cpp:50-86 +
config.cpp KV2Map): one ``key = value`` per line, ``#`` comments;
command-line pairs override file pairs.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, LightGBMError
from .config import Config, resolve_params
from .engine import train as train_fn
from .utils.log import log_info, log_warning

__all__ = ["main", "parse_args", "load_config_file"]


def load_config_file(path: str) -> Dict[str, str]:
    """Parse a ``key = value`` config file (Config::KV2Map semantics:
    '#' starts a comment, keys/values are stripped)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "=" not in line:
                log_warning(f"Unknown config line ignored: {line!r}")
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def parse_args(argv: List[str]) -> Dict[str, str]:
    """CLI pairs override config-file pairs (application.cpp:50-86)."""
    cli: Dict[str, str] = {}
    for a in argv:
        if "=" not in a:
            raise LightGBMError(f"Unknown argument (expected key=value): {a}")
        k, v = a.split("=", 1)
        cli[k.strip()] = v.strip()
    resolved = resolve_params(cli)
    conf_path = resolved.pop("config", None)
    params: Dict[str, str] = {}
    if conf_path:
        params.update(resolve_params(load_config_file(conf_path)))
    params.update(resolved)
    return params


def _load_dataset(cfg: Config, params: Dict[str, Any], path: str,
                  reference: Optional[Dataset] = None) -> Dataset:
    ds = Dataset(path, params=params, reference=reference)
    ds.construct()
    return ds


def _task_train(cfg: Config, params: Dict[str, Any]) -> None:
    if not cfg.data:
        raise LightGBMError("No training data: pass data=<file>")
    train_set = _load_dataset(cfg, params, cfg.data)
    valid_sets = [_load_dataset(cfg, params, v, reference=train_set)
                  for v in cfg.valid]
    valid_names = [f"valid_{i + 1}" for i in range(len(valid_sets))]

    callbacks: List[Any] = []
    if cfg.verbosity >= 1 and (valid_sets or cfg.is_provide_training_metric):
        callbacks.append(callback_mod.log_evaluation(
            period=max(1, cfg.metric_freq)))
    if cfg.snapshot_freq > 0:
        # periodic model snapshots (GBDT::Train, gbdt.cpp:250-254)
        out = cfg.output_model

        def _snapshot(env) -> None:
            it = env.iteration + 1
            if it % cfg.snapshot_freq == 0:
                env.model.save_model(f"{out}.snapshot_iter_{it}")

        _snapshot.order = 100
        callbacks.append(_snapshot)
    if cfg.is_provide_training_metric:
        valid_sets = [train_set] + valid_sets
        valid_names = ["training"] + valid_names

    booster = train_fn(
        params, train_set,
        num_boost_round=cfg.num_iterations,
        valid_sets=valid_sets, valid_names=valid_names,
        init_model=cfg.input_model or None,
        callbacks=callbacks)
    booster.save_model(cfg.output_model)
    log_info(f"Finished training; model saved to {cfg.output_model}")


def _task_predict(cfg: Config, params: Dict[str, Any]) -> None:
    if not cfg.input_model:
        raise LightGBMError("task=predict needs input_model=<model file>")
    if not cfg.data:
        raise LightGBMError("No data to predict: pass data=<file>")
    booster = Booster(model_file=cfg.input_model)
    from .basic import _load_text_file
    X, _, _, _ = _load_text_file(cfg.data, cfg)
    num_iteration = (cfg.num_iteration_predict
                     if cfg.num_iteration_predict > 0 else None)
    pred = booster.predict(
        X,
        start_iteration=cfg.start_iteration_predict,
        num_iteration=num_iteration,
        raw_score=cfg.predict_raw_score,
        pred_leaf=cfg.predict_leaf_index,
        pred_contrib=cfg.predict_contrib)
    pred = np.asarray(pred)
    if pred.ndim == 1:
        pred = pred[:, None]
    fmt = "%d" if cfg.predict_leaf_index else "%.18g"
    np.savetxt(cfg.output_result, pred, fmt=fmt, delimiter="\t")
    log_info(f"Finished prediction; results saved to {cfg.output_result}")


def _task_convert_model(cfg: Config, params: Dict[str, Any]) -> None:
    if not cfg.input_model:
        raise LightGBMError("task=convert_model needs input_model=<file>")
    if cfg.convert_model_language not in ("", "cpp"):
        raise LightGBMError(
            f"Unsupported convert_model_language: "
            f"{cfg.convert_model_language}")
    booster = Booster(model_file=cfg.input_model)
    from .convert import model_to_if_else
    code = model_to_if_else(booster)
    with open(cfg.convert_model, "w") as f:
        f.write(code)
    log_info(f"Converted model saved to {cfg.convert_model}")


def _task_refit(cfg: Config, params: Dict[str, Any]) -> None:
    if not cfg.input_model:
        raise LightGBMError("task=refit needs input_model=<model file>")
    if not cfg.data:
        raise LightGBMError("No refit data: pass data=<file>")
    booster = Booster(model_file=cfg.input_model)
    from .basic import _load_text_file
    X, y, w, _ = _load_text_file(cfg.data, cfg)
    refitted = booster.refit(X, y, decay_rate=cfg.refit_decay_rate, weight=w)
    refitted.save_model(cfg.output_model)
    log_info(f"Finished refit; model saved to {cfg.output_model}")


def _task_save_binary(cfg: Config, params: Dict[str, Any]) -> None:
    if not cfg.data:
        raise LightGBMError("No data: pass data=<file>")
    ds = _load_dataset(cfg, params, cfg.data)
    out = cfg.data + ".bin"
    ds.save_binary(out)
    log_info(f"Binned dataset saved to {out}")


_STATS_HELP = """\
usage: python -m lightgbm_tpu stats <file.jsonl | dir> [--fleet]

Fold a telemetry event stream (lightgbm_tpu.telemetry(path) callback /
LIGHTGBM_TPU_TELEMETRY=<path>) into the sorted per-phase summary table:
wall time, recompiles, peak HBM, fault events, final evals, a serve
summary row when the file carries {"event": "serve"} daemon lines
(docs/SERVING.md), an xla cost section when it carries
{"event": "compile"} records (flops / bytes / live roofline,
docs/ROOFLINE.md), and a per-phase total/count/mean/percent/skew
breakdown. See docs/OBSERVABILITY.md.

A DIRECTORY summarizes every *.jsonl file inside (recursively, .rankN
suffixes included) with per-file provenance headers — the fleet's
telemetry/ directory is the expected shape. --fleet appends the
merged cross-process view: trainer iteration/compile totals, summed
serve traffic with worst-case p99, shed and restart totals.

exit codes:
  0  summary printed
  1  unreadable/malformed input, or no known events in it
"""

_CHECKPOINTS_HELP = """\
usage: python -m lightgbm_tpu checkpoints <dir>

List every snapshot the resilience checkpoint callback wrote into a
directory, with validation status — the operator view for "can this run
resume, and from which iteration?". See docs/RESILIENCE.md.

exit codes:
  0  at least one valid (resumable) snapshot listed
  1  not a directory, no snapshots, or no valid snapshot
"""


def _summary_has_events(summary: Dict[str, Any]) -> bool:
    return bool(summary["iterations"] or summary.get("serve")
                or summary.get("publishes")
                or summary.get("compiles")
                or summary.get("fleet_events"))


def _task_stats(argv: List[str]) -> int:
    """``lightgbm_tpu stats <file.jsonl | dir> [--fleet]``: fold one
    telemetry event stream — or a directory of them, one per fleet
    process — into the sorted summary tables; ``--fleet`` appends the
    merged cross-process view."""
    if argv and argv[0] in ("-h", "--help"):
        print(_STATS_HELP)
        return 0
    fleet = "--fleet" in argv
    argv = [a for a in argv if a != "--fleet"]
    if not argv:
        print("usage: python -m lightgbm_tpu stats "
              "<file.jsonl | dir> [--fleet]", file=sys.stderr)
        return 1
    from .obs import render_stats_table, summarize_events
    path = argv[0]
    if os.path.isdir(path):
        from .obs import (merge_fleet_summaries, render_fleet_table,
                          summarize_directory)
        try:
            entries = summarize_directory(path)
        except OSError as e:
            print(f"[LightGBM-TPU] [Fatal] cannot read {path}: {e}",
                  file=sys.stderr)
            return 1
        except (ValueError, TypeError, AttributeError, KeyError) as e:
            print(f"[LightGBM-TPU] [Fatal] malformed telemetry under "
                  f"{path}: {e}", file=sys.stderr)
            return 1
        useful = [(rel, s) for rel, s in entries
                  if _summary_has_events(s)]
        if not useful:
            print(f"no telemetry events in any *.jsonl under {path}",
                  file=sys.stderr)
            return 1
        blocks = []
        for rel, summary in useful:
            blocks.append(f"== {rel} ==\n"
                          + render_stats_table(summary))
        if fleet:
            blocks.append(render_fleet_table(
                merge_fleet_summaries(useful)))
        print("\n\n".join(blocks))
        return 0
    try:
        summary = summarize_events(path)
    except OSError as e:
        print(f"[LightGBM-TPU] [Fatal] cannot read {path}: {e}",
              file=sys.stderr)
        return 1
    except (ValueError, TypeError, AttributeError, KeyError) as e:
        # malformed JSON line or structurally-wrong event object
        print(f"[LightGBM-TPU] [Fatal] malformed telemetry in {path}: "
              f"{e}", file=sys.stderr)
        return 1
    if not _summary_has_events(summary):
        print(f"no iteration, serve or publish events in {path}",
              file=sys.stderr)
        return 1
    print(render_stats_table(summary))
    if fleet:
        # --fleet on a single stream: the one-entry merged view (so
        # the flag is never silently ignored in scripts)
        from .obs import merge_fleet_summaries, render_fleet_table
        print()
        print(render_fleet_table(merge_fleet_summaries(
            [(os.path.basename(path), summary)])))
    return 0


def _task_checkpoints(argv: List[str]) -> int:
    """``lightgbm_tpu checkpoints <dir>``: list every snapshot the
    resilience checkpoint callback wrote into a directory, with
    validation status — the operator view for "can this run resume,
    and from which iteration?"."""
    if argv and argv[0] in ("-h", "--help"):
        print(_CHECKPOINTS_HELP)
        return 0
    if not argv:
        print("usage: python -m lightgbm_tpu checkpoints <dir>",
              file=sys.stderr)
        return 1
    directory = argv[0]
    if not os.path.isdir(directory):
        print(f"[LightGBM-TPU] [Fatal] not a directory: {directory}",
              file=sys.stderr)
        return 1
    from .resilience.checkpoint import list_snapshots
    rows = list_snapshots(directory)
    if not rows:
        print(f"no checkpoint snapshots in {directory}", file=sys.stderr)
        return 1
    import datetime as _dt
    print(f"{'iteration':>9s}  {'status':8s} {'trees':>6s} "
          f"{'size':>10s}  {'written':19s}  file")
    resumable = None
    for row in rows:
        when = _dt.datetime.fromtimestamp(
            row["mtime"]).strftime("%Y-%m-%d %H:%M:%S")
        if row["status"] == "ok":
            trees = str(row["num_trees"])
            resumable = row
        else:
            trees = "-"
        print(f"{row['iteration']:9d}  {row['status']:8s} {trees:>6s} "
              f"{row['bytes']:10d}  {when}  "
              f"{os.path.basename(row['path'])}")
        if row["status"] != "ok":
            print(f"           ^ {row['error']}")
    if resumable is not None:
        print(f"\nresume target: iteration {resumable['iteration']} "
              f"({os.path.basename(resumable['path'])})")
    else:
        print("\nno valid snapshot: this directory cannot be resumed "
              "from", file=sys.stderr)
        return 1
    return 0


_TASKS = {
    "train": _task_train,
    "refit": _task_refit,
    "refit_tree": _task_refit,
    "predict": _task_predict,
    "prediction": _task_predict,
    "test": _task_predict,
    "convert_model": _task_convert_model,
    "save_binary": _task_save_binary,
}


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv in (["-h"], ["--help"]):
        print(__doc__)
        return 0
    if argv[0] == "stats":
        return _task_stats(argv[1:])
    if argv[0] == "checkpoints":
        return _task_checkpoints(argv[1:])
    if argv[0] == "lint":
        # normally dispatched jax-free in __main__.py before this
        # module (and its jax imports) loads; kept here so programmatic
        # main() callers get the same surface
        from .analysis.cli import main as lint_main
        return lint_main(argv[1:])
    if argv[0] == "launch":
        # likewise dispatched jax-free in __main__.py; kept here for
        # programmatic main() callers
        from .resilience.elastic import main as launch_main
        return launch_main(argv[1:])
    if argv[0] == "serve":
        # likewise dispatched (jax-lazily) in __main__.py; kept here
        # for programmatic main() callers
        from .serve.daemon import main as serve_main
        return serve_main(argv[1:])
    if argv[0] == "trace":
        # likewise dispatched jax-free in __main__.py; kept here for
        # programmatic main() callers
        from .obs.trace import main as trace_main
        return trace_main(argv[1:])
    try:
        params = parse_args(argv)
        cfg = Config.from_params(params)
        if cfg.num_machines > 1:
            # Network::Init analog (application.cpp:171): wire this
            # process into the multi-controller runtime before any
            # device work happens
            from .parallel.distributed import init_distributed
            init_distributed(machines=cfg.machines or None,
                             machine_list_file=cfg.machine_list_file
                             or None)
        task = _TASKS.get(cfg.task)
        if task is None:
            raise LightGBMError(f"Unknown task: {cfg.task}")
        task(cfg, params)
    except (LightGBMError, ValueError, OSError) as e:
        print(f"[LightGBM-TPU] [Fatal] {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
