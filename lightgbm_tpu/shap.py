"""SHAP feature contributions (TreeSHAP).

Re-design of the reference's PredictContrib path
(/root/reference/src/boosting/gbdt.cpp:640 and the TreeSHAP recursion in
src/io/tree.cpp) as a ROW-VECTORIZED walk: the classic recursion carries
a per-row decision path, but only the binary ``one_fraction`` entries
are row-dependent — the cover ratios (``zero_fraction``) and the path's
feature sequence are properties of the tree node alone. So the walk
visits each tree node once, carrying the path state as ``[n, depth]``
numpy arrays and doing the extend/unwind algebra on whole row batches,
instead of recursing per row. Same math, O(num_nodes · depth) vector
steps instead of O(n · num_nodes · depth) Python steps.

``_tree_shap_row`` keeps the textbook single-row recursion as the
cross-check oracle for tests.
"""

from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["predict_contrib"]


# ---------------------------------------------------------------------------
# Vectorized TreeSHAP: one node visit, all rows at once
# ---------------------------------------------------------------------------

class _VecPath:
    """Decision-path state for a batch of rows at one recursion depth.

    feature_index / zero_fraction are per-element scalars (shared by all
    rows); one_fraction / pweight are [n, depth+1] row-wise."""

    __slots__ = ("feat", "zero", "one", "pw")

    def __init__(self, n: int, cap: int):
        self.feat = np.full(cap, -1, np.int64)
        self.zero = np.zeros(cap, np.float64)
        self.one = np.zeros((n, cap), np.float64)
        self.pw = np.zeros((n, cap), np.float64)

    def clone(self) -> "_VecPath":
        out = _VecPath.__new__(_VecPath)
        out.feat = self.feat.copy()
        out.zero = self.zero.copy()
        out.one = self.one.copy()
        out.pw = self.pw.copy()
        return out


def _vec_extend(path: _VecPath, d: int, zero: float, one: np.ndarray,
                feat: int) -> None:
    path.feat[d] = feat
    path.zero[d] = zero
    path.one[:, d] = one
    path.pw[:, d] = 1.0 if d == 0 else 0.0
    for i in range(d - 1, -1, -1):
        path.pw[:, i + 1] += one * path.pw[:, i] * (i + 1) / (d + 1)
        path.pw[:, i] *= zero * (d - i) / (d + 1)


def _vec_unwind(path: _VecPath, d: int, idx: int) -> None:
    one = path.one[:, idx]
    zero = path.zero[idx]
    nz = one != 0
    next_one = path.pw[:, d].copy()
    for i in range(d - 1, -1, -1):
        tmp = path.pw[:, i].copy()
        with np.errstate(divide="ignore", invalid="ignore"):
            pw_nz = next_one * (d + 1) / ((i + 1) * one)
        pw_z = tmp * (d + 1) / (zero * (d - i)) if zero * (d - i) != 0 \
            else np.zeros_like(tmp)
        path.pw[:, i] = np.where(nz, pw_nz, pw_z)
        next_one = np.where(nz,
                            tmp - path.pw[:, i] * zero * (d - i) / (d + 1),
                            next_one)
    path.feat[idx:d] = path.feat[idx + 1:d + 1]
    path.zero[idx:d] = path.zero[idx + 1:d + 1]
    path.one[:, idx:d] = path.one[:, idx + 1:d + 1]


def _vec_unwound_sum(path: _VecPath, d: int, idx: int) -> np.ndarray:
    one = path.one[:, idx]
    zero = path.zero[idx]
    nz = one != 0
    total = np.zeros(path.one.shape[0], np.float64)
    next_one = path.pw[:, d].copy()
    for i in range(d - 1, -1, -1):
        with np.errstate(divide="ignore", invalid="ignore"):
            tmp = np.where(nz, next_one * (d + 1) / ((i + 1) * one), 0.0)
        total += tmp
        next_one = np.where(nz,
                            path.pw[:, i] - tmp * zero * (d - i) / (d + 1),
                            next_one)
        if zero * (d - i) != 0:
            total += np.where(nz, 0.0,
                              path.pw[:, i]
                              / (zero * (d - i) / (d + 1)))
    return total


def _vec_tree_shap(tree, X: np.ndarray, phi: np.ndarray, node: int,
                   d: int, parent: _VecPath, pzero: float,
                   pone: np.ndarray, pfeat: int) -> None:
    """Visit ``node`` carrying all rows at once; rows whose
    one_fraction chain has hit zero contribute nothing downstream but
    stay in the batch for shape stability."""
    path = parent.clone()
    _vec_extend(path, d, pzero, pone, pfeat)

    if node < 0:  # leaf
        leaf_v = float(tree.leaf_value[~node])
        for i in range(1, d + 1):
            w = _vec_unwound_sum(path, d, i)
            phi[:, path.feat[i]] += w * (path.one[:, i] - path.zero[i]) \
                * leaf_v
        return

    f = int(tree.split_feature[node])
    l, r = int(tree.left_child[node]), int(tree.right_child[node])
    go_left = _decide_left_rows(tree, node, X[:, f])
    w_node = float(tree.internal_count[node])
    lz = _child_count(tree, l) / w_node if w_node > 0 else 0.0
    rz = _child_count(tree, r) / w_node if w_node > 0 else 0.0

    inc_zero = 1.0
    inc_one = np.ones(X.shape[0], np.float64)
    path_index = 0
    while path_index <= d:
        if path.feat[path_index] == f:
            break
        path_index += 1
    if path_index != d + 1:
        inc_zero = float(path.zero[path_index])
        inc_one = path.one[:, path_index].copy()
        _vec_unwind(path, d, path_index)
        d -= 1

    _vec_tree_shap(tree, X, phi, l, d + 1, path, lz * inc_zero,
                   inc_one * go_left, f)
    _vec_tree_shap(tree, X, phi, r, d + 1, path, rz * inc_zero,
                   inc_one * (1.0 - go_left), f)


def _decide_left_rows(tree, node: int, v: np.ndarray) -> np.ndarray:
    """Vectorized Tree::Decision over a column of raw values
    (NumericalDecision missing routing tree.h:338-356, categorical
    bitset probe tree.h:402-410)."""
    if tree.is_categorical_node(node):
        iv = np.where(np.isnan(v) | (v < 0), -1, v).astype(np.int64)
        cat_idx = int(tree.threshold[node])
        lo = int(tree.cat_boundaries[cat_idx])
        hi = int(tree.cat_boundaries[cat_idx + 1])
        word = iv >> 5
        ok = (iv >= 0) & (word < hi - lo)
        wsel = np.where(ok, lo + word, lo).astype(np.int64)
        bits = tree.cat_threshold[wsel].astype(np.int64)
        hit = ((bits >> (iv & 31)) & 1) != 0
        return (ok & hit).astype(np.float64)
    mt = tree.missing_type(node)
    dl = bool(tree.default_left(node))
    isnan = np.isnan(v)
    vv = np.where(isnan & (mt != 2), 0.0, v)
    out = vv <= tree.threshold[node]
    if mt == 2:
        out = np.where(isnan, dl, out)
    elif mt == 1:
        out = np.where(np.abs(vv) <= 1e-35, dl, out)
    return out.astype(np.float64)


# ---------------------------------------------------------------------------
# Reference single-row recursion (oracle for tests)
# ---------------------------------------------------------------------------

class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction",
                 "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0,
                 one_fraction=0.0, pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) \
            / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight \
            * (unique_depth - i) / (unique_depth + 1)


def _unwind_path(path: List[_PathElement], unique_depth: int,
                 path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) \
                / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction \
                * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) \
                / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) \
                / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction \
                * (unique_depth - i) / (unique_depth + 1)
        else:
            total += path[i].pweight / (
                zero_fraction * (unique_depth - i) / (unique_depth + 1))
    return total


def _tree_shap_row(tree, x: np.ndarray, phi: np.ndarray, node: int,
                   unique_depth: int, parent_path: List[_PathElement],
                   parent_zero_fraction: float, parent_one_fraction: float,
                   parent_feature_index: int) -> None:
    path = [
        _PathElement(p.feature_index, p.zero_fraction, p.one_fraction,
                     p.pweight)
        for p in parent_path[:unique_depth]
    ] + [_PathElement() for _ in range(tree.num_leaves + 2 - unique_depth)]
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction
                                          - el.zero_fraction) \
                * tree.leaf_value[leaf]
        return

    f = int(tree.split_feature[node])
    hot, cold = _decide_children(tree, node, x[f])
    w_node = float(tree.internal_count[node])
    hot_count = _child_count(tree, hot)
    cold_count = _child_count(tree, cold)
    hot_zero_fraction = hot_count / w_node if w_node > 0 else 0.0
    cold_zero_fraction = cold_count / w_node if w_node > 0 else 0.0
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0
    path_index = 0
    while path_index <= unique_depth:
        if path[path_index].feature_index == f:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap_row(tree, x, phi, hot, unique_depth + 1, path,
                   hot_zero_fraction * incoming_zero_fraction,
                   incoming_one_fraction, f)
    _tree_shap_row(tree, x, phi, cold, unique_depth + 1, path,
                   cold_zero_fraction * incoming_zero_fraction, 0.0, f)


def _child_count(tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


def _decide_children(tree, node: int, v: float):
    if tree.is_categorical_node(node):
        go_left = tree._cat_decision(node, v)
    else:
        go_left = tree._num_decision(node, v)
    l, r = int(tree.left_child[node]), int(tree.right_child[node])
    return (l, r) if go_left else (r, l)


def _expected_value(tree) -> float:
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    total = float(tree.internal_count[0])
    if total <= 0:
        return 0.0
    return float(np.sum(tree.leaf_value[: tree.num_leaves]
                        * tree.leaf_count[: tree.num_leaves]) / total)


def _max_depth(tree) -> int:
    depth = np.zeros(max(tree.num_nodes, 1), np.int64)
    best = 1
    for i in range(tree.num_nodes):
        for c in (int(tree.left_child[i]), int(tree.right_child[i])):
            if c >= 0:
                depth[c] = depth[i] + 1
                best = max(best, int(depth[c]) + 1)
            else:
                best = max(best, int(depth[i]) + 2)
    return best


def predict_contrib(booster, X: np.ndarray, trees, K: int,
                    row_chunk: int = 65536) -> np.ndarray:
    """Per-feature SHAP values + expected-value column, shape
    [n, (F+1)*K] matching LGBM_BoosterPredictForMat contrib layout."""
    n, _ = X.shape
    F = booster.num_feature()
    out = np.zeros((n, (F + 1) * K), np.float64)
    for ti, tree in enumerate(trees):
        k = ti % K
        base = k * (F + 1)
        if tree.num_leaves <= 1:
            out[:, base + F] += float(tree.leaf_value[0])
            continue
        ev = _expected_value(tree)
        cap = _max_depth(tree) + 2
        # up to `cap` recursion frames each clone [chunk, cap] f64
        # path state; scale the chunk down for deep trees so peak
        # memory stays bounded (~cap^2 * chunk * 16B)
        chunk = min(row_chunk, max(256, 8_000_000 // (cap * cap)))
        for r0 in range(0, n, chunk):
            Xc = X[r0: r0 + chunk]
            nc = Xc.shape[0]
            phi = np.zeros((nc, F + 1), np.float64)
            root = _VecPath(nc, cap)
            _vec_tree_shap(tree, Xc, phi, 0, 0, root, 1.0,
                           np.ones(nc, np.float64), -1)
            phi[:, F] += ev
            out[r0: r0 + nc, base: base + F + 1] += phi
    return out
