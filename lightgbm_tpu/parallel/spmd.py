"""Multi-controller (SPMD) training helpers.

The reference's distributed data loading protocol
(/root/reference/src/io/dataset_loader.cpp:1070
``ConstructBinMappersFromTextData``): each rank loads its row shard,
ranks find bins on disjoint feature subsets, and the serialized
BinMappers are allgathered (:1228-1236) so every rank bins against
IDENTICAL boundaries. The Dask layer then trains per-worker and keeps
worker 0's model (python-package/lightgbm/dask.py:_train_part).

Under JAX's multi-controller runtime the same protocol is three steps:
``init_distributed`` (parallel/distributed.py) wires the processes,
``sync_bin_mappers`` broadcasts process 0's mappers to all, and the
ordinary mesh-parallel Booster trains SPMD — every process computes the
identical replicated model, so there is no "keep worker 0's result"
step at all.

    from lightgbm_tpu.parallel import distributed, spmd
    distributed.init_distributed(...)          # Network::Init analog
    ds = spmd.distributed_dataset(my_shard_X, my_shard_y, params=...)
    bst = lgb.train(params | {"tree_learner": "data"}, ds, 100)
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

__all__ = ["sync_bin_mappers", "distributed_dataset",
           "aggregate_phase_snapshot", "verify_step_consistency"]


def verify_step_consistency(iteration: int, num_trees: int) -> None:
    """SPMD sanity guard: every process must agree on the iteration
    index and tree count at each host-level sync point (telemetry
    events, checkpoint writes).

    SPMD training computes the identical replicated model on every
    process, so any divergence here means a rank skipped or repeated an
    iteration — the failure mode that otherwise surfaces as a silent
    collective deadlock (ranks waiting in different allgathers) or as
    quietly different models per rank. One tiny [2]-int64 allgather per
    sync turns that into an immediate, attributable ``LightGBMError``.
    The allgather runs under the collective watchdog
    (resilience/watchdog.py), so a rank that died or stalled before
    this sync point surfaces as a deadline error naming this
    collective instead of an infinite hang. Single-process: free
    no-op."""
    import jax

    if jax.process_count() <= 1:
        return
    from .hostsync import host_allgather

    local = np.asarray([int(iteration), int(num_trees)], np.int64)
    g = host_allgather(local, "spmd/verify_step",
                       iteration=int(iteration))  # [P, 2]
    if not (g == g[0]).all():
        from ..basic import LightGBMError
        detail = "; ".join(
            f"rank {r}: iteration={int(a)}, trees={int(b)}"
            for r, (a, b) in enumerate(g))
        raise LightGBMError(
            "SPMD divergence: processes disagree on the training step "
            f"({detail}). The replicated models are no longer "
            "identical — aborting instead of hanging in a collective.")


def aggregate_phase_snapshot(snap: dict) -> dict:
    """Cross-host skew view of a ``Timer.snapshot()``: per-label
    ``{"min", "max", "mean"}`` of the phase totals across processes.

    Multi-chip stragglers hide inside a single process's wall clock —
    the collective phase of a skewed iteration shows up as *waiting* on
    the fast ranks — so the telemetry recorder runs every snapshot
    through here. SPMD processes execute the identical loop, hence hold
    the identical label set; callers must pass the UNFILTERED label set
    (the recorder does) so every rank joins the allgather with an
    identical vector shape. The totals are stacked into one vector and
    allgathered via the watchdog-guarded host transport (one small
    host collective per event, same transport as
    ``sync_bin_mappers``). A collective failure propagates — failing
    fast beats the rank-divergent deadlock a per-rank fallback would
    cause, with some ranks inside the collective and others already
    past it.

    Single-process: min == max == mean == the local total, so the JSONL
    schema is invariant to the topology.
    """
    import jax

    labels = sorted(snap)
    totals = np.asarray([snap[lb]["total"] for lb in labels], np.float64)
    if jax.process_count() > 1 and labels:
        from .hostsync import host_allgather
        g = host_allgather(totals, "telemetry/phase_skew")  # [P, L]
    else:
        g = totals[None, :]
    return {lb: {"min": float(g[:, i].min()),
                 "max": float(g[:, i].max()),
                 "mean": float(g[:, i].mean()),
                 "count": int(snap[lb]["count"])}
            for i, lb in enumerate(labels)}


def sync_bin_mappers(mappers: List) -> List:
    """Make bin boundaries identical on every process: serialize
    process 0's mappers and broadcast (the Network::Allgather of
    serialized BinMappers, dataset_loader.cpp:1228, collapsed to a
    one-to-all broadcast — process 0's sample decides, like rank-0
    bin-merging in ConstructFromSampleData :723)."""
    import jax

    if jax.process_count() <= 1:
        return mappers
    from ..ops.binning import BinMapper
    from .hostsync import host_broadcast_bytes

    payload = None
    if jax.process_index() == 0:
        payload = json.dumps([m.to_dict() for m in mappers]).encode()
    buf = host_broadcast_bytes(payload, "spmd/sync_bin_mappers")
    dicts = json.loads(buf.decode())
    return [BinMapper.from_dict(d) for d in dicts]


def distributed_dataset(X, label=None, params: Optional[dict] = None,
                        **kwargs):
    """Build the GLOBAL training Dataset from THIS process's row shard.

    Protocol (``pre_partition=false`` distributed loading,
    dataset_loader.cpp: every machine ends up binning against identical
    boundaries and the partition happens at the device level):
    1. bin the local shard, 2. broadcast process 0's BinMappers and
    re-bin against them (``sync_bin_mappers``), 3. allgather the BINNED
    u8/u16 shards + metadata so every process holds the identical
    global Dataset. Host RAM holds the full binned matrix (1-2 bytes
    per value); device HBM only ever receives each device's row shard
    — the mesh-parallel learner's input sharding does the partition.
    Every process then trains the identical replicated model — there
    is no "keep worker 0's result" step.

    Shards must have equal row counts across processes (pad the last
    shard if needed; padded rows can carry weight 0). For ranking,
    each shard must contain whole query groups.

    ``X`` may also be a chunked source (``data.RowChunkSource`` /
    ``Sequence`` / generator factory) holding THIS process's shard:
    the streaming construct already synchronized the bin mappers
    across ranks during its pass 1 and binned pass 2 against them
    (data/ingest.py), so only the binned-shard allgather remains — the
    dense float shard never exists on any host (docs/DATA.md).
    """
    from ..basic import Dataset

    ds = Dataset(X, label=label, params=params, **kwargs)
    ds.construct()
    import jax

    if jax.process_count() <= 1:
        return ds
    from ..basic import LightGBMError
    from ..ops.binning import bin_values
    from .hostsync import host_allgather

    # an allgather on unequal shard shapes fails with an opaque shape
    # error (or hangs); check ONE tiny metadata vector first — row
    # count, group-vector length, and which optional fields each rank
    # carries — and name the mismatched ranks before any bulk
    # collective can diverge
    meta = np.asarray([
        ds.num_data(),
        -1 if ds.group is None else len(np.asarray(ds.group)),
        0 if ds.label is None else 1,
        0 if ds.weight is None else 1,
        0 if ds.init_score is None else 1,
        0 if ds.position is None else 1,
    ], np.int64)
    gmeta = host_allgather(meta, "spmd/dataset_meta")      # [P, 6]
    n_locals = gmeta[:, 0]
    if len(set(n_locals.tolist())) > 1:
        detail = ", ".join(
            f"rank {r}: {int(n)} rows" for r, n in enumerate(n_locals))
        raise LightGBMError(
            "distributed_dataset requires equal row counts per process "
            f"(pad the last shard with weight-0 rows); got {detail}")
    for name, col in (("group", 1), ("label", 2), ("weight", 3),
                      ("init_score", 4), ("position", 5)):
        present = gmeta[:, col] >= (0 if col == 1 else 1)
        if present.any() and not present.all():
            have = [r for r in range(len(present)) if present[r]]
            miss = [r for r in range(len(present)) if not present[r]]
            raise LightGBMError(
                f"distributed_dataset: ranks {have} carry {name!r} but "
                f"ranks {miss} do not — every shard must provide the "
                "same metadata fields, or the bulk allgather "
                "deadlocks/misaligns")
    n_groups = gmeta[:, 1]

    if getattr(ds, "_ingest_stats", None) is not None:
        # streaming construct: mappers were synced between its two
        # passes, so the shard is already binned against the global
        # boundaries — and there is no raw matrix to re-bin anyway
        local_bins = ds._bins
    else:
        # sync the FULL per-feature mapper list, not the used subset:
        # a feature trivial on this shard but not on rank 0's means the
        # per-rank used-feature selections differ, and binning against
        # a mismatched mapper list silently pairs columns with the
        # wrong boundaries before the shard allgather diverges/hangs.
        # Deriving used from the synced list makes every rank agree.
        ds._full_mappers = sync_bin_mappers(ds._full_mappers)
        used = [j for j, m in enumerate(ds._full_mappers)
                if not m.is_trivial]
        ds._used_features = np.asarray(used, np.int32)
        ds.mappers = [ds._full_mappers[j] for j in used]
        ds._F = len(ds.mappers)
        # re-bin the local rows against the synchronized boundaries
        Xf = np.asarray(X, np.float64)
        cols = [Xf[:, j] for j in ds._used_features]
        local_bins = bin_values(cols, ds.mappers)

    def gather_rows(a, dtype, what="rows"):
        if a is None:
            return None
        a = np.asarray(a, dtype)
        g = host_allgather(a, f"spmd/dataset_{what}")  # [P, n_local, ...]
        return np.concatenate(list(g), axis=0)

    # shard residency (parallel/placement.py, docs/SHARDING.md): under
    # shard_residency=device on a pod (device host-transport), the
    # BINNED rows are NOT allgathered — each rank keeps only its shard
    # plus the row offset, and the engine lays it directly into its
    # NamedSharding mesh slice, so the global binned matrix never
    # exists on any single host. The kv transport (CPU worlds) still
    # gathers — there the engine frees the host copy after upload.
    from ..config import resolve_params
    from .hostsync import transport
    residency = str(resolve_params(params).get("shard_residency",
                                               "auto"))
    # "auto" resolves to device-residency in the engine whenever a
    # multi-device mesh runs on an accelerator backend (gbdt.py) —
    # which a device-transport pod is by construction — so the default
    # config must keep shards local here too, or the advertised
    # allgather-skip would only ever fire for an explicit "device"
    keep_local = residency != "host" and transport() == "device"
    if keep_local:
        ds._bins = local_bins
        ds._local_row_offset = int(jax.process_index()) \
            * int(n_locals[0])
    else:
        ds._bins = gather_rows(local_bins, local_bins.dtype, "bins")
    ds._device_bins = None
    ds._n = int(n_locals.sum())
    ds.label = gather_rows(ds.label, np.float64, "label")
    ds.weight = gather_rows(ds.weight, np.float64, "weight")
    ds.init_score = gather_rows(ds.init_score, np.float64, "init_score")
    ds.position = gather_rows(ds.position, np.int32, "position")
    if ds.group is not None:
        # per-rank GROUP COUNTS legitimately differ (whole query
        # groups per shard) even with equal row counts; pad every
        # rank's vector to the max length from the meta gather so the
        # allgather shapes agree, then strip the -1 padding
        gmax = int(n_groups.max())
        gv = np.full((gmax,), -1, np.int32)
        gv[: int(n_groups[jax.process_index()])] = \
            np.asarray(ds.group, np.int32)
        g = host_allgather(gv, "spmd/dataset_group")
        ds.group = np.concatenate([row[row >= 0] for row in g], axis=0)
        # rebuild the query boundaries for the GLOBAL row set (the
        # shard-local ones from construct() cover only n_local rows)
        ds._query_boundaries = np.concatenate(
            [[0], np.cumsum(np.asarray(ds.group, np.int64))])
    # the raw feature matrix still holds only the local shard; drop it
    # so num_data()/get_data() stay consistent (raw-data consumers —
    # linear_tree, refit — raise their usual "raw data not retained"
    # errors instead of silently pairing half a matrix with global
    # labels)
    ds.data = None
    if keep_local:
        # the checkpoint fingerprint hashes the global label plus the
        # FIRST 64 binned rows — which live on rank 0's shard only.
        # Rank 0 computes, everyone joins the broadcast (TPL007: the
        # rank branch builds only the argument).
        from .hostsync import host_broadcast_bytes
        payload = None
        if jax.process_index() == 0:
            from ..data.ingest import dataset_digest
            payload = b"" if ds.label is None else dataset_digest(
                np.asarray(ds.label, np.float64), ds._bins).encode()
        buf = host_broadcast_bytes(payload, "spmd/dataset_digest")
        ds._data_digest = buf.decode() or None
    else:
        # a streaming construct's fingerprint covers the LOCAL shard;
        # the Dataset is global now, so drop it — the checkpoint layer
        # recomputes from the gathered label/bins
        # (resilience/checkpoint.py)
        ds._data_digest = None
    return ds
