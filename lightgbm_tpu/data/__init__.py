"""Out-of-core streaming ingestion + sharded binning (docs/DATA.md).

``Dataset(chunked_source | path, params={"ingest_chunk_rows": N})``
constructs training data without the dense float matrix ever existing:
:mod:`~lightgbm_tpu.data.sources` defines the re-iterable
:class:`RowChunkSource` protocol and its adapters (numpy array,
generator factory, ``Sequence``, CSV/TSV, import-guarded
Arrow/parquet); :mod:`~lightgbm_tpu.data.ingest` runs the two-pass
pipeline — sample -> BinMappers (host-synced under the collective
watchdog in multi-process worlds) -> chunk-by-chunk binning into the
preallocated per-host shard.

Host-side numpy only; importing this package never imports jax.
"""

from .ingest import (INGEST_FAULT_ITERATION, IngestResult,
                     dataset_digest, ingest_dataset)
from .sources import (DEFAULT_CHUNK_ROWS, ArrayChunkSource,
                      ArrowChunkSource, CSVChunkSource,
                      GeneratorChunkSource, RowChunk, RowChunkSource,
                      SequenceChunkSource, coerce_chunk_source)

__all__ = [
    "RowChunk", "RowChunkSource", "ArrayChunkSource",
    "GeneratorChunkSource", "SequenceChunkSource", "CSVChunkSource",
    "ArrowChunkSource", "coerce_chunk_source", "DEFAULT_CHUNK_ROWS",
    "ingest_dataset", "IngestResult", "dataset_digest",
    "INGEST_FAULT_ITERATION",
]
