"""Position-debiased lambdarank + prediction early stop
(rank_objective.hpp position bias; prediction_early_stop.cpp)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _ranking_data(n_query=60, per_q=12, f=8, seed=5):
    rs = np.random.RandomState(seed)
    n = n_query * per_q
    X = rs.randn(n, f)
    rel = X[:, 0] * 1.5 + 0.5 * rs.randn(n)
    y = np.digitize(rel, np.quantile(rel, [0.5, 0.75, 0.9])).astype(float)
    group = np.full(n_query, per_q)
    return X, y, group


def test_lambdarank_position_debias_trains():
    X, y, group = _ranking_data()
    n = len(y)
    position = np.tile(np.arange(12), n // 12)
    d = lgb.Dataset(X, label=y, group=group, position=position)
    bst = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                     "eval_at": [5], "num_leaves": 15, "min_data_in_leaf": 5,
                     "verbosity": -1,
                     "lambdarank_position_bias_regularization": 0.1},
                    d, num_boost_round=10, valid_sets=[d])
    obj = bst._engine.objective
    assert obj.num_pos == 12
    biases = np.asarray(obj.pos_biases)
    assert np.all(np.isfinite(biases)) and np.any(biases != 0.0)
    p = bst.predict(X)
    assert np.all(np.isfinite(p))


def test_pred_early_stop_binary_matches_when_margin_large():
    rs = np.random.RandomState(0)
    X = rs.randn(1500, 6)
    y = ((X @ rs.randn(6)) > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, d, num_boost_round=30)
    full = bst.predict(X, raw_score=True)
    # huge margin -> no row freezes -> identical
    es = bst.predict(X, raw_score=True, pred_early_stop=True,
                     pred_early_stop_freq=5, pred_early_stop_margin=1e9)
    np.testing.assert_allclose(full, es, rtol=1e-5, atol=1e-5)
    # tiny margin -> rows freeze after the first chunk; scores differ but
    # the sign (the decision) overwhelmingly agrees
    es2 = bst.predict(X, raw_score=True, pred_early_stop=True,
                      pred_early_stop_freq=5, pred_early_stop_margin=0.01)
    agree = np.mean(np.sign(es2) == np.sign(full))
    assert agree > 0.9


def test_pred_early_stop_multiclass():
    rs = np.random.RandomState(1)
    X = rs.randn(900, 5)
    y = np.argmax(X[:, :3] + 0.3 * rs.randn(900, 3), axis=1).astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1}, d,
                    num_boost_round=12)
    full = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=3,
                     pred_early_stop_margin=1e9)
    np.testing.assert_allclose(full, es, rtol=1e-5, atol=1e-5)
