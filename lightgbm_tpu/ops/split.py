"""Best-split search over histograms.

Re-design of FeatureHistogram::FindBestThreshold
(/root/reference/src/treelearner/feature_histogram.hpp:165 and the
numerical scan ``FindBestThresholdSequentially``) as a fully vectorized
two-direction prefix-scan over all features at once — no per-feature loop,
no template zoo; XLA fuses the whole search into a handful of kernels.

Missing handling matches the reference's dual scan: the left->right scan
sends the NaN bin right (default_left = False); the right->left scan is
realized as "NaN bin joined to the left side" (default_left = True).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["SplitParams", "SplitResult", "find_best_split"]

K_EPS = 1e-15
K_MIN_SCORE = -jnp.inf


class SplitParams(NamedTuple):
    """Static split-search hyperparameters (baked into the jitted fn)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    # categorical-split knobs (feature_histogram.hpp categorical path)
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0
    # leaf-output smoothing toward the parent's output
    # (CalculateSplittedLeafOutput USE_SMOOTHING, feature_histogram.hpp:732)
    path_smooth: float = 0.0
    # depth-based gain penalty on monotone-feature splits
    # (ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:357)
    monotone_penalty: float = 0.0


class SplitResult(NamedTuple):
    """Best split for one leaf (SplitInfo analog, split_info.hpp)."""
    gain: jnp.ndarray          # f32 scalar; <= 0 means "no valid split"
    feature: jnp.ndarray       # i32
    threshold_bin: jnp.ndarray  # i32
    default_left: jnp.ndarray  # bool
    is_cat: jnp.ndarray        # bool — categorical membership split
    cat_mask: jnp.ndarray      # [B] bool — bins routed left (cat splits)
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_count: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray


def _threshold_l1(s, l1):
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_g, sum_h, p: SplitParams):
    """Optimal leaf value -T_l1(g) / (h + l2), clipped by max_delta_step
    (CalculateSplittedLeafOutput, feature_histogram.hpp)."""
    w = -_threshold_l1(sum_g, p.lambda_l1) / (sum_h + p.lambda_l2 + K_EPS)
    if p.max_delta_step > 0.0:
        w = jnp.clip(w, -p.max_delta_step, p.max_delta_step)
    return w


def leaf_gain(sum_g, sum_h, p: SplitParams):
    """Gain of a leaf at its optimal (possibly clipped) output."""
    if p.max_delta_step > 0.0:
        w = leaf_output(sum_g, sum_h, p)
        t = _threshold_l1(sum_g, p.lambda_l1)
        return -(2.0 * t * w + (sum_h + p.lambda_l2) * w * w)
    t = _threshold_l1(sum_g, p.lambda_l1)
    return t * t / (sum_h + p.lambda_l2 + K_EPS)


def gain_at_output(sum_g, sum_h, w, p: SplitParams):
    """Leaf gain evaluated at a fixed (smoothed/clamped) output
    (GetLeafGainGivenOutput, feature_histogram.hpp)."""
    t = _threshold_l1(sum_g, p.lambda_l1)
    return -(2.0 * t * w + (sum_h + p.lambda_l2) * w * w)


def smooth_output(w, cnt, parent_output, p: SplitParams):
    """Shrink a leaf output toward its parent's:
    ``w*(n/s)/(n/s+1) + parent/(n/s+1)`` with s = path_smooth
    (CalculateSplittedLeafOutput USE_SMOOTHING, feature_histogram.hpp:734)."""
    if p.path_smooth <= 0.0:
        return w
    a = cnt / p.path_smooth
    return w * a / (a + 1.0) + parent_output / (a + 1.0)


def split_bounds_lrc(bounds):
    """Resolve a bounds spec into (left, right, cat) bound pairs.

    2-tuple (min, max): one bound for both children (basic/intermediate
    modes — scalars). 6-tuple (lmin_l, lmax_l, lmin_r, lmax_r, smin,
    smax): per-(feature, threshold) [F, B] arrays for the left/right
    children plus scalar fallbacks for categorical candidates — the
    monotone precise mode (AdvancedLeafConstraints,
    monotone_constraints.hpp:858)."""
    if bounds is None:
        return None, None, None
    if len(bounds) == 6:
        return ((bounds[0], bounds[1]), (bounds[2], bounds[3]),
                (bounds[4], bounds[5]))
    return bounds, bounds, bounds


def _parent_gain_shifted(total, p: SplitParams, p_out):
    """Parent gain at its (path-smoothed) output + min_gain_to_split —
    the per-candidate shift both searches subtract before the argmax
    (ComputeBestSplitForFeature's gain_shift)."""
    if p.path_smooth > 0.0:
        w_parent = smooth_output(leaf_output(total[0], total[1], p),
                                 total[2], p_out, p)
        parent_gain = gain_at_output(total[0], total[1], w_parent, p)
    else:
        parent_gain = leaf_gain(total[0], total[1], p)
    return parent_gain + p.min_gain_to_split


def _winner_outputs(lgs, lhs, lcs, rgs, rhs, rcs, is_sorted_cat,
                    exact, p: SplitParams, p_out, b_lw, b_rw):
    """The winning split's child outputs: sorted-categorical winners
    use l2 + cat_l2 (feature_histogram.cpp:144); the exact path
    smooths and clamps (CalculateSplittedLeafOutput composition)."""
    p_cat = p._replace(lambda_l2=p.lambda_l2 + p.cat_l2)
    if exact:
        lo = jnp.where(
            is_sorted_cat,
            constrained_output(lgs, lhs, lcs, p_out, b_lw, p_cat),
            constrained_output(lgs, lhs, lcs, p_out, b_lw, p))
        ro = jnp.where(
            is_sorted_cat,
            constrained_output(rgs, rhs, rcs, p_out, b_rw, p_cat),
            constrained_output(rgs, rhs, rcs, p_out, b_rw, p))
    else:
        lo = jnp.where(is_sorted_cat, leaf_output(lgs, lhs, p_cat),
                       leaf_output(lgs, lhs, p))
        ro = jnp.where(is_sorted_cat, leaf_output(rgs, rhs, p_cat),
                       leaf_output(rgs, rhs, p))
    return lo, ro


def constrained_output(sum_g, sum_h, cnt, parent_output, bounds,
                       p: SplitParams):
    """Optimal output, then smoothing, then monotone min/max clamp — the
    composition order of CalculateSplittedLeafOutput<USE_MC,...>."""
    w = leaf_output(sum_g, sum_h, p)
    w = smooth_output(w, cnt, parent_output, p)
    if bounds is not None:
        w = jnp.clip(w, bounds[0], bounds[1])
    return w


def monotone_penalty_mult(leaf_depth, p: SplitParams):
    """Gain multiplier for monotone-feature splits at a given depth
    (ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:357-366)."""
    pen = p.monotone_penalty
    d = leaf_depth.astype(jnp.float32)
    if pen <= 0.0:
        return jnp.asarray(1.0, jnp.float32)
    if pen <= 1.0:
        base = 1.0 - pen / jnp.exp2(d) + K_EPS
    else:
        base = 1.0 - jnp.exp2(pen - 1.0 - d) + K_EPS
    return jnp.where(pen >= d + 1.0, K_EPS, base)


def _cat_split_eval(hist, parent_g, parent_h, parent_cnt,
                    feat_num_bins, p: SplitParams,
                    parent_output=None, bounds=None):
    """Categorical split candidates, vectorized over all features.

    Mirrors FindBestThresholdCategoricalInner
    (src/treelearner/feature_histogram.cpp:144):
    - features with <= max_cat_to_onehot bins: one-hot scan — each bin as
      a left-singleton, plain lambda_l2;
    - otherwise: bins with enough data sorted ascending by
      g / (h + cat_smooth); prefix scans from both ends, left-set size
      capped at min(max_cat_threshold, (used+1)//2), l2 += cat_l2.
    Deviation from the reference: the sequential ``cnt_cur_group``
    min_data_per_group regrouping is relaxed to the (necessary) condition
    ``left_count >= min_data_per_group`` — the reference's rule is a
    path-dependent scan that would serialize the TPU program; the
    relaxation admits a superset of candidate prefixes.

    Returns (gains_oh, gains_fwd, gains_bwd, csum_f, csum_b, aux) where
    gains_* are [F, B] (position-indexed for fwd/bwd) and aux carries the
    sort order data needed to reconstruct the winning bin set.
    """
    F, B, _ = hist.shape
    dtype = hist.dtype
    bins = jnp.arange(B)
    in_range = bins[None, :] < feat_num_bins[:, None]
    h3 = jnp.where(in_range[:, :, None], hist, jnp.zeros_like(hist))
    g, h, c = h3[..., 0], h3[..., 1], h3[..., 2]
    p_cat = p._replace(lambda_l2=p.lambda_l2 + p.cat_l2)
    exact = p.path_smooth > 0.0 or bounds is not None

    def pair_gain(lg_, lh_, lc_, rg_, rh_, rc_, pp):
        if not exact:
            return leaf_gain(lg_, lh_, pp) + leaf_gain(rg_, rh_, pp)
        wl = constrained_output(lg_, lh_, lc_, parent_output, bounds, pp)
        wr = constrained_output(rg_, rh_, rc_, parent_output, bounds, pp)
        return gain_at_output(lg_, lh_, wl, pp) \
            + gain_at_output(rg_, rh_, wr, pp)

    # ---- one-hot path (left = one category bin) ----
    rg, rh, rc = parent_g - g, parent_h - h, parent_cnt - c
    valid_oh = (
        in_range
        & (c >= p.min_data_in_leaf) & (rc >= p.min_data_in_leaf)
        & (h >= p.min_sum_hessian_in_leaf)
        & (rh >= p.min_sum_hessian_in_leaf)
        & (c > 0) & (rc > 0)
    )
    gain_oh = pair_gain(g, h, c, rg, rh, rc, p)
    use_onehot = feat_num_bins <= p.max_cat_to_onehot  # [F]
    gains_oh = jnp.where(use_onehot[:, None] & valid_oh, gain_oh,
                         K_MIN_SCORE)

    # ---- sorted-subset path ----
    participate = in_range & (c >= p.cat_smooth)
    ratio = jnp.where(participate, g / (h + p.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1, stable=True)          # [F, B]
    inv = jnp.argsort(order, axis=1, stable=True)            # bin -> rank
    used = jnp.sum(participate, axis=1).astype(jnp.int32)    # [F]
    part_sorted = jnp.take_along_axis(participate, order, axis=1)
    stats_sorted = jnp.take_along_axis(h3, order[:, :, None], axis=1) \
        * part_sorted[:, :, None].astype(dtype)
    csum_f = jnp.cumsum(stats_sorted, axis=1)                # [F, B, 3]
    rev_pos = jnp.clip(used[:, None] - 1 - bins[None, :], 0, B - 1)
    stats_rev = jnp.take_along_axis(stats_sorted, rev_pos[:, :, None],
                                    axis=1)
    csum_b = jnp.cumsum(stats_rev, axis=1)

    max_num_cat = jnp.minimum(p.max_cat_threshold, (used + 1) // 2)
    pos_ok = (bins[None, :] < max_num_cat[:, None]) \
        & (bins[None, :] < used[:, None])
    right_min = max(p.min_data_in_leaf, p.min_data_per_group)

    def prefix_gains(csum):
        lg, lh, lc = csum[..., 0], csum[..., 1], csum[..., 2]
        rg_, rh_, rc_ = parent_g - lg, parent_h - lh, parent_cnt - lc
        valid = (
            pos_ok
            & (lc >= p.min_data_in_leaf) & (lc >= p.min_data_per_group)
            & (lh >= p.min_sum_hessian_in_leaf)
            & (rc_ >= right_min) & (rh_ >= p.min_sum_hessian_in_leaf)
            & (lc > 0) & (rc_ > 0)
        )
        gain = pair_gain(lg, lh, lc, rg_, rh_, rc_, p_cat)
        return jnp.where(valid & ~use_onehot[:, None], gain, K_MIN_SCORE)

    gains_fwd = prefix_gains(csum_f)
    gains_bwd = prefix_gains(csum_b)
    aux = (inv, used, participate)
    return gains_oh, gains_fwd, gains_bwd, csum_f, csum_b, aux


def find_best_split(hist: jnp.ndarray,
                    parent_g: jnp.ndarray,
                    parent_h: jnp.ndarray,
                    parent_cnt: jnp.ndarray,
                    feat_num_bins: jnp.ndarray,
                    feat_nan_bin: jnp.ndarray,
                    feature_mask: jnp.ndarray,
                    p: SplitParams,
                    monotone_constraints: jnp.ndarray | None = None,
                    feat_is_cat: jnp.ndarray | None = None,
                    gain_penalty: jnp.ndarray | None = None,
                    parent_output: jnp.ndarray | None = None,
                    leaf_depth: jnp.ndarray | None = None,
                    bounds: tuple | None = None,
                    return_feature_gains: bool = False):
    """Find the best (feature, threshold) over a leaf's histograms.

    Args:
      hist: ``[F, B, 2]`` (sum_g, sum_h) per feature/bin — histogram
        entries carry no counts, exactly like the reference
        (``kHistEntrySize = 2 * sizeof(hist_t)``, bin.h:39).
      parent_g/h/cnt: scalars — the leaf's total stats (``parent_cnt``
        is the exact partition count).
      feat_num_bins: ``[F]`` i32 — #bins actually used per feature.
      feat_nan_bin: ``[F]`` i32 — index of the NaN bin, or -1.
      feature_mask: ``[F]`` bool — column-sampling / trivial-feature mask.
      monotone_constraints: optional ``[F]`` i8 in {-1, 0, +1}.
      gain_penalty: optional ``[F]`` — per-feature gain penalty (CEGB
        DeltaGain) subtracted from every candidate of that feature.
      parent_output: scalar — the leaf's current output value, used by
        path smoothing (GetParentOutput, serial_tree_learner.cpp:1005).
      leaf_depth: scalar i32 — depth of the leaf, drives the
        monotone_penalty gain multiplier.
      bounds: optional (min, max) scalars — the leaf's monotone output
        constraint entry (BasicConstraint); candidate outputs are
        clamped into this interval before gains are evaluated.

    Returns a scalar SplitResult; ``gain`` is already shifted by the parent
    gain and min_gain_to_split (so "> 0" means worth splitting). The
    returned left/right counts are hessian-ratio estimates
    ``cnt = round(hess * num_data / sum_hessian)``
    (feature_histogram.hpp:528,543) — callers holding real partition
    counts overwrite them (SplitInner, serial_tree_learner.cpp:789).
    """
    F, B, _ = hist.shape
    dtype = hist.dtype
    # synthesize the per-bin count channel from the hessian ratio, rounded
    # per bin exactly like the reference's scan accumulates RoundInt(...)
    cnt_factor = parent_cnt / jnp.maximum(parent_h, K_EPS)
    hist = jnp.concatenate(
        [hist, jnp.round(hist[..., 1:2] * cnt_factor)], axis=-1)
    total = jnp.stack([parent_g, parent_h, parent_cnt]).astype(dtype)

    has_nan = feat_nan_bin >= 0
    nan_stats = jnp.where(
        has_nan[:, None],
        jnp.take_along_axis(
            hist, jnp.maximum(feat_nan_bin, 0)[:, None, None].repeat(3, -1),
            axis=1)[:, 0, :],
        jnp.zeros((F, 3), dtype=dtype))  # [F, 3]

    bins = jnp.arange(B)
    # exclude the missing bin (NaN bin, or the zero bin for zero_as_missing
    # features — it may sit mid-range) from the prefix scan: missing rows
    # join a side via the learned default direction, never the threshold.
    miss_onehot = (bins[None, :] == jnp.maximum(feat_nan_bin, 0)[:, None]) \
        & has_nan[:, None]
    cum = jnp.cumsum(
        hist - miss_onehot[:, :, None] * nan_stats[:, None, :], axis=1)

    exact = p.path_smooth > 0.0 or bounds is not None
    p_out = jnp.asarray(0.0, dtype) if parent_output is None \
        else parent_output
    bounds_l, bounds_r, bounds_c = split_bounds_lrc(bounds)

    def eval_dir(left: jnp.ndarray, t_valid: jnp.ndarray):
        right = total[None, None, :] - left
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = right[..., 0], right[..., 1], right[..., 2]
        valid = (
            t_valid
            & (lc >= p.min_data_in_leaf) & (rc >= p.min_data_in_leaf)
            & (lh >= p.min_sum_hessian_in_leaf)
            & (rh >= p.min_sum_hessian_in_leaf)
            & (lc > 0) & (rc > 0)
        )
        if exact:
            lo = constrained_output(lg, lh, lc, p_out, bounds_l, p)
            ro = constrained_output(rg, rh, rc, p_out, bounds_r, p)
            gain = gain_at_output(lg, lh, lo, p) \
                + gain_at_output(rg, rh, ro, p)
        else:
            lo = ro = None
            gain = leaf_gain(lg, lh, p) + leaf_gain(rg, rh, p)
        if monotone_constraints is not None:
            if lo is None:
                lo = leaf_output(lg, lh, p)
                ro = leaf_output(rg, rh, p)
            mc = monotone_constraints[:, None]
            valid = valid & ~((mc > 0) & (lo > ro)) & ~((mc < 0) & (lo < ro))
        return jnp.where(valid, gain, K_MIN_SCORE)

    # direction 1: missing goes right — thresholds t in [0, nb-1]; the
    # lc>0/rc>0 validity checks prune degenerate all-left/all-right cuts.
    t_valid_r = bins[None, :] < feat_num_bins[:, None]
    gains_r = eval_dir(cum, t_valid_r)

    # direction 2: missing goes left — only exists for missing-typed
    # features; t = nb-1 would put everything left (rc=0, pruned anyway).
    left_l = cum + nan_stats[:, None, :]
    t_valid_l = has_nan[:, None] & (bins[None, :] < (feat_num_bins - 1)[:, None])
    gains_l = eval_dir(left_l, t_valid_l)

    fmask = feature_mask[:, None]
    gains_r = jnp.where(fmask, gains_r, K_MIN_SCORE)
    gains_l = jnp.where(fmask, gains_l, K_MIN_SCORE)

    if feat_is_cat is not None:
        num_ok = ~feat_is_cat[:, None]
        gains_r = jnp.where(num_ok, gains_r, K_MIN_SCORE)
        gains_l = jnp.where(num_ok, gains_l, K_MIN_SCORE)
        g_oh, g_fwd, g_bwd, csum_f, csum_b, (inv, used, participate) = \
            _cat_split_eval(hist, total[0], total[1], total[2],
                            feat_num_bins, p, p_out, bounds_c)
        cmask = fmask & feat_is_cat[:, None]
        g_oh = jnp.where(cmask, g_oh, K_MIN_SCORE)
        g_fwd = jnp.where(cmask, g_fwd, K_MIN_SCORE)
        g_bwd = jnp.where(cmask, g_bwd, K_MIN_SCORE)
        stacks = [gains_r, gains_l, g_oh, g_fwd, g_bwd]
    else:
        stacks = [gains_r, gains_l]

    # shift every candidate to its NET gain before the argmax: the
    # reference compares per-feature SplitInfo.gain values that are
    # already ``raw - gain_shift - DeltaGain``, optionally scaled by the
    # monotone depth penalty (ComputeBestSplitForFeature,
    # serial_tree_learner.cpp:988-997) — the scaling changes the
    # cross-feature ranking, so it must precede the argmax.
    shift = _parent_gain_shifted(total, p, p_out)
    if gain_penalty is not None:
        nets = [g - shift - gain_penalty[:, None] for g in stacks]
    else:
        nets = [g - shift for g in stacks]
    if monotone_constraints is not None and p.monotone_penalty > 0.0:
        depth = jnp.asarray(0, jnp.int32) if leaf_depth is None \
            else leaf_depth
        mult = monotone_penalty_mult(depth, p).astype(dtype)
        is_mono = (monotone_constraints != 0)[:, None]
        nets = [jnp.where(is_mono, g * mult, g) for g in nets]
    # argmax with deterministic tie-breaking: lower (dir, feature, bin) wins
    all_gains = jnp.stack(nets)  # [D, F, B]
    flat_idx = jnp.argmax(all_gains)
    best_gain_net = all_gains.reshape(-1)[flat_idx]
    d = flat_idx // (F * B)
    f = (flat_idx // B) % F
    t = flat_idx % B

    if feat_is_cat is not None:
        is_cat = d >= 2
        is_sorted_cat = d >= 3
        bins_b = jnp.arange(B)
        onehot_mask = bins_b == t
        fwd_mask = participate[f] & (inv[f] <= t)
        bwd_mask = participate[f] & (inv[f] >= used[f] - 1 - t)
        cat_mask = jnp.where(
            is_cat,
            jnp.where(d == 2, onehot_mask,
                      jnp.where(d == 3, fwd_mask, bwd_mask)),
            jnp.zeros((B,), jnp.bool_))
        num_left = jnp.where(d == 0, cum[f, t, :],
                             cum[f, t, :] + nan_stats[f, :])
        cat_left = jnp.where(d == 2, hist[f, t, :],
                             jnp.where(d == 3, csum_f[f, t, :],
                                       csum_b[f, t, :]))
        sel_left = jnp.where(is_cat, cat_left, num_left)
    else:
        is_cat = jnp.asarray(False)
        is_sorted_cat = jnp.asarray(False)
        cat_mask = jnp.zeros((B,), jnp.bool_)
        sel_left = jnp.where(
            d == 0,
            cum[f, t, :],
            cum[f, t, :] + nan_stats[f, :],
        )
    lg, lh, lc = sel_left[0], sel_left[1], sel_left[2]
    rg, rh, rc = total[0] - lg, total[1] - lh, total[2] - lc

    gain = jnp.where(jnp.isfinite(best_gain_net), best_gain_net,
                     K_MIN_SCORE)

    # the winner's bounds: scalar pair as-is, or — for the advanced
    # per-(feature, threshold) arrays — the values at (f, t) for
    # the numeric winner / the scalar fallbacks for a cat winner
    b_lw = b_rw = bounds
    if bounds is not None and len(bounds) == 6:
        b_lw = (jnp.where(is_cat, bounds[4], bounds[0][f, t]),
                jnp.where(is_cat, bounds[5], bounds[1][f, t]))
        b_rw = (jnp.where(is_cat, bounds[4], bounds[2][f, t]),
                jnp.where(is_cat, bounds[5], bounds[3][f, t]))
    lo, ro = _winner_outputs(lg, lh, lc, rg, rh, rc, is_sorted_cat,
                             exact, p, p_out, b_lw, b_rw)

    result = SplitResult(
        gain=gain.astype(dtype),
        feature=f.astype(jnp.int32),
        threshold_bin=t.astype(jnp.int32),
        default_left=(d == 1),
        is_cat=is_cat,
        cat_mask=cat_mask,
        left_sum_g=lg, left_sum_h=lh, left_count=lc,
        right_sum_g=rg, right_sum_h=rh, right_count=rc,
        left_output=lo,
        right_output=ro,
    )
    if return_feature_gains:
        # best net gain per feature — the voting-parallel learner's
        # local ballot (VotingParallelTreeLearner top-k proposals)
        return result, jnp.max(all_gains, axis=(0, 2))
    return result


def find_best_split_bundled(hist: jnp.ndarray,
                            parent_g: jnp.ndarray,
                            parent_h: jnp.ndarray,
                            parent_cnt: jnp.ndarray,
                            member_at: jnp.ndarray,
                            tloc_at: jnp.ndarray,
                            end_at: jnp.ndarray,
                            is_direct_f: jnp.ndarray,
                            nanpos_at: jnp.ndarray,
                            nan_at: jnp.ndarray,
                            feature_mask: jnp.ndarray,
                            p: SplitParams,
                            feat_is_cat: jnp.ndarray | None = None,
                            feat_num_bins: jnp.ndarray | None = None,
                            gain_penalty: jnp.ndarray | None = None,
                            col_mask: jnp.ndarray | None = None,
                            return_col_gains: bool = False,
                            monotone_constraints: jnp.ndarray | None = None,
                            parent_output: jnp.ndarray | None = None,
                            leaf_depth: jnp.ndarray | None = None,
                            bounds: tuple | None = None):
    """Best split over an EFB-bundled histogram (ops/bundling.py layout).

    Every candidate is one (bundle, position) cell:
    - direct (singleton) bundles behave exactly like the plain scan:
      ``left = cum[position]`` with threshold = position;
    - multi-member bundles host member thresholds at their mapped
      positions, with ``left = leaf_total - (range_end_cum - cum)`` -
      the member's bin-0 mass reconstructed from the leaf totals (the
      FixHistogram / most_freq_bin trick, dataset.h:760).
    Members with a NaN bin (direct OR multi) get the plain search's
    dual missing-direction scan: the NaN position (``nan_at``) is
    excluded from prefix sums and thresholds, and its mass
    (``nanpos_at``) joins whichever side the scanned direction sends
    missing rows to.

    Categorical members (round 5; FindGroups is type-blind,
    dataset.cpp): a bundled cat member is always in the one-hot regime
    (bundling caps membership at max_cat_to_onehot), so its candidates
    are one-hot per position — the position's own mass for tail
    categories, and the reconstructed default (bin-0 = most-frequent
    category) mass for t=0 — exactly the plain one-hot scan. Direct
    singleton cat columns carry their histogram verbatim, so the full
    plain machinery (_cat_split_eval: one-hot AND sorted-subset)
    runs on them unchanged.
    """
    G, B, _ = hist.shape
    dtype = hist.dtype
    cnt_factor = parent_cnt / jnp.maximum(parent_h, K_EPS)
    h3 = jnp.concatenate([hist, jnp.round(hist[..., 1:2] * cnt_factor)],
                         axis=-1)
    total = jnp.stack([parent_g, parent_h, parent_cnt]).astype(dtype)

    has_member = member_at >= 0
    member_ix = jnp.maximum(member_at, 0)
    direct_pos = is_direct_f[member_ix] & has_member
    # NaN-bin positions are excluded from the prefix scan exactly like
    # the plain search (missing rows join a side via the learned
    # default direction, never the threshold)
    has_nan = nanpos_at >= 0                               # [G, B]
    cum = jnp.cumsum(
        h3 * (~nan_at)[:, :, None].astype(dtype), axis=1)
    cum_flat = cum.reshape(G * B, 3)
    e = cum_flat[jnp.clip(end_at, 0, G * B - 1).reshape(-1)] \
        .reshape(G, B, 3)
    h3_flat = h3.reshape(G * B, 3)
    nan_stats = h3_flat[jnp.clip(nanpos_at, 0, G * B - 1).reshape(-1)] \
        .reshape(G, B, 3)
    nan_stats = nan_stats * has_nan[:, :, None].astype(dtype)

    if feat_is_cat is not None:
        is_cat_pos = feat_is_cat[member_ix] & has_member   # [G, B]
    else:
        is_cat_pos = jnp.zeros((G, B), jnp.bool_)
    if col_mask is not None:
        # feature-parallel: only this device's OWNED bundle columns
        # may propose candidates (window overlap on tail devices is
        # resolved by ownership, exactly like the plain fp search)
        has_member = has_member & col_mask[:, None]

    # monotone / path-smoothing support mirrors the plain search's
    # eval_dir: gains via (smoothed, clamped) outputs when exact,
    # directional validity per member's constraint sign — NEVER
    # applied to categorical candidates (plain cat gains bypass
    # direction checks too). Bounds are scalar pairs
    # (basic/intermediate) or — advanced mode — per-(feature,
    # threshold) [F_orig, B] arrays, gathered into candidate space
    # through the position->member map.
    exact = p.path_smooth > 0.0 or bounds is not None
    p_out = jnp.asarray(0.0, dtype) if parent_output is None \
        else parent_output
    bounds_l, bounds_r, bounds_c = split_bounds_lrc(bounds)
    adv = bounds is not None and len(bounds) == 6
    if adv:
        def _gpos(arr):
            # [F_orig, Bf] -> per-candidate [G, B]: the member's bound
            # at its local threshold bin (invalid cells are masked by
            # has_member before they can win)
            return arr[member_ix,
                       jnp.clip(tloc_at, 0, arr.shape[1] - 1)]

        bounds_l = (_gpos(bounds_l[0]), _gpos(bounds_l[1]))
        bounds_r = (_gpos(bounds_r[0]), _gpos(bounds_r[1]))
    if monotone_constraints is not None:
        # direction validity never applies to categorical candidates
        # (the plain cat families bypass it too)...
        mc_pos = jnp.where(is_cat_pos, 0,
                           monotone_constraints[member_ix])  # [G, B]
        # ...but the depth PENALTY rescales every candidate of a
        # constrained feature, cat or not (the plain search scales all
        # five stacks via is_mono per feature)
        mono_pos = (monotone_constraints[member_ix] != 0) & has_member
    else:
        mc_pos = None
        mono_pos = None

    def eval_left(left, extra_valid, bl=None, br=None):
        if bl is None:
            bl, br = bounds_l, bounds_r
        right = total[None, None, :] - left
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = right[..., 0], right[..., 1], right[..., 2]
        valid = (
            extra_valid & has_member & feature_mask[member_ix]
            & (lc >= p.min_data_in_leaf) & (rc >= p.min_data_in_leaf)
            & (lh >= p.min_sum_hessian_in_leaf)
            & (rh >= p.min_sum_hessian_in_leaf)
            & (lc > 0) & (rc > 0)
        )
        if exact:
            lo_ = constrained_output(lg, lh, lc, p_out, bl, p)
            ro_ = constrained_output(rg, rh, rc, p_out, br, p)
            gain = gain_at_output(lg, lh, lo_, p) \
                + gain_at_output(rg, rh, ro_, p)
        else:
            lo_ = ro_ = None
            gain = leaf_gain(lg, lh, p) + leaf_gain(rg, rh, p)
        if mc_pos is not None:
            if lo_ is None:
                lo_ = leaf_output(lg, lh, p)
                ro_ = leaf_output(rg, rh, p)
            valid = valid & ~((mc_pos > 0) & (lo_ > ro_)) \
                & ~((mc_pos < 0) & (lo_ < ro_))
        return jnp.where(valid, gain, K_MIN_SCORE)

    # direction 1: missing goes right. For multi members the member's
    # right side is its positions in (t, range_end] (NaN excluded by
    # cum) plus its NaN mass; left = total - right. Like the plain
    # scan, every member threshold is a candidate — the cut at the NaN
    # position duplicates its neighbor and is tolerated (degenerate
    # cuts are pruned by the lc/rc validity checks).
    left1 = jnp.where(direct_pos[:, :, None], cum,
                      total[None, None, :] - (e - cum) - nan_stats)
    g1 = eval_left(left1, ~is_cat_pos)
    # direction 2: missing joins the left side (NaN members only)
    left2 = jnp.where(direct_pos[:, :, None], cum + nan_stats,
                      total[None, None, :] - (e - cum))
    g2 = eval_left(left2, has_nan & ~is_cat_pos)

    shift = _parent_gain_shifted(total, p, p_out)
    if gain_penalty is not None:
        # CEGB DeltaGain per ORIGINAL feature, looked up through the
        # position->member map (cost_effective_gradient_boosting.hpp)
        shift = shift + jnp.where(has_member,
                                  gain_penalty[member_ix], 0.0)
    stacks = [g1 - shift, g2 - shift]

    if feat_is_cat is not None:
        # member num_bins at each position (nb = end - pos + tloc + 1
        # holds for both layouts: direct tloc == pos, end == nb - 1;
        # multi pos == off + tloc - 1, end == off + nb - 2)
        end_pos = end_at - (jnp.arange(G) * B)[:, None]
        nb_at = end_pos - jnp.arange(B)[None, :] + tloc_at + 1
        use_oh = nb_at <= p.max_cat_to_onehot
        # one-hot family: tail category = the position's own mass;
        # the default category (t=0) = the member's reconstructed
        # bin-0 mass (for direct columns bin 0 is stored, h3 works)
        left_oh = jnp.where(
            ((tloc_at == 0) & ~direct_pos)[:, :, None],
            total[None, None, :] - (e - cum), h3)
        # cat candidates take the CAT bounds (scalar fallbacks in
        # advanced mode), like the plain _cat_split_eval path
        g_oh = eval_left(left_oh, is_cat_pos & use_oh,
                         bounds_c, bounds_c)
        # sorted-subset family for direct wide-cat columns: their rows
        # of the bundle histogram ARE the feature histograms, so the
        # plain machinery runs verbatim
        direct_member = member_ix[:, 0]
        col_cat = is_direct_f[direct_member] \
            & feat_is_cat[direct_member] & (member_at[:, 0] >= 0)
        if col_mask is not None:
            col_cat = col_cat & col_mask
        col_nb = jnp.where(
            col_cat,
            feat_num_bins[direct_member] if feat_num_bins is not None
            else 0, 0)
        _, g_fwd, g_bwd, csum_f, csum_b, (inv, used, participate) = \
            _cat_split_eval(h3, total[0], total[1], total[2],
                            col_nb, p, p_out, bounds_c)
        cmask2 = (col_cat & feature_mask[direct_member])[:, None]
        g_fwd = jnp.where(cmask2, g_fwd, K_MIN_SCORE)
        g_bwd = jnp.where(cmask2, g_bwd, K_MIN_SCORE)
        stacks += [g_oh - shift, g_fwd - shift, g_bwd - shift]

    net = jnp.stack(stacks)                       # [D, G, B]
    if mono_pos is not None and p.monotone_penalty > 0.0:
        # the penalty rescales constrained features' NET gains before
        # the argmax (ComputeBestSplitForFeature ordering)
        depth_ = jnp.asarray(0, jnp.int32) if leaf_depth is None \
            else leaf_depth
        mult = monotone_penalty_mult(depth_, p).astype(dtype)
        net = jnp.where(mono_pos[None], net * mult, net)
    net = jnp.where(jnp.isfinite(net), net, K_MIN_SCORE)

    flat = jnp.argmax(net)
    d = flat // (G * B)
    g = (flat // B) % G
    pos = flat % B
    best = net.reshape(-1)[flat]
    if feat_is_cat is not None:
        sel = jnp.stack([left1[g, pos], left2[g, pos], left_oh[g, pos],
                         csum_f[g, pos], csum_b[g, pos]])[d]
        is_cat_win = d >= 2
        is_sorted_cat = d >= 3
        bpos = jnp.arange(B)
        oh_mask = bpos == tloc_at[g, pos]
        fwd_mask = participate[g] & (inv[g] <= pos)
        bwd_mask = participate[g] & (inv[g] >= used[g] - 1 - pos)
        cat_mask = jnp.where(
            is_cat_win,
            jnp.where(d == 2, oh_mask,
                      jnp.where(d == 3, fwd_mask, bwd_mask)),
            jnp.zeros((B,), jnp.bool_))
    else:
        sel = jnp.where(d == 0, left1[g, pos], left2[g, pos])
        is_cat_win = jnp.asarray(False)
        is_sorted_cat = jnp.asarray(False)
        cat_mask = jnp.zeros((B,), jnp.bool_)
    lgs, lhs, lcs = sel[0], sel[1], sel[2]
    rgs, rhs, rcs = total[0] - lgs, total[1] - lhs, total[2] - lcs
    if adv:
        # the winner's bounds: the gathered value at (g, pos) for a
        # numeric winner, the scalar cat fallbacks otherwise
        b_lw = (jnp.where(is_cat_win, bounds[4], bounds_l[0][g, pos]),
                jnp.where(is_cat_win, bounds[5], bounds_l[1][g, pos]))
        b_rw = (jnp.where(is_cat_win, bounds[4], bounds_r[0][g, pos]),
                jnp.where(is_cat_win, bounds[5], bounds_r[1][g, pos]))
    else:
        b_lw, b_rw = bounds_l, bounds_r
    lo, ro = _winner_outputs(lgs, lhs, lcs, rgs, rhs, rcs,
                             is_sorted_cat, exact, p, p_out,
                             b_lw, b_rw)
    result = SplitResult(
        gain=jnp.where(jnp.isfinite(best), best, K_MIN_SCORE)
        .astype(dtype),
        feature=member_at[g, pos].astype(jnp.int32),
        threshold_bin=tloc_at[g, pos].astype(jnp.int32),
        default_left=(d == 1),
        is_cat=is_cat_win,
        cat_mask=cat_mask,
        left_sum_g=lgs, left_sum_h=lhs, left_count=lcs,
        right_sum_g=rgs, right_sum_h=rhs, right_count=rcs,
        left_output=lo,
        right_output=ro)
    if return_col_gains:
        # best net gain per bundle COLUMN — the voting-parallel local
        # ballot in bundle space (VotingParallelTreeLearner top-k)
        return result, jnp.max(net, axis=(0, 2))
    return result
