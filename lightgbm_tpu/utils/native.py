"""Native extension loader: compile-on-first-use C++ via ctypes.

The reference ships its runtime (text parsing, IO) as compiled C++
(src/io/parser.cpp, text_reader.h). Here the native piece is built
lazily with the system toolchain and loaded through ctypes — no
pybind11, no install step; everything degrades to the numpy paths when
a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

from .log import log_warning

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "native")
_LIB = None
_LIB_TRIED = False


def _build_dir() -> str:
    """Per-user 0700 cache dir: a shared predictable /tmp path would
    let another local user plant a .so at the known hash name
    (CWE-379)."""
    d = os.environ.get("LIGHTGBM_TPU_BUILD_DIR")
    if not d:
        base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
            os.path.expanduser("~"), ".cache")
        d = os.path.join(base, "lightgbm_tpu", "native")
    os.makedirs(d, mode=0o700, exist_ok=True)
    st = os.stat(d)
    if st.st_uid != os.getuid():
        raise PermissionError(f"native build dir {d} not owned by us")
    return d


def _load() -> Optional[ctypes.CDLL]:
    """Compile (if needed) and dlopen the fastparse library."""
    global _LIB, _LIB_TRIED
    if _LIB is not None or _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
        return None
    src = os.path.join(_NATIVE_DIR, "fastparse.cpp")
    if not os.path.exists(src):
        return None
    with open(src, "rb") as fh:
        tag = hashlib.sha256(fh.read()).hexdigest()[:16]
    try:
        so = os.path.join(_build_dir(), f"fastparse_{tag}.so")
    except PermissionError as e:
        log_warning(f"native fastparse disabled: {e}")
        return None
    if not os.path.exists(so):
        # compile to a private temp name, then atomic-rename: a
        # concurrent process never dlopens a half-written file
        tmp = f"{so}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
               "-fopenmp", src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, so)
        except Exception as e:  # compiler missing / failed: fall back
            log_warning(f"native fastparse build failed ({e}); "
                        "falling back to numpy text parsing")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        log_warning(f"native fastparse load failed ({e})")
        return None
    lib.ltpu_sniff.restype = ctypes.c_int
    lib.ltpu_sniff.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_char)]
    lib.ltpu_parse_dense.restype = ctypes.c_int64
    lib.ltpu_parse_dense.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_char,
        ctypes.c_int64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")]
    lib.ltpu_bin_columns.restype = None
    lib.ltpu_bin_columns.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_void_p, ctypes.c_int]
    _LIB = lib
    return lib


def parse_dense_text(path: str, skip_header: bool) -> Optional[np.ndarray]:
    """Parse a delimited numeric file to [rows, cols] float64 with the
    native parser; None when native is unavailable (caller falls back
    to numpy)."""
    lib = _load()
    if lib is None:
        return None
    with open(path, "rb") as fh:
        buf = fh.read()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    delim = ctypes.c_char()
    rc = lib.ltpu_sniff(buf, len(buf), int(skip_header),
                        ctypes.byref(rows), ctypes.byref(cols),
                        ctypes.byref(delim))
    if rc != 0 or rows.value <= 0 or cols.value <= 0:
        return None
    out = np.empty((rows.value, cols.value), np.float64)
    got = lib.ltpu_parse_dense(buf, len(buf), int(skip_header),
                               delim.value, rows.value, cols.value, out)
    if got != rows.value:
        out = out[:got]
    return out


def bin_columns_native(X: np.ndarray, col_indices: np.ndarray,
                       bounds_list, nan_to: np.ndarray,
                       out_dtype) -> Optional[np.ndarray]:
    """Bin numerical columns of a row-major matrix with the native
    kernel (ltpu_bin_columns); None when native is unavailable or the
    matrix dtype is unsupported (caller falls back to numpy).

    ``bounds_list``: per-selected-column float64 ascending upper
    bounds; ``nan_to``: per-selected-column target bin for NaN cells.
    """
    lib = _load()
    if lib is None or X.ndim != 2:
        return None
    if X.dtype == np.float32:
        is_f64 = 0
    elif X.dtype == np.float64:
        is_f64 = 1
    else:
        return None
    X = np.ascontiguousarray(X)
    n, F = X.shape
    C = len(col_indices)
    bnd_off = np.zeros((C + 1,), np.int64)
    for i, b in enumerate(bounds_list):
        bnd_off[i + 1] = bnd_off[i] + len(b)
    bounds = np.concatenate(bounds_list).astype(np.float64) \
        if C else np.zeros((0,), np.float64)
    out = np.empty((n, C), out_dtype)
    lib.ltpu_bin_columns(
        X.ctypes.data_as(ctypes.c_void_p), is_f64, n, F,
        np.ascontiguousarray(col_indices, np.int32), C,
        np.ascontiguousarray(bounds), bnd_off,
        np.ascontiguousarray(nan_to, np.int32),
        out.ctypes.data_as(ctypes.c_void_p),
        int(out.dtype == np.uint16))
    return out
