"""Quantized-gradient training (use_quantized_grad: int8 stochastic
rounding, exact int32 MXU histograms — the reference's
gradient_discretizer.hpp feature) at bench scale on the real chip,
fused path. Secondary metric: the primary bench stays the reference's
own (non-quantized) Higgs config. Run:
    python benchmarks/quant_bench.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import time

import numpy as np

import lightgbm_tpu as lgb

N, F = 10_500_000, 28
rs = np.random.RandomState(0)
X = rs.randn(N, F).astype(np.float32)
coef = rs.randn(F).astype(np.float32)
y = ((X @ coef) > 0).astype(np.float64)
ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
ds.construct()
del X

for quant in (False, True):
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 255,
                              "max_bin": 255, "learning_rate": 0.1,
                              "verbosity": -1,
                              "use_quantized_grad": quant},
                      train_set=ds)
    eng = bst._engine
    t0 = time.perf_counter()
    eng.train_one_iter()
    eng.score.block_until_ready()
    wu = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        eng.train_one_iter()
    eng.score.block_until_ready()
    dt = (time.perf_counter() - t0) / 5
    print(f"quantized={quant}: {dt * 1e3:.1f} ms/iter "
          f"({1 / dt:.3f} it/s, vs_baseline "
          f"{1 / dt / (500 / 130.094):.3f}, warmup {wu:.0f}s)",
          flush=True)
