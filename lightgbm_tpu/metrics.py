"""Evaluation metrics.

Re-design of /root/reference/src/metric/* (regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, xentropy_metric.hpp; factory
metric.cpp:21-120) as jnp reductions. AUC uses a sort + tie-grouped
trapezoid (the parallel-sort AUC of binary_metric.hpp re-expressed as XLA
sort/segment ops).

Interface: ``Metric.eval(raw_score, label, weight, convert_fn) -> float``
with raw_score shaped [K, n]; ``higher_better`` drives early stopping.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config

__all__ = ["create_metrics", "Metric", "METRIC_ALIASES"]

METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1",
    "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2",
    "regression": "l2", "regression_l2": "l2",
    "rmse": "rmse", "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "quantile": "quantile",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc",
    "average_precision": "average_precision",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multi_error": "multi_error",
    "auc_mu": "auc_mu",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "xe_ndcg": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "": "",
    "none": "", "null": "", "na": "", "custom": "",
}


def _mean(x, w):
    if w is None:
        return jnp.mean(x)
    return jnp.sum(x * w) / jnp.sum(w)


class Metric:
    name: str = ""
    higher_better: bool = False

    def __init__(self, cfg: Config):
        self.cfg = cfg

    def eval(self, raw_score: jnp.ndarray, label: jnp.ndarray,
             weight: Optional[jnp.ndarray],
             convert_fn: Callable) -> jnp.ndarray:
        raise NotImplementedError


def _simple(name_, higher=False, needs_convert=True):
    def deco(fn):
        class _M(Metric):
            name = name_
            higher_better = higher

            def eval(self, raw_score, label, weight, convert_fn):
                pred = convert_fn(raw_score) if needs_convert else raw_score
                if pred.ndim == 2 and pred.shape[0] == 1:
                    pred = pred[0]
                return fn(self.cfg, pred, label, weight)
        _M.__name__ = f"Metric_{name_}"
        return _M
    return deco


@_simple("l1")
def _l1(cfg, pred, label, w):
    return _mean(jnp.abs(pred - label), w)


@_simple("l2")
def _l2(cfg, pred, label, w):
    return _mean((pred - label) ** 2, w)


@_simple("rmse")
def _rmse(cfg, pred, label, w):
    return jnp.sqrt(_mean((pred - label) ** 2, w))


@_simple("quantile")
def _quantile(cfg, pred, label, w):
    d = label - pred
    return _mean(jnp.where(d >= 0, cfg.alpha * d, (cfg.alpha - 1.0) * d), w)


@_simple("huber")
def _huber(cfg, pred, label, w):
    d = jnp.abs(pred - label)
    a = cfg.alpha
    loss = jnp.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
    return _mean(loss, w)


@_simple("fair")
def _fair(cfg, pred, label, w):
    d = jnp.abs(pred - label)
    c = cfg.fair_c
    return _mean(c * c * (d / c - jnp.log1p(d / c)), w)


@_simple("poisson")
def _poisson(cfg, pred, label, w):
    eps = 1e-10
    lp = jnp.log(jnp.maximum(pred, eps))
    return _mean(pred - label * lp, w)


@_simple("mape")
def _mape(cfg, pred, label, w):
    return _mean(jnp.abs(pred - label) / jnp.maximum(1.0, jnp.abs(label)), w)


@_simple("gamma")
def _gamma(cfg, pred, label, w):
    eps = 1e-10
    psi = 1.0
    theta = -1.0 / jnp.maximum(pred, eps)
    a = -jnp.log(-theta)
    return _mean(label * (-theta) + a - (psi - 1.0) *
                 jnp.log(jnp.maximum(label, eps)), w)


@_simple("gamma_deviance")
def _gamma_dev(cfg, pred, label, w):
    eps = 1e-10
    r = label / jnp.maximum(pred, eps)
    return 2.0 * _mean(-jnp.log(jnp.maximum(r, eps)) + r - 1.0, w)


@_simple("tweedie")
def _tweedie(cfg, pred, label, w):
    rho = cfg.tweedie_variance_power
    eps = 1e-10
    p = jnp.maximum(pred, eps)
    a = label * jnp.power(p, 1.0 - rho) / (1.0 - rho)
    b = jnp.power(p, 2.0 - rho) / (2.0 - rho)
    return _mean(-a + b, w)


@_simple("binary_logloss")
def _binary_logloss(cfg, prob, label, w):
    eps = 1e-15
    p = jnp.clip(prob, eps, 1.0 - eps)
    y = (label > 0).astype(p.dtype)
    return _mean(-(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p)), w)


@_simple("binary_error")
def _binary_error(cfg, prob, label, w):
    y = (label > 0).astype(prob.dtype)
    pred = (prob > 0.5).astype(prob.dtype)
    return _mean((pred != y).astype(prob.dtype), w)


@_simple("cross_entropy")
def _xentropy(cfg, prob, label, w):
    eps = 1e-15
    p = jnp.clip(prob, eps, 1.0 - eps)
    return _mean(-(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p)), w)


@_simple("cross_entropy_lambda")
def _xentlambda(cfg, z, label, w):
    # z > 0 is the converted output of cross_entropy_lambda
    eps = 1e-15
    zz = jnp.maximum(z, eps)
    return _mean(zz - label * jnp.log(jnp.maximum(-jnp.expm1(-zz), eps)), w)


@_simple("kldiv")
def _kldiv(cfg, prob, label, w):
    eps = 1e-15
    p = jnp.clip(prob, eps, 1.0 - eps)
    y = jnp.clip(label, eps, 1.0 - eps)
    kl = y * jnp.log(y / p) + (1.0 - y) * jnp.log((1.0 - y) / (1.0 - p))
    return _mean(kl, w)


class AUC(Metric):
    """Weighted AUC with tie handling (binary_metric.hpp AUCMetric)."""
    name = "auc"
    higher_better = True

    def eval(self, raw_score, label, weight, convert_fn):
        score = raw_score[0] if raw_score.ndim == 2 else raw_score
        return auc_jnp(score, label, weight)


@functools.partial(jax.jit)
def auc_jnp(score, label, weight=None):
    n = score.shape[0]
    y = (label > 0).astype(jnp.float64)
    w = jnp.ones_like(y) if weight is None else weight.astype(jnp.float64)
    order = jnp.argsort(score)  # ascending
    s = score[order]
    pw = (y * w)[order]
    nw = ((1.0 - y) * w)[order]
    # group equal scores; within a group positives see half the group's negs
    new_group = jnp.concatenate(
        [jnp.ones((1,), jnp.int32), (s[1:] != s[:-1]).astype(jnp.int32)])
    gid = jnp.cumsum(new_group) - 1
    g_neg = jax.ops.segment_sum(nw, gid, num_segments=n)
    g_negcum = jnp.cumsum(g_neg)
    neg_below = g_negcum[gid] - g_neg[gid]          # strictly-lower negs
    neg_equal = g_neg[gid]
    area = jnp.sum(pw * (neg_below + 0.5 * neg_equal))
    tp = jnp.sum(pw)
    tn = jnp.sum(nw)
    return jnp.where((tp > 0) & (tn > 0), area / (tp * tn), 1.0)


class AveragePrecision(Metric):
    name = "average_precision"
    higher_better = True

    def eval(self, raw_score, label, weight, convert_fn):
        score = raw_score[0] if raw_score.ndim == 2 else raw_score
        y = (label > 0).astype(jnp.float64)
        w = jnp.ones_like(y) if weight is None else weight.astype(jnp.float64)
        order = jnp.argsort(-score)
        yw = (y * w)[order]
        ww = w[order]
        ctp = jnp.cumsum(yw)
        call = jnp.cumsum(ww)
        precision = ctp / jnp.maximum(call, 1e-15)
        tp_total = jnp.maximum(jnp.sum(yw), 1e-15)
        return jnp.sum(precision * yw) / tp_total


class MultiLogloss(Metric):
    name = "multi_logloss"

    def eval(self, raw_score, label, weight, convert_fn):
        p = convert_fn(raw_score)  # [K, n]
        p = p / jnp.maximum(jnp.sum(p, axis=0, keepdims=True), 1e-15)
        eps = 1e-15
        idx = label.astype(jnp.int32)
        py = jnp.take_along_axis(p, idx[None, :], axis=0)[0]
        return _mean(-jnp.log(jnp.clip(py, eps, 1.0)), weight)


class AucMu(Metric):
    """AUC-mu multiclass ranking metric (Kleiman & Page), matching the
    reference's AucMuMetric (src/metric/multiclass_metric.hpp:183):
    pairwise class separability along the partition-vector direction
    ``v = W[i] - W[j]``, averaged over all class pairs. ``auc_mu_weights``
    supplies the flattened [K, K] misclassification-cost matrix W
    (default: ones off the diagonal, src/io/config.cpp:220-241)."""

    name = "auc_mu"
    higher_better = True

    def eval(self, raw_score, label, weight, convert_fn):
        import numpy as np
        score = np.asarray(raw_score, np.float64)        # [K, n]
        y = np.asarray(label).astype(np.int64)
        K = score.shape[0]
        if K < 2:
            # the reference's double arithmetic yields nan for a single
            # class; keep training alive the same way
            return jnp.asarray(np.nan)
        W = self.cfg.auc_mu_weights
        if W:
            if len(W) != K * K:
                raise ValueError(
                    f"auc_mu_weights must have {K * K} elements")
            W = np.asarray(W, np.float64).reshape(K, K)
            np.fill_diagonal(W, 0.0)
        else:
            W = 1.0 - np.eye(K)
        w = None if weight is None else np.asarray(weight, np.float64)
        cls_w = np.array([
            (np.sum(y == c) if w is None else np.sum(w[y == c]))
            for c in range(K)], np.float64)

        total = 0.0
        for i in range(K):
            for j in range(i + 1, K):
                sel = (y == i) | (y == j)
                v = W[i] - W[j]
                d = (v[i] - v[j]) * (v @ score[:, sel])
                is_j = (y[sel] == j).astype(np.float64)
                ww = np.ones_like(d) if w is None else w[sel]
                # Mann-Whitney with eps-ties worth half a concordance
                # (the reference's last_j_dist streaming tie rule)
                order = np.lexsort((-is_j, d))
                d_s, j_s, w_s = d[order], is_j[order], ww[order]
                j_mass = np.cumsum(j_s * w_s)
                lo = np.searchsorted(d_s, d_s - 1e-15, side="left")
                hi = np.searchsorted(d_s, d_s + 1e-15, side="right")
                before = np.where(lo > 0, j_mass[np.maximum(lo - 1, 0)], 0.0)
                tied = j_mass[hi - 1] - before
                i_mask = j_s == 0
                s_ij = np.sum((w_s * (before + 0.5 * tied))[i_mask])
                total += s_ij / (cls_w[i] * cls_w[j])
        return jnp.asarray(2.0 * total / (K * (K - 1)))


class MultiError(Metric):
    name = "multi_error"

    def eval(self, raw_score, label, weight, convert_fn):
        p = convert_fn(raw_score)  # [K, n]
        k = self.cfg.multi_error_top_k
        idx = label.astype(jnp.int32)
        py = jnp.take_along_axis(p, idx[None, :], axis=0)[0]
        # top-k error: correct if < k classes have strictly higher prob
        rank = jnp.sum(p > py[None, :], axis=0)
        err = (rank >= k).astype(p.dtype)
        return _mean(err, weight)


_REGISTRY = {
    "l1": _l1, "l2": _l2, "rmse": _rmse, "quantile": _quantile,
    "huber": _huber, "fair": _fair, "poisson": _poisson, "mape": _mape,
    "gamma": _gamma, "gamma_deviance": _gamma_dev, "tweedie": _tweedie,
    "binary_logloss": _binary_logloss, "binary_error": _binary_error,
    "auc": AUC, "average_precision": AveragePrecision,
    "multi_logloss": MultiLogloss, "multi_error": MultiError,
    "auc_mu": AucMu,
    "cross_entropy": _xentropy, "cross_entropy_lambda": _xentlambda,
    "kldiv": _kldiv,
}

_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber",
    "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metrics(cfg: Config) -> List[Metric]:
    names = list(cfg.metric)
    if not names:
        default = _DEFAULT_FOR_OBJECTIVE.get(cfg.objective)
        names = [default] if default else []
    out: List[Metric] = []
    seen = set()
    for raw in names:
        key = METRIC_ALIASES.get(raw.strip().lower())
        if key is None:
            raise ValueError(f"Unknown metric {raw}")
        if key == "" or key in seen:
            continue
        seen.add(key)
        if key in ("ndcg", "map"):
            from .ranking import create_ranking_metric
            out.extend(create_ranking_metric(key, cfg))
            continue
        out.append(_REGISTRY[key](cfg))
    return out
