"""Device-resident sharded training (ISSUE 10; docs/SHARDING.md):
``shard_residency=device`` NamedSharding dataset placement
(parallel/placement.py) + ``split_search=sharded`` reduce-scatter
split search (parallel/comms.py, ops/grow.py).

The invariants under test:

- the reduce-scatter chunk is BIT-IDENTICAL to the matching slice of
  the full allreduce at f32 wire — which is what makes sharded-search
  training byte-identical to the gathered baseline (proved for all
  three data-parallel growers);
- device residency frees the host binned matrix after the mesh upload
  (and says so clearly when a host consumer asks later), without
  changing a single tree byte;
- checkpoint save/restore crosses residency modes byte-identically,
  and a device-resident snapshot carries per-shard fingerprints;
- the post-reduction payload model shows the ~D cut the subsystem
  sells (the measured twin lives in __graft_entry__.dryrun_multichip);
- unequal per-rank shards fail with an error naming ranks and counts,
  not an opaque allgather shape error (2-proc kv world);
- host peak RSS under device residency sits ~one binned matrix below
  the gathered path (VmHWM-gated like test_two_round.py).
"""

import json
import os
import re
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax.experimental.shard_map import shard_map
except ImportError:  # pragma: no cover - jax>=0.8
    from jax import shard_map

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.parallel import comms, placement
from lightgbm_tpu.parallel.mesh import make_mesh

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))

needs_mesh = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs the 8-device mesh")

GROWERS = ("compact", "masked", "level")


def _data(n=500, f=11, seed=3):
    """f=11 over 4 devices: uneven Fl=3 chunks with scatter padding."""
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = ((X[:, 0] + 0.5 * X[:, 1] ** 2 - 0.3 * X[:, 2]
          + 0.1 * rs.randn(n)) > 0.2).astype(np.float64)
    return X, y


def _params(extra=None):
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "tree_learner": "data", "num_devices": 4, "seed": 7,
         "deterministic": True, "verbosity": -1}
    if extra:
        p.update(extra)
    return p


def _train(X, y, extra=None, rounds=5, **kw):
    p = _params(extra)
    ds = lgb.Dataset(X, label=y, params=p)
    return lgb.train(p, ds, num_boost_round=rounds, **kw), ds


def _strip_params(model_str):
    """Model text minus the recorded-params block (shard_residency /
    split_search legitimately differ between the runs under
    comparison; the TREES must not)."""
    return re.sub(r"parameters:.*?end of parameters", "", model_str,
                  flags=re.S)


# ---------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------

def test_config_validation():
    from lightgbm_tpu.config import Config
    assert Config.from_params({}).shard_residency == "auto"
    assert Config.from_params({}).split_search == "gathered"
    with pytest.raises(ValueError, match="shard_residency"):
        Config.from_params({"shard_residency": "hbm"})
    with pytest.raises(ValueError, match="split_search"):
        Config.from_params({"split_search": "scattered"})


# ---------------------------------------------------------------------
# the reduce-scatter primitive
# ---------------------------------------------------------------------

@needs_mesh
def test_f32_reduce_scatter_chunk_is_psum_slice_bitwise():
    """The foundation of the byte-identity claim: each device's
    psum_scatter chunk must equal the matching slice of the full psum
    BIT-FOR-BIT, so a sharded search scores exactly the numbers the
    gathered search scores."""
    mesh = make_mesh(8)
    axis = mesh.axis_names[0]
    rs = np.random.RandomState(0)
    x = rs.randn(8, 16, 9, 2).astype(np.float32) * 3.0

    def body(xl):
        return comms.hist_reduce_scatter(xl[0], axis, "f32")[None]

    chunks = np.asarray(jax.jit(shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_rep=False))(jnp.asarray(x)))
    ref = x.sum(axis=0)                       # [16, 9, 2]
    got = chunks.reshape(16, 9, 2)            # 8 ranks x 2-row chunks
    assert np.array_equal(got, ref)


@needs_mesh
@pytest.mark.parametrize("mode", ["int16", "int8"])
def test_int_reduce_scatter_close_and_ef_resumes(mode):
    """The quantized wire loses bits by design; the chunk must stay
    close to the exact reduction and the error-feedback residual must
    shrink a follow-up reduction's error (telescoping like the
    allreduce's)."""
    mesh = make_mesh(8)
    axis = mesh.axis_names[0]
    rs = np.random.RandomState(1)
    x = rs.randn(8, 16, 9, 2).astype(np.float32) * 5.0

    def body(xl):
        ef0 = jnp.zeros_like(xl[0])
        c1, ef1 = comms.hist_reduce_scatter(xl[0], axis, mode, ef0)
        c2, _ = comms.hist_reduce_scatter(xl[0], axis, mode, ef1)
        return c1[None], c2[None]

    c1, c2 = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P(axis),
        out_specs=(P(axis), P(axis)), check_rep=False))(jnp.asarray(x))
    ref = x.sum(axis=0)
    got1 = np.asarray(c1).reshape(16, 9, 2)
    got2 = np.asarray(c2).reshape(16, 9, 2)
    scale = np.abs(ref).max()
    tol = scale * (0.02 if mode == "int8" else 0.002)
    assert np.abs(got1 - ref).max() < tol
    # second round re-sends the first round's residual: its error must
    # not exceed the cold one (error feedback, not error compounding)
    assert np.abs(got2 - ref).max() <= np.abs(got1 - ref).max() + tol


# ---------------------------------------------------------------------
# payload model (the modeled twin of dryrun_multichip's measured arm)
# ---------------------------------------------------------------------

def test_post_reduction_payload_model_shows_the_d_cut():
    F, B, D = 4228, 255, 8
    full = comms.post_reduction_bytes("data", F, B, D, "gathered")
    shard = comms.post_reduction_bytes("data", F, B, D, "sharded")
    assert full == F * B * 2 * 4              # the full [F, B, 2] hist
    chunk = -(-F // D) * B * 2 * 4
    assert shard == chunk + D * comms.splitinfo_elems(B) * 4
    assert full >= 7.5 * shard                # ~D cut at the wide shape
    # gathered == the existing payload model (no behavior change)
    assert comms.post_reduction_elems("data", F, B, D, "gathered") \
        == comms.payload_elems("data", F, B)
    # non-data modes are untouched by the knob
    for m in ("feature", "voting"):
        assert comms.post_reduction_bytes(m, F, B, D, "sharded") \
            == comms.payload_bytes(m, F, B)
    # int wire shrinks the chunk but never the f32 SplitInfo records
    shard8 = comms.post_reduction_bytes("data", F, B, D, "sharded",
                                        "int8")
    assert D * comms.splitinfo_elems(B) * 4 < shard8 < shard


# ---------------------------------------------------------------------
# sharded split search: byte-identical training (all 3 growers)
# ---------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize("grower", GROWERS)
def test_sharded_search_byte_identical(grower):
    X, y = _data()
    base, _ = _train(X, y, {"grower": grower})
    shard, _ = _train(X, y, {"grower": grower,
                             "split_search": "sharded"})
    assert _strip_params(shard.model_to_string()) \
        == _strip_params(base.model_to_string())


@needs_mesh
def test_device_residency_byte_identical_and_frees_host():
    X, y = _data()
    base, _ = _train(X, y)
    dev, ds = _train(X, y, {"shard_residency": "device",
                            "split_search": "sharded"})
    assert _strip_params(dev.model_to_string()) \
        == _strip_params(base.model_to_string())
    # the host binned matrix is gone, and says so clearly
    assert ds._bins is None
    with pytest.raises(LightGBMError, match="freed after device"):
        ds.host_bins()
    from lightgbm_tpu.obs.registry import registry
    assert registry.gauge("host_binned_bytes").value == 0.0
    # prediction re-bins fresh input through the mappers — no host
    # binned matrix required
    p = dev.predict(X[:50])
    q = base.predict(X[:50])
    np.testing.assert_array_equal(p, q)
    # the training matrix is actually sharded over the mesh
    bins_T = dev._engine.bins_T
    assert len(bins_T.sharding.device_set) == 4


@needs_mesh
def test_sharded_efb_falls_back_to_gathered():
    """EFB-bundled matrices keep the gathered search (with a warning),
    and the model matches the bundled gathered baseline exactly."""
    rs = np.random.RandomState(5)
    n, groups, per = 600, 4, 6                # one-hot blocks bundle
    cols, signal = [], np.zeros(n)
    for g in range(groups):
        pick = rs.randint(0, per, n)
        block = np.zeros((n, per))
        vals = rs.rand(per) * 2
        block[np.arange(n), pick] = vals[pick]
        cols.append(block)
        signal += vals[pick]
    X = np.hstack(cols + [rs.randn(n, 2)])
    y = (signal + 0.5 * X[:, -1] > np.median(signal)).astype(float)
    extra = {"enable_bundle": True, "num_leaves": 7}
    base, _ = _train(X, y, extra, rounds=3)
    shard, _ = _train(X, y, dict(extra, split_search="sharded"),
                      rounds=3)
    assert base._engine.bundle is not None    # EFB really engaged
    assert _strip_params(shard.model_to_string()) \
        == _strip_params(base.model_to_string())
    assert shard._engine.grow_cfg.split_search == "gathered"


# ---------------------------------------------------------------------
# checkpoint: resume across residency modes, per-shard fingerprints
# ---------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize("write_res,resume_res",
                         [("device", "host"), ("host", "device")])
def test_checkpoint_resume_across_residency(write_res, resume_res,
                                            tmp_path):
    X, y = _data(n=400)
    full, _ = _train(X, y, rounds=8)
    _train(X, y, {"shard_residency": write_res,
                  "split_search": "sharded"}, rounds=4,
           callbacks=[lgb.checkpoint(str(tmp_path), every_n_iters=4)])
    resumed, _ = _train(X, y, {"shard_residency": resume_res},
                        rounds=8, resume_from=str(tmp_path))
    assert _strip_params(resumed.model_to_string()) \
        == _strip_params(full.model_to_string())


@needs_mesh
def test_device_snapshot_carries_shard_fingerprints(tmp_path):
    from lightgbm_tpu.resilience.checkpoint import write_snapshot
    X, y = _data(n=400)
    dev, _ = _train(X, y, {"shard_residency": "device"}, rounds=2)
    path = write_snapshot(str(tmp_path), dev)
    with np.load(path) as z:
        state = json.loads(bytes(z["state_json"]).decode())
        score = z["score"]
    fps = state["score_shard_fingerprints"]
    assert fps is not None and len(fps) == 4   # one per device shard
    assert len({f["sha256"] for f in fps}) >= 1
    # the snapshot stores the ASSEMBLED host matrix (resume works
    # across residency modes), matching fetch_global exactly
    np.testing.assert_array_equal(
        score, np.asarray(placement.fetch_global(dev._engine.score),
                          np.float32))


# ---------------------------------------------------------------------
# placement unit surface
# ---------------------------------------------------------------------

@needs_mesh
def test_place_rows_roundtrip_and_padding():
    mesh = make_mesh(8)
    rs = np.random.RandomState(2)
    host = rs.randint(0, 255, size=(5, 20), dtype=np.uint8)  # rows ax 1
    placed = placement.place_rows(mesh, host, row_axis=1, pad=4)
    assert placed.shape == (5, 24)
    back = np.asarray(placement.fetch_global(placed))
    np.testing.assert_array_equal(back[:, :20], host)
    assert not back[:, 20:].any()             # zero row padding
    fps = placement.shard_fingerprints(placed)
    assert len(fps) == 8
    # fingerprints are an identity: re-placing the same rows agrees
    fps2 = placement.shard_fingerprints(
        placement.place_rows(mesh, host, row_axis=1, pad=4))
    assert fps == fps2


def test_place_rows_requires_divisible_rows():
    mesh = make_mesh(4)
    with pytest.raises(ValueError, match="divisible"):
        placement.ShardPlan(mesh, 10)


def test_place_refuses_rows_outside_this_ranks_slices():
    """Multi-controller misalignment: a held row outside this rank's
    own device windows would be silently zero-filled by another rank's
    pad — place() must refuse BEFORE any upload (fake pod topology:
    this process owns the HIGH shards but holds rows [5, 10) of 12,
    and 10 is not on a rows_per_shard=3 boundary)."""
    class _Dev:
        def __init__(self, p):
            self.process_index = p

    class _Mesh:
        devices = np.array([_Dev(1), _Dev(1), _Dev(0), _Dev(0)])
        axis_names = ("data",)

    plan = placement.ShardPlan(_Mesh, 12)     # windows of 3 rows each
    with pytest.raises(ValueError, match="whole number of device"):
        plan.place(np.zeros((5, 4), np.uint8), row_axis=0,
                   local_offset=5, exclusive_rows=True)


def test_fetch_global_ships_shards_not_full_buffers(monkeypatch):
    """The multi-controller checkpoint gather must ship only this
    rank's shard data + index bounds through the host transport, never
    full-array-shaped buffers — and still reassemble exactly."""
    from lightgbm_tpu.parallel import hostsync

    full = np.arange(32, dtype=np.float32).reshape(4, 8)

    class _Shard:
        def __init__(self, index, data):
            self.index, self.data = index, data

    class _Arr:
        is_fully_addressable = False
        shape, dtype = full.shape, full.dtype
        addressable_shards = [_Shard((slice(0, 2), slice(0, 8)),
                                     full[0:2])]

    theirs_data = full[2:4][None]                       # [S=1, 2, 8]
    theirs_idx = np.asarray([[[2, 4], [0, 8]]], np.int64)
    sent = []

    def fake_allgather(a, tag):
        sent.append((tag, a.nbytes))
        other = theirs_idx if tag.endswith("_idx") else theirs_data
        return np.stack([a, other.reshape(a.shape)])

    monkeypatch.setattr(hostsync, "host_allgather", fake_allgather)
    out = placement.fetch_global(_Arr())
    np.testing.assert_array_equal(out, full)
    data_bytes = max(b for t, b in sent if not t.endswith("_idx"))
    assert data_bytes == full[0:2].nbytes      # half, not P x full

    # a missing cover must raise, not zero-fill
    def hole_allgather(a, tag):
        return a[None]                         # only this rank's half
    monkeypatch.setattr(hostsync, "host_allgather", hole_allgather)
    with pytest.raises(RuntimeError, match="tile"):
        placement.fetch_global(_Arr())


# ---------------------------------------------------------------------
# 2-process kv worlds (the multi-controller surface)
# ---------------------------------------------------------------------

def _spawn_world(tmp_path, mode):
    from _mp_utils import drain_all, free_port, spawn_worker, \
        worker_base_env
    port = free_port()
    worker = os.path.join(TESTS_DIR, "sharding_worker.py")
    procs = [
        spawn_worker([worker, str(tmp_path), mode], worker_base_env({
            "LIGHTGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LIGHTGBM_TPU_NUM_PROCS": "2",
            "LIGHTGBM_TPU_RANK": str(rank),
            "LIGHTGBM_TPU_COLLECTIVE_TIMEOUT": "60",
        }))
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=360)
        except subprocess.TimeoutExpired:
            drain_all(procs, f"sharding {mode} workers timed out")
        outs.append(out.decode(errors="replace"))
    return procs, outs


@pytest.mark.mp
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_two_process_kv_device_sharded_byte_identical(tmp_path):
    """The acceptance world: 2 CPU processes over the kv transport,
    device residency + sharded search, all three growers —
    byte-identical trees to the gathered baseline."""
    procs, outs = _spawn_world(tmp_path, "equiv")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank} DONE" in out
    with open(tmp_path / "models.json") as fh:
        models = json.load(fh)
    for grower in GROWERS:
        assert _strip_params(models[f"{grower}/sharded"]) \
            == _strip_params(models[f"{grower}/gathered"]), grower


@pytest.mark.mp
@pytest.mark.timeout(300)
def test_two_process_unequal_rows_named_error(tmp_path):
    """Unequal per-rank shard row counts must raise a LightGBMError
    naming the ranks and row counts BEFORE the bulk allgather (the old
    failure mode was an opaque shape error, spmd.py)."""
    procs, outs = _spawn_world(tmp_path, "unequal_rows")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank} UNEQUAL_ROWS_OK" in out


@pytest.mark.mp
@pytest.mark.timeout(300)
def test_two_process_unequal_metadata_named_error(tmp_path):
    """A rank carrying `weight` while another does not must be named
    before the metadata allgathers deadlock/misalign."""
    procs, outs = _spawn_world(tmp_path, "unequal_meta")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank} UNEQUAL_META_OK" in out


# ---------------------------------------------------------------------
# host peak RSS (VmHWM-gated like test_two_round.py — gVisor /proc
# has no VmHWM line)
# ---------------------------------------------------------------------

def _proc_has_vmhwm() -> bool:
    try:
        with open("/proc/self/status") as fh:
            return any(line.startswith("VmHWM:") for line in fh)
    except OSError:
        return False


def _run_mem_worker(mode):
    env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.environ.get("HOME", "/root"),
           "JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}
    out = subprocess.run(
        [sys.executable, os.path.join(TESTS_DIR,
                                      "sharding_mem_worker.py"), mode],
        env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
@pytest.mark.timeout(1800)
@pytest.mark.skipif(sys.platform != "linux" or not _proc_has_vmhwm(),
                    reason="peak measurement needs VmHWM in "
                           "/proc/self/status")
def test_device_residency_host_peak_below_gathered():
    """Construct+train lifetime peak RSS under shard_residency=device
    must sit below the gathered path's by a meaningful fraction of the
    binned matrix (the host copy both paths build, which only the
    device path frees before the training buffers grow on top)."""
    dev = _run_mem_worker("device")
    host = _run_mem_worker("host")
    assert dev["host_binned_bytes"] == 0, dev
    assert host["host_binned_bytes"] > 0, host
    saved_mb = (host["vmhwm_kb"] - dev["vmhwm_kb"]) / 1024
    assert saved_mb > 0.4 * host["bins_mb"], (host, dev)


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_mem_worker_reports_zero_resident_bytes_under_device():
    """VmHWM-free fallback of the residency claim, runnable in this
    container: after construct+train the device-residency worker holds
    ZERO host binned bytes while the host one holds the full matrix."""
    dev = _run_mem_worker("device")
    assert dev["host_binned_bytes"] == 0, dev
    host = _run_mem_worker("host")
    assert host["host_binned_bytes"] >= host["bins_mb"] * 2 ** 20 * 0.99, \
        host
