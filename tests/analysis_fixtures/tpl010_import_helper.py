# tpulint fixture: TPL010 positive — the branch lambda reaches the
# collective through a helper IMPORTED from a sibling module (the
# package-wide basename fallback must catch it).
import jax.numpy as jnp
from jax import lax

from .tpl010_pos import _window_reduce


def lambda_calls_imported_helper(pred, x, axis):
    # EXPECT: TPL010
    return lax.cond(pred,
                    lambda: _window_reduce(x, axis),
                    lambda: jnp.sum(x))
