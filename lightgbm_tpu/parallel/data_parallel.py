"""Data-parallel tree growth over a device mesh.

Re-design of DataParallelTreeLearner
(/root/reference/src/treelearner/data_parallel_tree_learner.cpp) for TPU:

reference (socket/MPI)                     ->  TPU (mesh + XLA collectives)
--------------------------------------------------------------------------
rank-strided row shards                    ->  rows sharded over mesh axis
ReduceScatter(histograms, HistogramSum)    ->  lax.psum of [F,B,3] inside
  + per-rank feature ownership (:223-300)      shard_map (XLA lowers to
                                               reduce-scatter+all-gather
                                               on ICI as it sees fit)
SyncUpGlobalBestSplit (allreduce max-gain) ->  not needed: every device
                                               sees the full summed
                                               histogram and computes the
                                               identical argmax
global leaf counts allreduce               ->  psum of root/leaf sums

``tree_learner=feature`` and ``=voting`` build the same shard_map with
the grower's ``parallel_mode`` switched (GrowConfig.parallel_mode):
feature-parallel replicates rows (every in_spec P()) and allreduces the
best SplitInfo across disjoint per-device feature shards
(feature_parallel_tree_learner.cpp:71); voting shards rows but keeps
the histogram cache local, reducing only vote-elected features per
search (voting_parallel_tree_learner.cpp:364).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.8: jax.shard_map, replication checking via check_vma
    from jax import shard_map as _shard_map

    def shard_map(fn, mesh, in_specs, out_specs, check_rep):
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..ops.grow import GrowConfig, grow_tree_impl

__all__ = ["make_dp_grow_fn"]


@functools.lru_cache(maxsize=32)
def _build(cfg: GrowConfig, mesh: Mesh, has_monotone: bool, has_cat: bool,
           has_quant_key: bool, has_interaction: bool = False,
           has_forced: bool = False, has_node_key: bool = False,
           has_bundle: bool = False):
    axis = mesh.axis_names[0]
    cfg = cfg._replace(axis_name=axis)
    if cfg.parallel_mode == "feature":
        # rows replicated: every device holds the full dataset and owns
        # a feature shard inside the grower's split search
        rowspec = P()
    else:
        rowspec = P(axis)
    rep = P()

    in_specs = (P(None, axis) if cfg.parallel_mode != "feature"
                else P(None, None),
                rowspec, rowspec, rowspec, rep, rep, rep)
    in_specs = in_specs + (rep,) * (int(has_monotone) + int(has_cat)
                                    + int(has_quant_key)
                                    + int(has_interaction)
                                    + 3 * int(has_forced)
                                    + int(has_node_key)
                                    # bundle metadata (8 host-built
                                    # arrays, ops/bundling.py) is a
                                    # dataset property — replicated,
                                    # like the bin-count metadata
                                    + 8 * int(has_bundle))
    out_specs = (rep, rowspec)  # tree replicated, row_leaf row-layout

    def fn(bins_T, grad, hess, row_w, fmask, fnb, fnan, *rest):
        rest = list(rest)
        mono = rest.pop(0) if has_monotone else None
        cat = rest.pop(0) if has_cat else None
        qkey = rest.pop(0) if has_quant_key else None
        groups = rest.pop(0) if has_interaction else None
        forced = None
        if has_forced:
            forced = tuple(rest[:3])
            rest = rest[3:]
        nkey = rest.pop(0) if has_node_key else None
        bundle = tuple(rest[:8]) if has_bundle else None
        return grow_tree_impl(cfg, bins_T, grad, hess, row_w, fmask,
                              fnb, fnan, mono, cat, qkey, groups, forced,
                              None, nkey, bundle)

    sharded = shard_map(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)
    return jax.jit(sharded)


def make_dp_grow_fn(cfg: GrowConfig, mesh: Mesh,
                    has_monotone: bool = False, has_cat: bool = False,
                    has_quant_key: bool = False,
                    has_interaction: bool = False,
                    has_forced: bool = False,
                    has_node_key: bool = False,
                    has_bundle: bool = False):
    """Returns grow(bins_T, grad, hess, row_w, fmask, fnb, fnan[, mono]
    [, feat_is_cat][, quant_key][, groups][, forced...][, node_key]
    [, bundle x8]) running data-parallel over ``mesh``. Row inputs must
    be padded to a multiple of the device count (pad rows carry
    row_weight 0)."""
    return _build(cfg, mesh, has_monotone, has_cat, has_quant_key,
                  has_interaction, has_forced, has_node_key, has_bundle)
