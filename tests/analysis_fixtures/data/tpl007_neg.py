# tpulint fixture: TPL007 negative — the REAL ingestion idioms
# (lightgbm_tpu/data/ingest.py, parallel/spmd.py) must stay clean:
# world-size gates are rank-invariant, and a rank-dependent ARGUMENT
# to a collective every rank joins is fine. No EXPECT lines.
import json

import jax

from lightgbm_tpu.parallel.hostsync import (host_allgather,
                                            host_broadcast_bytes)


def pass1_mapper_sync(mappers):
    """The pipeline's pass-1 shape: sync only when a world exists
    (process_count is rank-invariant), with rank 0 supplying the
    payload every rank receives."""
    if jax.process_count() <= 1:
        return mappers
    payload = None
    if jax.process_index() == 0:
        payload = json.dumps(mappers).encode()
    return json.loads(host_broadcast_bytes(
        payload, "spmd/sync_bin_mappers").decode())


def pass2_shard_gather(local_bins):
    """The pass-2 tail: every rank contributes its binned shard once;
    rank-gated work AFTER the collective (rank-0-only writes) is the
    idiom, not a hazard."""
    if jax.process_count() <= 1:
        return local_bins[None]
    g = host_allgather(local_bins, "spmd/dataset_bins")
    if jax.process_index() == 0:
        return g
    return g
