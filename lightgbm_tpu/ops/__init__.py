"""Device-side compute ops."""
