"""CPU/TPU dual parity (the reference's env-gated test_dual.py): the
same data trains on both backends with approximately equal quality.

Gated on LIGHTGBM_TPU_TEST_DUAL=1 because it needs a real accelerator
next to the CPU path (the conftest pins the suite to CPU; this test
spawns a subprocess on the ambient backend instead)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("LIGHTGBM_TPU_TEST_DUAL", "") != "1",
    reason="set LIGHTGBM_TPU_TEST_DUAL=1 (needs an accelerator)")

_CHILD = r"""
import json, sys
import numpy as np
import lightgbm_tpu as lgb
rs = np.random.RandomState(7)
X = rs.randn(20000, 10)
y = ((X[:, 0] + 0.5 * X[:, 1] * X[:, 2]) > 0).astype(float)
bst = lgb.train({"objective": "binary", "verbosity": -1,
                 "num_leaves": 31}, lgb.Dataset(X[:16000], label=y[:16000]),
                num_boost_round=20)
p = bst.predict(X[16000:])
yv = y[16000:]
ll = -np.mean(yv * np.log(np.clip(p, 1e-12, 1))
              + (1 - yv) * np.log(np.clip(1 - p, 1e-12, 1)))
import jax
print(json.dumps({"backend": jax.default_backend(), "logloss": float(ll)}))
"""


def test_cpu_accelerator_logloss_parity():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # ambient accelerator
    out = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                         capture_output=True, text=True, timeout=1800,
                         cwd=os.path.dirname(os.path.dirname(__file__)))
    assert out.returncode == 0, out.stderr[-2000:]
    acc = json.loads(out.stdout.strip().splitlines()[-1])

    env_cpu = dict(env, JAX_PLATFORMS="cpu")
    out2 = subprocess.run([sys.executable, "-c", _CHILD], env=env_cpu,
                          capture_output=True, text=True, timeout=1800,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    assert out2.returncode == 0, out2.stderr[-2000:]
    cpu = json.loads(out2.stdout.strip().splitlines()[-1])

    # single-precision histogram parity bound (the reference's dual
    # test allows 1e-4 relative for single precision)
    assert abs(acc["logloss"] - cpu["logloss"]) \
        <= 1e-2 * max(1.0, cpu["logloss"]), (acc, cpu)
