"""The driver contract of bench.py: ONE parseable JSON line on stdout
and exit code 0, regardless of backend health (BENCH_r01/r03/r04 were
lost to stack traces or timeouts before this was hardened)."""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, timeout):
    # fixed minimal env: an ambient BENCH_* leak (e.g. BENCH_WORKER=1
    # or a short BENCH_DEADLINE) would silently change which protocol
    # path runs — same env-poisoning class the RSS test scrubs for
    env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.environ.get("HOME", "/root")}
    env.update(env_extra)
    return subprocess.run([sys.executable, BENCH], env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


def test_bench_success_emits_one_json_line():
    r = _run({"BENCH_PLATFORM": "cpu", "BENCH_ROWS": "4000",
              "BENCH_VALID": "1000", "BENCH_ITERS": "2",
              "BENCH_AUC_ITERS": "3", "BENCH_LEAVES": "7",
              "BENCH_BINS": "15", "BENCH_DEADLINE": "700"},
             timeout=900)
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    assert rec["value"] is not None and rec["value"] > 0
    assert "error" not in rec
    # the embedded run-telemetry block (docs/OBSERVABILITY.md): phase
    # wall times, jit recompile count, HBM gauges (nulls on CPU)
    telem = rec["telemetry"]
    assert isinstance(telem["recompiles"], int) and \
        telem["recompiles"] >= 1  # at least the grow compile
    assert telem["phases"], telem
    for label, v in telem["phases"].items():
        assert v["total"] >= 0 and v["count"] >= 1, (label, v)
    assert "bytes_in_use" in telem["hbm"]


def test_probe_budget_capped_under_hostile_settings():
    """The r04 regression class: probe retries must never outlive the
    deadline. Even with an absurd retry budget (100 probes x 1000 s
    timeouts) against a backend that always fails init, the probe loop
    stops at its BENCH_DEADLINE/2 cutoff and the supervisor emits the
    one failure line inside the deadline."""
    t0 = time.time()
    r = _run({"BENCH_PLATFORM": "bogus_backend",  # probe always fails
              "BENCH_ROWS": "4000",
              "BENCH_PROBE_RETRIES": "100",
              "BENCH_PROBE_TIMEOUT": "1000",
              "BENCH_PROBE_BACKOFF": "1",
              "BENCH_DEADLINE": "90"},
             timeout=200)
    wall = time.time() - t0
    assert r.returncode == 0, r.stderr[-1500:]
    assert wall < 90, f"probe loop outlived BENCH_DEADLINE ({wall:.0f}s)"
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["value"] is None and "error" in rec


def test_bench_failure_emits_one_json_line_within_deadline():
    """A dead backend must still produce the one-line record, inside
    BENCH_DEADLINE, with value null and the error recorded. Forced
    deterministically by giving the probe a zero retry budget."""
    t0 = time.time()
    r = _run({"BENCH_PLATFORM": "cpu", "BENCH_ROWS": "4000",
              "BENCH_PROBE_RETRIES": "0", "BENCH_DEADLINE": "120"},
             timeout=300)
    wall = time.time() - t0
    assert r.returncode == 0, r.stderr[-1500:]
    assert wall < 120, f"exceeded BENCH_DEADLINE ({wall:.0f}s)"
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["value"] is None
    assert rec["vs_baseline"] is None
    assert "error" in rec
    assert "last_measured" in rec and \
        rec["last_measured"]["value"] is not None


# ---------------------------------------------------------------------
# tracing plane cost contract (ISSUE 16): always-on must mean free
# ---------------------------------------------------------------------

def test_tracing_off_iteration_path_is_structurally_free():
    """With no capture live, the fused iteration's timed() sections
    must still resolve to the SHARED no-op context — the tracing
    plane adds zero objects and zero clock reads to the hot loop.
    This is the structural half of the <=1%-overhead bench contract
    (the timing half below bounds the only per-iteration addition)."""
    from lightgbm_tpu.utils import timer as tm
    from lightgbm_tpu.utils.timer import EnvCapture
    assert not tm.Timer._enabled
    assert tm.timed("boosting/fused_scan") is tm._NULL
    # and the engine's env-capture hook is skipped entirely: no knob
    # set -> no object, the loop never takes the per-iteration calls
    assert EnvCapture.from_env({}) is None


def test_span_derivation_within_overhead_budget():
    """The ONLY tracing work an instrumented iteration adds is
    record_iteration_spans (recorder-side, off the hot path). Budget:
    <=1% of the seed's ~130 ms/iter fused iteration = 1.3 ms. Assert
    a generous half of that per call on a realistic phase table so a
    regression (per-row spans, clock storms) fails loudly while CI
    jitter does not."""
    import time as _time

    from lightgbm_tpu.obs.trace import (drain_span_events,
                                        record_iteration_spans,
                                        set_current_trace)
    event = {"iteration": 5, "scan": {"window": 8},
             "phases": {f"phase{i}": {"total": 0.01, "count": 4}
                        for i in range(8)}}
    event["phases"]["boosting/fused_scan"] = {"total": 0.08,
                                              "count": 1}
    set_current_trace(None)
    record_iteration_spans(event, 0.0, 0.13)  # warm the path
    n = 50
    t0 = _time.perf_counter()
    for _ in range(n):
        record_iteration_spans(event, 0.0, 0.13)
    per_call = (_time.perf_counter() - t0) / n
    drain_span_events()
    set_current_trace(None)
    assert per_call < 0.65e-3, (
        f"span derivation costs {per_call * 1e3:.3f} ms/iteration — "
        "over the 1% tracing-overhead budget (1.3 ms) headroom")


def test_span_event_schema_is_documented():
    """{"event": "span"} is part of the telemetry JSONL contract:
    every key of SPAN_EVENT_KEYS appears in docs/OBSERVABILITY.md
    (same documentation gate the iteration/compile events meet)."""
    from lightgbm_tpu.obs.trace import SPAN_EVENT_KEYS
    assert SPAN_EVENT_KEYS[0] == "event"
    doc = open(os.path.join(REPO, "docs", "OBSERVABILITY.md"),
               encoding="utf-8").read()
    assert '"event": "span"' in doc
    for key in SPAN_EVENT_KEYS:
        assert f"`{key}`" in doc, (
            f"span schema key {key!r} undocumented in "
            "docs/OBSERVABILITY.md")
