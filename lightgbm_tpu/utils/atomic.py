"""Crash-safe file writes: same-directory tmp file + ``os.replace``.

The pattern mirrors the native-extension build path (utils/native.py:
compile to a private temp name, then atomic-rename so a concurrent or
killed process never observes a half-written artifact). Model files and
checkpoint snapshots go through here: a process killed mid-write leaves
either the previous complete file or nothing — never a truncated one.

POSIX ``rename(2)`` is atomic only within a filesystem, which is why the
tmp file is created in the *target's* directory rather than ``$TMPDIR``.
"""

from __future__ import annotations

import os
import tempfile

__all__ = ["atomic_write_bytes", "atomic_write_text"]


def atomic_write_bytes(path, data: bytes) -> None:
    """Write ``data`` to ``path`` so that the file is either fully
    written or untouched (tmp file + fsync + ``os.replace``)."""
    path = os.fspath(path)
    dirname = os.path.dirname(os.path.abspath(path))
    os.makedirs(dirname, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=dirname, prefix=os.path.basename(path) + ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            # flush alone leaves the bytes in the page cache; a machine
            # crash after replace() could then surface an empty file
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path, text: str, encoding: str = "utf-8") -> None:
    atomic_write_bytes(path, text.encode(encoding))
