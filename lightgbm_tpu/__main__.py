import sys

if __name__ == "__main__":
    # `lint` runs the jax-free static analyzer (lightgbm_tpu/analysis/);
    # dispatch it BEFORE importing the training CLI, whose module
    # imports pull in jax — tpulint must work where no backend can
    # initialize.
    if len(sys.argv) > 1 and sys.argv[1] == "lint":
        from .analysis.cli import main as lint_main
        raise SystemExit(lint_main(sys.argv[2:]))

    # `launch` is the elastic restart supervisor (resilience/elastic.py):
    # it must not import jax either — the supervisor outlives dying
    # worker worlds and must never pin the accelerator devices the
    # workers need.
    if len(sys.argv) > 1 and sys.argv[1] == "launch":
        from .resilience.elastic import main as launch_main
        raise SystemExit(launch_main(sys.argv[2:]))

    # `pipeline` is the continuous train->publish->serve lifecycle
    # driver (pipeline.py, docs/PIPELINE.md). Its supervisor loop,
    # load generator and --help are jax-free like `launch` — jax only
    # loads inside the spawned training workers and serve replicas
    # (the hidden --train-worker mode re-enters here).
    if len(sys.argv) > 1 and sys.argv[1] == "pipeline":
        from .pipeline import main as pipeline_main
        raise SystemExit(pipeline_main(sys.argv[2:]))

    # `trace` merges the fleet's telemetry streams into a clock-
    # corrected Chrome trace-event export + critical-path table
    # (obs/trace.py, docs/OBSERVABILITY.md "Tracing"). Pure JSONL
    # post-processing — jax-free like `lint` and `launch`.
    if len(sys.argv) > 1 and sys.argv[1] == "trace":
        from .obs.trace import main as trace_main
        raise SystemExit(trace_main(sys.argv[2:]))

    # `serve` is the inference daemon (serve/daemon.py). Its argument
    # parse, --help and bad-model-path errors are jax-free (the serve
    # package __init__ is PEP-562 lazy); jax loads only once a model
    # is actually compiled — so operator typos fail fast even where no
    # backend can initialize.
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        from .serve.daemon import main as serve_main
        raise SystemExit(serve_main(sys.argv[2:]))

    from .cli import main
    raise SystemExit(main())
