# tpulint fixture: TPL007 positive — ingestion-pipeline shapes. The
# two-pass streaming construct (lightgbm_tpu/data/ingest.py) runs host
# collectives between its passes (bin-mapper sync) and around shard
# gathers; rank-divergent reach of any of them deadlocks the world.
# An `# EXPECT: <RULE>` comment pins a finding on the following line.
import jax

from lightgbm_tpu.parallel.hostsync import host_allgather
from lightgbm_tpu.parallel.spmd import sync_bin_mappers


def pass1_sync_only_on_rank0(mappers):
    """Pass-1 mapper sync gated on the rank: every other rank skips
    the broadcast it is supposed to join."""
    if jax.process_index() == 0:
        # EXPECT: TPL007
        mappers = sync_bin_mappers(mappers)
    return mappers


def pass2_gather_in_recovery(shard):
    """Retrying the binned-shard gather from an except handler: only
    ranks that hit the error re-join."""
    try:
        out = host_allgather(shard, "ok/ingest_bins")
    except RuntimeError:
        # EXPECT: TPL007
        out = host_allgather(shard, "bad/ingest_bins_retry")
    return out


def per_rank_chunk_count_gathers(chunks):
    """Gathering once per LOCAL chunk: ranks with different chunk
    counts join a different number of collectives."""
    me = jax.process_index()
    for _ in range(me):
        # EXPECT: TPL007
        host_allgather(chunks, "bad/per_chunk")
