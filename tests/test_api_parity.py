"""Reference python-package API-surface parity: generic field access,
subset/add_features_from, ref chains, attrs, model_from_string, score
bounds (basic.py Dataset/Booster method inventory)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture()
def fitted():
    rs = np.random.RandomState(0)
    X = rs.randn(800, 5)
    y = (X[:, 0] > 0).astype(float)
    d = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, d, 5)
    return X, y, d, bst


def test_dataset_field_access_and_ref_chain(fitted):
    X, y, d, _ = fitted
    assert np.array_equal(d.get_field("label"), y)
    d.set_field("weight", np.ones(len(y)))
    assert np.allclose(d.get_field("weight"), 1.0)
    assert d.get_data() is not None
    v = d.create_valid(X[:100], label=y[:100])
    assert d in v.get_ref_chain()
    assert v in v.get_ref_chain()
    with pytest.raises(lgb.LightGBMError):
        d.get_field("nope")


def test_dataset_subset_and_add_features(fitted):
    X, y, d, _ = fitted
    sub = d.subset(np.arange(0, 400))
    assert sub.num_data() == 400
    dA = lgb.Dataset(X[:, :3].copy(), label=y)
    dB = lgb.Dataset(X[:, 3:].copy(), label=y)
    dA.construct(), dB.construct()
    dA.add_features_from(dB)
    assert dA.num_features() == 5
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, dA, 3)
    assert np.all(np.isfinite(bst.predict(X[:50])))


def test_booster_attrs_and_model_from_string(fitted):
    X, _, _, bst = fitted
    b2 = lgb.Booster.model_from_string(bst.model_to_string())
    np.testing.assert_allclose(bst.predict(X[:50]), b2.predict(X[:50]),
                               rtol=1e-6)
    bst.set_attr(note="hello", run="1")
    assert bst.attr("note") == "hello"
    assert bst.attr("run") == "1"
    bst.set_attr(note=None)
    assert bst.attr("note") is None


def test_booster_score_bounds(fitted):
    X, _, _, bst = fitted
    lo, hi = bst.lower_bound(), bst.upper_bound()
    raw = bst.predict(X, raw_score=True)
    assert raw.min() >= lo - 1e-6
    assert raw.max() <= hi + 1e-6


def test_set_reference_and_feature_names():
    rs = np.random.RandomState(1)
    X = rs.randn(300, 3)
    y = (X[:, 0] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    v = lgb.Dataset(X[:50], label=y[:50])
    v.set_reference(d)
    assert v.reference is d
    d.set_feature_name(["a", "b", "c"])
    d.construct()
    assert d.get_feature_name() == ["a", "b", "c"]
    with pytest.raises(lgb.LightGBMError):
        d.set_categorical_feature([0])  # after construct
