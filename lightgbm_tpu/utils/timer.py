"""Phase timing — the USE_TIMETAG subsystem re-imagined for JAX.

The reference compiles a global ``Common::Timer`` + RAII ``FunctionTimer``
into every hot-path phase and logs a sorted per-label wall-time table at
process exit (/root/reference/include/LightGBM/utils/common.h:973-1057,
instrumentation points listed in SURVEY.md §5). On TPU the device runs
asynchronously from Python, so two complementary mechanisms are provided:

- ``Timer`` / ``timed(label)``: host wall-clock aggregation per label.
  Because dispatch is async, a label's time only reflects device work if
  the section itself synchronizes (the train loop's per-iteration sync
  points do). Enabled with env ``LIGHTGBM_TPU_TIMETAG=1`` or
  ``Timer.enable()``; ``Timer.log_summary()`` prints the sorted table.
- every timed section also enters a ``jax.profiler.TraceAnnotation`` so
  the phases show up as named spans inside ``jax.profiler.trace``
  captures (the tensorboard/xplane view) even when host timing is off.
"""

from __future__ import annotations

import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Iterator

from .log import log_info

__all__ = ["Timer", "timed", "trace_to"]


class Timer:
    """Process-global label -> accumulated wall seconds."""

    _acc: Dict[str, float] = defaultdict(float)
    _cnt: Dict[str, int] = defaultdict(int)
    _enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")

    @classmethod
    def enable(cls, on: bool = True) -> None:
        cls._enabled = on

    @classmethod
    def enabled(cls) -> bool:
        return cls._enabled

    @classmethod
    def add(cls, label: str, seconds: float) -> None:
        cls._acc[label] += seconds
        cls._cnt[label] += 1

    @classmethod
    def reset(cls) -> None:
        cls._acc.clear()
        cls._cnt.clear()

    @classmethod
    def summary(cls) -> Dict[str, float]:
        return dict(cls._acc)

    @classmethod
    def log_summary(cls) -> None:
        if not cls._acc:
            return
        log_info("lightgbm_tpu phase timings (host wall):")
        for label, sec in sorted(cls._acc.items(), key=lambda kv: -kv[1]):
            log_info(f"  {label:32s} {sec:10.3f} s  x{cls._cnt[label]}")


@contextmanager
def timed(label: str) -> Iterator[None]:
    """Time a phase and annotate it for device traces."""
    import jax

    with jax.profiler.TraceAnnotation(label):
        if not Timer._enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            Timer.add(label, time.perf_counter() - t0)


@contextmanager
def trace_to(log_dir: str) -> Iterator[None]:
    """Capture a full device trace (jax.profiler.trace wrapper) — view
    with tensorboard's profile plugin, or any xplane.pb reader."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
