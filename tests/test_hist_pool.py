"""Histogram pool (HistogramPool analog): bounded [PS, F, B, 2] slot
cache with LRU eviction + recompute-on-miss, budget from
``histogram_pool_size`` (MB, -1 = unlimited — reference config.h:301).

The pooled grower must produce the SAME trees as the full cache: the
recompute path streams the same window chunks in the same order, so
quantized training is bit-exact and float training agrees on any data
whose splits aren't knife-edge ties.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=3000, f=12, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = ((X[:, 0] + 0.5 * X[:, 1] - 0.3 * X[:, 2] +
          0.2 * rs.randn(n)) > 0).astype(float)
    return X, y


def _trees(bst):
    return bst.dump_model()["tree_info"]


@pytest.mark.parametrize("quant", [True, False])
def test_pooled_equals_full_cache(quant):
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 10, "seed": 3}
    if quant:
        base.update({"use_quantized_grad": True,
                     "stochastic_rounding": False})
    full = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=5)
    # ~6 slots: well under 31 leaves, so eviction + recompute engage
    per_leaf_mb = 12 * 256 * 2 * 4 / 2 ** 20
    pooled = lgb.train({**base,
                        "histogram_pool_size": 6.4 * per_leaf_mb},
                       lgb.Dataset(X, label=y), num_boost_round=5)
    assert pooled._engine.grow_cfg.hist_pool_slots > 0
    assert pooled._engine.grow_cfg.hist_pool_slots < 31
    if quant:
        # int32 histograms: the recompute path accumulates the same
        # chunk sequence exactly, so pooled training is bit-identical
        tf, tp = _trees(full), _trees(pooled)
        for a, b in zip(tf, tp):
            assert a["num_leaves"] == b["num_leaves"]
            assert a["tree_structure"] == b["tree_structure"]
        np.testing.assert_allclose(full.predict(X[:200]),
                                   pooled.predict(X[:200]), rtol=1e-6)
    else:
        # float histograms: a recomputed parent differs from the
        # cached one in the last ulp (subtract vs fresh accumulate),
        # which may flip knife-edge tie splits — require model
        # QUALITY parity instead of structural identity
        def logloss(b):
            p = np.clip(b.predict(X), 1e-7, 1 - 1e-7)
            return -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
        lf, lp = logloss(full), logloss(pooled)
        assert abs(lf - lp) < 0.02 * max(lf, 1e-3)


def test_pool_disabled_when_budget_suffices():
    X, y = _data(n=800, f=5)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "histogram_pool_size": 512.0, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    assert bst._engine.grow_cfg.hist_pool_slots == 0


def _exact_quant_pair(extra, n=2500, f=12, rounds=4, seed=0,
                      leaves=31):
    """Train full-cache vs pooled under quantized gradients (exact
    int32 histograms -> bit-identical trees) with ``extra`` params."""
    X, y = _data(n=n, f=f, seed=seed)
    base = {"objective": "binary", "num_leaves": leaves, "verbosity": -1,
            "min_data_in_leaf": 10, "seed": 3,
            "use_quantized_grad": True, "stochastic_rounding": False}
    base.update(extra)
    full = lgb.train(base, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)
    per_leaf_mb = f * 256 * 2 * 4 / 2 ** 20
    pooled = lgb.train({**base,
                        "histogram_pool_size": 6.4 * per_leaf_mb},
                       lgb.Dataset(X, label=y), num_boost_round=rounds)
    assert 0 < pooled._engine.grow_cfg.hist_pool_slots < leaves, \
        "pool did not engage"
    tf, tp = _trees(full), _trees(pooled)
    for a, b in zip(tf, tp):
        assert a["num_leaves"] == b["num_leaves"]
        assert a["tree_structure"] == b["tree_structure"]
    np.testing.assert_allclose(full.predict(X[:200]),
                               pooled.predict(X[:200]), rtol=1e-6)
    return full, pooled


def test_pool_with_cegb_tree_exact():
    """Round 4: CEGB's stored-candidate re-search now runs under the
    pool (recompute-on-miss), tree-exact vs the full cache — the
    reference pool serves CEGB too (feature_histogram.hpp)."""
    _exact_quant_pair({"cegb_penalty_split": 1e-4,
                       "cegb_tradeoff": 0.5,
                       "cegb_penalty_feature_coupled":
                           [0.01] * 12})


def test_pool_with_intermediate_monotone_tree_exact():
    """Intermediate monotone's every-split re-search under the pool."""
    _exact_quant_pair({"monotone_constraints":
                           [1, -1] + [0] * 10,
                       "monotone_constraints_method": "intermediate"})


def test_pool_with_forced_splits_tree_exact(tmp_path):
    """Forced splits read the parent histogram through the pool."""
    import json
    p = tmp_path / "forced.json"
    p.write_text(json.dumps({"feature": 0, "threshold": 0.0,
                             "left": {"feature": 1,
                                      "threshold": 0.0}}))
    _exact_quant_pair({"forcedsplits_filename": str(p)})


def test_wide_dense_matrix_trains_with_bounded_cache():
    """The memory-budget scenario the pool exists for: many DENSE
    (non-bundleable) features, where the full [L, F, B, 2] cache would
    dwarf the budget."""
    rs = np.random.RandomState(7)
    n, f = 2000, 600
    X = rs.randn(n, f)
    y = ((X[:, :5].sum(axis=1) + 0.3 * rs.randn(n)) > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 63,
                     "histogram_pool_size": 8.0, "max_bin": 63,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    eng = bst._engine
    assert 0 < eng.grow_cfg.hist_pool_slots < 63
    p = bst.predict(X[:400])
    assert np.isfinite(p).all()
    assert np.mean((p > 0.5) == (y[:400] > 0.5)) > 0.8
