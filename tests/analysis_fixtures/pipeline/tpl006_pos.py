# tpulint fixture: TPL006 positive — the lifecycle supervisor holding
# its stats lock across a jax dispatch (a pipeline step that scores
# the freshly published model while a loadgen thread wants the lock
# for its own bookkeeping: one slow device call stalls every request
# outcome record).
import threading

import jax.numpy as jnp

_lock = threading.Lock()
_summary = {"auc_sum": 0.0}


def record_generation_auc(scores):
    with _lock:
        # EXPECT: TPL006
        auc = jnp.mean(scores)        # dispatch while holding _lock
        _summary["auc_sum"] += float(auc)
