"""Shared scaffolding for multi-process (subprocess-spawning) tests.

One home for the launch/cleanup idioms `tests/test_multiprocess.py`
introduced — free-port pick, session-group SIGKILL, drain-with-partial-
output — so the distributed chaos tests (test_distributed_resilience.py)
reuse them instead of re-growing copies.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, Optional, Sequence

import pytest

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_DIR = os.path.dirname(TESTS_DIR)

# the free-port / group-SIGKILL primitives live in the (jax-free)
# elastic supervisor — one implementation, reused here
from lightgbm_tpu.resilience.elastic import (  # noqa: E402
    _free_port as free_port, _kill_group as kill_group)


def drain_all(procs: Sequence[subprocess.Popen], reason: str) -> None:
    """Kill every worker group and fail with their partial output —
    a hung collective must not leak orphan workers into the tier-1
    budget, and the partial logs are the only diagnostic there is."""
    for q in procs:
        kill_group(q)
    partials = []
    for rank, q in enumerate(procs):
        try:
            out, _ = q.communicate(timeout=30)
        except Exception:
            out = b""
        partials.append(f"--- rank {rank} partial output "
                        f"(returncode {q.returncode}) ---\n"
                        f"{(out or b'').decode(errors='replace')}")
    pytest.fail(reason + "; killed worker process groups.\n"
                + "\n".join(partials))


def worker_base_env(extra: Optional[Dict[str, str]] = None
                    ) -> Dict[str, str]:
    """Environment for a spawned worker: the test runner's env minus
    the single-process JAX platform pins (workers set their own), with
    the repo importable."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS",
                        "LIGHTGBM_TPU_FAULT_INJECT",
                        "LIGHTGBM_TPU_CHECKPOINT",
                        "LIGHTGBM_TPU_TELEMETRY")}
    env["PYTHONPATH"] = REPO_DIR
    if extra:
        env.update(extra)
    return env


def spawn_worker(args: Sequence[str], env: Dict[str, str],
                 **popen_kwargs) -> subprocess.Popen:
    """Start one python worker in its own session with captured
    output."""
    return subprocess.Popen(
        [sys.executable, *args], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True, **popen_kwargs)


def _cpu_backend_lacks_multiprocess() -> bool:
    """jaxlib <= 0.4.x refuses multiprocess XLA computations on the
    CPU backend ("Multiprocess computations aren't implemented on the
    CPU backend"), so device-transport collective tests can only run
    where a real accelerator mesh exists."""
    import jax

    if jax.default_backend() != "cpu":
        return False
    try:
        import jaxlib
        major, minor = (int(x) for x in
                        jaxlib.__version__.split(".")[:2])
        return (major, minor) < (0, 5)
    except Exception:
        return True


#: mark for tests that need jit-level collectives ACROSS processes
#: (the kv host-transport tests do not — they run everywhere)
requires_multiprocess_computations = pytest.mark.skipif(
    _cpu_backend_lacks_multiprocess(),
    reason="CPU backend in this jaxlib cannot run multiprocess XLA "
           "computations (device-transport collectives need TPU/GPU)")
