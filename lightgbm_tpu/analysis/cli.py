"""``python -m lightgbm_tpu lint`` — the tpulint CLI.

Deliberately importable (and runnable) WITHOUT jax: the dispatcher in
``lightgbm_tpu/__main__.py`` routes ``lint`` here before the training
CLI (and its jax import) ever loads, so the analyzer runs in
environments that cannot initialize a backend at all (CI formatters,
pre-commit hooks, docs builds).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Set

EXIT_CODES = """\
exit codes:
  0  clean: no findings outside the baseline
  1  findings (or stale/unjustified baseline entries with --strict)
  2  usage or internal error
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu lint",
        description=(
            "tpulint: JAX/TPU-aware static analyzer for the boosting "
            "hot path and the distributed layer. Builds a cross-module "
            "call graph, computes jit-reachability (which functions "
            "are only ever entered through a jax.jit/pjit/shard_map "
            "wrapper) plus per-function CFGs with rank-taint and "
            "lock dataflow, and checks the hazard catalog "
            "TPL001-TPL010 (eager lax loops, host syncs, recompile "
            "storms, donation violations, order-unstable iteration, "
            "locks across dispatch, rank-divergent collective order, "
            "thread-shared-state races, float64 promotion leaks, "
            "device collectives under traced conditionals) plus the "
            "cross-process contract pass TPL015-TPL018 (JSONL event "
            "schemas, metric families, LIGHTGBM_TPU_* env vars, and "
            "fault kinds checked against the single-source registries "
            "in obs/schemas.py). "
            "With --ir it additionally lowers every register_jit "
            "entry point on CPU (never executing) and checks the IR "
            "contracts TPL011-TPL014 (strong float64 in the jaxpr, "
            "collective bytes vs tools/ir_budgets.json, donation "
            "honored in the lowered program, recompile surface "
            "declared). See docs/STATIC_ANALYSIS.md."),
        epilog=EXIT_CODES,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text",
                   help="output format (default: text); sarif emits "
                        "SARIF 2.1.0 for code-review tooling")
    p.add_argument("--changed", metavar="REF", nargs="?", const="HEAD",
                   default=None,
                   help="lint only package files differing from git "
                        "REF (default HEAD) — the ~100 ms pre-commit "
                        "mode; with no changed files the analyzer is "
                        "not even constructed")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="accepted-findings file (default: "
                        "tools/tpulint_baseline.txt when present; "
                        "pass an empty string to disable)")
    p.add_argument("--rule", metavar="TPLNNN", action="append",
                   default=None,
                   help="run only this rule (repeatable); default: "
                        "TPL001-TPL010 and the contract pass "
                        "TPL015-TPL018 (TPL011-TPL014 also need "
                        "--ir)")
    p.add_argument("--ir", action="store_true",
                   help="also lower every register_jit entry point "
                        "at its declared signatures and run the IR "
                        "rules TPL011-TPL014; the only lint mode "
                        "that imports jax (CPU, lowering only)")
    p.add_argument("--ir-entry", metavar="NAME", action="append",
                   default=None,
                   help="with --ir: lower only this entry point "
                        "(repeatable; 'parallel/dp_grow' or "
                        "'parallel/dp_grow@wide-sharded')")
    p.add_argument("--root", metavar="DIR", default=None,
                   help="package directory to analyze (default: the "
                        "installed lightgbm_tpu package)")
    p.add_argument("--write-baseline", metavar="FILE", default=None,
                   help="write ALL current findings to FILE as a "
                        "baseline skeleton (justifications left as "
                        "TODOs) and exit 0")
    p.add_argument("--strict", action="store_true",
                   help="also fail (exit 1) on stale or unjustified "
                        "baseline entries")
    return p


def changed_relpaths(root: str, ref: str) -> Set[str]:
    """Package-relative paths of ``*.py`` files differing from git
    ``ref`` (committed diffs + working tree + untracked). Raises
    ``ValueError`` when git cannot answer (not a repo, bad ref)."""
    import subprocess
    pkg = os.path.basename(os.path.normpath(root))
    repo = os.path.dirname(os.path.abspath(root))
    out: Set[str] = set()
    # --relative: diff paths come out relative to cwd (the package's
    # parent), not the repo toplevel — required when the package lives
    # below the repo root, and what ls-files already does
    cmds = [
        ["git", "diff", "--relative", "--name-only", ref, "--", pkg],
        ["git", "ls-files", "--others", "--exclude-standard",
         "--", pkg],
    ]
    for cmd in cmds:
        try:
            proc = subprocess.run(cmd, cwd=repo, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.SubprocessError) as e:
            raise ValueError(f"--changed: {' '.join(cmd[:2])} failed "
                             f"({e})")
        if proc.returncode != 0:
            raise ValueError(
                f"--changed: `{' '.join(cmd)}` failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith(pkg + "/") and line.endswith(".py"):
                rel = line[len(pkg) + 1:]
                # deleted files have nothing left to lint
                if os.path.exists(os.path.join(root, rel)):
                    out.add(rel)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(
        sys.argv[1:] if argv is None else argv)
    if args.write_baseline and args.rule:
        # a rule-filtered run sees only a slice of the findings;
        # writing it out would silently drop every other rule's
        # accepted entries (and their justifications)
        print("tpulint: error: --write-baseline requires a full run "
              "(drop --rule)", file=sys.stderr)
        return 2
    if args.write_baseline and args.changed is not None:
        print("tpulint: error: --write-baseline requires a full run "
              "(drop --changed)", file=sys.stderr)
        return 2
    if args.ir_entry and not args.ir:
        print("tpulint: error: --ir-entry requires --ir",
              file=sys.stderr)
        return 2
    ir_rule_ids = {"TPL011", "TPL012", "TPL013", "TPL014"}
    if args.rule and not args.ir and ir_rule_ids & set(args.rule):
        # keep the contract explicit: the jax import only ever
        # happens under --ir, never because a rule id implied it
        print(f"tpulint: error: "
              f"{', '.join(sorted(ir_rule_ids & set(args.rule)))} "
              f"are IR rules — add --ir", file=sys.stderr)
        return 2
    from .engine import default_scope, package_root, run_lint
    scope = None
    if args.changed is not None:
        root = args.root or package_root()
        try:
            changed = changed_relpaths(root, args.changed)
        except ValueError as e:
            print(f"tpulint: error: {e}", file=sys.stderr)
            return 2
        scope = default_scope(sorted(changed))
        if not scope:
            # the pre-commit fast path: nothing in the rule scope
            # changed, so don't even parse the package
            print(f"tpulint: 0 findings (no files in scope changed "
                  f"vs {args.changed})")
            return 0
    try:
        result = run_lint(root=args.root, rules=args.rule,
                          baseline_path=args.baseline, scope=scope,
                          ir=args.ir, ir_entries=args.ir_entry)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"tpulint: error: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        result.write_baseline(args.write_baseline)
        print(f"tpulint: wrote {len(result.findings) + len(result.baselined)} "
              f"entries to {args.write_baseline}")
        return 0
    if args.format == "json":
        from .report import render_json
        print(render_json(result))
    elif args.format == "sarif":
        from .report import render_sarif
        print(render_sarif(result))
    else:
        from .report import render_text
        print(render_text(result))
    if result.findings:
        return 1
    if args.strict and (result.stale_baseline
                        or result.unjustified_baseline
                        or result.stale_budget
                        or result.unjustified_budget):
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
