# tpulint fixture: TPL002 positive — host syncs in traced / hot code.
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def traced_sync(x):
    # EXPECT: TPL002
    host = np.asarray(x)          # concretizes a tracer
    # EXPECT: TPL002
    s = float(x[0])               # float() on a tracer
    return jnp.sum(jnp.asarray(host)) + s


# tpulint: hot
def per_iteration_driver(score, tree):
    # EXPECT: TPL002
    fetched = jax.device_get(score)
    # EXPECT: TPL002
    n = tree.num_leaves.item()
    # EXPECT: TPL002
    score.block_until_ready()
    return fetched, n
