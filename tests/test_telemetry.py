"""The run-telemetry subsystem (lightgbm_tpu/obs/): JSONL event schema,
recompile counting, disabled-is-free, registry semantics, and the cv()
composition — docs/OBSERVABILITY.md is the contract under test."""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as cbm
from lightgbm_tpu import obs
from lightgbm_tpu.obs import (ITERATION_EVENT_KEYS, MetricsRegistry,
                              RecompileWatcher, device_memory_stats,
                              register_jit, summarize_events)
from lightgbm_tpu.utils.timer import Timer
from tests.conftest import make_synthetic_binary


def _small_train(tmp_path, callbacks=None, rounds=5, valid=True,
                 params=None):
    X, y = make_synthetic_binary(n=800, f=8)
    ds = lgb.Dataset(X[:600], label=y[:600])
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "min_data_in_leaf": 5}
    p.update(params or {})
    valid_sets = None
    if valid:
        vs = lgb.Dataset(X[600:], label=y[600:], reference=ds)
        valid_sets = [vs]
    return lgb.train(p, ds, num_boost_round=rounds,
                     valid_sets=valid_sets, callbacks=callbacks)


# ---------------------------------------------------------------------
# MetricsRegistry
# ---------------------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("iters").inc()
    reg.counter("iters").inc(2)
    reg.gauge("hbm", device="0").set(100)
    reg.gauge("hbm", device="0").set(50)
    reg.histogram("phase_seconds", phase="grow").observe(0.5)
    reg.histogram("phase_seconds", phase="grow").observe(1.5)
    snap = reg.snapshot()
    assert snap["iters"]["series"][0]["value"] == 3
    g = snap["hbm"]["series"][0]
    assert g["labels"] == {"device": "0"}
    assert g["value"] == 50 and g["max"] == 100
    h = snap["phase_seconds"]["series"][0]
    assert h["count"] == 2 and h["total"] == 2.0 and h["mean"] == 1.0
    assert h["min"] == 0.5 and h["max"] == 1.5


def test_registry_kind_collision_raises():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")


def test_registry_counter_rejects_negative():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.counter("x").inc(-1)


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def hammer():
        for _ in range(500):
            reg.counter("n").inc()
            reg.histogram("h", phase="p").observe(1.0)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["n"]["series"][0]["value"] == 4000
    assert snap["h"]["series"][0]["count"] == 4000


# ---------------------------------------------------------------------
# recompile tracking
# ---------------------------------------------------------------------

def test_recompile_counter_increments_once_on_shape_change():
    fn = register_jit("test/shape_change",
                      jax.jit(lambda x: (x * 2).sum()))
    watch = RecompileWatcher()
    fn(jnp.ones((8,)))
    assert watch.delta() == 1          # first shape: one compile
    fn(jnp.ones((8,)))
    assert watch.delta() == 0          # cache hit: no compile
    fn(jnp.ones((9,)))
    assert watch.delta() == 1          # shape change: exactly one
    assert watch.total == 2


def test_register_jit_passthrough_for_plain_callables():
    def plain(x):
        return x

    assert register_jit("test/plain", plain) is plain


def test_watcher_counts_replacement_as_new_compiles():
    fn1 = register_jit("test/replaced", jax.jit(lambda x: x + 1))
    watch = RecompileWatcher()
    fn1(jnp.ones(3))
    assert watch.delta() == 1
    # rebuild (reset_parameter / per-fold pattern): new function, its
    # compiles must count even though the old cache size "disappears"
    fn2 = register_jit("test/replaced", jax.jit(lambda x: x + 2))
    fn2(jnp.ones(3))
    assert watch.delta() == 1


def test_device_memory_stats_keys():
    stats = device_memory_stats()
    assert set(stats) == {"bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit"}
    for v in stats.values():
        assert v is None or isinstance(v, int)


# ---------------------------------------------------------------------
# the JSONL event stream
# ---------------------------------------------------------------------

def test_jsonl_schema_one_valid_event_per_iteration(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rounds = 5
    # num_leaves unique to this test: a guaranteed grower cache miss at
    # iteration 0 regardless of what compiled earlier in the process
    _small_train(tmp_path, callbacks=[cbm.telemetry(path)],
                 rounds=rounds, params={"num_leaves": 11})
    lines = [ln for ln in open(path).read().splitlines() if ln]
    all_events = [json.loads(ln) for ln in lines]
    # the guaranteed cache miss records its XLA cost attribution
    # (obs/cost.py) ahead of iteration 0's line; iteration events stay
    # strictly one per round
    compiles = [ev for ev in all_events if ev["event"] == "compile"]
    assert compiles, "the iteration-0 cache miss must record a " \
                     "compile event"
    assert all(ev["entry"] for ev in compiles)
    iter_lines = [json.dumps(ev) for ev in all_events
                  if ev["event"] == "iteration"]
    assert len(iter_lines) == rounds
    for i, line in enumerate(iter_lines):
        ev = json.loads(line)
        for key in ITERATION_EVENT_KEYS:
            assert key in ev, f"missing {key!r} in event {i}"
        assert ev["event"] == "iteration"
        assert ev["iteration"] == i
        assert ev["phases"], "phase table must not be empty"
        for label, v in ev["phases"].items():
            assert v["count"] >= 0 and v["total"] >= 0.0, (label, v)
        assert ev["recompiles"]["delta"] >= 0
        assert ev["recompiles"]["total"] >= ev["recompiles"]["delta"]
        for key in ("bytes_in_use", "peak_bytes_in_use"):
            assert key in ev["hbm"]
        assert ev["tree"]["leaves"] is not None
        assert ev["tree"]["leaves"] >= 1
        assert ev["tree"]["split_gain_sum"] >= 0.0
        assert ev["eval"], "valid set present -> eval results required"
    # first iteration compiles the grower; later cache hits
    first = json.loads(iter_lines[0])
    assert first["recompiles"]["delta"] >= 1


def test_process_fault_log_pollution_is_isolated_a():
    """First half of the order-independence regression (the
    test_distributed_resilience -> test_jsonl_schema flake, ISSUE 11):
    leave stray events in the PROCESS-LEVEL fault log exactly like the
    in-process chaos tests do and rely on the conftest autouse fixture
    to drain them after this test."""
    from lightgbm_tpu.resilience.faults import record_fault_event
    record_fault_event("collective_timeout", iteration=12,
                       action="raise", detail="synthetic leak (test)")
    record_fault_event("init_retry", action="retry",
                       detail="synthetic leak (test)")


def test_process_fault_log_pollution_is_isolated_b(tmp_path):
    """Second half: the previous test's leaked process-level fault
    events must NOT appear in this run's JSONL stream — without the
    conftest isolation fixture the recorder drains them here and the
    one-event-per-iteration schema breaks (reproduced at b344f30 with
    test_distributed_resilience running first)."""
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS
    assert not FAULT_EVENTS, (
        "process-level fault log leaked across tests — the conftest "
        "_isolate_process_fault_log fixture is gone or broken")
    path = str(tmp_path / "isolated.jsonl")
    rounds = 3
    _small_train(tmp_path, callbacks=[cbm.telemetry(path)],
                 rounds=rounds, valid=False)
    lines = [ln for ln in open(path).read().splitlines() if ln]
    events = [json.loads(ln) for ln in lines]
    # compile events are this RUN's own cost attribution, not leakage;
    # fault events here would be the cross-test pollution
    assert [e["event"] for e in events
            if e["event"] != "compile"] == ["iteration"] * rounds


def test_telemetry_records_fused_path_tree_stats(tmp_path):
    """No valid sets -> the fused/deferred path; tree stats must still
    be read (via the pending async copies, without flushing them)."""
    path = str(tmp_path / "fused.jsonl")
    bst = _small_train(tmp_path, callbacks=[cbm.telemetry(path)],
                       rounds=4, valid=False)
    events = [json.loads(ln) for ln in open(path).read().splitlines()
              if ln]
    events = [ev for ev in events if ev["event"] == "iteration"]
    assert len(events) == 4
    assert all(ev["tree"]["leaves"] >= 1 for ev in events)
    # the deferred queue must still materialize the full model
    assert bst.num_trees() == 4


def test_disabled_recorder_writes_nothing(tmp_path):
    path = str(tmp_path / "never.jsonl")
    was_enabled = Timer.enabled()
    _small_train(tmp_path, callbacks=None, rounds=3)
    assert not os.path.exists(path)
    assert Timer.enabled() == was_enabled


def test_timer_state_restored_after_telemetry(tmp_path):
    path = str(tmp_path / "run.jsonl")
    assert not Timer.enabled()
    _small_train(tmp_path, callbacks=[cbm.telemetry(path)], rounds=2)
    assert not Timer.enabled()


def test_env_var_activates_telemetry(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("LIGHTGBM_TPU_TELEMETRY", path)
    _small_train(tmp_path, rounds=3)
    events = [json.loads(ln) for ln in open(path).read().splitlines()
              if ln]
    events = [ev for ev in events if ev["event"] == "iteration"]
    assert len(events) == 3


def test_cv_composes_with_telemetry(tmp_path):
    path = str(tmp_path / "cv.jsonl")
    X, y = make_synthetic_binary(n=600, f=6)
    ds = lgb.Dataset(X, label=y)
    res = lgb.cv({"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "min_data_in_leaf": 5},
                 ds, num_boost_round=4, nfold=3,
                 callbacks=[cbm.telemetry(path)])
    assert any(k.endswith("-mean") for k in res)
    events = [json.loads(ln) for ln in open(path).read().splitlines()
              if ln]
    events = [ev for ev in events if ev["event"] == "iteration"]
    assert len(events) == 4          # one event per cv iteration
    # tree stats aggregate across the fold engines: 3 folds x 1 tree
    assert all(ev["tree"]["trees"] == 3 for ev in events)
    assert all(ev["eval"] for ev in events)


def test_early_stopping_still_closes_recorder(tmp_path):
    path = str(tmp_path / "es.jsonl")
    X, y = make_synthetic_binary(n=800, f=8)
    ds = lgb.Dataset(X[:600], label=y[:600])
    vs = lgb.Dataset(X[600:], label=y[600:], reference=ds)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "min_data_in_leaf": 5},
              ds, num_boost_round=50, valid_sets=[vs],
              callbacks=[cbm.early_stopping(2, verbose=False),
                         cbm.telemetry(path)])
    assert not Timer.enabled()       # finish() ran despite the unwind
    assert os.path.exists(path)


# ---------------------------------------------------------------------
# stats summarizer + CLI
# ---------------------------------------------------------------------

def test_stats_summary_and_cli(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    _small_train(tmp_path, callbacks=[cbm.telemetry(path)], rounds=4)
    summary = summarize_events(path)
    assert summary["iterations"] == 4
    assert summary["recompiles"] >= 0  # 0 when the grower is cache-warm
    assert summary["total_leaves"] >= 4
    assert "tree_learner/grow" in summary["phases"]
    assert summary["last_eval"]

    from lightgbm_tpu.cli import main
    assert main(["stats", path]) == 0
    out = capsys.readouterr().out
    assert "iterations" in out
    assert "tree_learner/grow" in out


def test_stats_cli_missing_file(capsys):
    from lightgbm_tpu.cli import main
    assert main(["stats", "/nonexistent/nope.jsonl"]) == 1


def test_verbosity_param_silences_info(capsys):
    """Satellite regression: verbosity=-1 must silence [Info] lines for
    the call and restore the prior level afterwards."""
    from lightgbm_tpu.utils.log import get_verbosity
    prev = get_verbosity()
    X, y = make_synthetic_binary(n=400, f=6)
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "binary", "num_leaves": 4, "verbosity": -1,
               "min_data_in_leaf": 5}, ds, num_boost_round=2,
              valid_sets=[ds])
    out = capsys.readouterr().out
    assert "[Info]" not in out
    assert get_verbosity() == prev


def test_fault_event_drain_is_atomic_under_concurrent_appends():
    """Regression for the lost-event race: the recorder used to drain
    fault logs with a bare ``list(log), []`` swap, so an event appended
    between the copy and the clear (a watchdog abort on another thread,
    a concurrent trainer) vanished. ``faults.drain_events`` swaps under
    the same lock ``append_fault_event`` takes — every event must land
    in exactly one drain."""
    import threading

    from lightgbm_tpu.resilience import faults

    # isolate from any events other tests left behind
    faults.drain_events(faults.FAULT_EVENTS)
    n_threads, per_thread = 4, 100  # 400 < the 512 cap: nothing ages out
    start = threading.Barrier(n_threads + 1)

    def writer(tid):
        start.wait()
        for i in range(per_thread):
            faults.record_fault_event(
                "test_race", iteration=i, action="noop",
                detail=f"t{tid}/{i}")

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    drained = []
    start.wait()
    while any(t.is_alive() for t in threads):
        drained.extend(faults.drain_events(faults.FAULT_EVENTS))
    for t in threads:
        t.join()
    drained.extend(faults.drain_events(faults.FAULT_EVENTS))
    mine = [ev for ev in drained if ev["kind"] == "test_race"]
    assert len(mine) == n_threads * per_thread, (
        f"lost {n_threads * per_thread - len(mine)} fault events "
        "across concurrent drains")
    assert len({ev["detail"] for ev in mine}) == n_threads * per_thread
    assert not faults.FAULT_EVENTS


def test_event_key_lists_are_the_schema_registry():
    """Satellite of the contract-lint PR: exactly one declaration per
    event. The recorder's ITERATION_EVENT_KEYS and the fault machinery
    are derived views of obs/schemas.py, never parallel lists."""
    from lightgbm_tpu.obs import schemas
    from lightgbm_tpu.resilience import elastic, faults
    assert ITERATION_EVENT_KEYS == \
        tuple(schemas.EVENTS["iteration"]["required"])
    assert faults._KNOWN_KINDS == schemas.injectable_fault_kinds()
    assert elastic._ONE_SHOT_KINDS == schemas.one_shot_fault_kinds()
    # the one-shot strip list is a subset classification of the
    # injectable kinds, not an independent registry
    assert set(elastic._ONE_SHOT_KINDS) <= set(faults._KNOWN_KINDS)
    # every declared event carries "event" itself as a required key
    for name, spec in schemas.EVENTS.items():
        assert "event" in spec["required"], name


def test_summarize_events_rejects_undeclared_event(tmp_path):
    """Ride-along bugfix: an undeclared event name is a corrupt or
    foreign-version stream -> named error, not a silent skip (and
    never a KeyError)."""
    from lightgbm_tpu.obs import UnknownEventError
    path = tmp_path / "bad.jsonl"
    path.write_text(
        json.dumps({"event": "fault", "kind": "nonfinite",
                    "iteration": 0, "action": "skip_tree",
                    "detail": "x", "time": 1.0}) + "\n"
        + json.dumps({"event": "iterration", "iteration": 0}) + "\n")
    with pytest.raises(UnknownEventError) as exc:
        summarize_events(str(path))
    assert exc.value.event_name == "iterration"
    assert "iterration" in str(exc.value)


def test_summarize_events_undeclared_tolerates_truncated_tail(tmp_path):
    """The truncated-final-line tolerance survives the undeclared-name
    check: a SIGKILL mid-write still yields the stream's summary."""
    good = json.dumps({"event": "fault", "kind": "nonfinite",
                       "iteration": 0, "action": "skip_tree",
                       "detail": "x", "time": 1.0})
    path = tmp_path / "cut.jsonl"
    path.write_text(good + "\n" + '{"event": "iterr')  # torn tail
    summary = summarize_events(str(path))
    assert summary["faults"] == {"nonfinite": 1}
