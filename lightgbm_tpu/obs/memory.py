"""Device HBM gauges via ``device.memory_stats()``.

TPU/GPU runtimes expose allocator stats; the CPU backend returns
``None``. The telemetry schema keeps the keys with explicit nulls in
that case so consumers can rely on their presence.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["device_memory_stats"]

_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats(device=None) -> Dict[str, Optional[int]]:
    """HBM usage for ``device`` (default: first local device).

    Always returns the full key set; values are ``None`` when the
    backend has no allocator stats (CPU) or the query fails (a dead
    tunnel must degrade telemetry, never training).
    """
    out: Dict[str, Optional[int]] = {k: None for k in _KEYS}
    try:
        if device is None:
            import jax
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return out
    if not stats:
        return out
    for k in _KEYS:
        v = stats.get(k)
        if v is not None:
            out[k] = int(v)
    return out
