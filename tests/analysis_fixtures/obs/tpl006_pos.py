# tpulint fixture: TPL006 positive — lock held across jax dispatch.
# Lives under obs/ because the rule is scoped to the telemetry layer.
import threading

import jax
import jax.numpy as jnp

_lock = threading.Lock()
_state = {"total": 0.0}


def record(values):
    with _lock:
        # EXPECT: TPL006
        total = jnp.sum(values)        # dispatch while holding _lock
        _state["total"] += float(total)


class Recorder:
    def __init__(self):
        self._lock = threading.RLock()
        self.acc = None

    def observe(self, x):
        with self._lock:
            # EXPECT: TPL006
            y = jax.device_put(x)
            self.acc = y
