"""Two-pass streaming ingestion: chunks -> BinMappers -> binned shard.

The out-of-core construction pipeline behind
``Dataset(chunked_source | path, params={"ingest_chunk_rows": N})``
(ROADMAP open item 3; the reference's layer-3 DatasetLoader two-round
load, dataset_loader.cpp:299,960, rebuilt over arbitrary chunk
sources):

pass 1
    Stream the source once: count rows, validate the feature width,
    and collect the bin-construction sample. When the source declares
    its row count the sample is the EXACT row-index draw the eager
    constructor makes (``rng.choice(n, sample_cnt)`` under
    ``data_random_seed``), so the resulting BinMappers are
    bit-identical to an in-memory construct of the same data
    (``find_bin`` is input-order-invariant — it reduces through
    ``np.unique``). Unknown-length sources fall back to reservoir
    sampling under the same seed; the two agree whenever
    ``bin_construct_sample_cnt`` covers the whole stream. Under a
    multi-process world, process 0's mappers are then broadcast
    through the watchdog-guarded host transport
    (``parallel.spmd.sync_bin_mappers``) so every rank bins against
    identical boundaries.

pass 2
    Stream the source again: each chunk is binned against the (synced)
    mappers and written straight into this host's preallocated
    ``[n, F_used]`` u8/u16 shard. The checkpoint data fingerprint is
    accumulated incrementally over the label/bin chunks as they pass
    through (``dataset_digest``), so ``resume_from`` works without the
    raw data ever existing — and still refuses snapshots written
    against different data.

Peak host memory is ``O(ingest_chunk_rows x n_features)`` floats plus
the bounded sample (``bin_construct_sample_cnt x n_features``) plus
the binned product (1-2 bytes/value) — never the dense float matrix.
Host-side numpy only; jax is touched exclusively through the lazy
world-size probe below, so ingestion stays importable (and lintable)
where no backend exists.
"""

from __future__ import annotations

import hashlib
import sys
import time
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from .sources import DEFAULT_CHUNK_ROWS, RowChunkSource

__all__ = ["ingest_dataset", "IngestResult", "dataset_digest",
           "INGEST_FAULT_ITERATION"]

#: the pseudo-iteration distributed fault kinds fire at during ingest:
#: ``LIGHTGBM_TPU_FAULT_INJECT=rank_kill@-1`` kills the selected rank
#: right before the pass-1 mapper sync (docs/RESILIENCE.md), so the
#: survivors' watchdog must abort naming ``spmd/sync_bin_mappers``.
INGEST_FAULT_ITERATION = -1


class IngestResult(NamedTuple):
    bins: np.ndarray               # [n, F_used] u8/u16
    mappers: List                  # used-feature BinMappers
    used: np.ndarray               # [F_used] int32 original indices
    full_mappers: List             # one per original feature
    n: int
    F: int
    label: Optional[np.ndarray]    # [n] float64, None if source had none
    weight: Optional[np.ndarray]   # [n] float64, None if source had none
    digest: Optional[str]          # checkpoint data digest (source labels)
    raw: Optional[np.ndarray]      # [n, F_used] f32, only when keep_raw
    stats: Dict[str, Any]          # the obs `ingest` event payload


def dataset_digest(label: np.ndarray, bins: np.ndarray) -> str:
    """THE training-data identity hash (checkpoint ``data_fingerprint``):
    sha256 over the float64 label vector followed by the first 64
    binned rows. One definition shared by the eager path
    (resilience/checkpoint.py) and the incremental accumulation below,
    so a streaming construct and an in-memory construct of the same
    data agree — resume works across ingestion modes."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(
        np.asarray(label, np.float64)).tobytes())
    h.update(np.ascontiguousarray(bins[:64]).tobytes())
    return h.hexdigest()


def _world_size() -> int:
    """Process count WITHOUT forcing a jax import: if jax was never
    imported, ``jax.distributed`` cannot have been initialized (its
    setup requires the import), so the world is single-process and a
    CPU-only construct stays jax-free."""
    if "jax" not in sys.modules:
        return 1
    import jax

    try:
        return jax.process_count()
    except Exception:
        return 1


def _chunk_rows_of(source: RowChunkSource, cfg) -> int:
    return int(getattr(cfg, "ingest_chunk_rows", 0) or 0) \
        or int(getattr(source, "chunk_rows", 0) or 0) \
        or DEFAULT_CHUNK_ROWS


class _SampleAccumulator:
    """Collect the pass-1 bin-construction sample from streamed chunks.

    ``n_declared`` known: gather exactly the eager constructor's
    ``rng.choice`` row set (or every row when the budget covers n).
    Unknown: vectorized reservoir over the stream, capacity
    ``bin_construct_sample_cnt``."""

    def __init__(self, cfg, n_declared: Optional[int]):
        self._cap = max(int(cfg.bin_construct_sample_cnt), 2)
        self._rs = np.random.RandomState(cfg.data_random_seed)
        self._wanted: Optional[np.ndarray] = None
        self._take_all = False
        if n_declared is not None:
            sample_cnt = min(self._cap, n_declared)
            if sample_cnt < n_declared:
                self._wanted = np.sort(self._rs.choice(
                    n_declared, size=sample_cnt, replace=False))
            else:
                self._take_all = True
        self._parts: List[np.ndarray] = []
        self._buf: Optional[np.ndarray] = None   # reservoir storage
        self._filled = 0

    def add(self, Xc: np.ndarray, start: int) -> None:
        c = Xc.shape[0]
        if self._take_all:
            self._parts.append(np.asarray(Xc, np.float64))
            return
        if self._wanted is not None:
            lo = int(np.searchsorted(self._wanted, start))
            hi = int(np.searchsorted(self._wanted, start + c))
            if hi > lo:
                self._parts.append(np.asarray(
                    Xc[self._wanted[lo:hi] - start], np.float64))
            return
        # reservoir: head-fill to capacity, then uniform replacement
        if self._buf is None:
            self._buf = np.empty((self._cap, Xc.shape[1]), np.float64)
        head = min(max(self._cap - self._filled, 0), c)
        if head:
            self._buf[self._filled:self._filled + head] = Xc[:head]
        if c > head:
            seen = np.arange(start + head, start + c, dtype=np.int64)
            j = (self._rs.random_sample(len(seen))
                 * (seen + 1)).astype(np.int64)
            repl = j < self._cap
            if repl.any():
                self._buf[j[repl]] = Xc[head:][repl]
        self._filled = min(self._filled + c, self._cap)

    def sample(self) -> np.ndarray:
        if self._buf is not None:
            return self._buf[:self._filled]
        if not self._parts:
            return np.zeros((0, 0), np.float64)
        return self._parts[0] if len(self._parts) == 1 \
            else np.concatenate(self._parts, axis=0)


def _stream_count(source: RowChunkSource, cfg,
                  sampler: Optional[_SampleAccumulator]):
    """One pass over the source: (n, F, chunk_count), feeding the
    sampler when bin mappers are being found."""
    from .sources import _as_chunk, _err

    n = 0
    F: Optional[int] = source.num_features()
    chunks = 0
    for obj in source.chunks():
        # custom RowChunkSource subclasses may yield unnormalized
        # chunks (wrong dtype, 1-D X, int labels); _as_chunk is
        # idempotent for the built-in adapters
        Xc = _as_chunk(obj).X
        if F is None:
            F = int(Xc.shape[1])
        elif Xc.shape[1] != F:
            raise _err(
                f"ingest: chunk {chunks} has {Xc.shape[1]} features, "
                f"expected {F}")
        if sampler is not None:
            sampler.add(Xc, n)
        n += Xc.shape[0]
        chunks += 1
    if n == 0:
        raise _err("ingest: the chunk source produced no rows")
    n_decl = source.num_rows()
    if n_decl is not None and n != n_decl:
        raise _err(
            f"ingest: source declared {n_decl} rows but streamed {n}")
    return n, int(F), chunks


def _find_chunk_mappers(sample: np.ndarray, cfg, cat_idx_set) -> List:
    """The eager constructor's mapper loop, verbatim, over the gathered
    sample — same per-feature budget, same missing handling."""
    from ..ops.binning import BinType, find_bin

    full_mappers = []
    for j in range(sample.shape[1]):
        mb = cfg.max_bin
        if cfg.max_bin_by_feature and j < len(cfg.max_bin_by_feature):
            mb = cfg.max_bin_by_feature[j]
        full_mappers.append(find_bin(
            sample[:, j], mb,
            min_data_in_bin=cfg.min_data_in_bin,
            bin_type=(BinType.CATEGORICAL if j in cat_idx_set
                      else BinType.NUMERICAL),
            use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing))
    return full_mappers


def ingest_dataset(source: RowChunkSource, cfg, cat_idx_set,
                   reference=None, keep_raw: bool = False) -> IngestResult:
    """Run the two-pass pipeline over ``source``.

    ``reference`` (a constructed Dataset) short-circuits pass-1 mapper
    finding: validation sets bin against the training set's mappers
    (LoadFromFileAlignWithOtherDataset semantics), so only the row
    count — skipped entirely when the source declares it — and the
    binning pass remain.

    ``keep_raw`` additionally retains the used-column raw values as
    ``[n, F_used]`` float32 during pass 2 — what ``linear_tree``
    consumers need, at exactly the eager path's retention cost (the
    reference keeps ``raw_data_`` when linear trees are on); the
    FULL-width float matrix still never exists.
    """
    from ..ops.binning import bin_matrix
    from ..utils.timer import timed
    from .sources import _as_chunk, _err

    chunk_rows = _chunk_rows_of(source, cfg)
    t0 = time.perf_counter()
    sampled_rows = 0

    # ---- pass 1: count + sample -> mappers (synced across hosts) ----
    with timed("ingest/pass1"):
        if reference is not None:
            full_mappers = reference._full_mappers
            used = np.asarray(reference._used_features, np.int32)
            mappers = list(reference.mappers)
            n_known = source.num_rows()
            if n_known is not None:
                n, F = int(n_known), len(full_mappers)
            else:
                n, F, _ = _stream_count(source, cfg, sampler=None)
            if F != len(full_mappers):
                raise _err(
                    f"ingest: source has {F} features, the reference "
                    f"dataset has {len(full_mappers)}")
        else:
            sampler = _SampleAccumulator(cfg, source.num_rows())
            n, F, _ = _stream_count(source, cfg, sampler=sampler)
            sample = sampler.sample()
            sampled_rows = int(sample.shape[0])
            full_mappers = _find_chunk_mappers(sample, cfg, cat_idx_set)
            del sample

            # chaos hook: rank_kill@-1 / stall_rank@-1 fire HERE, right
            # before the mapper sync — the survivors must watchdog-abort
            # naming the collective instead of hanging (docs/RESILIENCE.md)
            from ..resilience.faults import FaultPlan
            plan = FaultPlan.from_env()
            if plan.active:
                plan.maybe_distributed_fault(INGEST_FAULT_ITERATION)

            if _world_size() > 1:
                # broadcast process 0's FULL mapper list (not just the
                # non-trivial subset): the used-feature selection must be
                # derived from identical mappers on every rank, or the
                # binned shard widths diverge and the later allgather
                # deadlocks
                from ..parallel.spmd import sync_bin_mappers
                full_mappers = sync_bin_mappers(full_mappers)
            used = np.asarray(
                [j for j, m in enumerate(full_mappers)
                 if not m.is_trivial], np.int32)
            mappers = [full_mappers[j] for j in used]
    t1 = time.perf_counter()

    # ---- pass 2: bin chunks straight into the preallocated shard ----
    max_bins = max((m.num_bins for m in mappers), default=2)
    bdtype = np.uint8 if max_bins <= 256 else np.uint16
    bins = np.zeros((n, len(used)), bdtype)
    raw = np.zeros((n, len(used)), np.float32) if keep_raw else None
    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    label_hash = hashlib.sha256()
    row = 0
    chunks = 0
    with timed("ingest/pass2"):
        for obj in source.chunks():
            # normalize here too: the digest hashes the label BYTES,
            # so a custom source yielding float32 labels must be
            # widened to the float64 the stored vector (and the eager
            # fingerprint) uses before hashing
            Xc, yc, wc = _as_chunk(obj)
            c = Xc.shape[0]
            if Xc.shape[1] != F:
                raise _err(
                    f"ingest: pass-2 chunk {chunks} has {Xc.shape[1]} "
                    f"features, expected {F}")
            if row + c > n:
                raise _err(
                    f"ingest: second pass produced more rows than the "
                    f"first ({row + c} > {n}); chunk sources must be "
                    "re-iterable over identical data")
            if not Xc.flags.c_contiguous:
                Xc = np.ascontiguousarray(Xc)
            if len(used):
                bins[row:row + c] = bin_matrix(Xc, used, mappers, bdtype)
                if raw is not None:
                    raw[row:row + c] = Xc[:, used]
            if yc is not None:
                if label is None:
                    if row != 0:
                        raise _err(
                            "ingest: labels appeared mid-stream; every "
                            "chunk must carry them or none may")
                    label = np.zeros(n, np.float64)
                label[row:row + c] = yc
                label_hash.update(np.ascontiguousarray(yc).tobytes())
            elif label is not None:
                raise _err(
                    "ingest: labels disappeared mid-stream; every "
                    "chunk must carry them or none may")
            if wc is not None:
                if weight is None:
                    if row != 0:
                        raise _err(
                            "ingest: weights appeared mid-stream; "
                            "every chunk must carry them or none may")
                    weight = np.zeros(n, np.float64)
                weight[row:row + c] = wc
            elif weight is not None:
                raise _err(
                    "ingest: weights disappeared mid-stream; every "
                    "chunk must carry them or none may")
            row += c
            chunks += 1
    if row != n:
        raise _err(
            f"ingest: second pass streamed {row} rows, first pass {n}")
    t2 = time.perf_counter()

    digest = None
    if label is not None:
        label_hash.update(np.ascontiguousarray(bins[:64]).tobytes())
        digest = label_hash.hexdigest()

    stats = {
        "rows": int(n),
        "features": int(F),
        "used_features": int(len(used)),
        "chunks": int(chunks),
        "chunk_rows": int(chunk_rows),
        "sample_rows": int(sampled_rows),
        "pass1_s": round(t1 - t0, 6),
        "pass2_s": round(t2 - t1, 6),
        # host footprint of THIS rank's binned shard — the number a
        # shard_residency=device run drops to ~0 after placement
        # (parallel/placement.py publishes the live gauge; bench.py
        # --streaming records both so the "no host holds the global
        # matrix" claim is measured, not asserted)
        "host_binned_bytes": int(bins.nbytes),
        "source": type(source).__name__,
        "world": _world_size(),
    }
    try:
        from ..obs.registry import registry
        registry.counter("ingest_chunks").inc(chunks)
        registry.counter("ingest_rows").inc(n)
        registry.gauge("host_binned_bytes").set(float(bins.nbytes))
    except Exception:
        pass
    return IngestResult(bins, mappers, used, full_mappers, n, F,
                        label, weight, digest, raw, stats)
