"""Regression tests for reference-semantics fixes.

- cv() must row-subset ``position`` for position-debiased lambdarank
  (Metadata subset semantics, dataset.h:48-398).
- An invalid forced split aborts ALL remaining forced splits
  (abort_last_forced_split, serial_tree_learner.cpp:695-699).
- cross_entropy keeps NeedAccuratePrediction() == true, so prediction
  early-stop must never engage for it (predictor.hpp:46).
"""

import json

import numpy as np

import lightgbm_tpu as lgb
from conftest import make_synthetic_binary


def _ranking_data(n_query=40, per_q=12, f=6, seed=3):
    rs = np.random.RandomState(seed)
    n = n_query * per_q
    X = rs.randn(n, f)
    y = rs.randint(0, 4, size=n).astype(np.float64)
    group = np.full(n_query, per_q, np.int64)
    position = np.tile(np.arange(per_q), n_query)
    return X, y, group, position


def test_cv_subsets_position():
    X, y, group, position = _ranking_data()
    ds = lgb.Dataset(X, label=y, group=group, position=position)
    out = lgb.cv({"objective": "lambdarank", "num_leaves": 7,
                  "verbosity": -1, "lambdarank_position_bias_regularization":
                  0.5, "metric": "ndcg", "ndcg_eval_at": [3]},
                 ds, num_boost_round=4, nfold=2, stratified=False)
    key = [k for k in out if "ndcg" in k and "mean" in k][0]
    assert len(out[key]) == 4
    assert np.all(np.isfinite(out[key]))


def test_invalid_forced_split_aborts_rest(tmp_path):
    X, y = make_synthetic_binary(n=1500, f=5, seed=11)
    # root forced at the median of feature 2 (valid); the left child is
    # forced on the SAME (feature, threshold) — all its rows already
    # satisfy f2 <= t, so the grandchild side is empty -> invalid. The
    # abort must also discard the would-be-valid grandchild spec, so the
    # model must equal a run forcing only the root split.
    fs_full = {"feature": 2, "threshold": 0.0,
               "left": {"feature": 2, "threshold": 0.0,
                        "left": {"feature": 0, "threshold": 0.0}}}
    fs_root = {"feature": 2, "threshold": 0.0}
    p_full = tmp_path / "forced_full.json"
    p_full.write_text(json.dumps(fs_full))
    p_root = tmp_path / "forced_root.json"
    p_root.write_text(json.dumps(fs_root))
    base = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
            "min_data_in_leaf": 5}
    b_full = lgb.train(dict(base, forcedsplits_filename=str(p_full)),
                       lgb.Dataset(X, label=y), num_boost_round=2)
    b_root = lgb.train(dict(base, forcedsplits_filename=str(p_root)),
                       lgb.Dataset(X, label=y), num_boost_round=2)
    for tf, tp in zip(b_full._models, b_root._models):
        np.testing.assert_array_equal(tf.split_feature, tp.split_feature)
        np.testing.assert_allclose(tf.threshold, tp.threshold)


def test_cross_entropy_prediction_exact_with_early_stop():
    X, y01 = make_synthetic_binary(n=1200, f=6, seed=17)
    y = np.clip(y01 * 0.9 + 0.05, 0.0, 1.0)  # probabilistic labels
    bst = lgb.train({"objective": "cross_entropy", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=30)
    p_plain = bst.predict(X)
    p_es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=1,
                       pred_early_stop_margin=0.01)
    # the aggressive margin would corrupt sums if early stop engaged
    np.testing.assert_array_equal(p_plain, p_es)


def test_gather_small_matches_indexing_including_2d():
    """gather_small (round-4 generalization) matches table[idx] for 1-D
    and [L, k] tables, and the debug mode rejects out-of-range ids."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.gather import gather_small

    rs = np.random.RandomState(0)
    idx = jnp.asarray(rs.randint(0, 7, size=100), jnp.int32)
    t1 = jnp.asarray(rs.randn(7))
    np.testing.assert_array_equal(np.asarray(gather_small(t1, idx)),
                                  np.asarray(t1)[np.asarray(idx)])
    t2 = jnp.asarray(rs.randn(7, 3))
    np.testing.assert_array_equal(np.asarray(gather_small(t2, idx)),
                                  np.asarray(t2)[np.asarray(idx)])
    import os
    os.environ["LIGHTGBM_TPU_DEBUG_GATHER"] = "1"
    try:
        with np.testing.assert_raises(ValueError):
            gather_small(t1, jnp.asarray([7], jnp.int32))
    finally:
        del os.environ["LIGHTGBM_TPU_DEBUG_GATHER"]


def test_linear_tree_predictions_still_exact():
    """linear-leaf eval switched to gather_small; outputs must be
    bit-identical to the straight-indexing implementation."""
    X, y = make_synthetic_binary(n=1500, f=6, seed=5)
    bst = lgb.train({"objective": "regression", "linear_tree": True,
                     "num_leaves": 15, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    p = bst.predict(X)
    assert np.all(np.isfinite(p))
    # NaN rows exercise the fallback gather path
    Xn = X.copy()
    Xn[:50, 0] = np.nan
    pn = bst.predict(Xn)
    assert np.all(np.isfinite(pn))


def test_two_round_name_label_column_defers_to_eager(tmp_path):
    """two_round + ``label_column=name:<col>`` must NOT silently treat
    column 0 as the label (ADVICE r4, basic.py:141): the two-round
    fast path defers to the eager loader's header resolution. The
    label lives in the LAST column here, so training on column 0
    would produce garbage."""
    rs = np.random.RandomState(4)
    n, f = 4000, 5
    X = rs.randn(n, f)
    y = ((X[:, 1] + 0.5 * X[:, 3]) > 0).astype(float)
    path = tmp_path / "named.csv"
    cols = [f"feat{j}" for j in range(f)] + ["target"]
    data = np.column_stack([X, y])
    with open(path, "w") as fh:
        fh.write(",".join(cols) + "\n")
        for row in data:
            fh.write(",".join(f"{v:.6f}" for v in row) + "\n")
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "header": True, "label_column": "name:target",
              "two_round": True}
    bst = lgb.train(dict(params), lgb.Dataset(str(path), params=params),
                    num_boost_round=10)
    acc = np.mean((bst.predict(X) > 0.5) == (y > 0.5))
    assert acc > 0.9, acc
