"""Multi-device data-parallel equivalence on the 8-virtual-device mesh.

The reference's pattern: tests/distributed/_test_distributed.py:54 runs
the same training 2-machine vs single-process and asserts equivalence.
Here the 'machines' are the conftest-provisioned virtual CPU devices;
``tree_learner=data`` shards rows over the mesh and must produce
IDENTICAL trees to single-device training (data_parallel.py's
determinism claim: every shard sees the psum-reduced histograms and
computes the same argmax).
"""

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from conftest import make_synthetic_binary, make_synthetic_regression

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs the virtual multi-device mesh")


def _trees_equal(b_dp, b_sp, value_tol=2e-4):
    assert len(b_dp._models) == len(b_sp._models)
    for td, ts in zip(b_dp._models, b_sp._models):
        assert td.num_leaves == ts.num_leaves
        np.testing.assert_array_equal(td.split_feature, ts.split_feature)
        np.testing.assert_array_equal(td.left_child, ts.left_child)
        np.testing.assert_array_equal(td.right_child, ts.right_child)
        np.testing.assert_allclose(td.threshold, ts.threshold,
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(td.leaf_value, ts.leaf_value,
                                   rtol=value_tol, atol=value_tol)


def _train_pair(params, X, y, rounds=5, **ds_kw):
    sp = lgb.train(dict(params), lgb.Dataset(X, label=y, **ds_kw),
                   num_boost_round=rounds)
    dp = lgb.train(dict(params, tree_learner="data"),
                   lgb.Dataset(X, label=y, **ds_kw),
                   num_boost_round=rounds)
    return dp, sp


def test_dp_binary_identical_trees():
    X, y = make_synthetic_binary(n=4000, f=8, seed=5)
    dp, sp = _train_pair({"objective": "binary", "num_leaves": 15,
                          "min_data_in_leaf": 5, "verbosity": -1}, X, y)
    _trees_equal(dp, sp)
    np.testing.assert_allclose(dp.predict(X[:200]), sp.predict(X[:200]),
                               rtol=1e-4, atol=1e-5)


def test_dp_regression_identical_trees():
    X, y = make_synthetic_regression(n=4000, f=8, seed=6)
    dp, sp = _train_pair({"objective": "regression", "num_leaves": 31,
                          "min_data_in_leaf": 10, "verbosity": -1}, X, y)
    _trees_equal(dp, sp)


def test_dp_multiclass_identical_trees():
    rs = np.random.RandomState(8)
    X = rs.randn(3000, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int) \
        + (X[:, 2] > 0.5).astype(int)
    dp, sp = _train_pair({"objective": "multiclass", "num_class": 3,
                          "num_leaves": 7, "min_data_in_leaf": 5,
                          "verbosity": -1}, X, y.astype(float), rounds=3)
    _trees_equal(dp, sp)


def test_dp_categorical_accuracy_parity():
    """Categorical splits sort bins by g/(h+smooth); the psum's shard
    accumulation order perturbs those ratios at f32 epsilon, so exact
    tree identity is not guaranteed (the reference's distributed suite
    likewise asserts accuracy, not tree equality —
    _test_distributed.py:54). Require prediction-quality parity."""
    rs = np.random.RandomState(9)
    n = 3000
    Xc = rs.randint(0, 12, size=(n, 2)).astype(np.float64)
    Xn = rs.randn(n, 4)
    X = np.concatenate([Xc, Xn], axis=1)
    y = ((Xc[:, 0] % 3 == 0) ^ (Xn[:, 0] > 0)).astype(np.float64)
    sp = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "min_data_in_leaf": 5,
                    "categorical_feature": [0, 1]},
                   lgb.Dataset(X, label=y), num_boost_round=4)
    dp = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "min_data_in_leaf": 5,
                    "categorical_feature": [0, 1], "tree_learner": "data"},
                   lgb.Dataset(X, label=y), num_boost_round=4)
    acc_sp = np.mean((sp.predict(X) > 0.5) == y)
    acc_dp = np.mean((dp.predict(X) > 0.5) == y)
    assert abs(acc_sp - acc_dp) < 0.02
    assert acc_dp > 0.9


def test_dp_quantized_identical_trees():
    X, y = make_synthetic_binary(n=4000, f=6, seed=10)
    # stochastic rounding draws per-shard fold_in keys, so disable it for
    # bit-identical single-vs-multi comparison
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "use_quantized_grad": True,
              "stochastic_rounding": False, "num_grad_quant_bins": 16}
    dp, sp = _train_pair(params, X, y)
    _trees_equal(dp, sp)


def test_dp_monotone_identical_trees():
    X, y = make_synthetic_regression(n=3000, f=5, seed=11)
    params = {"objective": "regression", "num_leaves": 15,
              "verbosity": -1, "min_data_in_leaf": 5,
              "monotone_constraints": [1, -1, 0, 0, 0]}
    dp, sp = _train_pair(params, X, y)
    _trees_equal(dp, sp)


def test_dp_bagging_identical_trees():
    X, y = make_synthetic_binary(n=4000, f=6, seed=12)
    # bagging weights are drawn from an iteration-folded key shared by
    # every shard (rows sharded AFTER weighting), so trees must match
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "bagging_fraction": 0.6,
              "bagging_freq": 1, "seed": 7}
    dp, sp = _train_pair(params, X, y)
    _trees_equal(dp, sp)


def test_dp_forced_splits_identical_trees(tmp_path):
    import json
    X, y = make_synthetic_binary(n=3000, f=5, seed=13)
    path = tmp_path / "forced.json"
    path.write_text(json.dumps({"feature": 1, "threshold": 0.0}))
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "min_data_in_leaf": 5, "forcedsplits_filename": str(path)}
    dp, sp = _train_pair(params, X, y, rounds=3)
    _trees_equal(dp, sp)
    for t in dp._models:
        assert int(t.split_feature[0]) == 1


def test_parse_machines_formats(tmp_path):
    from lightgbm_tpu.parallel.distributed import parse_machines
    assert parse_machines("10.0.0.1:12400,10.0.0.2:12401") == [
        ("10.0.0.1", 12400), ("10.0.0.2", 12401)]
    mfile = tmp_path / "mlist.txt"
    mfile.write_text("hostA 500\nhostB:600\n")
    assert parse_machines(machine_list_file=str(mfile)) == [
        ("hostA", 500), ("hostB", 600)]


def test_init_distributed_single_machine_noop():
    # num_machines=1 machine lists must not try to wire a cluster
    from lightgbm_tpu.parallel.distributed import init_distributed
    init_distributed(machines="localhost:12400")  # single entry: no-op


def test_spmd_single_process_passthrough():
    """sync_bin_mappers / distributed_dataset are identity on one
    process (the num_machines=1 degenerate case)."""
    import numpy as np
    from lightgbm_tpu.parallel.spmd import distributed_dataset, \
        sync_bin_mappers
    rs = np.random.RandomState(0)
    X = rs.randn(500, 4)
    y = (X[:, 0] > 0).astype(float)
    ds = distributed_dataset(X, label=y, params={"verbosity": -1})
    assert ds.num_data() == 500
    same = sync_bin_mappers(ds.mappers)
    assert same is ds.mappers or len(same) == len(ds.mappers)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, ds, num_boost_round=3)
    assert np.all(np.isfinite(bst.predict(X[:50])))


def _sparse_onehot_dp(n, groups, per_group, seed=0):
    """One-hot blocks (mutually exclusive by construction) so EFB has
    something to bundle; mirrors test_bundling._sparse_onehot."""
    rs = np.random.RandomState(seed)
    cols = []
    signal = np.zeros(n)
    for g in range(groups):
        pick = rs.randint(0, per_group, n)
        block = np.zeros((n, per_group))
        vals = rs.rand(per_group) * 2
        block[np.arange(n), pick] = vals[pick]
        cols.append(block)
        signal += vals[pick]
    dense = rs.randn(n, 2)
    X = np.hstack(cols + [dense])
    y = (signal + 0.5 * dense[:, 0]
         + 0.3 * rs.randn(n) > np.median(signal)).astype(float)
    return X, y


def test_dp_bundled_identical_trees():
    """EFB x data-parallel (VERDICT r4 #4): bundling is a dataset
    property below the parallel layer (feature_group.h:26) — bundle
    columns shard by rows, bundle histograms psum, and the 8-device
    trees must equal the single-device bundled trees exactly."""
    X, y = _sparse_onehot_dp(4096, groups=4, per_group=6, seed=11)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "enable_bundle": True}
    dp, sp = _train_pair(params, X, y, rounds=5)
    assert sp._engine.bundle is not None, "single-device EFB not engaged"
    assert dp._engine.bundle is not None, "data-parallel EFB not engaged"
    assert dp._engine.mesh is not None, "mesh not engaged"
    _trees_equal(dp, sp)
    np.testing.assert_allclose(dp.predict(X[:256]), sp.predict(X[:256]),
                               rtol=1e-4, atol=1e-5)


def test_dp_bundled_matches_unbundled_dp():
    """Same data, data-parallel with and without EFB: identical
    structure (the bundled search is a re-indexing, not a different
    algorithm), matching test_bundling's single-device guarantee."""
    X, y = _sparse_onehot_dp(4096, groups=3, per_group=5, seed=12)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "tree_learner": "data"}
    bundled = lgb.train(dict(params, enable_bundle=True),
                        lgb.Dataset(X, label=y), num_boost_round=4)
    plain = lgb.train(dict(params, enable_bundle=False),
                      lgb.Dataset(X, label=y), num_boost_round=4)
    assert bundled._engine.bundle is not None
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=2e-4, atol=2e-4)
