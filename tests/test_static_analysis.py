"""tpulint (lightgbm_tpu/analysis/) — the tier-1 static-analysis gate.

Four layers, all jax-free and fast (<10 s over the whole package):

1. The package itself must lint clean against the checked-in baseline
   (tools/tpulint_baseline.txt), every baseline entry must carry a
   justification, and no entry may be stale.
2. The derived jit-reachable set must cover the entry points the old
   hand-maintained ``KNOWN_JITTED`` allowlist tracked — renaming
   ``_grow_masked_impl`` (or breaking its jit wrapping) fails here, so
   the allowlist is now computed, not maintained.
3. Per-rule fixtures (tests/analysis_fixtures/): one positive and one
   negative file per rule, asserted by finding id and line number via
   ``# EXPECT: TPLNNN`` markers (the marker pins the line after it).
4. CLI contract: ``python -m lightgbm_tpu lint`` runs WITHOUT importing
   jax, honors --rule/--format/--baseline, and exits 0/1 as documented.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "lightgbm_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")
BASELINE = os.path.join(REPO, "tools", "tpulint_baseline.txt")

sys.path.insert(0, REPO)

from lightgbm_tpu.analysis import build_callgraph, run_lint  # noqa: E402
from lightgbm_tpu.analysis.baseline import load_baseline  # noqa: E402

import functools  # noqa: E402


# tests/test_hot_path_lint.py re-exports several of these tests (thin
# compat wrapper), so pytest runs them twice per tier-1 pass; cache the
# package-wide analyses so the duplicates cost ~0 instead of ~2 s each
@functools.lru_cache(maxsize=None)
def _cached_graph():
    return build_callgraph(PKG)


@functools.lru_cache(maxsize=None)
def _cached_lint(rules=None):
    return run_lint(root=PKG, rules=list(rules) if rules else None,
                    baseline_path=BASELINE)


# ---------------------------------------------------------------------
# 1. the shipped tree is clean
# ---------------------------------------------------------------------

def test_package_lints_clean_against_baseline():
    res = _cached_lint()
    assert not res.findings, (
        "new tpulint findings (fix them, or baseline WITH a "
        "justification — see docs/STATIC_ANALYSIS.md):\n  "
        + "\n  ".join(f"{f.fid} @ {f.relpath}:{f.lineno}"
                      for f in res.findings))
    assert not res.stale_baseline, (
        "stale baseline entries (the finding no longer occurs — "
        "delete them from tools/tpulint_baseline.txt):\n  "
        + "\n  ".join(e.fid for e in res.stale_baseline))
    assert res.elapsed < 10.0, (
        f"analyzer took {res.elapsed:.1f}s over the package; the "
        "review-time budget is 10s")


def test_baseline_entries_all_justified():
    entries = load_baseline(BASELINE)
    assert entries, "baseline file missing or empty (expected at "\
        f"{BASELINE})"
    unjustified = [e.fid for e in entries if not e.justification]
    assert not unjustified, (
        "baseline entries without an inline justification comment: "
        + ", ".join(unjustified))


# ---------------------------------------------------------------------
# 2. KNOWN_JITTED, migrated: the allowlist is now DERIVED
# ---------------------------------------------------------------------

# The old tests/test_hot_path_lint.py allowlist (minus the stale
# `predict_forest_raw` entry, which tpulint exposed as a dead eager
# loop nothing ever jitted — removed in the same change), plus the
# wider lax-loop-bearing entry points the call graph proves. If any of
# these leaves the derived set (renamed, de-jitted, newly referenced
# from eager code), this fails and names it.
KNOWN_JITTED = {
    ("ops/gather.py", "_gather_small"),
    ("ops/grow.py", "_grow_masked_impl"),
    ("ops/grow.py", "_grow_compact_impl"),
    ("ops/grow.py", "grow_tree_impl"),
    ("ops/histogram.py", "_hist_from_rows_impl"),
    ("ops/histogram.py", "_hist_scatter"),
    ("ops/histogram.py", "build_histogram"),
    ("ops/predict.py", "_traverse"),
    ("ops/predict.py", "predict_leaf_binned"),
    ("ops/predict.py", "predict_leaf_raw"),
    ("ranking.py", "_lambdarank_grads"),
    ("models/gbdt.py", "GBDTBooster._get_fused_fn.step"),
}


def test_known_jitted_covered_by_derived_set():
    graph = _cached_graph()
    missing = KNOWN_JITTED - graph.jit_reachable
    assert not missing, (
        "functions expected to be jit-only left the DERIVED "
        "jit-reachable set (renamed? de-jitted? now referenced from "
        f"eager code?): {sorted(missing)}")


def test_known_jitted_entries_exist():
    """A renamed/deleted function must be pruned here — stale entries
    would silently stop guarding anything (the failure mode that let
    the old allowlist carry `predict_forest_raw` for a dead
    function)."""
    graph = _cached_graph()
    live = {(p, q) for (p, q) in graph.funcs}
    stale = KNOWN_JITTED - live
    assert not stale, f"prune stale KNOWN_JITTED entries: {sorted(stale)}"


def test_every_hot_path_lax_loop_is_jit_reachable():
    """The old test's core property, generalized from models/gbdt.py +
    ops/ to the full rule scope: zero non-baselined TPL001s."""
    res = _cached_lint(("TPL001",))
    assert not res.findings, (
        "eager-dispatch risk (PROFILE.md 530 ms/iter class):\n  "
        + "\n  ".join(f"{f.relpath}:{f.lineno}: {f.fid}"
                      for f in res.findings))


# ---------------------------------------------------------------------
# 3. per-rule fixtures, asserted by id + line
# ---------------------------------------------------------------------

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(TPL\d{3})\s*$")


def _expected_findings(path: str):
    """(rule, lineno) pairs pinned by `# EXPECT: TPLNNN` markers — the
    marker names the line that FOLLOWS it."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                out.append((m.group(1), i + 1))
    return sorted(out)


_FIXTURES = [
    "tpl001_pos.py", "tpl001_neg.py",
    "tpl002_pos.py", "tpl002_neg.py",
    "tpl003_pos.py", "tpl003_neg.py",
    "tpl004_pos.py", "tpl004_neg.py",
    "tpl005_pos.py", "tpl005_neg.py",
    "obs/tpl006_pos.py", "obs/tpl006_neg.py",
    "resilience/tpl006_pos.py", "resilience/tpl006_neg.py",
]


@pytest.mark.parametrize("relpath", _FIXTURES)
def test_rule_fixture(relpath):
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=[relpath], baseline_path="")
    got = sorted((f.rule, f.lineno) for f in res.findings)
    expected = _expected_findings(os.path.join(FIXTURES, relpath))
    assert got == expected, (
        f"{relpath}: findings diverge from # EXPECT markers\n"
        f"  expected: {expected}\n  got:      {got}\n  "
        + "\n  ".join(f"{f.fid} @ {f.lineno}: {f.message[:100]}"
                      for f in res.findings))


def test_fixture_positive_files_have_expectations():
    for rel in _FIXTURES:
        expected = _expected_findings(os.path.join(FIXTURES, rel))
        if "_pos" in rel:
            assert expected, f"{rel} has no # EXPECT markers"
        else:
            assert not expected, f"{rel} is a negative fixture but " \
                                 "carries # EXPECT markers"


def test_every_rule_has_fixture_coverage():
    from lightgbm_tpu.analysis import ALL_RULES
    covered = set()
    for rel in _FIXTURES:
        for rule, _ in _expected_findings(os.path.join(FIXTURES, rel)):
            covered.add(rule)
    missing = {r.id for r in ALL_RULES} - covered
    assert not missing, f"rules without a positive fixture: {missing}"


# ---------------------------------------------------------------------
# 4. CLI contract (and the no-jax guarantee)
# ---------------------------------------------------------------------

def test_cli_lint_runs_without_jax():
    """`python -m lightgbm_tpu lint` must complete without importing
    jax anywhere on its path (review-time tooling runs where no
    backend can initialize). Proved in a subprocess: after a full lint
    run, 'jax' must be absent from sys.modules."""
    code = (
        "import sys\n"
        "from lightgbm_tpu.analysis.cli import main\n"
        "rc = main(['--format', 'json'])\n"
        "assert 'jax' not in sys.modules, 'lint imported jax!'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["jit_reachable"], "empty derived jit-reachable set"


def test_cli_rule_filter_and_exit_code():
    # a fresh finding (no baseline) must exit 1 and honor --rule
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=["tpl001_pos.py"], rules=["TPL001"],
                   baseline_path="")
    assert res.findings and all(f.rule == "TPL001" for f in res.findings)
    res2 = run_lint(root=FIXTURES, package="tpulint_fixtures",
                    files=["tpl001_pos.py"], rules=["TPL004"],
                    baseline_path="")
    assert not res2.findings  # rule filter excludes the TPL001 hits
    with pytest.raises(ValueError):
        run_lint(root=FIXTURES, package="tpulint_fixtures",
                 files=["tpl001_pos.py"], rules=["TPL999"])


def test_cli_help_mentions_exit_codes():
    from lightgbm_tpu.analysis.cli import EXIT_CODES, build_parser
    text = build_parser().format_help()
    assert "exit codes:" in text
    assert "--rule" in text and "--baseline" in text
    assert EXIT_CODES.strip().splitlines()[1].strip().startswith("0")


def test_finding_ids_are_line_number_free():
    res = run_lint(root=FIXTURES, package="tpulint_fixtures",
                   files=["tpl001_pos.py"], baseline_path="")
    for f in res.findings:
        assert f.fid == f"{f.rule}:{f.relpath}:{f.func}:{f.symbol}#" \
            + f.fid.rsplit("#", 1)[1]
        assert str(f.lineno) not in f.fid.rsplit("#", 1)[0].replace(
            f.relpath, "")


# ---------------------------------------------------------------------
# carried over from the old test_hot_path_lint.py: the resilience-guard
# placement contract (docs/RESILIENCE.md) — still a plain-ast check
# ---------------------------------------------------------------------

def _function_node(tree, qualpath):
    nodes = [tree]
    for name in qualpath:
        found = None
        for node in nodes:
            for child in ast.walk(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and child.name == name:
                    found = child
                    break
            if found is not None:
                break
        assert found is not None, \
            f"function {'.'.join(qualpath)} not found"
        nodes = [found]
    return nodes[0]


def test_nonfinite_guard_stays_inside_jitted_step():
    """The resilience guard contract: the non-finite check on
    gradients/hessians/leaf values must live INSIDE the fused jitted
    step (one fused reduction), and the fused iteration wrapper must
    not grow an eager per-iteration host fetch — TPL002 enforces the
    latter through the `# tpulint: hot` marker, re-asserted here."""
    path = os.path.join(PKG, "models", "gbdt.py")
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)

    guard_helpers = {"_gh_flag_clamp", "_leaf_guard"}

    def _calls(fn_node):
        names = set()
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute):
                    names.add(n.func.attr)
                elif isinstance(n.func, ast.Name):
                    names.add(n.func.id)
        return names

    step = _function_node(tree, ["_get_fused_fn", "step"])
    step_calls = _calls(step)
    assert "isfinite" in step_calls or (step_calls & guard_helpers), (
        "the non-finite guard left the fused jitted step: "
        "_get_fused_fn.step must trace jnp.isfinite (directly or via "
        "_gh_flag_clamp/_leaf_guard), not check eagerly")
    for helper in guard_helpers & step_calls:
        node = _function_node(tree, [helper])
        assert "isfinite" in _calls(node), (
            f"{helper} no longer reduces via jnp.isfinite — the fused "
            "guard is gone")

    # (2) no host materialization in the fused iteration driver —
    # now the analyzer's job: _train_one_iter_fused is hot-marked and
    # models/gbdt.py TPL002 findings are limited to the baseline
    res = _cached_lint(("TPL002",))
    fused = [f for f in res.findings
             if f.func.endswith("_train_one_iter_fused")]
    assert not fused, (
        "eager host fetch in _train_one_iter_fused (guard/fault flags "
        "must ride the async _push_guard_flags queue):\n  "
        + "\n  ".join(f"line {f.lineno}: {f.symbol}" for f in fused))
    scan = res.graph.scans["models/gbdt.py"]
    hot = {q for q, i in scan.funcs.items() if i.is_hot}
    assert "GBDTBooster._train_one_iter_fused" in hot, (
        "_train_one_iter_fused lost its '# tpulint: hot' marker — "
        "TPL002 no longer guards the fused driver")
