"""Atomic model publication: the train -> serve handoff over a store.

The missing edge of the continuous lifecycle (docs/PIPELINE.md):
training produces a model, the serve daemon (serve/daemon.py) polls a
watch target for the newest artifact — this module is the writer side
of that contract, and it must survive being killed at any byte.

Every verb here rides an :class:`~.store.ArtifactStore`
(resilience/store.py), so the trainer and the serving fleet no longer
need a shared filesystem: a path target publishes into a local
directory (the PR-12 behavior, byte-for-byte), a ``mem://<name>``
target publishes through the faultable in-process object store, and
any object-store/rsync/KV-shaped transport plugs in behind the same
five blob verbs.

Protocol (manifest-first):

1. ``<name>.manifest.json`` is put atomically, carrying the artifact's
   identity: its exact byte length and sha256, plus caller metadata
   (generation, data digest, train metrics) and — when the caller
   provides one — a **canary**: a small validation batch of input rows
   and the raw scores the publishing model produced for them. The
   manifest lands BEFORE the model blob it describes, so a watcher can
   validate every model artifact it ever observes, and a replica can
   score the canary through its real compiled forest BEFORE swapping
   (docs/SERVING.md).
2. ``<name>`` (the model text) is put atomically.

A watcher that finds a model whose bytes do not match its manifest is
looking at a TORN publication — a writer that died between the two
steps, or a non-atomic writer mid-write. The serve watcher skips such
an artifact with a ``swap_failure`` fault event and retries next poll
(the atomic re-publish below will replace it); it never swaps to it.
Artifacts without a manifest (hand-dropped model files, checkpoint
snapshots) keep the legacy behavior: served as-is once they parse.

Transient publication failures (full disk, a store outage, the
injected ``publish_torn@G`` / ``store_outage@G`` chaos kinds) are
retried with jittered exponential backoff — the same retry shape as
``init_distributed`` — and counted in the ``publish_retries`` /
``publish_backoff_seconds`` registry counters. The ``publish_poison@G``
chaos kind publishes a byte-valid manifest whose canary expectations
are wrong — the shape of a trainer that published a garbage model —
which only the serve-side canary gate can catch.

This module never imports jax: the pipeline supervisor and the serve
watcher both consume it on jax-free paths.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.registry import bump_counter as _count
from ..utils.log import log_info, log_warning
from .store import ArtifactStore, LocalDirStore, StoreError, store_for

__all__ = ["PublishError", "publish_model", "manifest_path",
           "load_manifest", "load_manifest_in", "validate_artifact",
           "validate_artifact_in", "latest_manifest",
           "latest_manifest_in", "prune_publications",
           "rollback_publication"]

MANIFEST_MAGIC = "lightgbm_tpu.publish.v1"
MANIFEST_SUFFIX = ".manifest.json"

#: retry/backoff defaults — overridable per call and via Config
#: (publish_retries / publish_backoff_sec, docs/PARAMETERS.md)
DEFAULT_RETRIES = 5
DEFAULT_BACKOFF_SEC = 0.25
BACKOFF_CAP_SEC = 15.0


class PublishError(RuntimeError):
    """A model publication failed (exhausted retries), or an artifact
    failed its manifest validation (torn / partial write)."""


def manifest_path(model_path) -> str:
    return os.fspath(model_path) + MANIFEST_SUFFIX


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _is_store_target(target) -> bool:
    if isinstance(target, ArtifactStore):
        return True
    try:
        return os.fspath(target).startswith("mem://")
    except TypeError:
        return False


def _poison_scores(scores):
    """Shift every canary expectation far outside any tolerance —
    byte-valid, semantically garbage (the ``publish_poison`` shape)."""
    if isinstance(scores, (list, tuple)):
        return [_poison_scores(s) for s in scores]
    return float(scores) + 1.0e3


def publish_model(model, directory, name: str, *,
                  metadata: Optional[Dict[str, Any]] = None,
                  canary: Optional[Dict[str, Any]] = None,
                  retries: int = DEFAULT_RETRIES,
                  backoff_base_sec: float = DEFAULT_BACKOFF_SEC,
                  fault_iteration: int = -1,
                  keep: int = 0,
                  protect_shas=(),
                  _sleep: Callable[[float], None] = time.sleep,
                  _rng: Callable[[], float] = random.random
                  ) -> Dict[str, Any]:
    """Publish ``model`` into ``directory`` as ``name`` with a
    validating manifest; returns the manifest dict.

    ``directory`` is any store target (a local directory path, a
    ``mem://`` spec, or an :class:`~.store.ArtifactStore`). ``model``
    is a model-text string or anything with ``model_to_string()`` (a
    Booster). ``metadata`` is merged into the manifest (generation
    number, data digest, train metrics — whatever the retrain loop
    wants the serve side and post-mortems to see). ``canary`` — a dict
    of ``{"rows": [[...]], "scores": [...], "tol": float}`` — embeds
    the serve-side validation batch (docs/SERVING.md).
    ``fault_iteration`` keys the ``publish_torn@G`` /
    ``store_outage@G`` / ``publish_poison@G`` chaos kinds (typically
    the retrain generation number). ``keep`` > 0 prunes publications
    beyond the ``keep`` newest valid manifests after a successful
    publish (``protect_shas`` are never pruned — the currently-served
    / last-known-good models).

    Transient failures (OSError — which store outages subclass — and
    injected tears) retry up to ``retries`` times with jittered
    exponential backoff (``backoff_base_sec`` doubling per attempt,
    capped at 15 s, x[0.5, 1.5) jitter); exhaustion raises
    :class:`PublishError`.
    """
    if not isinstance(model, str):
        model = model.model_to_string()
    t_start = time.perf_counter()
    payload = model.encode("utf-8")
    store = store_for(directory)
    where = store.url
    # trace context (obs/trace.py): inherit the publishing process's
    # current trace (the pipeline supervisor's per-generation context,
    # via LIGHTGBM_TPU_TRACE_CTX) or start a fresh one, and stamp it
    # INTO the manifest — the serve watcher's validate->load->swap
    # spans then correlate back to the generation that published
    from ..obs import trace as _trace
    ctx = _trace.current_context()
    trace_id = ctx["trace_id"] if ctx else _trace.new_trace_id()
    parent_id = ctx["span_id"] if ctx else None
    span_id = _trace.new_span_id()
    manifest = {
        "magic": MANIFEST_MAGIC,
        "file": name,
        "bytes": len(payload),
        "sha256": _sha256_hex(payload),
        "created_unix": time.time(),
        "trace": {"trace_id": trace_id, "span_id": span_id},
        **(metadata or {}),
    }
    from .faults import FaultPlan, record_fault_event
    plan = FaultPlan.from_env()
    if canary:
        if plan.take("publish_poison", fault_iteration):
            # chaos: the publication stays byte-valid (manifest sha
            # matches the model blob) but its canary expectations are
            # garbage — indistinguishable from a trainer that
            # published a broken model. sha256 validation MUST accept
            # it; only the serve-side canary gate can refuse it.
            canary = dict(canary,
                          scores=_poison_scores(canary.get("scores")))
            record_fault_event(
                "publish_poison", iteration=fault_iteration,
                action="published_poisoned",
                detail=f"injected poisoned canary in {name} "
                       "(LIGHTGBM_TPU_FAULT_INJECT)")
        manifest["canary"] = canary
    last_err: Optional[BaseException] = None
    for attempt in range(max(0, int(retries)) + 1):
        try:
            if plan.take("store_outage", fault_iteration):
                # chaos: the transport is down for this attempt — the
                # retry/backoff loop must carry the publication through
                record_fault_event(
                    "store_outage", iteration=fault_iteration,
                    action="retry",
                    detail=f"injected store outage publishing {name} "
                           "(LIGHTGBM_TPU_FAULT_INJECT)")
                raise StoreError(
                    f"injected store outage publishing {name} "
                    "(LIGHTGBM_TPU_FAULT_INJECT)")
            # manifest FIRST: every model artifact a watcher can ever
            # observe under this protocol is validatable
            store.put_bytes(
                name + MANIFEST_SUFFIX,
                (json.dumps(manifest) + "\n").encode("utf-8"))
            if plan.take("publish_torn", fault_iteration):
                # chaos: leave the torn artifact a crashed / non-atomic
                # writer would — a partial prefix — then fail this
                # attempt so the retry loop (and the watcher's
                # validation) must both do their jobs
                store.put_bytes(
                    name, payload[: max(1, len(payload) // 3)])
                record_fault_event(
                    "publish_torn", iteration=fault_iteration,
                    action="retry",
                    detail=f"injected torn publish of {name} "
                           "(LIGHTGBM_TPU_FAULT_INJECT)")
                raise PublishError(
                    f"injected torn publish of {name} "
                    "(LIGHTGBM_TPU_FAULT_INJECT)")
            store.put_bytes(name, payload)
        except (OSError, PublishError) as e:
            last_err = e
            if attempt >= retries:
                break
            delay = min(BACKOFF_CAP_SEC,
                        float(backoff_base_sec) * (2 ** attempt))
            delay *= 0.5 + _rng()            # jitter: x[0.5, 1.5)
            _count("publish_retries")
            _count("publish_backoff_seconds", delay)
            log_warning(f"publish: attempt {attempt + 1} for {name} "
                        f"failed ({e}); retrying in {delay:.2f}s")
            _sleep(delay)
            continue
        _count("publish_total")
        _trace.record_span(
            "publish/model", t_start, trace_id=trace_id,
            span_id=span_id, parent_id=parent_id,
            attrs={"file": name,
                   "generation": (metadata or {}).get("generation"),
                   "sha256": manifest["sha256"][:12],
                   "attempts": attempt + 1})
        log_info(f"publish: wrote {name} into {where} "
                 f"({len(payload)} bytes, sha256 "
                 f"{manifest['sha256'][:12]}…)")
        if keep > 0:
            # retention failures must never fail a successful publish
            try:
                prune_publications(
                    store, keep,
                    protect_shas=(tuple(protect_shas)
                                  + (manifest["sha256"],)))
            except (OSError, PublishError) as e:
                log_warning(f"publish: retention prune in {where} "
                            f"failed ({e}); will retry next publish")
        return manifest
    _count("publish_failures")
    raise PublishError(
        f"publishing {name} into {where} failed after "
        f"{retries + 1} attempt(s): {last_err}") from last_err


def load_manifest_in(store: ArtifactStore,
                     name: str) -> Optional[Dict[str, Any]]:
    """The manifest published alongside blob ``name`` in ``store``, or
    None when the artifact is unmanaged (no sidecar). A sidecar that
    exists but is unreadable/foreign raises :class:`PublishError` — a
    manifest is put atomically, so garbage there is corruption, not a
    mid-write artifact."""
    where = f"{store.url}/{name + MANIFEST_SUFFIX}"
    try:
        raw = store.get_bytes(name + MANIFEST_SUFFIX)
    except FileNotFoundError:
        return None
    except OSError as e:
        raise PublishError(f"{where}: unreadable manifest ({e})") from e
    try:
        manifest = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise PublishError(f"{where}: malformed manifest ({e})") from e
    if not isinstance(manifest, dict) \
            or manifest.get("magic") != MANIFEST_MAGIC:
        raise PublishError(f"{where}: bad manifest magic "
                           f"{manifest.get('magic') if isinstance(manifest, dict) else None!r}")
    return manifest


def load_manifest(model_path) -> Optional[Dict[str, Any]]:
    """Path-flavored :func:`load_manifest_in` (shared-filesystem
    callers and the PR-12 API)."""
    path = os.fspath(model_path)
    return load_manifest_in(LocalDirStore(os.path.dirname(path) or "."),
                            os.path.basename(path))


def validate_artifact_in(store: ArtifactStore,
                         name: str) -> Optional[Dict[str, Any]]:
    """Validate blob ``name`` against its published manifest.

    Returns the manifest when the bytes match, None when the artifact
    carries no manifest (legacy / hand-dropped file — the caller
    decides whether to trust it), and raises :class:`PublishError` on
    a mismatch: the artifact is torn (a publisher died between the
    manifest and the model put, or a non-atomic writer is mid-way
    through) and must not be served."""
    manifest = load_manifest_in(store, name)
    if manifest is None:
        return None
    try:
        data = store.get_bytes(name)
    except FileNotFoundError:
        data = b""
    if len(data) != int(manifest.get("bytes", -1)) \
            or _sha256_hex(data) != manifest.get("sha256"):
        raise PublishError(
            f"{store.url}/{name}: torn or partial artifact — "
            f"{len(data)} bytes in store vs {manifest.get('bytes')} "
            "published (sha256 mismatch); a publisher retry or the "
            "next atomic replace will supersede it")
    return manifest


def validate_artifact(model_path) -> Optional[Dict[str, Any]]:
    """Path-flavored :func:`validate_artifact_in` (shared-filesystem
    callers and the PR-12 API)."""
    path = os.fspath(model_path)
    return validate_artifact_in(
        LocalDirStore(os.path.dirname(path) or "."),
        os.path.basename(path))


def _manifest_entries(
        store: ArtifactStore
        ) -> List[Tuple[float, str, Dict[str, Any]]]:
    """``(created_unix, model_name, manifest)`` for every loadable
    manifest in ``store``, unsorted; unusable sidecars are skipped
    with a warning."""
    entries: List[Tuple[float, str, Dict[str, Any]]] = []
    for nm in store.list_names():
        if not nm.endswith(MANIFEST_SUFFIX):
            continue
        model_name = nm[: -len(MANIFEST_SUFFIX)]
        try:
            manifest = load_manifest_in(store, model_name)
        except PublishError as e:
            log_warning(f"publish: skipping unusable publication "
                        f"{store.url}/{model_name} ({e})")
            continue
        if manifest is None:
            continue
        entries.append(
            (float(manifest.get("created_unix", 0.0)), model_name,
             manifest))
    return entries


def latest_manifest_in(
        store: ArtifactStore
        ) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Newest VALIDATED publication in ``store``: ``(name, manifest)``
    by manifest creation time, skipping torn or unreadable entries
    (with a warning). None when nothing validates — the warm-start
    path then trains from scratch.

    Ordering comes from the (cheap, json-read) manifests alone;
    artifact bytes are only hashed newest-first until one validates —
    a long-lived publish target is not re-hashed end to end on every
    generation."""
    entries = _manifest_entries(store)
    for _, name, manifest in sorted(entries, reverse=True,
                                    key=lambda c: (c[0], c[1])):
        try:
            if validate_artifact_in(store, name) is not None:
                return name, manifest
        except (PublishError, OSError) as e:
            log_warning(f"publish: skipping unusable publication "
                        f"{store.url}/{name} ({e})")
    return None


def latest_manifest(target) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Newest validated publication in ``target`` (any store target).

    For a directory path the first element is the joined model PATH
    (the PR-12 API); for a store / ``mem://`` target it is the blob
    name."""
    if _is_store_target(target):
        return latest_manifest_in(store_for(target))
    directory = os.fspath(target)
    found = latest_manifest_in(LocalDirStore(directory))
    if found is None:
        return None
    name, manifest = found
    return os.path.join(directory, name), manifest


def prune_publications(target, keep: int,
                       protect_shas=()) -> List[str]:
    """Prune publications beyond the ``keep`` newest valid manifests;
    returns the pruned model names.

    Publications whose sha256 is in ``protect_shas`` (the
    currently-served model, the last-known-good rollback target) are
    never pruned, wherever they rank. The artifact blob is deleted
    BEFORE its manifest: a prune that dies half-way leaves a
    manifest-without-artifact, which every reader already skips as
    torn — never a bare, manifest-less model file that the legacy
    watcher path would trust."""
    if keep <= 0:
        return []
    store = store_for(target)
    protect = set(protect_shas)
    entries = sorted(_manifest_entries(store), reverse=True,
                     key=lambda c: (c[0], c[1]))
    pruned: List[str] = []
    for rank, (_, name, manifest) in enumerate(entries):
        if rank < keep or manifest.get("sha256") in protect:
            continue
        store.delete(name)
        store.delete(name + MANIFEST_SUFFIX)
        pruned.append(name)
        _count("publish_pruned")
    if pruned:
        log_info(f"publish: pruned {len(pruned)} publication(s) "
                 f"beyond the {keep} newest from {store.url}")
    return pruned


def rollback_publication(target, bad_name: str, good_name: str, *,
                         retries: int = DEFAULT_RETRIES,
                         backoff_base_sec: float = DEFAULT_BACKOFF_SEC
                         ) -> Dict[str, Any]:
    """Supersede a bad publication with a re-publication of a known
    good one; returns the new manifest.

    The bad blob and its manifest are deleted first (artifact before
    manifest, same torn-safe order as pruning) so no watcher can pick
    the bad publication up again, then ``good_name``'s bytes are
    re-published under a fresh name — newest-wins polling then swaps
    every replica (back) onto the good model, including replicas that
    never saw it. The new manifest carries ``rollback_of`` (the bad
    sha) and the good publication's canary/generation metadata."""
    store = store_for(target)
    good_manifest = load_manifest_in(store, good_name)
    if good_manifest is None:
        raise PublishError(
            f"rollback target {store.url}/{good_name} has no manifest")
    data = store.get_bytes(good_name)
    if _sha256_hex(data) != good_manifest.get("sha256"):
        raise PublishError(
            f"rollback target {store.url}/{good_name} failed its own "
            "manifest validation; refusing to republish it")
    bad_sha = ""
    try:
        bad = load_manifest_in(store, bad_name)
        bad_sha = (bad or {}).get("sha256", "")
    except PublishError:
        pass
    store.delete(bad_name)
    store.delete(bad_name + MANIFEST_SUFFIX)
    metadata = {k: good_manifest[k]
                for k in ("generation", "train_auc", "refit_auc",
                          "data_digest")
                if k in good_manifest}
    metadata["rollback_of"] = bad_sha or bad_name
    new_name = f"rollback_{(bad_sha or 'unknown')[:8]}_{good_name}"
    manifest = publish_model(
        data.decode("utf-8"), store, new_name, metadata=metadata,
        canary=good_manifest.get("canary"), retries=retries,
        backoff_base_sec=backoff_base_sec)
    _count("publish_rollbacks")
    log_info(f"publish: rolled back {bad_name} "
             f"(sha {bad_sha[:12] or '?'}…) to {good_name} "
             f"as {new_name}")
    return manifest
