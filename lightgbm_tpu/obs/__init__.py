"""Run telemetry: metrics registry, recompile/HBM tracking, JSONL events.

The observability spine the perf ROADMAP items report against. Round 5's
PROFILE.md lesson is that per-op microbenchmarks lie in both directions
on this codebase — only in-situ measurement of the real boosting loop is
trustworthy — so every layer here instruments the *actual* hot path and
is a strict no-op when disabled:

- :class:`MetricsRegistry` — label-keyed, thread-safe counters / gauges /
  histograms (`registry` is the process-global instance).
- :mod:`~lightgbm_tpu.obs.jit_tracker` — registered jitted entry points
  (grow / fused-iteration / predict) expose XLA cache-size deltas, so a
  shape-change recompile shows up as a counted event, not a mystery
  530 ms stall.
- :func:`device_memory_stats` — HBM gauges via ``device.memory_stats()``
  with explicit ``None`` on backends that lack it (CPU).
- :class:`TelemetryRecorder` — one JSONL event per boosting iteration
  (phase wall times, recompiles, HBM, tree stats, eval results),
  activated by ``lightgbm_tpu.callback.telemetry(path)`` or the
  ``LIGHTGBM_TPU_TELEMETRY=<path>`` env var.
- :mod:`~lightgbm_tpu.obs.export` — the fleet metrics plane: the
  registry rendered as OpenMetrics text on a jax-free stdlib
  ``/metrics`` endpoint (``metrics_port`` / ``--metrics-port``,
  port + rank per process) and the strict parser the fleet scrapers
  and tests read it back with.
- :mod:`~lightgbm_tpu.obs.cost` — in-band XLA cost attribution: each
  registered entry point's first compile per signature records
  flops / bytes / compile wall / cost-model-optimal ms as
  ``{"event": "compile"}`` telemetry (docs/ROOFLINE.md made live).
- :mod:`~lightgbm_tpu.obs.trace` — the distributed tracing plane:
  jax-free spans (``{"event": "span"}``) across the whole
  train -> publish -> serve lifecycle, clock-skew-corrected and
  merged into Perfetto-loadable Chrome trace JSON plus named
  critical paths by ``python -m lightgbm_tpu trace <dir>``.

See docs/OBSERVABILITY.md for the event schema and workflow.
"""

from .cost import (CostTracked, compile_events_snapshot, device_peaks,
                   drain_compile_events, roofline_optimal_ms)
from .export import (MetricsHTTPServer, ensure_metrics_server,
                     parse_openmetrics, render_openmetrics)
from .jit_tracker import (RecompileWatcher, jit_cache_sizes,
                          jit_declarations, register_jit,
                          total_recompiles)
from .memory import device_memory_stats
from .recorder import (ITERATION_EVENT_KEYS, TelemetryRecorder,
                       UnknownEventError, merge_fleet_summaries,
                       render_fleet_table, render_stats_table,
                       summarize_directory, summarize_events)
from .schemas import (ENV_VARS, EVENT_NAMES, EVENTS, FAULT_EVENT_KINDS,
                      FAULT_KINDS, METRICS, event_keys,
                      fault_event_kinds, injectable_fault_kinds,
                      one_shot_fault_kinds, required_keys)
from .registry import Counter, Gauge, Histogram, MetricsRegistry, registry
from .trace import (SPAN_EVENT_KEYS, current_context, drain_span_events,
                    new_span_id, new_trace_id, record_span,
                    set_current_trace, span, span_events_snapshot)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "registry",
    "register_jit", "jit_cache_sizes", "jit_declarations",
    "total_recompiles",
    "RecompileWatcher", "device_memory_stats",
    "TelemetryRecorder", "ITERATION_EVENT_KEYS", "UnknownEventError",
    "EVENTS", "EVENT_NAMES", "METRICS", "ENV_VARS", "FAULT_KINDS",
    "FAULT_EVENT_KINDS", "event_keys", "required_keys",
    "injectable_fault_kinds", "one_shot_fault_kinds",
    "fault_event_kinds",
    "summarize_events", "render_stats_table",
    "summarize_directory", "merge_fleet_summaries",
    "render_fleet_table",
    "render_openmetrics", "parse_openmetrics", "MetricsHTTPServer",
    "ensure_metrics_server",
    "CostTracked", "drain_compile_events", "compile_events_snapshot",
    "device_peaks", "roofline_optimal_ms",
    "SPAN_EVENT_KEYS", "record_span", "span", "drain_span_events",
    "span_events_snapshot", "new_trace_id", "new_span_id",
    "current_context", "set_current_trace",
]
