"""End-to-end training smoke tests (the minimum slice of SURVEY.md §7)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import make_synthetic_binary, make_synthetic_regression


def test_binary_end_to_end():
    X, y = make_synthetic_binary(n=2000, f=10)
    train = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
         "metric": ["binary_logloss", "auc"], "verbosity": -1,
         "min_data_in_leaf": 5},
        train, num_boost_round=20)
    assert bst.current_iteration() == 20
    pred = bst.predict(X)
    assert pred.shape == (2000,)
    assert np.all((pred >= 0) & (pred <= 1))
    acc = np.mean((pred > 0.5) == (y > 0))
    assert acc > 0.9, f"accuracy too low: {acc}"


def test_binary_eval_improves():
    X, y = make_synthetic_binary(n=3000, f=8, seed=3)
    Xtr, ytr = X[:2000], y[:2000]
    Xva, yva = X[2000:], y[2000:]
    train = lgb.Dataset(Xtr, label=ytr)
    valid = train.create_valid(Xva, label=yva)
    record = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 31, "metric": "binary_logloss",
         "verbosity": -1},
        train, num_boost_round=30, valid_sets=[valid],
        callbacks=[lgb.record_evaluation(record)])
    ll = record["valid_0"]["binary_logloss"]
    assert ll[-1] < ll[0] * 0.7, f"logloss did not improve: {ll[0]} -> {ll[-1]}"
    assert ll[-1] < 0.45


def test_regression_l2():
    X, y = make_synthetic_regression(n=2000, f=10)
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "metric": "l2",
         "verbosity": -1},
        train, num_boost_round=50)
    pred = bst.predict(X)
    mse = np.mean((pred - y) ** 2)
    var = np.var(y)
    assert mse < 0.2 * var, f"mse {mse} vs var {var}"


def test_predict_matches_internal_score():
    X, y = make_synthetic_binary(n=1000, f=6, seed=11)
    train = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        train, num_boost_round=10)
    raw = bst.predict(X, raw_score=True)
    internal = bst._engine.current_score(0)[0]
    np.testing.assert_allclose(raw, internal, rtol=1e-4, atol=1e-5)


def test_model_save_load_roundtrip(tmp_path):
    X, y = make_synthetic_binary(n=1000, f=6, seed=5)
    train = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        train, num_boost_round=5)
    pred0 = bst.predict(X)
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    bst2 = lgb.Booster(model_file=str(path))
    pred1 = bst2.predict(X)
    np.testing.assert_allclose(pred0, pred1, rtol=1e-6)
    # round-trip the string form too
    s = bst2.model_to_string()
    bst3 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(pred0, bst3.predict(X), rtol=1e-6)


def test_early_stopping():
    X, y = make_synthetic_binary(n=3000, f=8, seed=13)
    train = lgb.Dataset(X[:2000], label=y[:2000])
    valid = train.create_valid(X[2000:], label=y[2000:])
    bst = lgb.train(
        {"objective": "binary", "metric": "binary_logloss",
         "verbosity": -1},
        train, num_boost_round=500, valid_sets=[valid],
        callbacks=[lgb.early_stopping(5, verbose=False)])
    assert bst.best_iteration < 500


def test_multiclass():
    rs = np.random.RandomState(0)
    n, f, k = 1500, 8, 3
    X = rs.randn(n, f)
    centers = rs.randn(k, f) * 2
    y = np.argmin(((X[:, None, :] - centers[None]) ** 2).sum(-1), axis=1)
    train = lgb.Dataset(X, label=y.astype(np.float64), free_raw_data=False)
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
         "verbosity": -1},
        train, num_boost_round=20)
    pred = bst.predict(X)
    assert pred.shape == (n, k)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-4)
    acc = np.mean(pred.argmax(axis=1) == y)
    assert acc > 0.85, acc


def test_quantized_training_close_to_float():
    """use_quantized_grad (GradientDiscretizer analog): int8 histograms
    with stochastic rounding + leaf renewal track the float path
    (reference test_engine.py quantized-training tolerance model)."""
    import lightgbm_tpu as lgb

    X, y = make_synthetic_binary(n=3000, f=10, seed=11)
    base = {"objective": "binary", "metric": "auc", "num_leaves": 15,
            "min_data_in_leaf": 20, "verbosity": -1, "seed": 3}
    d1 = lgb.Dataset(X, label=y)
    b_float = lgb.train(dict(base), d1, num_boost_round=20)
    d2 = lgb.Dataset(X, label=y)
    b_quant = lgb.train(dict(base, use_quantized_grad=True,
                             num_grad_quant_bins=8,
                             quant_train_renew_leaf=True), d2,
                        num_boost_round=20)
    from sklearn.metrics import roc_auc_score
    auc_f = roc_auc_score(y, b_float.predict(X))
    auc_q = roc_auc_score(y, b_quant.predict(X))
    assert auc_q > 0.95 * auc_f
    assert auc_q > 0.8


def test_quantized_training_auc_parity():
    """Quantify the quantized-gradient count semantics (grow.py
    hessian-estimated in-bag counts under int8 grads): held-out AUC
    must track float training closely on realistic data."""
    rs = np.random.RandomState(23)
    n = 6000
    X = rs.randn(n, 8)
    y = ((X[:, 0] + 0.6 * X[:, 1] * X[:, 2] + 0.4 * rs.randn(n)) > 0
         ).astype(float)
    tr, te = slice(0, 5000), slice(5000, n)

    def auc(y_, p_):
        o = np.argsort(p_)
        r = np.empty(len(p_)); r[o] = np.arange(1, len(p_) + 1)
        npos = y_.sum(); nneg = len(y_) - npos
        return (r[y_ > 0].sum() - npos * (npos + 1) / 2) / (npos * nneg)

    base = {"objective": "binary", "verbosity": -1, "num_leaves": 31}
    f32 = lgb.train(base, lgb.Dataset(X[tr], label=y[tr]),
                    num_boost_round=40)
    q = lgb.train({**base, "use_quantized_grad": True,
                   "quant_train_renew_leaf": True},
                  lgb.Dataset(X[tr], label=y[tr]), num_boost_round=40)
    a_f, a_q = auc(y[te], f32.predict(X[te])), auc(y[te], q.predict(X[te]))
    assert a_q > a_f - 0.01, (a_f, a_q)
