"""Quantized-training benches, two arms:

1. (default) Quantized-GRADIENT training (use_quantized_grad: int8
   stochastic rounding, exact int32 MXU histograms — the reference's
   gradient_discretizer.hpp feature) at bench scale on the real chip,
   fused path. Secondary metric: the primary bench stays the
   reference's own (non-quantized) Higgs config. Run:
       python benchmarks/quant_bench.py

2. (--comms) Quantized histogram ALLREDUCE (parallel/comms.py,
   hist_comm): time f32 vs int16 vs int8 reductions of the
   Allstate-wide [F=4228, B=255, 2] histogram on 8 devices and print
   a flip/keep verdict in the fused_iter_bench.py format — the gate
   for letting hist_comm="auto" resolve to int8 instead of int16.
   On the chip the int modes run the real int-wire exchange
   (all_to_all + all_gather); on CPU hosts the shared-scale psum
   transport is timed instead (and the wire saving is a model — see
   docs/COLLECTIVES.md). Run:
       python benchmarks/quant_bench.py --comms
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import time

import numpy as np


def main_comms() -> None:
    # a CPU host still measures an 8-rank world (virtual devices; the
    # flag only affects the host platform, so a TPU backend ignores it)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.parallel import comms
    # the jax-version shard_map shim the package already maintains
    from lightgbm_tpu.parallel.data_parallel import shard_map
    from lightgbm_tpu.parallel.mesh import make_mesh

    F, B, reps = 4228, 255, 8
    ndev = min(8, len(jax.devices()))
    mesh = make_mesh(ndev)
    axis = mesh.axis_names[0]
    rs = np.random.RandomState(0)
    # per-device histogram shards (one [F, B, 2] local hist each)
    hists = jnp.asarray(rs.randn(ndev, F, B, 2).astype(np.float32))
    print(f"comms arm: [F={F}, B={B}, 2] histogram allreduce, "
          f"world={ndev}, backend={jax.default_backend()}, "
          f"{reps} chained reductions/measure", flush=True)

    times = {}
    for mode in ("f32", "int16", "int8"):
        def step(h):
            h = h[0]
            ef = jnp.zeros_like(h)
            out = jnp.zeros_like(h)
            # chain reps reductions so dispatch overhead amortizes and
            # the EF carry is exercised like the grower's loop
            for _ in range(reps):
                y, ef = comms.hist_allreduce(h + out * 1e-9, axis,
                                             mode, ef)
                out = y
            return out[None]

        fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P(axis),
                               out_specs=P(axis), check_rep=False))
        fn(hists).block_until_ready()          # compile
        t0 = time.perf_counter()
        n_meas = 3
        for _ in range(n_meas):
            fn(hists).block_until_ready()
        dt = (time.perf_counter() - t0) / (n_meas * reps)
        times[mode] = dt
        bytes_model = comms.payload_bytes("data", F, B, mode)
        print(f"hist_comm={mode:5s}: {dt * 1e3:8.2f} ms/allreduce "
              f"(modeled wire {bytes_model / 2 ** 20:.2f} MiB)",
              flush=True)

    # the pending decision this arm gates (resolve_hist_comm): does
    # auto resolve to int8 instead of int16 past the quantize
    # threshold? int8 must beat BOTH int16 and f32 to flip; otherwise
    # the verdict names which of the current rules stands.
    if times["int8"] < times["int16"] and times["int8"] < times["f32"]:
        verdict = "FLIP hist_comm auto to int8"
    elif times["int16"] < times["f32"]:
        verdict = "keep auto->int16 rule (int8 not winning)"
    else:
        verdict = "keep f32 (quantized wire not winning on this backend)"
    print(f"int8 vs int16: {times['int16'] / times['int8']:.3f}x, "
          f"int8 vs f32 allreduce: {times['f32'] / times['int8']:.3f}x "
          f"— {verdict} "
          "(record the verdict in docs/COLLECTIVES.md + PROFILE.md)",
          flush=True)


def main_quant() -> None:
    import lightgbm_tpu as lgb

    N, F = 10_500_000, 28
    rs = np.random.RandomState(0)
    X = rs.randn(N, F).astype(np.float32)
    coef = rs.randn(F).astype(np.float32)
    y = ((X @ coef) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
    ds.construct()
    del X

    for quant in (False, True):
        bst = lgb.Booster(params={"objective": "binary",
                                  "num_leaves": 255,
                                  "max_bin": 255, "learning_rate": 0.1,
                                  "verbosity": -1,
                                  "use_quantized_grad": quant},
                          train_set=ds)
        eng = bst._engine
        t0 = time.perf_counter()
        eng.train_one_iter()
        eng.score.block_until_ready()
        wu = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(5):
            eng.train_one_iter()
        eng.score.block_until_ready()
        dt = (time.perf_counter() - t0) / 5
        print(f"quantized={quant}: {dt * 1e3:.1f} ms/iter "
              f"({1 / dt:.3f} it/s, vs_baseline "
              f"{1 / dt / (500 / 130.094):.3f}, warmup {wu:.0f}s)",
              flush=True)


if __name__ == "__main__":
    if "--comms" in sys.argv:
        main_comms()
    else:
        main_quant()
