"""Training/CV entry points.

Re-design of /root/reference/python-package/lightgbm/engine.py:
``train`` (:109, iteration loop :309-322), ``cv`` (:625), ``CVBooster``
(:354). Callback ordering, early-stopping unwinding and best_iteration
bookkeeping match the reference semantics.
"""

from __future__ import annotations

import copy
import os
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, LightGBMError
from .config import Config, resolve_params
from .utils.log import log_info, log_warning, scoped_verbosity
from .utils.timer import EnvCapture, Timer, timed


def _setup_metrics_endpoint(cfg: Config) -> None:
    """Start the per-process OpenMetrics /metrics endpoint
    (obs/export.py) when ``metrics_port`` is configured — via params
    or the LIGHTGBM_TPU_METRICS_PORT env var the fleet supervisors
    export. Each rank binds base + rank so a multi-process world's
    endpoints never collide; idempotent per process (cv folds and the
    pipeline's generations reuse the first server)."""
    # the env var OVERRIDES the param (config.py's documented
    # precedence): under a supervisor the exported base must win, or a
    # params-level metrics_port would collide with the supervisor's
    # own endpoint and desync the rank -> port attribution its
    # world-shape scraper relies on
    port = cfg.metrics_port
    env_port = os.environ.get("LIGHTGBM_TPU_METRICS_PORT")
    if env_port:
        try:
            port = int(env_port)
        except ValueError:
            pass
    if not port:
        return
    rank = 0
    rank_env = os.environ.get("LIGHTGBM_TPU_RANK")
    if rank_env:
        try:
            rank = int(rank_env)
        except ValueError:
            rank = 0
    else:
        try:
            import jax
            rank = jax.process_index()
        except Exception:
            rank = 0
    from .obs.export import ensure_metrics_server
    ensure_metrics_server(port + rank)


def _setup_telemetry(callbacks: List[Callable], model) -> None:
    """Activate run telemetry: honor ``LIGHTGBM_TPU_TELEMETRY=<path>``
    unless a telemetry callback is already present, then bind every
    recorder-bearing callback to the model before the first iteration
    (so iteration 0's event already carries tree stats)."""
    telem_path = os.environ.get("LIGHTGBM_TPU_TELEMETRY")
    if telem_path and not any(isinstance(cb, callback_mod._Telemetry)
                              for cb in callbacks):
        callbacks.append(callback_mod.telemetry(telem_path))
    for cb in callbacks:
        if isinstance(cb, callback_mod._Telemetry):
            cb.attach(model)


def _finish_callbacks(callbacks: List[Callable]) -> None:
    for cb in callbacks:
        if isinstance(cb, callback_mod._Telemetry):
            cb.finish()


# callbacks the fused scan window may legally run ahead of: they read
# no mid-window engine state the pop-per-update driver cannot serve
# per iteration (tree stats / phases / eval tuples — evaluation forces
# the eager path anyway, so these are inert on scan-eligible runs).
# Anything else (reset_parameter, user callbacks) pins the lookahead
# to 1: a window must never skate past a state read it cannot predict.
_SCAN_INERT_CALLBACKS = (callback_mod._Telemetry,
                         callback_mod._LogEvaluation,
                         callback_mod._RecordEvaluation,
                         callback_mod._EarlyStopping)


def _scan_lookahead(callbacks: List[Callable], iteration: int,
                    end_iteration: int,
                    engine_iteration: int,
                    eval_every: Optional[int] = None) -> int:
    """How many iterations the multi-iteration fused scan
    (models/gbdt.py, docs/FUSED.md) may run ahead of the callback loop
    starting at loop index ``iteration``: never past end-of-training,
    never past the next checkpoint firing — the Checkpoint callback
    keys on the engine's ABSOLUTE ``iter_`` (``engine_iteration``;
    offset from the loop index under init_model continued training),
    and `it % every_n == 0` reads the score, so windows must END on
    that cadence so snapshots see committed state — never past the
    loop's own inline evaluation (``eval_every`` = metric_freq when
    the train set is evaluated as a valid set; that cadence is
    loop-indexed), and 1 the moment an unknown callback could observe
    mid-window state."""
    from .resilience.checkpoint import Checkpoint

    horizon = end_iteration - iteration
    if eval_every is not None:
        every = max(1, int(eval_every))
        horizon = min(horizon, every - (iteration % every))
    for cb in callbacks:
        if isinstance(cb, Checkpoint):
            every = max(1, int(cb.every_n_iters))
            horizon = min(horizon, every - (engine_iteration % every))
        elif not isinstance(cb, _SCAN_INERT_CALLBACKS):
            return 1
    return max(1, horizon)

__all__ = ["train", "cv", "CVBooster"]


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval: Optional[Union[Callable, List[Callable]]] = None,
          init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          fobj: Optional[Callable] = None,
          resume_from: Optional[str] = None) -> Booster:
    """Train one model (engine.py:109 analog).

    ``resume_from``: checkpoint directory written by the
    ``resilience.checkpoint`` callback — the newest valid snapshot is
    restored and training continues from its iteration toward
    ``num_boost_round`` *total* iterations (a directory without usable
    snapshots trains from scratch). With ``init_model``,
    ``num_boost_round`` counts the NEW iterations on top of the
    adopted trees (reference ``init_iteration + num_boost_round``
    semantics), and a snapshot written by such a run records the
    offset — so resuming with the *identical* command finishes at the
    same iteration the uninterrupted run would have. The
    ``LIGHTGBM_TPU_CHECKPOINT`` environment variable implies both
    ``resume_from`` and the checkpoint callback itself; see
    docs/RESILIENCE.md.
    """
    params = resolve_params(params)
    # num_boost_round from params wins (alias resolution)
    if "num_iterations" in params:
        num_boost_round = int(params["num_iterations"])
    params["num_iterations"] = num_boost_round
    cfg = Config.from_params(params)
    with scoped_verbosity(cfg.verbosity):
        return _train_impl(params, cfg, train_set, num_boost_round,
                           valid_sets, valid_names, feval, init_model,
                           keep_training_booster, callbacks, fobj,
                           resume_from)


def _train_impl(params: Dict[str, Any], cfg: Config, train_set: Dataset,
                num_boost_round: int, valid_sets, valid_names, feval,
                init_model, keep_training_booster, callbacks,
                fobj, resume_from=None) -> Booster:
    if cfg.objective == "custom" and fobj is None:
        raise LightGBMError(
            "objective=none requires a custom objective function (fobj)")

    if not isinstance(train_set, Dataset):
        raise TypeError("train() only accepts Dataset object(s)")

    _setup_metrics_endpoint(cfg)
    booster = Booster(params=params, train_set=train_set)

    # -- crash recovery (resilience/checkpoint.py): an explicit
    # resume_from wins; LIGHTGBM_TPU_CHECKPOINT is the hands-off env
    # switch that both resumes from and checkpoints into one directory
    from .resilience.checkpoint import (Checkpoint, checkpoint,
                                        load_latest_snapshot,
                                        restore_booster)
    ckpt_env = os.environ.get("LIGHTGBM_TPU_CHECKPOINT")
    resume_dir = resume_from or ckpt_env
    snap = load_latest_snapshot(resume_dir) if resume_dir else None
    resumed_iteration = 0
    if snap is not None:
        if init_model is not None:
            log_warning("resume_from checkpoint takes precedence over "
                        "init_model")
        resumed_iteration = restore_booster(booster, snap)
        log_info(f"Resumed from checkpoint {snap['path']} at iteration "
                 f"{resumed_iteration}")
    elif init_model is not None:
        # continued training (engine.py init_model -> num_init_iteration)
        if isinstance(init_model, (str, Path)):
            base = Booster(model_file=str(init_model))
        elif isinstance(init_model, Booster):
            base = init_model
        else:
            raise TypeError(
                "init_model should be a str, pathlib.Path or Booster")
        booster._preload(base)
    valid_sets = valid_sets or []
    is_valid_contain_train = False
    train_data_name = "training"
    name_list = []
    for i, vd in enumerate(valid_sets):
        if valid_names is not None and i < len(valid_names):
            name = valid_names[i]
        else:
            name = f"valid_{i}"
        if vd is train_set:
            is_valid_contain_train = True
            train_data_name = name
            booster._train_data_name = name
            continue
        vd.construct()
        booster.add_valid(vd, name)
        name_list.append(name)

    # callbacks setup (before/after split, ordering by .order)
    callbacks = list(callbacks) if callbacks else []
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round,
            first_metric_only=cfg.first_metric_only,
            min_delta=cfg.early_stopping_min_delta,
            verbose=cfg.verbosity >= 1))
    if cfg.verbosity >= 1 and cfg.is_provide_training_metric:
        pass  # training metric printed through evaluation list below
    if ckpt_env and not any(isinstance(cb, Checkpoint)
                            for cb in callbacks):
        every_raw = os.environ.get("LIGHTGBM_TPU_CHECKPOINT_EVERY", "1")
        try:
            every = max(1, int(every_raw or 1))
        except ValueError:
            log_warning("LIGHTGBM_TPU_CHECKPOINT_EVERY="
                        f"{every_raw!r} is not an integer; "
                        "checkpointing every iteration")
            every = 1
        callbacks.append(checkpoint(ckpt_env, every_n_iters=every))
    _setup_telemetry(callbacks, booster)
    # lists, not a set (tpulint TPL005): `sorted` is stable, so
    # callbacks with EQUAL .order used to run in set hash order —
    # varying per process (PYTHONHASHSEED) and across SPMD ranks.
    # Registration order now breaks ties, like the cv() path.
    cbs_before = [cb for cb in callbacks
                  if getattr(cb, "before_iteration", False)]
    cbs_after = [cb for cb in callbacks
                 if not getattr(cb, "before_iteration", False)]
    cbs_before = sorted(cbs_before, key=lambda c: getattr(c, "order", 0))
    cbs_after = sorted(cbs_after, key=lambda c: getattr(c, "order", 0))

    from .resilience import watchdog
    from .resilience.faults import FaultPlan
    fault_plan = FaultPlan.from_env()
    # host-collective deadline for this run's sync points
    # (telemetry/checkpoint collectives; parallel/spmd.py). Env var
    # still overrides inside deadline_seconds().
    watchdog.configure(cfg.collective_timeout_sec)

    # iteration window (reference engine.py: range(init_iteration,
    # init_iteration + num_boost_round)): continued training
    # (init_model) adds num_boost_round NEW iterations on top of the
    # adopted trees, with loop indices running on the ENGINE-ABSOLUTE
    # iteration so callbacks/eval cadence and checkpoints agree with
    # the engine's own iter_. Resume continues toward the SAME end the
    # uninterrupted run had (train 20 == train 10 then resume to 20;
    # the snapshot records the init offset, so a crashed warm-start
    # retrain — the pipeline's rank_kill chaos, docs/PIPELINE.md —
    # relaunched with the identical command still finishes at
    # init + num_boost_round instead of stopping short).
    init_iteration = 0
    if booster._engine is not None:
        init_iteration = int(getattr(booster._engine,
                                     "init_iteration", 0))
    begin_iteration = resumed_iteration if snap is not None \
        else init_iteration
    end_iteration = max(begin_iteration,
                        init_iteration + num_boost_round)
    evaluation_result_list: List[Tuple] = []
    # env-driven device captures (LIGHTGBM_TPU_TRACE_TO whole-run /
    # LIGHTGBM_TPU_XPROF=dir:iters=A-B window); None — and zero
    # per-iteration cost — when neither knob is set
    env_capture = EnvCapture.from_env()
    try:
        for i in range(begin_iteration, end_iteration):
            fault_plan.maybe_kill(i)
            fault_plan.maybe_distributed_fault(i)
            if env_capture is not None:
                env_capture.before_iteration(i)
            if booster._engine is not None:
                # fused-scan lookahead (docs/FUSED.md): the engine
                # loop is the only place that knows the callback set
                # and end_iteration, so it bounds how far one scan
                # window may run ahead of the per-iteration cadence.
                # valid_sets=[train_set] keeps engine.valid_sets empty
                # (scan stays eligible) but this loop then evaluates
                # the TRAIN score inline every metric_freq iterations
                # — windows must end on that cadence too.
                booster._engine._scan_horizon = _scan_lookahead(
                    callbacks, i, end_iteration,
                    engine_iteration=int(booster._engine.iter_),
                    eval_every=(max(1, cfg.metric_freq)
                                if is_valid_contain_train else None))
            for cb in cbs_before:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=begin_iteration,
                    end_iteration=end_iteration,
                    evaluation_result_list=None))
            finished = booster.update(fobj=fobj)

            evaluation_result_list = []
            if (i + 1) % max(1, cfg.metric_freq) == 0 or \
                    i == end_iteration - 1:
                if valid_sets or is_valid_contain_train:
                    with timed("engine/eval"):
                        if is_valid_contain_train:
                            evaluation_result_list.extend(
                                booster.eval_train(feval))
                        evaluation_result_list.extend(
                            booster.eval_valid(feval))
            try:
                for cb in cbs_after:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=begin_iteration,
                        end_iteration=end_iteration,
                        evaluation_result_list=evaluation_result_list))
            except callback_mod.EarlyStopException as es:
                booster.best_iteration = es.best_iteration + 1
                evaluation_result_list = es.best_score
                # roll the model back to best_iteration for storage parity
                break
            if env_capture is not None:
                env_capture.after_iteration(i)
            if finished:
                log_info("Stopped training because there are no more "
                         "leaves that meet the split requirements")
                break
        # guard flags of the last fused iteration are still in flight
        # (the async check runs one iteration late): drain them now so
        # a fault on the final iteration still enforces its policy
        if booster._engine is not None:
            booster._engine.finish_faults()
    finally:
        if booster._engine is not None:
            # restore the documented direct-API behavior: only this
            # loop may grant lookahead, so a booster returned with a
            # stale multi-iteration horizon (break on stall / early
            # stop / an exception) must not dispatch windows from
            # plain update() calls
            booster._engine._scan_horizon = 1
        if env_capture is not None:
            # finalize capture files even when the loop raised
            env_capture.close()
        _finish_callbacks(callbacks)

    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration()
    for item in (evaluation_result_list or []):
        booster.best_score.setdefault(item[0], {})[item[1]] = item[2]
    if Timer.enabled():
        Timer.log_summary()
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (engine.py:354)."""

    def __init__(self, model_file: Optional[str] = None):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name: str):
        def handler_function(*args: Any, **kwargs: Any) -> List[Any]:
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict,
                  seed: int, stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    label = np.asarray(full_data.get_label())
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        if hasattr(folds, "split"):
            group = full_data.get_group()
            group_info = None if group is None else np.asarray(group)
            flatted_group = np.zeros(num_data, dtype=np.int64)
            if group_info is not None:
                flatted_group = np.repeat(range(len(group_info)), group_info)
            folds = folds.split(X=np.empty(num_data), y=label,
                                groups=flatted_group)
        return list(folds)
    rng = np.random.RandomState(seed)
    if full_data.get_group() is not None:
        # group-aware folds: whole queries per fold
        group = np.asarray(full_data.get_group())
        nq = len(group)
        q_idx = np.arange(nq)
        if shuffle:
            rng.shuffle(q_idx)
        q_fold = np.arange(nq) % nfold
        row_fold = np.zeros(num_data, np.int64)
        starts = np.concatenate([[0], np.cumsum(group)])
        for qi, f in zip(q_idx, q_fold):
            row_fold[starts[qi]:starts[qi + 1]] = f
        return [(np.where(row_fold != f)[0], np.where(row_fold == f)[0])
                for f in range(nfold)]
    if stratified:
        # label-sorted striping keeps class ratios per fold; with shuffle,
        # rows are permuted within each label block first so fold
        # membership is random rather than row-order-determined
        order = np.argsort(label, kind="stable")
        if shuffle:
            sorted_labels = label[order]
            block_starts = np.concatenate(
                [[0], np.where(np.diff(sorted_labels) != 0)[0] + 1,
                 [num_data]])
            for a, b in zip(block_starts[:-1], block_starts[1:]):
                perm = rng.permutation(b - a)
                order[a:b] = order[a:b][perm]
        fold_of = np.empty(num_data, np.int64)
        fold_of[order] = np.arange(num_data) % nfold
        return [(np.where(fold_of != f)[0], np.where(fold_of == f)[0])
                for f in range(nfold)]
    idx = np.arange(num_data)
    if shuffle:
        rng.shuffle(idx)
    return [(np.concatenate([idx[: (f * num_data) // nfold],
                             idx[((f + 1) * num_data) // nfold:]]),
             idx[(f * num_data) // nfold: ((f + 1) * num_data) // nfold])
            for f in range(nfold)]


def _agg_cv_result(raw_results: List[List[Tuple]]):
    cvmap: Dict[str, List[float]] = {}
    metric_type: Dict[str, bool] = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = f"{one_line[0]} {one_line[1]}"
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k],
             float(np.std(v))) for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset,
       num_boost_round: int = 100, folds=None, nfold: int = 5,
       stratified: bool = True, shuffle: bool = True,
       metrics=None, feval=None, init_model=None,
       fpreproc=None, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, Any]:
    """K-fold cross validation (engine.py:625 analog)."""
    if not isinstance(train_set, Dataset):
        raise TypeError("cv() only accepts Dataset object(s)")
    params = resolve_params(params)
    if "num_iterations" in params:
        num_boost_round = int(params["num_iterations"])
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config.from_params(params)
    with scoped_verbosity(cfg.verbosity):
        return _cv_impl(params, cfg, train_set, num_boost_round, folds,
                        nfold, stratified, shuffle, feval, fpreproc, seed,
                        callbacks, eval_train_metric, return_cvbooster)


def _cv_impl(params: Dict[str, Any], cfg: Config, train_set: Dataset,
             num_boost_round: int, folds, nfold, stratified, shuffle,
             feval, fpreproc, seed, callbacks, eval_train_metric,
             return_cvbooster) -> Dict[str, Any]:
    if cfg.objective in ("binary", "multiclass", "multiclassova",
                         "lambdarank", "rank_xendcg"):
        stratified = stratified and cfg.objective == "binary"
    else:
        stratified = False

    train_set.construct()
    folds = _make_n_folds(train_set, folds, nfold, params, seed,
                          stratified, shuffle)
    label = np.asarray(train_set.get_label())
    weight = train_set.get_weight()
    group = train_set.get_group()
    # raw feature matrix must still be around for fold slicing
    X = train_set.host_bins()  # binned is fine: folds share bin mappers

    cvbooster = CVBooster()
    results: Dict[str, List[float]] = {}

    boosters = []
    for train_idx, test_idx in folds:
        tr = _subset_dataset(train_set, train_idx, params)
        te = _subset_dataset(train_set, test_idx, params)
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, params.copy())
        booster = Booster(params=params, train_set=tr)
        booster.add_valid(te, "valid")
        if eval_train_metric:
            booster._train_data_name = "train"
        boosters.append(booster)
        cvbooster._append(booster)

    callbacks = list(callbacks) if callbacks else []
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round,
            first_metric_only=cfg.first_metric_only,
            verbose=cfg.verbosity >= 1))
    _setup_telemetry(callbacks, cvbooster)
    cbs_before = sorted((cb for cb in callbacks
                         if getattr(cb, "before_iteration", False)),
                        key=lambda c: getattr(c, "order", 0))
    cbs_after = sorted((cb for cb in callbacks
                        if not getattr(cb, "before_iteration", False)),
                       key=lambda c: getattr(c, "order", 0))

    try:
        for i in range(num_boost_round):
            for cb in cbs_before:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=None))
            for booster in boosters:
                booster.update()
            raw = []
            with timed("engine/eval"):
                for booster in boosters:
                    one = []
                    if eval_train_metric:
                        one.extend(booster.eval_train(feval))
                    one.extend(booster.eval_valid(feval))
                    raw.append(one)
            res = _agg_cv_result(raw)
            for (_, key, mean, _, std) in res:
                results.setdefault(f"{key}-mean", []).append(mean)
                results.setdefault(f"{key}-stdv", []).append(std)
            try:
                for cb in cbs_after:
                    cb(callback_mod.CallbackEnv(
                        model=cvbooster, params=params, iteration=i,
                        begin_iteration=0, end_iteration=num_boost_round,
                        evaluation_result_list=res))
            except callback_mod.EarlyStopException as es:
                cvbooster.best_iteration = es.best_iteration + 1
                for bst in boosters:
                    bst.best_iteration = cvbooster.best_iteration
                for k in results:
                    results[k] = results[k][: cvbooster.best_iteration]
                break
    finally:
        _finish_callbacks(callbacks)

    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return dict(results)


def _subset_dataset(full: Dataset, idx: np.ndarray,
                    params: Dict) -> Dataset:
    """Row-subset sharing the parent's bin mappers (Dataset::CopySubrow /
    Subset analog, dataset.h:661)."""
    full.construct()
    sub = Dataset.__new__(Dataset)
    sub.__dict__.update({k: v for k, v in full.__dict__.items()})
    sub.reference = full
    sub._bins = full._bins[idx]
    sub._device_bins = None
    sub._n = len(idx)
    rn = full.raw_numeric()
    sub._raw_numeric = None if rn is None else rn[idx]
    sub._device_raw = None
    sub.label = np.asarray(full.get_label())[idx]
    w = full.get_weight()
    sub.weight = None if w is None else np.asarray(w)[idx]
    init = full.get_init_score()
    sub.init_score = None if init is None else np.asarray(init)[idx]
    pos = full.get_position()
    sub.position = None if pos is None else np.asarray(pos)[idx]
    qb = full.query_boundaries()
    if qb is not None:
        # reconstruct boundaries for the kept (whole) queries
        row_query = np.searchsorted(qb, idx, side="right") - 1
        kept_q, counts = np.unique(row_query, return_counts=True)
        sub._query_boundaries = np.concatenate(
            [[0], np.cumsum(counts)]).astype(np.int64)
    sub.used_indices = np.asarray(idx)
    sub._handle = True
    return sub
