"""Supervised elastic restart: ``python -m lightgbm_tpu launch``.

The missing half of distributed fault tolerance: the collective
watchdog (resilience/watchdog.py) turns a hung world into per-rank
*errors*, and the checkpoint layer (resilience/checkpoint.py) makes the
training state durable — but something still has to notice dead
workers, tear down the survivors, and bring the world back up. That is
this supervisor::

    python -m lightgbm_tpu launch 4 -- python train.py

It spawns one training subprocess per rank with the coordinator
environment pre-wired (``LIGHTGBM_TPU_COORDINATOR`` /
``LIGHTGBM_TPU_NUM_PROCS`` / ``LIGHTGBM_TPU_RANK`` — a bare
``init_distributed()`` in the training script picks them up), watches
for any rank exiting nonzero (a crash, or a surviving rank's watchdog
abort), kills the rest of the world, and relaunches everything on a
fresh coordinator port. With ``LIGHTGBM_TPU_CHECKPOINT`` exported (or
``--checkpoint-dir``), every relaunch auto-resumes from the newest
snapshot, so the restarted run converges to the same model an
uninterrupted run produces (docs/RESILIENCE.md "Distributed
failures").

One-shot injected faults (``rank_kill`` / ``stall_rank`` in
``LIGHTGBM_TPU_FAULT_INJECT``) are stripped from the environment on
relaunch — consume-on-fire cannot survive a process restart, and
without stripping the injected failure would recur every generation
forever.

This module (and the whole ``launch`` dispatch in ``__main__``) never
imports jax: the supervisor must stay alive and tiny while worlds die
around it, and must not pin accelerator devices the workers need.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from ..utils.log import log_info, log_warning

__all__ = ["main", "supervise", "worker_env", "strip_one_shot_faults"]

#: fault kinds that must not re-fire after a supervised restart
_ONE_SHOT_KINDS = ("rank_kill", "stall_rank")

_POLL_SECONDS = 0.2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _kill_group(proc: subprocess.Popen) -> None:
    """SIGKILL a worker's whole process group (workers run in their own
    session); fall back to killing the process alone."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass


def strip_one_shot_faults(spec: str) -> str:
    """Drop ``rank_kill``/``stall_rank`` tokens from a
    ``LIGHTGBM_TPU_FAULT_INJECT`` value for a relaunch."""
    kept = [tok for tok in spec.split(",")
            if tok.strip()
            and tok.split("@", 1)[0].strip() not in _ONE_SHOT_KINDS]
    return ",".join(kept)


def worker_env(base: Dict[str, str], rank: int, nprocs: int,
               port: int, generation: int = 0) -> Dict[str, str]:
    """The per-rank environment one generation of workers runs with."""
    env = dict(base)
    env["LIGHTGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    env["LIGHTGBM_TPU_NUM_PROCS"] = str(nprocs)
    env["LIGHTGBM_TPU_RANK"] = str(rank)
    env["LIGHTGBM_TPU_RESTART_COUNT"] = str(generation)
    if generation > 0 and env.get("LIGHTGBM_TPU_FAULT_INJECT"):
        env["LIGHTGBM_TPU_FAULT_INJECT"] = strip_one_shot_faults(
            env["LIGHTGBM_TPU_FAULT_INJECT"])
    return env


def _launch_generation(cmd: Sequence[str], nprocs: int, port: int,
                       generation: int, log_dir: str,
                       base_env: Dict[str, str]) -> List[subprocess.Popen]:
    procs = []
    try:
        for rank in range(nprocs):
            log_path = os.path.join(
                log_dir, f"elastic_g{generation}_rank{rank}.log")
            log_file = open(log_path, "ab")
            try:
                procs.append(subprocess.Popen(
                    list(cmd),
                    env=worker_env(base_env, rank, nprocs, port,
                                   generation),
                    stdout=log_file, stderr=subprocess.STDOUT,
                    start_new_session=True))
            finally:
                log_file.close()   # the child holds its own fd now
    except BaseException:
        # a mid-loop failure (EMFILE, deleted log dir) must not leave
        # the already-spawned ranks orphaned, waiting on peers that
        # will never come up
        for p in procs:
            _kill_group(p)
        raise
    return procs


def _wait_generation(procs: List[subprocess.Popen],
                     grace: float) -> int:
    """Block until the generation resolves: 0 when every rank exited
    cleanly, else the first nonzero exit code (the rest of the world is
    killed after ``grace`` seconds — survivors are either hung in a
    collective or about to watchdog-abort; their state is already
    checkpointed)."""
    while True:
        first_bad: Optional[subprocess.Popen] = None
        alive = 0
        for p in procs:
            rc = p.poll()
            if rc is None:
                alive += 1
            elif rc != 0 and first_bad is None:
                first_bad = p
        if first_bad is not None:
            rank = procs.index(first_bad)
            rc = first_bad.returncode
            log_warning(f"elastic: rank {rank} exited with code "
                        f"{rc}; stopping the world")
            deadline = time.monotonic() + max(0.0, grace)
            while time.monotonic() < deadline and any(
                    p.poll() is None for p in procs):
                time.sleep(_POLL_SECONDS)
            for p in procs:
                if p.poll() is None:
                    _kill_group(p)
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    _kill_group(p)
            # signal deaths carry a NEGATIVE returncode; surface them
            # shell-style (128+signum) so SystemExit doesn't truncate
            # -9 into an unrelated 247
            return (128 - rc) if rc and rc < 0 else (rc or 1)
        if alive == 0:
            return 0
        time.sleep(_POLL_SECONDS)


def supervise(nprocs: int, cmd: Sequence[str], max_restarts: int = 3,
              port: Optional[int] = None, log_dir: str = ".",
              grace: float = 5.0,
              env: Optional[Dict[str, str]] = None) -> int:
    """Run ``cmd`` as an ``nprocs``-rank world under supervision;
    returns the final exit code (0 = a generation completed cleanly).

    Each generation gets a fresh coordinator port — the previous
    coordinator died with its rank-0 worker, and its socket may linger
    in TIME_WAIT. Worker output goes to
    ``{log_dir}/elastic_g{generation}_rank{rank}.log``.
    """
    if nprocs < 1:
        raise ValueError("nprocs must be >= 1")
    if not cmd:
        raise ValueError("no worker command given (pass it after --)")
    base_env = dict(os.environ if env is None else env)
    os.makedirs(log_dir, exist_ok=True)
    generation = 0
    while True:
        gen_port = port if port else _free_port()
        log_info(f"elastic: generation {generation}: launching "
                 f"{nprocs} rank(s), coordinator 127.0.0.1:{gen_port}")
        procs = _launch_generation(cmd, nprocs, gen_port, generation,
                                   log_dir, base_env)
        try:
            rc = _wait_generation(procs, grace)
        except BaseException:   # ctrl-C etc.: never leak a world
            for p in procs:
                if p.poll() is None:
                    _kill_group(p)
            raise
        if rc == 0:
            log_info(f"elastic: generation {generation} completed "
                     "cleanly")
            return 0
        if generation >= max_restarts:
            log_warning(
                f"elastic: generation {generation} failed (exit {rc}) "
                f"and the restart budget ({max_restarts}) is spent — "
                "giving up")
            return rc
        generation += 1
        try:
            from ..obs.registry import registry
            registry.counter("elastic_restarts").inc()
        except Exception:
            pass
        log_info(f"elastic: restarting the world (restart {generation}"
                 f"/{max_restarts}); training resumes from the newest "
                 "checkpoint if LIGHTGBM_TPU_CHECKPOINT is set")


_HELP_EPILOG = """\
The worker command runs once per rank with LIGHTGBM_TPU_COORDINATOR /
LIGHTGBM_TPU_NUM_PROCS / LIGHTGBM_TPU_RANK exported; a bare
init_distributed() call inside it joins the world. Export
LIGHTGBM_TPU_CHECKPOINT=<dir> (or pass --checkpoint-dir) so every
restart resumes from the newest snapshot. See docs/RESILIENCE.md
"Distributed failures".

exit codes:
  0  a generation completed cleanly on every rank
  N  the last failing rank's exit code, once restarts are exhausted
     (signal deaths surface shell-style as 128+signum, e.g. 137 for
     SIGKILL)
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu launch",
        usage="python -m lightgbm_tpu launch <nprocs> [options] "
              "-- <worker cmd...>",
        description="Supervised elastic launcher: spawn one training "
                    "process per rank, restart the world from the "
                    "newest checkpoint when a rank dies or a "
                    "collective watchdog aborts.",
        epilog=_HELP_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("nprocs", type=int, help="number of ranks to spawn")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="world restarts before giving up (default 3)")
    p.add_argument("--port", type=int, default=0,
                   help="fixed coordinator port (default: a fresh free "
                        "port per generation)")
    p.add_argument("--log-dir", default=".",
                   help="directory for per-rank worker logs "
                        "(default: .)")
    p.add_argument("--grace", type=float, default=5.0,
                   help="seconds to let surviving ranks exit on their "
                        "own before killing them (default 5)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="export LIGHTGBM_TPU_CHECKPOINT=<dir> to the "
                        "workers (auto-checkpoint + auto-resume)")
    # NOTE: the worker command is NOT an argparse positional — a
    # REMAINDER positional swallows the supervisor's own options, so
    # main() splits on the `--` separator before parsing
    return p


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # split on the `--` separator OURSELVES: argparse's REMAINDER is
    # greedy and would swallow the supervisor's own options into the
    # worker command
    if "--" in argv:
        split = argv.index("--")
        head, cmd = argv[:split], argv[split + 1:]
    else:
        head, cmd = argv, []
    args = build_parser().parse_args(head)
    if not cmd:
        print("launch: no worker command given (usage: launch <nprocs> "
              "-- <cmd...>)", file=sys.stderr)
        return 2
    env = dict(os.environ)
    if args.checkpoint_dir:
        env["LIGHTGBM_TPU_CHECKPOINT"] = args.checkpoint_dir
    try:
        return supervise(args.nprocs, cmd,
                         max_restarts=args.max_restarts,
                         port=args.port or None, log_dir=args.log_dir,
                         grace=args.grace, env=env)
    except KeyboardInterrupt:
        print("launch: interrupted", file=sys.stderr)
        return 130
