# tpulint fixture: TPL004 positive — use after donation.
import jax
import jax.numpy as jnp


def _step(score, grad):
    return score + grad


fused = jax.jit(_step, donate_argnums=(0,))


def train(score, grad):
    new_score = fused(score, grad)
    # EXPECT: TPL004
    drift = jnp.sum(score)       # `score` was donated above: dead
    return new_score, drift
