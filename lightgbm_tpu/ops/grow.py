"""Leaf-wise tree growth as one jitted XLA program.

Re-design of SerialTreeLearner::Train
(/root/reference/src/treelearner/serial_tree_learner.cpp:179-245) and the
device-resident CUDA learner
(src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp) for TPU:

- The growth loop runs ``num_leaves - 1`` *static* split steps inside a
  ``lax.fori_loop`` (XLA needs static trip counts); a step whose best gain
  is <= 0 is a no-op, and since nothing changes afterwards all remaining
  steps stay no-ops — equivalent to the reference's early ``break``
  (serial_tree_learner.cpp:225).
- Rows are never compacted per leaf: a ``row_leaf`` vector (the
  DataPartition analog, data_partition.hpp) assigns each row to a leaf
  slot, and leaf histograms are built by masking the per-row payload.
- Leaf slots follow the reference Tree convention (tree.h: ``Split``):
  the left child keeps the parent's leaf slot, the right child takes slot
  ``num_leaves_so_far``; internal node k is created by split k; child
  pointers store ``~leaf`` for leaves.
- Histogram subtraction: only the smaller child is scatter-accumulated,
  the sibling = parent - smaller (serial_tree_learner.cpp:473-520).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import comms
from .histogram import (build_histogram, hist_from_rows,
                        hist_from_rows_int, subtract_histogram)
from .predict import predict_leaf_binned
from .split import (SplitParams, SplitResult, constrained_output,
                    find_best_split, find_best_split_bundled,
                    gain_at_output, leaf_gain, leaf_output)

from .partition_kernel import route_concentrate

__all__ = ["GrowConfig", "TreeArrays", "grow_tree", "route_concentrate"]


def _axis_size(name) -> int:
    """Static mapped-axis size. ``lax.axis_size`` only exists on
    jax>=0.4.38; 0.4.37's accessor is ``core.axis_frame`` (returns the
    int size under shard_map)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(name)
    return jax.core.axis_frame(name)

NEG_INF = -jnp.inf


def _combine_split_infos(r: SplitResult, axis_name) -> SplitResult:
    """SyncUpGlobalBestSplit (parallel_tree_learner.h:209-232):
    allreduce the max-gain SplitInfo across devices searching disjoint
    feature subsets; ties resolve to the lower feature id (SplitInfo
    total order, split_info.hpp). Shared by the feature-parallel mode
    and the sharded data-parallel split search — with disjoint
    ownership exactly one device wins, so the psum-broadcast of each
    field is the winner's exact bit pattern."""
    gmax = lax.pmax(r.gain, axis_name)
    at_max = r.gain >= gmax
    packed = jnp.where(at_max, r.feature, jnp.int32(2 ** 30))
    fwin = lax.pmin(packed, axis_name)
    win = at_max & (r.feature == fwin)
    cnt = lax.psum(win.astype(jnp.float32), axis_name)

    def bc(x):
        xf = x.astype(jnp.float32)
        mean = lax.psum(jnp.where(win, xf, 0.0), axis_name) / cnt
        if x.dtype == jnp.bool_:
            return mean > 0.5
        if jnp.issubdtype(x.dtype, jnp.integer):
            return jnp.round(mean).astype(x.dtype)
        return mean.astype(x.dtype)

    return SplitResult(*(bc(field) for field in r))


class GrowConfig(NamedTuple):
    """Static (trace-time) growth configuration.

    ``axis_name``: when set, the grower runs inside shard_map/pjit with
    rows sharded over that mesh axis; histograms and root sums are
    psum-reduced — the TPU analog of the reference's data-parallel
    ReduceScatter+Allreduce (data_parallel_tree_learner.cpp:284-294,
    SURVEY.md §2.6). Split finding then happens identically on every
    device (deterministic), replacing SyncUpGlobalBestSplit.

    ``grower``: "compact" keeps rows grouped by leaf (DataPartition
    analog) so per-split work is proportional to the leaf size;
    "masked" builds every histogram with a full-row masked pass.
    """
    num_leaves: int
    num_bins: int
    max_depth: int = -1
    split: SplitParams = SplitParams()
    hist_method: str = "scatter"
    hist_precision: str = "default"  # mxu matmul passes: default|high|highest
    chunk: int = 16384           # rows per streaming chunk (compact grower)
    # Bulk-batching chunk size: each leaf window is partitioned as
    # floor(cnt/big_chunk) BIG chunks followed by K-sized tail chunks.
    # MEASURED NEUTRAL-TO-NEGATIVE on v5e (round 4: 158->162 ms/tree at
    # 1M rows with 131072, 392->428 ms at 10.5M): the chunk body is
    # throughput-bound (the bitonic sort's per-row work grows ~log^2 CK,
    # cancelling the amortized dispatch overhead), NOT dispatch-bound as
    # PROFILE.md round-3 option 2 hypothesized. Kept as a tuning knob;
    # 0 (default) disables.
    big_chunk: int = 0
    axis_name: Optional[str] = None
    grower: str = "compact"
    # quantized-gradient training (use_quantized_grad; the reference's
    # GradientDiscretizer, gradient_discretizer.hpp): g/h discretized to
    # int8, histograms accumulate in exact int32 on the int MXU.
    quantized: bool = False
    quant_bins: int = 4          # num_grad_quant_bins
    renew_leaf: bool = False     # quant_train_renew_leaf
    stochastic: bool = True      # stochastic_rounding
    # CEGB (cost_effective_gradient_boosting.hpp): gain penalties for
    # splits / first feature use / per-row feature acquisition
    cegb: bool = False
    cegb_lazy: bool = False
    cegb_coupled: bool = False   # any cegb_penalty_feature_coupled > 0
    cegb_tradeoff: float = 1.0
    cegb_split: float = 0.0
    # monotone constraint strategy (LeafConstraintsBase::Create,
    # monotone_constraints.hpp:1176): "basic" tracks per-leaf output
    # bounds set to the split midpoint; "intermediate" uses the sibling
    # subtree's extreme CURRENT outputs, refreshed (and every leaf's
    # best split re-searched) after each split — the batch fixed-point
    # of the reference's leaves_to_update propagation
    # (IntermediateLeafConstraints::Update), without the per-threshold
    # range refinement.
    monotone_method: str = "basic"
    # feature_fraction_bynode (ColSampler::GetByNode, col_sampler.hpp):
    # a fresh feature subset sampled per node from the per-tree set
    bynode: float = 1.0
    # distributed strategy under ``axis_name`` (SURVEY §2.6):
    # "data"    — rows sharded; histograms psum-reduced
    #             (DataParallelTreeLearner)
    # "feature" — rows replicated; devices search disjoint feature
    #             subsets and the winning SplitInfo is allreduced
    #             (FeatureParallelTreeLearner; on TPU the fused MXU
    #             histogram still covers all features — the sharing is
    #             in the split search, see best_for)
    # "voting"  — rows sharded; each device proposes its local top-k
    #             features, a global vote elects 2k, and only elected
    #             features' histograms are globally reduced
    #             (VotingParallelTreeLearner / PV-Tree)
    parallel_mode: str = "data"
    voting_top_k: int = 20
    # Exclusive Feature Bundling (ops/bundling.py): bins_T holds bundle
    # columns and the split search runs in bundle-position space
    bundled: bool = False
    # histogram cache budget (HistogramPool, the reference's
    # histogram_pool_size: src/treelearner/serial_tree_learner.cpp
    # GetShareStates + feature_histogram.hpp HistogramPool): 0 keeps
    # the full [L, F, B, 2] per-leaf cache HBM-resident; a positive
    # value caps the cache at that many leaf slots — evicted leaves'
    # histograms are recomputed from their (physically contiguous)
    # row window on demand, including inside the stored-candidate
    # re-search paths (CEGB / intermediate monotone / forced splits),
    # which walk leaves serving each hist from slot or recompute.
    hist_pool_slots: int = 0
    # in-chunk stable partition primitive (compact grower):
    # "sort"  — one variadic lax.sort on a (side, position) key.
    #           Default: XLA:TPU's variadic sort measures ~35us per
    #           16K chunk in situ (xplane, benchmarks/PROFILE.md) —
    #           NOT the chunk bottleneck.
    # "route" — two butterfly concentration passes (log2(K) stages of
    #           stride exchanges, LSB-first) steered by destination
    #           bits (ops/partition_kernel.py). Fewer stages on paper,
    #           but Mosaic/XLA lower the stage chain poorly on TPU
    #           today; kept as an option + correctness oracle.
    partition: str = "sort"
    # carry per-row ids + in-bag bits (ord2) through the partition.
    # Only needed when something consumes them: exact in-bag child
    # counts under bagging/GOSS (weight-0 rows), CEGB's lazy per-row
    # feature sets, or the bundled final merge. Plain full-data
    # training (the benchmark path) drops the column: one less sort
    # operand in every chunk body and no in-bag bookkeeping.
    track_rows: bool = True
    # histogram allreduce wire format under data-parallel sharding
    # (parallel/comms.py, EQuARX-style block quantization):
    # "f32" exact psum | "int16"/"int8" blockwise-quantized exchange
    # with an error-feedback residual threaded through the growth
    # loop carry. Scalar/count psums stay f32; quantized-gradient
    # training (cfg.quantized: exact int32 histograms) and the
    # feature-parallel mode (no histogram reduction) ignore it.
    hist_comm: str = "f32"
    # data-parallel split search (parallel/comms.py, docs/SHARDING.md):
    # "gathered" — the reduced [F, B, 2] histogram is allreduced and
    #              every device searches all features (the legacy psum
    #              path; XLA's ring allreduce broadcasts the full
    #              payload back to every device);
    # "sharded"  — the reference DataParallelTreeLearner's
    #              ReduceScatter + per-worker feature-subset search
    #              (data_parallel_tree_learner.cpp:223-300): histograms
    #              are reduce-scattered so each device owns and
    #              searches only its ceil(F/D) feature chunk, then the
    #              per-device best SplitInfo records are allreduced
    #              (SyncUpGlobalBestSplit). Post-reduction traffic
    #              drops from the full histogram broadcast to a 1/D
    #              chunk + O(D) split records; split decisions are
    #              byte-identical to the gathered path (psum_scatter
    #              chunks are bit-identical to psum slices; the
    #              SplitInfo combine broadcasts the single winner's
    #              exact field bits).
    # Only meaningful under axis_name + parallel_mode="data"; feature/
    # voting parallelism have their own search sharding already.
    split_search: str = "gathered"


class TreeArrays(NamedTuple):
    """Flat-tensor tree (the Tree class re-imagined as arrays;
    include/LightGBM/tree.h:63-252). Sizes: L leaves, L-1 internal nodes."""
    split_feature: jnp.ndarray   # [L-1] i32
    threshold_bin: jnp.ndarray   # [L-1] i32
    default_left: jnp.ndarray    # [L-1] bool
    left_child: jnp.ndarray      # [L-1] i32 (~leaf for leaves)
    right_child: jnp.ndarray     # [L-1] i32
    split_gain: jnp.ndarray      # [L-1] f32
    internal_value: jnp.ndarray  # [L-1] f32
    internal_weight: jnp.ndarray  # [L-1] f32
    internal_count: jnp.ndarray  # [L-1] f32
    leaf_value: jnp.ndarray      # [L] f32
    leaf_weight: jnp.ndarray     # [L] f32 (sum of hessians)
    leaf_count: jnp.ndarray      # [L] f32
    leaf_parent: jnp.ndarray     # [L] i32
    leaf_depth: jnp.ndarray      # [L] i32
    num_leaves: jnp.ndarray      # scalar i32 (actual leaves grown)
    split_is_cat: jnp.ndarray    # [L-1] bool — categorical membership split
    split_cat_mask: jnp.ndarray  # [L-1, B] bool — bins routed left


class _BestSplits(NamedTuple):
    """Per-leaf-slot best candidate split (the SplitInfo-per-leaf arrays)."""
    gain: jnp.ndarray
    feature: jnp.ndarray
    threshold_bin: jnp.ndarray
    default_left: jnp.ndarray
    is_cat: jnp.ndarray        # [L] bool
    cat_mask: jnp.ndarray      # [L, B] bool
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_count: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray

    @staticmethod
    def init(L: int, B: int, dtype) -> "_BestSplits":
        zf = jnp.zeros((L,), dtype=dtype)
        return _BestSplits(
            gain=jnp.full((L,), NEG_INF, dtype=dtype),
            feature=jnp.zeros((L,), jnp.int32),
            threshold_bin=jnp.zeros((L,), jnp.int32),
            default_left=jnp.zeros((L,), jnp.bool_),
            is_cat=jnp.zeros((L,), jnp.bool_),
            cat_mask=jnp.zeros((L, B), jnp.bool_),
            left_sum_g=zf, left_sum_h=zf, left_count=zf,
            right_sum_g=zf, right_sum_h=zf, right_count=zf,
            left_output=zf, right_output=zf,
        )

    def store(self, i, r: SplitResult, allowed) -> "_BestSplits":
        gain = jnp.where(allowed, r.gain, NEG_INF)
        return _BestSplits(
            gain=self.gain.at[i].set(gain),
            feature=self.feature.at[i].set(r.feature),
            threshold_bin=self.threshold_bin.at[i].set(r.threshold_bin),
            default_left=self.default_left.at[i].set(r.default_left),
            is_cat=self.is_cat.at[i].set(r.is_cat),
            cat_mask=self.cat_mask.at[i].set(r.cat_mask),
            left_sum_g=self.left_sum_g.at[i].set(r.left_sum_g),
            left_sum_h=self.left_sum_h.at[i].set(r.left_sum_h),
            left_count=self.left_count.at[i].set(r.left_count),
            right_sum_g=self.right_sum_g.at[i].set(r.right_sum_g),
            right_sum_h=self.right_sum_h.at[i].set(r.right_sum_h),
            right_count=self.right_count.at[i].set(r.right_count),
            left_output=self.left_output.at[i].set(r.left_output),
            right_output=self.right_output.at[i].set(r.right_output),
        )


class _GrowState(NamedTuple):
    tree: TreeArrays
    best: _BestSplits
    hists: jnp.ndarray      # [L, F, B, 2]
    row_leaf: jnp.ndarray   # [n] i32
    num_splits: jnp.ndarray  # scalar i32
    comm_ef: jnp.ndarray = ()  # quantized-allreduce error feedback
                               # (hist_comm int8/int16; comms.py)


def _init_tree(L: int, B: int, dtype) -> TreeArrays:
    return TreeArrays(
        split_is_cat=jnp.zeros((L - 1,), jnp.bool_),
        split_cat_mask=jnp.zeros((L - 1, B), jnp.bool_),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        default_left=jnp.zeros((L - 1,), jnp.bool_),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        split_gain=jnp.zeros((L - 1,), dtype),
        internal_value=jnp.zeros((L - 1,), dtype),
        internal_weight=jnp.zeros((L - 1,), dtype),
        internal_count=jnp.zeros((L - 1,), dtype),
        leaf_value=jnp.zeros((L,), dtype),
        leaf_weight=jnp.zeros((L,), dtype),
        leaf_count=jnp.zeros((L,), dtype),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        num_leaves=jnp.asarray(1, jnp.int32),
    )


def _apply_split_to_tree(tree: TreeArrays, best: _BestSplits, leaf, R, ns,
                         p: SplitParams, left_cnt=None,
                         right_cnt=None) -> TreeArrays:
    """Record split ``ns`` of leaf slot ``leaf`` (Tree::Split, tree.h:63).

    The left child keeps the parent's leaf slot; the right child takes
    slot ``R``; internal node ``ns`` is created by this split.
    ``left_cnt``/``right_cnt`` are the exact partition counts when the
    caller has them (SplitInner overwrites the search-time estimates the
    same way, serial_tree_learner.cpp:789-791); the stored candidate
    counts are hessian-ratio estimates otherwise."""
    f = best.feature[leaf]
    t = best.threshold_bin[leaf]
    dl = best.default_left[leaf]
    cm = best.cat_mask[leaf]
    parent = tree.leaf_parent[leaf]
    pidx = jnp.maximum(parent, 0)
    lc = tree.left_child
    rc = tree.right_child
    lc = lc.at[pidx].set(jnp.where((parent >= 0) & (lc[pidx] == ~leaf),
                                   ns, lc[pidx]))
    rc = rc.at[pidx].set(jnp.where((parent >= 0) & (rc[pidx] == ~leaf),
                                   ns, rc[pidx]))
    lc = lc.at[ns].set(~leaf)
    rc = rc.at[ns].set(~R)
    lcnt = best.left_count[leaf] if left_cnt is None else left_cnt
    rcnt = best.right_count[leaf] if right_cnt is None else right_cnt
    parent_g = best.left_sum_g[leaf] + best.right_sum_g[leaf]
    parent_h = best.left_sum_h[leaf] + best.right_sum_h[leaf]
    parent_c = lcnt + rcnt
    new_depth = tree.leaf_depth[leaf] + 1
    return tree._replace(
        split_feature=tree.split_feature.at[ns].set(f),
        threshold_bin=tree.threshold_bin.at[ns].set(t),
        default_left=tree.default_left.at[ns].set(dl),
        split_is_cat=tree.split_is_cat.at[ns].set(best.is_cat[leaf]),
        split_cat_mask=tree.split_cat_mask.at[ns].set(cm),
        left_child=lc,
        right_child=rc,
        split_gain=tree.split_gain.at[ns].set(best.gain[leaf]),
        internal_value=tree.internal_value.at[ns].set(
            leaf_output(parent_g, parent_h, p)),
        internal_weight=tree.internal_weight.at[ns].set(parent_h),
        internal_count=tree.internal_count.at[ns].set(parent_c),
        leaf_value=tree.leaf_value.at[leaf].set(best.left_output[leaf])
        .at[R].set(best.right_output[leaf]),
        leaf_weight=tree.leaf_weight.at[leaf].set(best.left_sum_h[leaf])
        .at[R].set(best.right_sum_h[leaf]),
        leaf_count=tree.leaf_count.at[leaf].set(lcnt).at[R].set(rcnt),
        leaf_parent=tree.leaf_parent.at[leaf].set(ns).at[R].set(ns),
        leaf_depth=tree.leaf_depth.at[leaf].set(new_depth)
        .at[R].set(new_depth),
        num_leaves=tree.num_leaves + 1,
    )


def grow_tree_impl(cfg: GrowConfig,
                   bins_T: jnp.ndarray,
                   grad: jnp.ndarray,
                   hess: jnp.ndarray,
                   row_weight: jnp.ndarray,
                   feature_mask: jnp.ndarray,
                   feat_num_bins: jnp.ndarray,
                   feat_nan_bin: jnp.ndarray,
                   monotone_constraints: Optional[jnp.ndarray] = None,
                   feat_is_cat: Optional[jnp.ndarray] = None,
                   quant_key: Optional[jnp.ndarray] = None,
                   interaction_groups: Optional[jnp.ndarray] = None,
                   forced: Optional[tuple] = None,
                   cegb_arrays: Optional[tuple] = None,
                   node_key: Optional[jnp.ndarray] = None,
                   bundle_arrays: Optional[tuple] = None):
    """Grow one leaf-wise tree. Returns (TreeArrays, row_leaf)
    (+ (coupled_used, lazy_used) when cfg.cegb).

    Args:
      bins_T: [F, n] uint8/uint16 bin matrix.
      grad/hess: [n] float.
      row_weight: [n] float sampling weight (bagging/GOSS; 1.0 = use row).
      feature_mask: [F] bool usable-feature mask (feature_fraction etc).
      feat_num_bins / feat_nan_bin: [F] i32 per-feature bin metadata.
      quant_key: PRNG key for stochastic gradient rounding (quantized
        mode only).
      interaction_groups: optional [G, F] bool — allowed feature groups
        (interaction_constraints); compact grower only.
      forced: optional (leaf [M], feature [M], bin [M]) i32 arrays — the
        pre-planned forced splits (forcedsplits_filename, BFS order);
        compact grower only.
      node_key: PRNG key for per-node column sampling
        (feature_fraction_bynode; cfg.bynode < 1).
    """
    if cfg.split_search == "sharded" and cfg.bundled:
        raise NotImplementedError(
            "split_search='sharded' does not cover EFB bundling yet — "
            "the engine keeps bundled runs on the gathered search "
            "(models/gbdt.py)")
    if cfg.grower == "compact":
        return _grow_compact_impl(cfg, bins_T, grad, hess, row_weight,
                                  feature_mask, feat_num_bins, feat_nan_bin,
                                  monotone_constraints, feat_is_cat,
                                  quant_key, interaction_groups, forced,
                                  cegb_arrays, node_key, bundle_arrays)
    if cfg.grower == "level":
        if cfg.bundled or interaction_groups is not None \
                or forced is not None or cegb_arrays is not None \
                or cfg.quantized or cfg.bynode < 1.0 \
                or cfg.split.path_smooth > 0.0 \
                or cfg.hist_pool_slots > 0 \
                or (cfg.axis_name is not None
                    and cfg.parallel_mode != "data"):
            raise NotImplementedError(
                "grower='level' covers the core feature set only (no "
                "EFB/interaction/forced/CEGB/quantized/bynode/"
                "path-smooth/histogram-pool; data-parallel sharding "
                "only) — use grower='compact'")
        return _grow_level_impl(cfg, bins_T, grad, hess, row_weight,
                                feature_mask, feat_num_bins,
                                feat_nan_bin, monotone_constraints,
                                feat_is_cat)
    if cfg.bundled:
        raise NotImplementedError(
            "EFB bundling requires the compact grower")
    if interaction_groups is not None or forced is not None \
            or cegb_arrays is not None:
        raise NotImplementedError(
            "interaction_constraints/forced splits/CEGB require the "
            "compact grower")
    if cfg.bynode < 1.0 or cfg.split.path_smooth > 0.0:
        # path smoothing and per-node column sampling live on the
        # flagship compact grower only (gbdt.py routes those configs
        # there); the masked grower keeps monotone as a validity check
        # without output-bound entries (legacy behavior).
        raise NotImplementedError(
            "path_smooth/feature_fraction_bynode require the compact "
            "grower")
    return _grow_masked_impl(cfg, bins_T, grad, hess, row_weight,
                             feature_mask, feat_num_bins, feat_nan_bin,
                             monotone_constraints, feat_is_cat)


def _make_sharded_search(cfg: GrowConfig, F: int, qm: str,
                         use_ef: bool):
    """Reduce-scatter sharded-search context shared by every grower
    (docs/SHARDING.md): each device owns ``Fl = ceil(F/D)`` features
    of the reduced histogram (feature axis padded to ``Fsp = D * Fl``
    so psum_scatter chunks align), searches only its chunk, and the
    winning SplitInfo is allreduced — the reference
    DataParallelTreeLearner shape. Returns ``(Fl, Fsp, f_start,
    dev_idx, rs_pad, hist_psum_ef, owned_slice)``; the feature axis is
    third-from-last in every histogram shape the growers reduce
    ([F, B, 2] root / [L, F, B, 2] level batch), so the scatter axis
    is positional. Must be called inside the traced program (it takes
    ``lax.axis_index``)."""
    D_sh = _axis_size(cfg.axis_name)
    dev_idx = lax.axis_index(cfg.axis_name)
    Fl = -(-F // D_sh)
    Fsp = Fl * D_sh
    f_start = dev_idx * Fl

    def rs_pad(x):
        """Pad the feature axis (third-from-last) to Fsp."""
        if Fsp == F:
            return x
        pw = [(0, 0)] * x.ndim
        pw[x.ndim - 3] = (0, Fsp - F)
        return jnp.pad(x, pw)

    def hist_psum_ef(x, ef):
        x = rs_pad(x)
        ax = x.ndim - 3
        if not use_ef:
            return lax.psum_scatter(
                x, cfg.axis_name, scatter_dimension=ax,
                tiled=True), ef
        return comms.hist_reduce_scatter(x, cfg.axis_name, qm, ef, ax)

    def owned_slice(v, fill):
        """This device's Fl-slice of a per-feature vector."""
        if v is None:
            return None
        if Fsp > F:
            padv = jnp.full((Fsp - F,), fill, v.dtype)
            v = jnp.concatenate([v, padv])
        return lax.dynamic_slice(v, (f_start,), (Fl,))

    return Fl, Fsp, f_start, dev_idx, rs_pad, hist_psum_ef, owned_slice


def _grow_masked_impl(cfg: GrowConfig,
                      bins_T: jnp.ndarray,
                      grad: jnp.ndarray,
                      hess: jnp.ndarray,
                      row_weight: jnp.ndarray,
                      feature_mask: jnp.ndarray,
                      feat_num_bins: jnp.ndarray,
                      feat_nan_bin: jnp.ndarray,
                      monotone_constraints: Optional[jnp.ndarray] = None,
                      feat_is_cat: Optional[jnp.ndarray] = None):
    """Masked-pass grower: every histogram is a full-row masked pass."""
    L = cfg.num_leaves
    B = cfg.num_bins
    F = bins_T.shape[0]
    n = bins_T.shape[1]
    dtype = grad.dtype
    p = cfg.split
    sharded = (cfg.axis_name is not None and cfg.parallel_mode == "data"
               and cfg.split_search == "sharded")

    def psum(x):
        return lax.psum(x, cfg.axis_name) if cfg.axis_name else x

    qm, use_ef, _gath_ef = comms.make_hist_psum_ef(
        cfg.axis_name, cfg.hist_comm)

    if sharded:
        Fl, Fsp, f_start, dev_idx, _rs_pad, hist_psum_ef, _ssl = \
            _make_sharded_search(cfg, F, qm, use_ef)
        FH = Fl

        def best_for(hist, sg, sh, sc):
            owned = (f_start + jnp.arange(Fl)) < F
            r = find_best_split(hist, sg, sh, sc,
                                _ssl(feat_num_bins, 1),
                                _ssl(feat_nan_bin, -1),
                                _ssl(feature_mask, False) & owned, p,
                                _ssl(monotone_constraints, 0),
                                _ssl(feat_is_cat, False))
            r = r._replace(feature=r.feature + f_start)
            return _combine_split_infos(r, cfg.axis_name)
    else:
        FH = F
        hist_psum_ef = _gath_ef

        def best_for(hist, sg, sh, sc):
            return find_best_split(hist, sg, sh, sc, feat_num_bins,
                                   feat_nan_bin, feature_mask, p,
                                   monotone_constraints, feat_is_cat)

    # ---- root (GlobalSyncUpBySum analog for the root tuple) ----
    w = row_weight.astype(dtype)
    inbag = row_weight > 0
    total_g = psum(jnp.sum(grad * w))
    total_h = psum(jnp.sum(hess * w))
    total_c = psum(jnp.sum(inbag.astype(dtype)))
    all_rows = jnp.ones((n,), jnp.bool_)
    comm_ef0 = jnp.zeros((Fsp if sharded else F, B, 2), dtype) \
        if use_ef else ()
    root_hist, comm_ef0 = hist_psum_ef(
        build_histogram(bins_T, grad, hess, row_weight, all_rows, B,
                        cfg.hist_method, cfg.hist_precision), comm_ef0)

    tree = _init_tree(L, B, dtype)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(leaf_output(total_g, total_h, p)),
        leaf_weight=tree.leaf_weight.at[0].set(total_h),
        leaf_count=tree.leaf_count.at[0].set(total_c),
    )
    best = _BestSplits.init(L, B, dtype)
    best = best.store(0, best_for(root_hist, total_g, total_h, total_c),
                      jnp.asarray(True))
    hists = jnp.zeros((L, FH, B, 2), dtype).at[0].set(root_hist)
    state = _GrowState(tree=tree, best=best, hists=hists,
                       row_leaf=jnp.zeros((n,), jnp.int32),
                       num_splits=jnp.asarray(0, jnp.int32),
                       comm_ef=comm_ef0)

    def depth_ok(d):
        if cfg.max_depth <= 0:
            return jnp.asarray(True)
        return d < cfg.max_depth

    def do_split(state: _GrowState) -> _GrowState:
        tree, best, hists, row_leaf, ns, comm_ef = state
        leaf = jnp.argmax(best.gain).astype(jnp.int32)
        R = ns + 1  # new (right-child) leaf slot
        f = best.feature[leaf]
        t = best.threshold_bin[leaf]
        dl = best.default_left[leaf]

        # -- partition rows of `leaf` (DataPartition::Split analog) --
        col = lax.dynamic_index_in_dim(bins_T, f, axis=0,
                                       keepdims=False).astype(jnp.int32)
        nan_bin = feat_nan_bin[f]
        go_left_num = jnp.where((nan_bin >= 0) & (col == nan_bin), dl,
                                col <= t)
        cm = best.cat_mask[leaf]
        go_left = jnp.where(best.is_cat[leaf], cm[col], go_left_num)
        on_leaf = row_leaf == leaf
        # exact partition counts replace the search-time hessian-ratio
        # estimates (SplitInner update_cnt, serial_tree_learner.cpp:789)
        nl_ex = psum(jnp.sum((on_leaf & go_left & inbag).astype(dtype)))
        nr_ex = tree.leaf_count[leaf] - nl_ex
        row_leaf = jnp.where(on_leaf & ~go_left, R, row_leaf)

        # -- tree arrays update (Tree::Split, tree.h:63) --
        new_depth = tree.leaf_depth[leaf] + 1
        tree = _apply_split_to_tree(tree, best, leaf, R, ns, p,
                                    nl_ex, nr_ex)

        # -- histograms: scatter the smaller child, subtract for sibling --
        left_smaller = nl_ex <= nr_ex
        small_slot = jnp.where(left_smaller, leaf, R)
        small_mask = row_leaf == small_slot
        small_hist, comm_ef = hist_psum_ef(
            build_histogram(bins_T, grad, hess, row_weight, small_mask,
                            B, cfg.hist_method, cfg.hist_precision),
            comm_ef)
        parent_hist = hists[leaf]
        big_hist = subtract_histogram(parent_hist, small_hist)
        left_hist = jnp.where(left_smaller, small_hist, big_hist)
        right_hist = jnp.where(left_smaller, big_hist, small_hist)
        hists = hists.at[leaf].set(left_hist).at[R].set(right_hist)

        # -- child best splits --
        can_go_deeper = depth_ok(new_depth)
        rl = best_for(left_hist, best.left_sum_g[leaf],
                      best.left_sum_h[leaf], nl_ex)
        rr = best_for(right_hist, best.right_sum_g[leaf],
                      best.right_sum_h[leaf], nr_ex)
        best = best.store(leaf, rl, can_go_deeper)
        best = best.store(R, rr, can_go_deeper)

        return _GrowState(tree=tree, best=best, hists=hists,
                          row_leaf=row_leaf, num_splits=ns + 1,
                          comm_ef=comm_ef)

    def step(_, state: _GrowState) -> _GrowState:
        can = jnp.max(state.best.gain) > 0.0
        # tpulint: replicated-cond best.gain comes from psum-reduced histograms, so `can` is bit-identical on every device
        return lax.cond(can, do_split, lambda s: s, state)

    state = lax.fori_loop(0, L - 1, step, state)
    return state.tree, state.row_leaf


# ---------------------------------------------------------------------------
# Level grower: depth-wise growth, one fused step per frontier level
# ---------------------------------------------------------------------------

class _LevelState(NamedTuple):
    tree: TreeArrays
    best: _BestSplits
    hists: jnp.ndarray       # [L, F, B, 2]
    row_leaf: jnp.ndarray    # [n] i32
    num_splits: jnp.ndarray  # scalar i32
    level: jnp.ndarray       # scalar i32 — depth of the current frontier
    comm_ef: jnp.ndarray = ()  # error-feedback residual of the
                               # quantized histogram allreduce
                               # (hist_comm int8/int16): the scatter
                               # path reduces the whole [L, F, B, 2]
                               # level batch in one call, so its EF
                               # matches that shape; the kernel paths
                               # reduce one [F, B, 2] child at a time
                               # and carry a rolling [F, B, 2] buffer
                               # (the telescope bounds accumulated
                               # error regardless of leaf attribution
                               # — see _CompactState.comm_ef — at 1/L
                               # the HBM of a per-leaf buffer)


def _grow_level_impl(cfg: GrowConfig,
                     bins_T: jnp.ndarray,
                     grad: jnp.ndarray,
                     hess: jnp.ndarray,
                     row_weight: jnp.ndarray,
                     feature_mask: jnp.ndarray,
                     feat_num_bins: jnp.ndarray,
                     feat_nan_bin: jnp.ndarray,
                     monotone_constraints: Optional[jnp.ndarray] = None,
                     feat_is_cat: Optional[jnp.ndarray] = None):
    """Depth-wise (level-order) growth with the whole frontier fused
    into ONE loop iteration per level — the GPU tree-boosting pipeline
    shape (arXiv:1706.08359 §4, arXiv:2011.02022 "Booster") on the
    masked-state layout.

    Where the leaf-wise growers alternate argmax -> split -> re-score
    once per SPLIT (each hop round-tripping an ``[F, B, 2]`` histogram
    and an ``[n]`` leaf mask through HBM between separately-fused op
    islands), one level step here:

    1. elects every frontier leaf whose stored best gain is positive
       (gain-ranked when the remaining ``num_leaves`` budget can't take
       the whole frontier — the depth-wise analog of leaf-wise's
       global argmax),
    2. partitions the rows of ALL elected leaves,
    3. builds the level's child histograms in one batched pass over
       the rows of the (estimated-smaller) children only — one
       leaf-segmented scatter pass for ``hist_method="scatter"``, one
       masked kernel pass per small child for the MXU/Pallas methods —
       with every sibling recovered by subtraction, and
    4. scores best splits for the whole new frontier in ONE vmapped
       ``find_best_split`` batch over the ``[L, F, B, 2]`` cache.

    The whole tree is a single traced program (a ``lax.while_loop``
    with one iteration per level), so histogram -> best-split ->
    partition never crosses a dispatch boundary. With
    ``hist_method="scatter"`` the leaf-segmented pass makes total
    histogram work O(rows) per LEVEL instead of O(rows) per split;
    the mxu/pallas paths keep per-splitting-child masked passes (no
    segment axis in those kernels yet — see the note in step 3), so
    there the win is the fusion, sibling subtraction, and per-level
    batched scoring, not asymptotic histogram work. Depth-wise
    trees differ from leaf-wise trees whenever the leaf budget binds
    before the frontier is exhausted — that is the point of the mode
    (the reference's ``growing policy``), not a numerical gap; with a
    non-binding budget both policies split the identical leaf set.

    Supports the core feature set (numeric + categorical splits,
    bagging weights, max_depth, data-parallel ``axis_name`` psums);
    the flagship compact grower keeps everything else.
    """
    L = cfg.num_leaves
    B = cfg.num_bins
    F = bins_T.shape[0]
    n = bins_T.shape[1]
    dtype = grad.dtype
    p = cfg.split
    has_cat = feat_is_cat is not None
    hmethod = cfg.hist_method \
        if cfg.hist_method in ("scatter", "pallas") else "mxu"
    sharded = (cfg.axis_name is not None and cfg.parallel_mode == "data"
               and cfg.split_search == "sharded")

    def psum(x):
        return lax.psum(x, cfg.axis_name) if cfg.axis_name else x

    qm, use_ef, _gath_ef = comms.make_hist_psum_ef(
        cfg.axis_name, cfg.hist_comm)

    if sharded:
        Fl, Fsp, f_start, dev_idx, _rs_pad, hist_psum_ef, _ssl = \
            _make_sharded_search(cfg, F, qm, use_ef)
        FH = Fl

        def best_for(hist, sg, sh, sc):
            owned = (f_start + jnp.arange(Fl)) < F
            r = find_best_split(hist, sg, sh, sc,
                                _ssl(feat_num_bins, 1),
                                _ssl(feat_nan_bin, -1),
                                _ssl(feature_mask, False) & owned, p,
                                _ssl(monotone_constraints, 0),
                                _ssl(feat_is_cat, False))
            r = r._replace(feature=r.feature + f_start)
            return _combine_split_infos(r, cfg.axis_name)
    else:
        FH = F
        hist_psum_ef = _gath_ef

        def best_for(hist, sg, sh, sc):
            return find_best_split(hist, sg, sh, sc, feat_num_bins,
                                   feat_nan_bin, feature_mask, p,
                                   monotone_constraints, feat_is_cat)

    def depth_ok(d):
        if cfg.max_depth <= 0:
            return jnp.asarray(True)
        return d < cfg.max_depth

    # ---- root ----
    w = row_weight.astype(dtype)
    inbag = row_weight > 0
    gh = jnp.stack([grad * w, hess * w], axis=-1)          # [n, 2]
    total_g = psum(jnp.sum(gh[:, 0]))
    total_h = psum(jnp.sum(gh[:, 1]))
    total_c = psum(jnp.sum(inbag.astype(dtype)))
    all_rows = jnp.ones((n,), jnp.bool_)
    comm_ef0 = ()
    FE = Fsp if sharded else F        # EF feature width (scatter-padded)
    if use_ef:
        # EF shape follows the reduction the path issues (_LevelState)
        if hmethod == "scatter":
            comm_ef0 = jnp.zeros((L, FE, B, 2), dtype)
            root_hist, ef_slot0 = hist_psum_ef(
                build_histogram(bins_T, grad, hess, row_weight,
                                all_rows, B, hmethod,
                                cfg.hist_precision),
                comm_ef0[0])
            comm_ef0 = comm_ef0.at[0].set(ef_slot0)
        else:
            root_hist, comm_ef0 = hist_psum_ef(
                build_histogram(bins_T, grad, hess, row_weight,
                                all_rows, B, hmethod,
                                cfg.hist_precision),
                jnp.zeros((FE, B, 2), dtype))
    else:
        root_hist, _ = hist_psum_ef(
            build_histogram(bins_T, grad, hess, row_weight, all_rows,
                            B, hmethod, cfg.hist_precision), ())
    tree = _init_tree(L, B, dtype)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(
            leaf_output(total_g, total_h, p)),
        leaf_weight=tree.leaf_weight.at[0].set(total_h),
        leaf_count=tree.leaf_count.at[0].set(total_c),
    )
    best = _BestSplits.init(L, B, dtype)
    best = best.store(0, best_for(root_hist, total_g, total_h, total_c),
                      jnp.asarray(True))
    hists = jnp.zeros((L, FH, B, 2), dtype).at[0].set(root_hist)
    state = _LevelState(tree=tree, best=best, hists=hists,
                        row_leaf=jnp.zeros((n,), jnp.int32),
                        num_splits=jnp.asarray(0, jnp.int32),
                        level=jnp.asarray(0, jnp.int32),
                        comm_ef=comm_ef0)
    slots = jnp.arange(L, dtype=jnp.int32)

    def level_step(state: _LevelState) -> _LevelState:
        tree, best, hists, row_leaf, ns, level, comm_ef = state

        # -- 1. elect the level's splits, gain-ranked under the budget --
        active = slots < tree.num_leaves
        frontier = active & (tree.leaf_depth == level)
        cand = frontier & (best.gain > 0.0)
        capacity = jnp.asarray(L - 1, jnp.int32) - ns
        order = jnp.argsort(jnp.where(cand, -best.gain, jnp.inf))
        rank = jnp.argsort(order).astype(jnp.int32)
        splitting = cand & (rank < capacity)
        # node ids / right-child slots in slot order (creation order is
        # a labeling choice; the Tree convention only needs left child
        # = parent slot, right child = next free slot)
        ordn = jnp.cumsum(splitting.astype(jnp.int32)) - 1
        node_ids = ns + ordn
        r_slots = jnp.clip(ns + 1 + ordn, 0, L - 1)

        # -- 2. partition every elected leaf's rows (the level's single
        # DataPartition::Split sweep) + record the split in the tree --
        def split_one(l, carry):
            def do(carry):
                tree, best, row_leaf = carry
                R = r_slots[l]
                f = best.feature[l]
                t = best.threshold_bin[l]
                dl = best.default_left[l]
                col = lax.dynamic_index_in_dim(
                    bins_T, f, axis=0, keepdims=False).astype(jnp.int32)
                nanb = feat_nan_bin[f]
                gl = jnp.where((nanb >= 0) & (col == nanb), dl, col <= t)
                if has_cat:
                    gl = jnp.where(best.is_cat[l], best.cat_mask[l][col],
                                   gl)
                on_leaf = row_leaf == l
                nl_ex = psum(jnp.sum(
                    (on_leaf & gl & inbag).astype(dtype)))
                nr_ex = tree.leaf_count[l] - nl_ex
                row_leaf = jnp.where(on_leaf & ~gl, R, row_leaf)
                tree = _apply_split_to_tree(tree, best, l, R,
                                            node_ids[l], p, nl_ex, nr_ex)
                return tree, best, row_leaf

            # COLLECTIVE-IN-COND INVARIANT (data-parallel): the taken
            # branch psums the exact left count; `splitting` derives
            # only from globally-reduced histograms and the
            # deterministic election, so every device takes the same
            # branch sequence.
            # tpulint: replicated-cond splitting is a pure function of replicated state
            return lax.cond(splitting[l], do, lambda c: c, carry)

        tree, best, row_leaf = lax.fori_loop(
            0, L, split_one, (tree, best, row_leaf))

        # -- 3. the level's child histograms: one batched pass over the
        # (estimated-smaller) children's rows; siblings by subtraction --
        left_cnt = tree.leaf_count                       # [L] post-split
        right_cnt = tree.leaf_count[r_slots]
        left_small = left_cnt <= right_cnt
        small_slot = jnp.where(left_small, slots, r_slots)
        drop = jnp.asarray(L, jnp.int32)
        is_small = jnp.zeros((L,), jnp.bool_).at[
            jnp.where(splitting, small_slot, drop)].set(True, mode="drop")

        if hmethod == "scatter":
            # leaf-segmented scatter: ONE pass over all rows builds
            # every small child's histogram at once (segment id =
            # row_leaf, payload masked to small-child rows)
            seg = row_leaf
            m = is_small[seg].astype(dtype)[:, None]     # [n, 1]
            pay = gh * m

            def seg_body(carry, bins_f):
                idx = seg * B + bins_f.astype(jnp.int32)
                h = jnp.zeros((L * B, 2), dtype).at[idx].add(
                    pay, mode="drop")
                return carry, h

            _, h_f = lax.scan(seg_body, None, bins_T)    # [F, L*B, 2]
            small_hists, comm_ef = hist_psum_ef(
                h_f.reshape(F, L, B, 2).transpose(1, 0, 2, 3),
                comm_ef)
        else:
            # MXU / Pallas kernels have no segment axis: one masked
            # kernel pass per small child, cond-skipped for idle
            # slots. NB: each taken pass streams the FULL bin matrix
            # with the other leaves' payload zeroed, so per-level hist
            # cost on these paths is (#splitting children) x O(n*F) —
            # the fusion/sibling-subtraction/batched-scoring wins
            # apply, but the O(rows)-per-level property belongs to the
            # scatter segment pass above. A segment-aware kernel pass
            # (gather the small child's rows first) is the open
            # follow-up for the TPU paths.
            def hist_one(l, carry):
                def do(carry):
                    acc, ef = carry
                    mask = row_leaf == small_slot[l]
                    h = build_histogram(bins_T, grad, hess, row_weight,
                                        mask, B, hmethod,
                                        cfg.hist_precision)
                    # rolling EF: each child reduction consumes +
                    # refills the one [F, B, 2] buffer in sequence
                    # (ef passes through untouched at exact f32 wire)
                    h, ef = hist_psum_ef(h, ef)
                    acc = lax.dynamic_update_index_in_dim(
                        acc, h, small_slot[l], axis=0)
                    return acc, ef

                # tpulint: replicated-cond splitting is replicated (see the partition sweep)
                return lax.cond(splitting[l], do, lambda c: c, carry)

            small_hists, comm_ef = lax.fori_loop(
                0, L, hist_one,
                (jnp.zeros((L, FH, B, 2), dtype), comm_ef))

        def sib_one(l, hists):
            def do(hists):
                R = r_slots[l]
                parent = hists[l]
                small = lax.dynamic_index_in_dim(
                    small_hists, small_slot[l], keepdims=False)
                other = subtract_histogram(parent, small)
                lh = jnp.where(left_small[l], small, other)
                rh = jnp.where(left_small[l], other, small)
                return hists.at[l].set(lh).at[R].set(rh)

            return lax.cond(splitting[l], do, lambda h: h, hists)

        hists = lax.fori_loop(0, L, sib_one, hists)

        # -- 4. score the whole new frontier in one vmapped batch;
        # every other slot (including just-retired frontier leaves that
        # didn't make the election) drops to -inf and never splits --
        if sharded:
            # leaf (g, h) totals from the GLOBAL feature-0 histogram
            # row — owned by device 0 (f_start == 0), broadcast with
            # one tiny [L, B, 2] psum so every device sums the exact
            # bin sequence the gathered path sums (hists[:, 0] on a
            # chunk is a different feature per device: same total,
            # different addition order, hence different last-ulp bits)
            row0 = lax.psum(
                jnp.where(dev_idx == 0, hists[:, 0],
                          jnp.zeros_like(hists[:, 0])), cfg.axis_name)
            sums = row0.sum(axis=1)                      # [L, 2]
        else:
            sums = hists[:, 0].sum(axis=1)               # [L, 2]
        r = jax.vmap(best_for)(hists, sums[:, 0], sums[:, 1],
                               tree.leaf_count)
        is_child = (slots < tree.num_leaves) \
            & (tree.leaf_depth == level + 1)
        allowed = is_child & depth_ok(level + 1)
        best = _BestSplits(jnp.where(allowed, r.gain, NEG_INF),
                           *tuple(r)[1:])
        return _LevelState(tree=tree, best=best, hists=hists,
                           row_leaf=row_leaf,
                           num_splits=ns + jnp.sum(
                               splitting.astype(jnp.int32)),
                           level=level + 1, comm_ef=comm_ef)

    def can_grow(state: _LevelState):
        return (state.num_splits < L - 1) \
            & jnp.any(state.best.gain > 0.0)

    state = lax.while_loop(can_grow, level_step, state)
    return state.tree, state.row_leaf


# ---------------------------------------------------------------------------
# Compact grower: rows grouped by leaf (DataPartition re-imagined)
# ---------------------------------------------------------------------------

class _CompactState(NamedTuple):
    tree: TreeArrays
    best: _BestSplits
    hists: jnp.ndarray       # [L, F, B, 2] (sum_grad, sum_hess); when
                             # the histogram pool is active, [P, F, B,
                             # 2] slot storage instead (see pool)
    bins2: jnp.ndarray       # [2*(n+2K), NW] u32 — bin columns packed
                             # 4 (u8) / 2 (u16) per word; two ping-pong
                             # halves laid out flat; half b's window
                             # positions start at b*(n+2K) + K (K rows
                             # of pad on both sides of each half absorb
                             # full-chunk write tails)
    pay2: jnp.ndarray        # [2*(n+2K), 2] f32/i8 — (g, h) payload
    ord2: jnp.ndarray        # [2*(n+2K)] u32 — original row id, top
                             # bit = in-bag flag
    leaf_buf: jnp.ndarray    # [L] i32 — which half (0/1) holds each
                             # leaf's window; the left child stays in
                             # the parent's half, the right child moves
                             # to the other
    leaf_begin: jnp.ndarray  # [L] i32 (local raw offsets)
    leaf_count: jnp.ndarray  # [L] i32 (local raw counts)
    branch: jnp.ndarray      # [L, F] bool — features used on leaf's path
    num_splits: jnp.ndarray  # scalar i32
    cegb: tuple = ()         # (coupled_used [F], lazy_used [n,F],
                             #  lazy_nu [L,F]) when cfg.cegb
    mono: tuple = ()         # (leaf_min [L], leaf_max [L]) output-bound
                             # entries (BasicConstraint analogs) when
                             # monotone constraints are active; plus
                             # (anc [L, L-1] i8: 0=not under node,
                             # 1=left subtree, 2=right) for intermediate
    node_masks: tuple = ()   # ([L, F] bool,) — per-node sampled feature
                             # sets when cfg.bynode < 1
    pool: tuple = ()         # histogram pool bookkeeping when
                             # cfg.hist_pool_slots > 0:
                             # (leaf2slot [L] i32, -1 = evicted;
                             #  slot2leaf [P] i32, -1 = free;
                             #  lru [P] i32 last-use split tick)
    comm_ef: jnp.ndarray = ()  # [F, B, 2] error-feedback residual of
                             # the quantized histogram allreduce
                             # (hist_comm int8/int16; parallel/
                             # comms.py). One rolling buffer, not
                             # per-leaf slots: the EF telescope bounds
                             # accumulated error across the SEQUENCE
                             # of reductions regardless of leaf
                             # attribution, at 1/L the memory of the
                             # histogram cache it rides beside.
    pcache: jnp.ndarray = () # [F, B, 2] prefetched parent histogram of
                             # the NEXT split's leaf (non-pooled only).
                             # Reading the parent from the carry instead
                             # of `hists[leaf]` removes the only
                             # pre-update use of `hists` in the loop
                             # body, so XLA aliases the two child
                             # dynamic-update-slices in place instead of
                             # copying the whole [L, F, B, 2] buffer
                             # twice per split (measured: 2x 14.6 MB at
                             # Higgs, 2x 167 MB at Allstate width)


_IB_BIT = jnp.uint32(1 << 31)


def _leaf_values_at_positions(leaf_begin, leaf_count, values, n):
    """Spread per-leaf int ``values`` onto the [n] grouped positions
    (ranges partition [0, n)).

    At each active range start, scatter the DELTA between consecutive
    begin-sorted leaves' values (an L-sized scatter — cheap), then one
    [n] cumsum materializes the value per position. No [n]-sized
    gather: XLA:TPU serializes gathers per element (~8.6 ms per
    million rows measured, benchmarks/PROFILE.md), while scatter-of-L
    + cumsum is pure vector work."""
    active = leaf_count > 0
    keys = jnp.where(active, leaf_begin, n + 1)
    ls = jnp.argsort(keys)  # leaves ordered by begin, inactive last
    flag = active[ls].astype(jnp.int32)
    v = values[ls].astype(jnp.int32)
    prev = jnp.concatenate([jnp.zeros((1,), v.dtype), v[:-1]])
    delta = (v - prev) * flag
    marks = jnp.zeros((n,), jnp.int32).at[
        jnp.clip(leaf_begin[ls], 0, n - 1)].add(delta)
    return jnp.cumsum(marks)


def _leaf_of_positions(leaf_begin, leaf_count, n, L):
    """[n] leaf id per grouped position (see _leaf_values_at_positions)."""
    return _leaf_values_at_positions(leaf_begin, leaf_count,
                                     jnp.arange(L, dtype=jnp.int32), n)


def _row_leaf_from_order(order, leaf_of_pos):
    """Positional->row-id inversion as a variadic sort (a vectorized
    sorting network) rather than a scatter, which XLA:TPU serializes
    per element."""
    _, row_leaf = lax.sort((order, leaf_of_pos), num_keys=1)
    return row_leaf


# Variadic-sort width management for the chunk partition. The TPU
# backend's codegen for one variadic sort degrades SUPER-LINEARLY in
# operand count — measured on v5e (16K rows, compile seconds):
#   5-operand 7.0 | 9-op 15.0 | 13-op 25.0 | 17-op: minutes each |
#   168-op (Allstate EFB width, 667 bundle columns packed 4/word):
#   never returned within 2.5 h.
# (CPU-backend compile stays seconds at every width, so it is the TPU
# sort codegen, not XLA frontend passes.) Splitting into small-group
# sorts that each re-sort the SAME key is result-identical — the key
# (side*CK + lane) is unique per row, so every group sort computes the
# same permutation — at the cost of one extra key column of VMEM
# traffic per group. Narrow datasets (the Higgs shape: 8-9 payload
# operands) keep the proven single sort; wide ones pay ~12% more sort
# traffic to make compile linear in width (~15 s per 9-operand group,
# one-time with the persistent compilation cache).
_SORT_SINGLE_MAX = 12
_SORT_GROUP = 8


def _sort_by_key(key, cols):
    """Multi-operand sort by a UNIQUE key, group-split past
    _SORT_SINGLE_MAX payload operands (see note above). Returns
    (sorted_key, *sorted_cols) like lax.sort((key,) + cols).

    The wide path VMAPS one _SORT_GROUP-operand sort over the groups
    (same-dtype columns stacked [G, group, n], key broadcast) so the
    whole partition lowers to ONE batched sort HLO per dtype — compile
    cost is then CONSTANT in width, where even a Python loop of small
    sorts still compiled super-additively (F=256: 9 loop sorts ≈
    520 s; the batched form is the narrow program's ~15 s)."""
    cols = tuple(cols)
    if len(cols) <= _SORT_SINGLE_MAX:
        return lax.sort((key,) + cols, num_keys=1)
    by_dtype: dict = {}
    for i, c in enumerate(cols):
        by_dtype.setdefault(jnp.dtype(c.dtype), []).append(i)
    out = [None] * len(cols)
    key_sorted = None
    for dt, idxs in by_dtype.items():
        arrs = [cols[i] for i in idxs]
        if len(arrs) <= _SORT_GROUP:
            res = lax.sort((key,) + tuple(arrs), num_keys=1)
            if key_sorted is None:
                key_sorted = res[0]
            for j, i in enumerate(idxs):
                out[i] = res[1 + j]
            continue
        G = -(-len(arrs) // _SORT_GROUP)
        pad = G * _SORT_GROUP - len(arrs)
        stack = jnp.stack(arrs + [arrs[-1]] * pad)
        stack = stack.reshape(G, _SORT_GROUP, key.shape[0])
        keyb = jnp.broadcast_to(key, (G,) + key.shape)

        def _one(k, ws):
            r = lax.sort((k,) + tuple(ws[i] for i in
                                      range(_SORT_GROUP)), num_keys=1)
            return r[0], jnp.stack(r[1:])

        ks, ws = jax.vmap(_one)(keyb, stack)
        if key_sorted is None:
            key_sorted = ks[0]
        flat = ws.reshape(G * _SORT_GROUP, key.shape[0])
        for j, i in enumerate(idxs):
            out[i] = flat[j]
    return (key_sorted,) + tuple(out)


def _grow_compact_impl(cfg: GrowConfig,
                       bins_T: jnp.ndarray,
                       grad: jnp.ndarray,
                       hess: jnp.ndarray,
                       row_weight: jnp.ndarray,
                       feature_mask: jnp.ndarray,
                       feat_num_bins: jnp.ndarray,
                       feat_nan_bin: jnp.ndarray,
                       monotone_constraints: Optional[jnp.ndarray] = None,
                       feat_is_cat: Optional[jnp.ndarray] = None,
                       quant_key: Optional[jnp.ndarray] = None,
                       interaction_groups: Optional[jnp.ndarray] = None,
                       forced: Optional[tuple] = None,
                       cegb_arrays: Optional[tuple] = None,
                       node_key: Optional[jnp.ndarray] = None,
                       bundle_arrays: Optional[tuple] = None):
    """Leaf-wise growth with rows kept PHYSICALLY grouped by leaf.

    The reference's DataPartition (data_partition.hpp) + CUDA partition
    (cuda_data_partition.cu) analog, re-shaped for the TPU memory
    system: the bin rows, payload, in-bag flags and row ids are
    physically re-ordered on every split so each leaf occupies a
    contiguous range. All per-split work then streams CONTIGUOUS
    fixed-size chunks through ``lax.fori_loop`` bodies — no random
    gathers (TPU gathers serialize per element) and no ``lax.switch``
    over window sizes (XLA copies big conditional operands; while-loop
    carries alias in place). Histograms ride the MXU via the nibble
    decomposition (histogram.py). The partition is a SINGLE streaming
    pass per split: each chunk is sort-partitioned in registers and its
    left/right runs are appended (masked RMW) into the opposite buffer
    of a leading-axis ping-pong pair, with the child histogram
    accumulated from the same resident chunk — the CUDA bit-vector +
    prefix-sum + histogram kernels (cuda_data_partition.cu,
    cuda_histogram_constructor.cu) fused into one data movement."""
    L = cfg.num_leaves
    B = cfg.num_bins
    F = bins_T.shape[0]
    # ORIGINAL feature count: equals F except in bundled mode, where
    # bins_T holds bundle columns but SplitResult.feature, the
    # per-node masks (bynode / interaction) and branch sets all live
    # in original-feature space
    F_orig = feature_mask.shape[0]
    n = bins_T.shape[1]
    dtype = grad.dtype
    p = cfg.split
    K = cfg.chunk
    while K >= 2 * n:
        K //= 2
    K = max(K, 256)
    route = cfg.partition == "route"
    if route:
        K = 1 << (K.bit_length() - 1)   # butterfly needs a power of two
    # big-chunk bulk batching (see GrowConfig.big_chunk); the butterfly
    # router is K-sized, so route mode keeps the tail loop only
    BK = cfg.big_chunk
    while BK >= 2 * n:
        BK //= 2
    use_big = (not route) and BK > K
    PAD = BK if use_big else K   # write-tail padding absorbs one chunk

    fp = cfg.axis_name is not None and cfg.parallel_mode == "feature"
    vp = cfg.axis_name is not None and cfg.parallel_mode == "voting"
    # reduce-scatter sharded split search (docs/SHARDING.md): data-
    # parallel rows + feature-parallel search. Histograms built over
    # local rows are reduce-scattered so each device owns (and
    # searches) only its ceil(F/D) feature chunk of the globally
    # reduced histogram; the winning SplitInfo records are allreduced
    # (_fp_combine) — the reference DataParallelTreeLearner's
    # ReduceScatter + per-worker subset search.
    sharded = (cfg.axis_name is not None and cfg.parallel_mode == "data"
               and cfg.split_search == "sharded")

    def psum(x):
        """Row-sharded reduction; identity in feature-parallel mode
        (rows are replicated there)."""
        if cfg.axis_name is None or fp:
            return x
        return lax.psum(x, cfg.axis_name)

    # histogram wire format (parallel/comms.py): quantized exchange
    # only where a histogram reduction actually happens — data-parallel
    # float histograms. Quantized-gradient training reduces EXACT int32
    # histograms (psum stays exact and is already 4x-dense payload-
    # wise), so it keeps the plain path.
    qm, use_ef, _psum_ef = comms.make_hist_psum_ef(
        cfg.axis_name, cfg.hist_comm,
        quantize=not (fp or vp or cfg.quantized))

    def hist_psum(x):
        """Histogram reduction: identity for feature-parallel (every
        device holds all rows, so a local histogram is already global)
        AND for voting (the cache stays local; the reduction happens
        per-search over elected features only); a reduce-scatter to
        this device's owned chunk under the sharded split search.
        (``_rs_pad``/``Fsp`` are assigned below, before any call —
        closures bind late.)"""
        if cfg.axis_name is None or fp or vp:
            return x
        if sharded:
            return comms.hist_reduce_scatter(_rs_pad(x), cfg.axis_name,
                                             qm)
        return comms.hist_allreduce(x, cfg.axis_name, qm)

    def hist_psum_ef(x, ef):
        """EF-threaded histogram reduction: the hot per-split child
        reduction (and the root) consume + refill the error-feedback
        residual carried in _CompactState.comm_ef so accumulated
        quantization error telescopes instead of compounding
        (comms.hist_allreduce docstring). ``ef`` passes through
        untouched when the wire is exact f32 — and no reduction at all
        happens under feature/voting parallelism (a local histogram is
        already the one the search consumes). Sharded search: the
        reduction is the EF-threaded reduce-scatter, and the result is
        this device's chunk."""
        if fp or vp:
            return x, ef
        if sharded:
            return _sh_psum_ef(x, ef)
        return _psum_ef(x, ef)

    has_mono = monotone_constraints is not None
    # "advanced" (monotone precise mode) keeps intermediate's every-split
    # re-search machinery and replaces the scalar output bounds with
    # per-(feature, threshold) bounds computed from leaf boxes
    advanced = has_mono and cfg.monotone_method == "advanced"
    intermediate = has_mono and cfg.monotone_method in ("intermediate",
                                                        "advanced")
    use_bynode = cfg.bynode < 1.0 and node_key is not None
    smoothing = p.path_smooth > 0.0

    bundled = cfg.bundled and bundle_arrays is not None
    if bundled:
        # Bundling sits BELOW the learner layer exactly like the
        # reference's FeatureGroup (feature_group.h:26 is a dataset
        # property every learner consumes), and composes with the FULL
        # feature matrix (round 5) — nothing is gated:
        # - all three parallel modes: data (rows shard, bundle hists
        #   psum), feature (bundle columns window/own per device),
        #   voting (ballot/election/exchange in bundle-column space);
        # - interaction/bynode/CEGB: [F_orig]-space inputs (masks,
        #   branch sets, penalties) consumed per member
        #   (feature_mask[member_ix] / gain_penalty[member_ix]);
        # - every monotone method: basic/intermediate use scalar
        #   per-leaf bounds; advanced's [F_orig, B] per-threshold
        #   bound arrays gather into candidate space through the
        #   position->member map;
        # - path smoothing, forced splits (member-range reconstruction
        #   in forced_result), categorical members.
        (bundle_of, offset_of, bundle_is_direct, member_at, tloc_at,
         end_at, bundle_nanpos, bundle_nan_at) = bundle_arrays

    def _fp_combine(r: SplitResult) -> SplitResult:
        """SyncUpGlobalBestSplit over disjoint per-device feature
        subsets (module-level :func:`_combine_split_infos`)."""
        return _combine_split_infos(r, cfg.axis_name)

    def best_for(hist, sg, sh, sc, extra_mask=None, gain_penalty=None,
                 parent_output=None, depth=None, bounds=None):
        fmask = feature_mask if extra_mask is None \
            else feature_mask & extra_mask
        if sharded:
            # sharded split search: the reduce-scattered chunk covers
            # features [f_start, f_start + Fl); slice every per-feature
            # input to the window, search locally, globalize the
            # winner's feature id and allreduce the SplitInfo
            # (SyncUpGlobalBestSplit) — the same search sharding the
            # feature-parallel mode uses, fed by scattered rows;
            # ``ssl`` is _make_sharded_search's owned_slice
            owned = (f_start + jnp.arange(Fl)) < F
            if bounds is not None and len(bounds) == 6:
                # advanced monotone: slice the per-[F, B] bound arrays
                # to this device's feature window
                def bsl(b):
                    if Fsp > F:
                        b = jnp.concatenate(
                            [b, jnp.zeros((Fsp - F, B), b.dtype)])
                    return lax.dynamic_slice(b, (f_start, 0), (Fl, B))

                bounds = tuple(bsl(b) for b in bounds[:4]) + bounds[4:]
            r = find_best_split(hist, sg, sh, sc,
                                ssl(feat_num_bins, 1),
                                ssl(feat_nan_bin, -1),
                                ssl(fmask, False) & owned, p,
                                ssl(monotone_constraints, 0),
                                ssl(feat_is_cat, False),
                                ssl(gain_penalty, 0.0),
                                parent_output, depth, bounds)
            r = r._replace(feature=r.feature + f_start)
            return _fp_combine(r)
        if bundled and not vp:
            b_member, b_tloc = member_at, tloc_at
            b_end, b_nanpos, b_nan = end_at, bundle_nanpos, bundle_nan_at
            col_mask = None
            if fp:
                # feature-parallel over BUNDLE columns: slice the
                # [G, B] metadata to this device's word-aligned column
                # window, rebase the flat (g*B + p) indices into
                # window space, and mask candidates to OWNED columns.
                # fmask / feat_is_cat / feat_num_bins / gain_penalty
                # stay GLOBAL — the search indexes them by ORIGINAL
                # member feature id, which needs no rebasing (so the
                # winning SplitInfo's feature is already global too).
                def gsl(v, fill):
                    if Fp > F:
                        pad = jnp.full((Fp - F, v.shape[1]), fill,
                                       v.dtype)
                        v = jnp.concatenate([v, pad])
                    return lax.dynamic_slice(
                        v, (f_start, 0), (Fl, v.shape[1]))

                b_member = gsl(member_at, -1)
                b_tloc = gsl(tloc_at, 0)
                b_end = jnp.where(b_member >= 0,
                                  gsl(end_at, 0) - f_start * B, 0)
                np_s = gsl(bundle_nanpos, -1)
                b_nanpos = jnp.where(np_s >= 0, np_s - f_start * B, -1)
                b_nan = gsl(bundle_nan_at, False)
                col_mask = _fp_owner(f_start + jnp.arange(Fl)) == dev_idx
            r = find_best_split_bundled(hist, sg, sh, sc, b_member,
                                        b_tloc, b_end,
                                        bundle_is_direct,
                                        b_nanpos, b_nan,
                                        fmask, p, feat_is_cat,
                                        feat_num_bins, gain_penalty,
                                        col_mask,
                                        monotone_constraints=
                                        monotone_constraints,
                                        parent_output=parent_output,
                                        leaf_depth=depth, bounds=bounds)
            return _fp_combine(r) if fp else r
        if fp:
            # disjoint feature ownership over word-aligned windows: the
            # device's histogram covers ONLY its own Fl columns (built
            # that way, _local_hist_rows), its search runs on the
            # matching slice of the per-feature metadata masked to the
            # features it OWNS (windows of tail devices overlap when D
            # does not divide NW; _fp_owner keeps the cover exact), and
            # the winning SplitInfo is allreduced with the feature id
            # globalized (FeatureParallelTreeLearner,
            # feature_parallel_tree_learner.cpp:71 +
            # SyncUpGlobalBestSplit)
            def lsl(v, fill):
                """Device's Fl-slice of a per-feature vector (padded to
                the packed width so the window stays in range)."""
                if v is None:
                    return None
                if Fp > F:
                    pad = jnp.full((Fp - F,), fill, v.dtype)
                    v = jnp.concatenate([v, pad])
                return lax.dynamic_slice(v, (f_start,), (Fl,))

            owned = _fp_owner(f_start + jnp.arange(Fl)) == dev_idx
            if bounds is not None and len(bounds) == 6:
                # advanced monotone: slice the per-[F, B] bound arrays
                # to this device's feature window (pad rows are masked
                # off by `owned` anyway)
                def bsl(b):
                    if Fp > F:
                        b = jnp.concatenate(
                            [b, jnp.zeros((Fp - F, B), b.dtype)])
                    return lax.dynamic_slice(b, (f_start, 0), (Fl, B))

                bounds = tuple(bsl(b) for b in bounds[:4]) + bounds[4:]
            r = find_best_split(hist, sg, sh, sc,
                                lsl(feat_num_bins, 1),
                                lsl(feat_nan_bin, -1),
                                lsl(fmask, False) & owned, p,
                                lsl(monotone_constraints, 0),
                                lsl(feat_is_cat, False),
                                lsl(gain_penalty, 0.0),
                                parent_output, depth, bounds)
            r = r._replace(feature=r.feature + f_start)
            return _fp_combine(r)
        if vp:
            # PV-Tree (VotingParallelTreeLearner, voting_parallel_tree_
            # learner.cpp:364): local top-k ballot over per-feature best
            # gains -> global election of 2k features -> reduce ONLY the
            # elected features' histograms -> one global search over
            # them. The exchanged payload is the static-shape [k2, B, C]
            # selection (k2 = min(2k, F)) — O(2k*B) bytes on the wire
            # per search like the reference's CopyLocalHistogram buffer
            # (parallel_tree_learner.h:153-161), not the full
            # O(F*B) a data-parallel reduction pays.
            ax = cfg.axis_name
            # the ballot judges LOCAL histograms, so it must use local
            # leaf sums and shard-scaled data constraints (the
            # reference's local_config_, voting_parallel_tree_learner
            # .cpp:61-63)
            ndev = _axis_size(ax)
            lh_tot = jnp.sum(hist[0], axis=0)   # feature 0 sees all rows
            sg_loc, sh_loc = lh_tot[0], lh_tot[1]
            sc_loc = jnp.round(sc * sh_loc / jnp.maximum(sh, 1e-15))
            p_loc = p._replace(
                min_data_in_leaf=p.min_data_in_leaf / ndev,
                min_sum_hessian_in_leaf=p.min_sum_hessian_in_leaf / ndev)
            if bundled:
                # ballots/election/exchange run in bundle-COLUMN space
                # (F here is the bundle-column count); the bundled
                # search supplies per-column gains and the final
                # search masks to elected columns
                _, fgains = find_best_split_bundled(
                    hist, sg_loc, sh_loc, sc_loc, member_at, tloc_at,
                    end_at, bundle_is_direct, bundle_nanpos,
                    bundle_nan_at, fmask, p_loc, feat_is_cat,
                    feat_num_bins, gain_penalty,
                    return_col_gains=True,
                    monotone_constraints=monotone_constraints,
                    parent_output=parent_output,
                    leaf_depth=depth, bounds=bounds)
            else:
                _, fgains = find_best_split(
                    hist, sg_loc, sh_loc, sc_loc, feat_num_bins,
                    feat_nan_bin, fmask, p_loc,
                    monotone_constraints, feat_is_cat, gain_penalty,
                    parent_output, depth, bounds,
                    return_feature_gains=True)
            k = min(cfg.voting_top_k, F)
            kth = jnp.sort(fgains)[F - k]
            ballot = jnp.isfinite(fgains) & (fgains >= kth)
            votes = lax.psum(ballot.astype(jnp.int32), ax)
            k2 = min(2 * cfg.voting_top_k, F)
            # deterministic election, identical on every device: vote
            # count, ties to the lower feature id (GlobalVoting,
            # voting_parallel_tree_learner.cpp:205)
            score = votes * F + (F - 1 - jnp.arange(F))
            idx = lax.top_k(score, k2)[1]                 # [k2]
            E = idx[:, None] == jnp.arange(F)[None, :]    # [k2, F] bool
            elected = jnp.any(E, axis=0)                  # [F]
            # select elected rows (masked reduce, exact for int32 too),
            # psum the SMALL [k2, B, C] buffer, scatter back
            sel = jnp.sum(jnp.where(E[:, :, None, None], hist[None], 0),
                          axis=1)                         # [k2, B, C]
            # the elected-buffer exchange is the voting mode's one
            # histogram reduction — quantize it under hist_comm too.
            # Stateless + the vmap-safe shared-scale strategy: this
            # site runs under jax.vmap (both children's searches fuse
            # into one batched collective), where all_to_all has no
            # batching story; int32 hists (quantized grads) fall back
            # to the exact psum inside.
            gsel = comms.hist_allreduce(sel, ax, cfg.hist_comm,
                                        strategy="psum")
            ghist = jnp.sum(jnp.where(E[:, :, None, None], gsel[:, None],
                                      0), axis=0)         # [F, B, C]
            if bundled:
                return find_best_split_bundled(
                    ghist, sg, sh, sc, member_at, tloc_at, end_at,
                    bundle_is_direct, bundle_nanpos, bundle_nan_at,
                    fmask, p, feat_is_cat, feat_num_bins,
                    gain_penalty, col_mask=elected,
                    monotone_constraints=monotone_constraints,
                    parent_output=parent_output,
                    leaf_depth=depth, bounds=bounds)
            return find_best_split(ghist, sg, sh, sc, feat_num_bins,
                                   feat_nan_bin, fmask & elected, p,
                                   monotone_constraints, feat_is_cat,
                                   gain_penalty, parent_output, depth,
                                   bounds)
        return find_best_split(hist, sg, sh, sc, feat_num_bins,
                               feat_nan_bin, fmask, p,
                               monotone_constraints, feat_is_cat,
                               gain_penalty, parent_output, depth,
                               bounds)

    def node_feature_mask(idx):
        """Per-node feature subset (ColSampler::GetByNode): rank a fresh
        uniform draw over the tree's usable features, keep
        max(1, round(bynode * |usable|)). The reference samples with its
        sequential Random stream; this keyed-fold stream is an equally
        deterministic redesign."""
        u = jax.random.uniform(jax.random.fold_in(node_key, idx),
                               (F_orig,))
        u = jnp.where(feature_mask, u, jnp.inf)
        rank = jnp.argsort(jnp.argsort(u))
        total = jnp.sum(feature_mask.astype(jnp.int32))
        k = jnp.maximum(jnp.round(total * cfg.bynode).astype(jnp.int32),
                        jnp.minimum(1, total))
        return (rank < k) & feature_mask

    def allowed_features(branch_set):
        """Features usable at a node whose path used ``branch_set``
        (ColSampler::GetByNode, col_sampler.hpp:205): union of the
        constraint groups that contain the whole branch set."""
        contains = ~jnp.any(branch_set[None, :] & ~interaction_groups,
                            axis=1)                       # [G]
        return jnp.any(interaction_groups & contains[:, None], axis=0)

    def advanced_bounds(box_lo, box_hi, values, num_leaves_, bl, bh):
        """Per-(feature, threshold) monotone output bounds for the
        children of a split of the leaf whose bin-space box is
        [bl, bh) — AdvancedLeafConstraints ("monotone precise mode",
        monotone_constraints.hpp:858) re-expressed as box algebra.

        The reference walks up the leaf's path and recursively down
        each monotone ancestor's opposing subtree, collecting leaf
        outputs into per-threshold segment lists
        (GoDownToFindConstrainingLeaves / UpdateConstraints). The
        constraining set it visits is exactly: leaves whose boxes
        OVERLAP the searched leaf's box in every feature except one
        monotone feature m, where they are disjoint-ordered (the LCA
        split on m is the monotone ancestor; categorical splits leave
        both children's boxes equal to the parent's, reproducing the
        reference's keep-going-both-ways treatment of categorical
        nodes). So, tensorized over the CURRENT leaves:
        - route m != j (t-refined only through the child's j-interval
          overlap): ordered-in-m leaves bound the child wherever their
          j-interval overlaps the child's;
        - route m == j: leaves ordered in j against the CHILD interval
          ([lo_j, t+1) left / [t+1, hi_j) right) bound it directly.
        Upper bounds come from increasing-feature-above or
        decreasing-feature-below leaves (min of their outputs); lower
        bounds are symmetric (max).

        Returns the 6-tuple consumed by split_bounds_lrc: per-[F, B]
        (lmin_l, lmax_l, lmin_r, lmax_r) plus scalar fallbacks
        (smin, smax) for categorical candidates (a categorical split
        leaves both children's boxes equal to the parent's, so only the
        t-independent route applies)."""
        inf_ = jnp.asarray(jnp.inf, dtype)
        act = jnp.arange(L) < num_leaves_                  # [L]
        ov = (box_lo < bh[None, :]) & (box_hi > bl[None, :])   # [L, F]
        nonov = (~ov).astype(jnp.int32)
        cnt_no = jnp.sum(nonov, axis=1)                    # [L]
        only_m = (cnt_no[:, None] - nonov) == 0            # [L, F]
        above = box_lo >= bh[None, :]                      # [L, F]
        below = box_hi <= bl[None, :]
        mc_i = monotone_constraints.astype(jnp.int32)
        inc = (mc_i > 0)[None, :]
        dec = (mc_i < 0)[None, :]
        up_any = jnp.any(only_m & ((inc & above) | (dec & below)),
                         axis=1) & act                     # [L]
        dn_any = jnp.any(only_m & ((inc & below) | (dec & above)),
                         axis=1) & act
        t = jnp.arange(B)[None, None, :]                   # thresholds
        # overlap of each leaf's j-interval with the child's:
        # left child [bl_j, t+1), right child [t+1, bh_j)
        ovl_l = (box_lo[:, :, None] <= t) \
            & (box_hi[:, :, None] > bl[None, :, None])     # [L, F, B]
        ovl_r = (box_lo[:, :, None] < bh[None, :, None]) \
            & (box_hi[:, :, None] > t + 1)
        # route m == j: ordering against the child's own j-interval
        oj = (only_m & act[:, None])[:, :, None]           # [L, F, 1]
        above_l = box_lo[:, :, None] >= t + 1              # [L, F, B]
        below_r = box_hi[:, :, None] <= t + 1
        up_l2 = oj & ((inc[:, :, None] & above_l)
                      | (dec & below)[:, :, None])
        dn_l2 = oj & ((inc & below)[:, :, None]
                      | (dec[:, :, None] & above_l))
        up_r2 = oj & ((inc & above)[:, :, None]
                      | (dec[:, :, None] & below_r))
        dn_r2 = oj & ((inc[:, :, None] & below_r)
                      | (dec & above)[:, :, None])
        v = values[:, None, None]

        def vmin(mask):
            return jnp.min(jnp.where(mask, v, inf_), axis=0)

        def vmax(mask):
            return jnp.max(jnp.where(mask, v, -inf_), axis=0)

        u_any = up_any[:, None, None]
        d_any = dn_any[:, None, None]
        lmax_l = vmin((u_any & ovl_l) | up_l2)             # [F, B]
        lmin_l = vmax((d_any & ovl_l) | dn_l2)
        lmax_r = vmin((u_any & ovl_r) | up_r2)
        lmin_r = vmax((d_any & ovl_r) | dn_r2)
        smax = jnp.min(jnp.where(up_any, values, inf_))
        smin = jnp.max(jnp.where(dn_any, values, -inf_))
        return (lmin_l, lmax_l, lmin_r, lmax_r, smin, smax)

    cegb = cfg.cegb
    cegb_lazy = cfg.cegb_lazy and cegb
    cegb_coupled = cfg.cegb_coupled and cegb
    if cegb:
        pen_coupled, pen_lazy, coupled_used0, lazy_used0 = cegb_arrays
        if cegb_lazy and lazy_used0 is None:
            raise ValueError("cegb_lazy requires a lazy_used matrix")

        # Penalties count in-bag rows only: the reference's
        # num_data_in_leaf / GetIndexOnLeaf walk the bagged partition
        # (cost_effective_gradient_boosting.hpp:81,128-137), which holds
        # no out-of-bag rows.
        def cegb_penalty(cnt, coupled_used, lazy_nu_leaf):
            """DeltaGain (cost_effective_gradient_boosting.hpp:81-97):
            tradeoff * (penalty_split*n + coupled-first-use + lazy)."""
            pen = jnp.full((F_orig,), cfg.cegb_tradeoff
                           * cfg.cegb_split * 1.0, dtype) \
                * cnt.astype(dtype)
            pen = pen + jnp.where(coupled_used, 0.0,
                                  cfg.cegb_tradeoff * pen_coupled)
            if cegb_lazy:
                pen = pen + cfg.cegb_tradeoff * pen_lazy * lazy_nu_leaf
            return pen

    # row-id / in-bag tracking (see GrowConfig.track_rows); consumers
    # force it on regardless of the flag
    track = cfg.track_rows or cegb or bundled
    bins_rm = bins_T.T                      # [n, F] row-major for gathers
    w = row_weight.astype(dtype)
    inbag = row_weight > 0
    gw2 = jnp.stack([grad * w, hess * w], axis=-1)  # [n, 2]
    # scatter and pallas pass through; anything else ("onehot" legacy
    # spelling included) maps to the MXU nibble kernel
    hmethod = cfg.hist_method \
        if cfg.hist_method in ("scatter", "pallas") else "mxu"

    quant = cfg.quantized
    if quant:
        # GradientDiscretizer analog (gradient_discretizer.hpp:35):
        # per-tree scales, stochastic rounding, int8 payload.
        def pmax(x):
            return lax.pmax(x, cfg.axis_name) if cfg.axis_name else x

        half = max(1, cfg.quant_bins // 2)
        gs = jnp.maximum(pmax(jnp.max(jnp.abs(gw2[:, 0]))), 1e-30) / half
        hs = jnp.maximum(pmax(jnp.max(gw2[:, 1])), 1e-30) \
            / max(1, cfg.quant_bins)
        if cfg.stochastic and quant_key is not None:
            k = quant_key
            if cfg.axis_name and not fp:
                # feature-parallel replicates rows: every device must
                # round identically
                k = jax.random.fold_in(k, lax.axis_index(cfg.axis_name))
            u = jax.random.uniform(k, (n, 2), dtype)
        else:
            u = jnp.full((n, 2), 0.5, dtype)
        gq = jnp.clip(jnp.floor(gw2[:, 0] / gs + u[:, 0]), -127, 127)
        hq = jnp.clip(jnp.floor(gw2[:, 1] / hs + u[:, 1]), 0, 127)
        gw2_q = jnp.stack([gq, hq], axis=-1).astype(jnp.int8)
        scale2 = jnp.stack([gs, hs])

    def hist_f(h):
        """int32 histogram -> float stats for split search."""
        if quant:
            return h.astype(dtype) * scale2[None, None, :]
        return h

    # The bin matrix and payload are PHYSICALLY re-ordered on every split
    # so that each leaf's rows are contiguous. All ordered arrays carry K
    # rows of padding so chunk slices/updates never clamp at the end;
    # garbage lands in (and is read from) the pad region and is masked.
    C = 2

    def window_chunks(cnt):
        return lax.div(cnt + (K - 1), jnp.asarray(K, cnt.dtype))

    has_cat = feat_is_cat is not None
    bin_dt = bins_T.dtype
    # bin columns per u32 word of the streamed copy: 8 when every
    # feature fits 4 bits (the reference's 4-bit DenseBin,
    # src/io/dense_bin.hpp is_4bit path), else 4 (u8) / 2 (u16)
    nibble_bins = bin_dt == jnp.uint8 and B <= 16
    pack_w = 8 if nibble_bins else (4 if bin_dt == jnp.uint8 else 2)
    Fp = -(-F // pack_w) * pack_w
    NW = Fp // pack_w                             # u32 words per row

    # feature-parallel work sharding: each device owns a word-aligned
    # block of NWl packed words (Fl = NWl*pack_w feature columns) and
    # builds histograms ONLY for that block — F/D of the MXU hist work,
    # the TPU analog of each rank's ConstructHistograms over its own
    # subset (feature_parallel_tree_learner.cpp:71). Rows stay
    # replicated (like the reference: full data on every worker, so the
    # partition needs no collective); only the winning SplitInfo is
    # allreduced (_fp_combine). When D does not divide NW the tail
    # devices' windows CLAMP to the last NWl words (so the hist slice
    # never reads out of range) and ownership inside the overlapping
    # windows is made exact by ``_fp_owned``: feature f belongs to
    # device min(f // Fl, D-1) only — each device's search mask keeps
    # just its owned columns, so hist rows and metadata stay aligned.
    if fp:
        D_fp = _axis_size(cfg.axis_name)          # static under shard_map
        dev_idx = lax.axis_index(cfg.axis_name)   # traced
        NWl = -(-NW // D_fp)
        Fl = NWl * pack_w
        # this device's window start, in words / in feature columns
        w_start = jnp.minimum(dev_idx * NWl, NW - NWl)
        f_start = w_start * pack_w

        def _fp_owner(f):
            return jnp.minimum(f // Fl, D_fp - 1)
    elif sharded:
        # sharded-search ownership windows: DISJOINT equal chunks over
        # a D*ceil(F/D)-padded feature axis (psum_scatter needs equal
        # chunks; unlike fp's word-aligned clamped windows there is no
        # packing constraint — the hist is built at full width and
        # scattered, so a plain ceil split keeps ownership exact)
        Fl, Fsp, f_start, dev_idx, _rs_pad, _sh_psum_ef, ssl = \
            _make_sharded_search(cfg, F, qm, use_ef)
    else:
        Fl = F
    FB = Fl if fp else F       # hist BUILD feature count (local pass)
    FH = Fl if (fp or sharded) else F   # hist CACHE/search feature count

    def chunk_goleft(col, f, t, dl, isc, cm):
        """go-left decision for one chunk given the SPLIT column's bins
        ``col`` [CK] (extracted from the packed words by _extract_col)
        — all vector ops (a cm[col] table gather would serialize per
        element on TPU)."""
        if bundled:
            # the split references an ORIGINAL feature; resolve its
            # bundle member range (ops/bundling.py layout)
            off = offset_of[f]
            nb = feat_num_bins[f]
            nanb = feat_nan_bin[f]
            left_direct = jnp.where((nanb >= 0) & (col == nanb), dl,
                                    col <= t)
            # member bins > t occupy positions [off + t, off + nb - 2];
            # a NaN member's NaN bin maps to its LAST position, which
            # routes by the learned default direction instead
            is_nanrow = (nanb >= 0) & (col == off + nanb - 1)
            right_multi = (col >= off + t) & (col <= off + nb - 2) \
                & ~is_nanrow
            left_multi = jnp.where(is_nanrow, dl, ~right_multi)
            gl_b = jnp.where(bundle_is_direct[f], left_direct,
                             left_multi)
            if has_cat:
                # categorical membership split: recover the member's
                # LOCAL bin (direct columns store it verbatim; multi
                # members map bins 1..nb-1 to [off, off+nb-2], rows
                # outside the range sit at the member's bin 0), then
                # route by the [B] membership mask like the plain path
                local = jnp.where(
                    bundle_is_direct[f], col,
                    jnp.where((col >= off) & (col <= off + nb - 2),
                              col - off + 1, 0))
                cm_col = jnp.any(
                    (local[:, None] == jnp.arange(B)[None, :])
                    & cm[None, :], axis=1)
                gl_b = jnp.where(isc, cm_col, gl_b)
            return gl_b
        nanb = feat_nan_bin[f]
        gl = jnp.where((nanb >= 0) & (col == nanb), dl, col <= t)
        if has_cat:
            cm_col = jnp.any((col[:, None] == jnp.arange(B)[None, :])
                             & cm[None, :], axis=1)
            gl = jnp.where(isc, cm_col, gl)
        return gl

    def _unpack_words(w32):
        """[S, nw] u32 words -> [S, nw*pack_w] native-width bins."""
        S, nw = w32.shape
        if nibble_bins:
            nibs = [((w32 >> (4 * k)) & 0xF).astype(bin_dt)
                    for k in range(8)]                    # 8 x [S, nw]
            u = jnp.stack(nibs, axis=2)                   # [S, nw, 8]
        else:
            u = lax.bitcast_convert_type(w32, bin_dt)     # [S, nw, pack_w]
        return u.reshape(S, nw * pack_w)

    def _extract_col(blk_w, c):
        """ONE bin column [CK] from the packed [CK, NW] words.

        The partition body needs only the SPLIT column to route rows;
        unpacking the whole [CK, F] block for it cost O(F) VPU work
        per chunk — invisible at Higgs width (F=28) but ~6% of a wide
        EFB iteration (1044 bundle columns). c is traced (the split's
        column index)."""
        w = c // pack_w
        wordcol = lax.dynamic_slice(blk_w, (jnp.int32(0), w),
                                    (blk_w.shape[0], 1))[:, 0]
        bits = 32 // pack_w
        shift = (c % pack_w) * bits
        return ((wordcol >> shift.astype(jnp.uint32))
                & jnp.uint32((1 << bits) - 1)).astype(jnp.int32)

    def _local_hist_rows(w32, pos0, CK):
        """The rows fed to the MXU histogram: all F features, or — in
        feature-parallel — ONLY this device's NWl-word block (F/D of
        the one-hot/matmul work)."""
        if fp:
            if wide_part:
                blk = lax.dynamic_slice(_bins_slice(w32, pos0, CK),
                                        (jnp.int32(0), w_start),
                                        (CK, NWl))
            else:
                blk = lax.dynamic_slice(
                    w32, (pos0, jnp.asarray(w_start, pos0.dtype)),
                    (CK, NWl))
            return _unpack_words(blk)                     # [CK, Fl]
        blk = _bins_slice(w32, pos0, CK)
        return _unpack_words(blk)[:, :F]

    def rot(a, s):
        """a shifted so that out[j] = a[j - (CK - s)] — dynamic roll via
        self-concatenation (vectorized; no per-element gather)."""
        if a.ndim == 2:
            return lax.dynamic_slice(jnp.concatenate([a, a], axis=0),
                                     (s, jnp.zeros((), s.dtype)),
                                     (a.shape[0], a.shape[1]))
        return lax.dynamic_slice(jnp.concatenate([a, a]), (s,),
                                 (a.shape[0],))

    # bf16 payload storage on TPU: the streamed (g, h) pairs only ever
    # feed the MXU histogram, whose single-pass default truncates f32
    # inputs to bf16 anyway — so storing them as bf16 is numerically
    # IDENTICAL on TPU while halving payload bytes in every chunk
    # slice/sort/write (and packing the pair into one u32 sort column).
    # Exact float sums (root totals, leaf renewal) read the original
    # f32 gw2, never pay2. CPU keeps f32: its matmuls don't truncate.
    bf16_pay = (not quant) and jax.default_backend() == "tpu" \
        and cfg.hist_method != "scatter" and cfg.hist_precision == "default"
    if quant:
        # int8 (g, h) pairs ride the sort as ONE u16 column
        def _pack_pay(blk_p):
            return (lax.bitcast_convert_type(
                blk_p.reshape(blk_p.shape[0], 1, 2), jnp.uint16)[:, 0],)

        def _unpack_pay(cols):
            return lax.bitcast_convert_type(
                cols[0][:, None], jnp.int8).reshape(cols[0].shape[0], 2)
        NPAY = 1
    elif bf16_pay:
        # bf16 (g, h) pairs ride the sort as ONE u32 column
        def _pack_pay(blk_p):
            return (lax.bitcast_convert_type(
                blk_p.reshape(blk_p.shape[0], 1, 2), jnp.uint32)[:, 0],)

        def _unpack_pay(cols):
            return lax.bitcast_convert_type(
                cols[0][:, None],
                jnp.bfloat16).reshape(cols[0].shape[0], 2)
        NPAY = 1
    else:
        def _pack_pay(blk_p):
            return (blk_p[:, 0], blk_p[:, 1])

        def _unpack_pay(cols):
            return jnp.stack(cols, axis=1)
        NPAY = 2

    SEG = n + 2 * PAD  # rows per ping-pong half (PAD rows both sides)

    # WIDE partition mode (round 5): at EFB width the per-chunk
    # partition permutes rows with a (key, iota) sort + row GATHERS of
    # the packed words instead of carrying all NW word columns through
    # the variadic sort (which costs O(NW) traffic per bitonic stage —
    # 0.77 ms/chunk at NW=167 vs 35 us at Higgs width). The gather and
    # its DUS writeback want the ROW-MAJOR layout, while the histogram
    # one-hot wants rows minor; storing bins2 FLAT (1-D) pins the
    # row-major linearization globally, so XLA relayouts only
    # chunk-sized hist inputs instead of transposing the whole
    # multi-hundred-MB ping-pong buffer twice per chunk (measured
    # in-situ: the whole-buffer copies were 1.7 s/tree at 131K x 665).
    # (the 2**31 guard: flat offsets are int32 products pos*NW — past
    # ~2^31 elements they would wrap and silently corrupt the
    # partition, so such shapes — which exceed v5e HBM anyway — keep
    # the group-sort path)
    wide_part = (not route) \
        and NW + NPAY + (1 if track else 0) > _SORT_SINGLE_MAX \
        and 2 * (n + 2 * PAD) * NW < 2 ** 31

    def _bins_slice(w32, pos0, CK):
        """[CK, NW] chunk of the packed words at row offset pos0
        (the ndim check keeps the root-hist pass, which reads the
        pre-pad 2-D [n, NW] block, on the plain slice)."""
        if wide_part and w32.ndim == 1:
            return lax.dynamic_slice(
                w32, (pos0 * NW,), (CK * NW,)).reshape(CK, NW)
        return lax.dynamic_slice(
            w32, (pos0, jnp.zeros((), pos0.dtype)), (CK, NW))

    def _bins_write(arr, off, block, m):
        """Masked RMW of a [CK, NW] block at row offset ``off``
        (the wide mode addresses the flat buffer)."""
        if not wide_part:
            z = jnp.zeros((), off.dtype)
            cur = lax.dynamic_slice(arr, (off, z), block.shape)
            out = jnp.where(m[:, None], block, cur)
            return lax.dynamic_update_slice(arr, out, (off, z))
        CK = block.shape[0]
        cur = lax.dynamic_slice(
            arr, (off * NW,), (CK * NW,)).reshape(CK, NW)
        out = jnp.where(m[:, None], block, cur)
        return lax.dynamic_update_slice(arr, out.reshape(-1),
                                        (off * NW,))

    def chunk_hist(bins2, pay2, pos0, limit, CK):
        """Histogram of one CK-row chunk at dynamic row offset ``pos0``:
        slice the packed bin words + payload, mask the window tail
        (rows past ``limit`` relative to the chunk start), accumulate
        on the MXU. Shared by the post-partition child pass and the
        pool-miss window recompute."""
        blk_b = _local_hist_rows(bins2, pos0, CK)
        blk_p = lax.dynamic_slice(
            pay2, (pos0, jnp.zeros((), pos0.dtype)), (CK, C))
        valid = jnp.arange(CK) < jnp.clip(limit, 0, CK)
        hp = blk_p * valid[:, None].astype(blk_p.dtype)
        if quant:
            return hist_from_rows_int(blk_b, hp, B, hmethod), valid
        return hist_from_rows(blk_b, hp, B, hmethod,
                              cfg.hist_precision), valid

    def part_apply(bins2, pay2, ord2, lazy_used, src, start, cnt,
                   f, t, dl, isc, cm, est_left_small, comm_ef):
        """Stable two-way window compaction + child histogram in ONE
        streaming pass over the leaf's window.

        The two ping-pong halves live in one flat array; the half
        choice is plain row-offset arithmetic (``b*SEG + PAD``), so every
        access is the dynamic-row-slice pattern XLA:TPU aliases well —
        no conditional branches, no dynamic major-axis indexing.

        Each K-row chunk is read from the source half, partitioned
        in-registers by a variadic sort on a (side, position) key — the
        TPU's one fast data-movement primitive (gathers/scatters
        serialize per element) — then:
        - LEFT runs append forward IN PLACE in the source half (safely
          behind the read frontier: l_off + K <= (c+1)K);
        - RIGHT runs pack backward from ``start + cnt`` in the OTHER
          half (dead space: window ranges partition [0, n) and only one
          half per range is live).
        Both writes are masked read-modify-writes: a full-chunk block's
        garbage lanes would otherwise spill across the window edge into
        a NEIGHBORING leaf's live rows whenever cnt is not K-aligned.
        The left child therefore stays in the parent's half and the
        right child lands in the opposite half (leaf_buf tracks this).
        The histogram of the (estimated-)smaller child is then built
        in a SECOND streaming pass over that child's now-contiguous
        rows only — the sibling follows by subtraction — so histogram
        work scales with Sum(min-child) instead of Sum(parent) rows.
        The CUDA analog is GenDataToLeftBitVector + prefix-sum
        compaction (cuda_data_partition.cu) followed by
        ConstructHistogramForLeaf on the smaller leaf
        (cuda_histogram_constructor.cu).

        ``est_left_small`` picks the histogrammed side from the stored
        SplitInfo's count estimates — decided before streaming (the
        reference re-checks with exact counts, but exact counts only
        exist after the pass; estimates are deterministic and
        replicated across shards).
        """
        src_base = src * SEG + PAD + start
        dst_base = (1 - src) * SEG + PAD + start
        zero = jnp.asarray(0, jnp.int32)
        acc0 = jnp.zeros((FB, B, C), jnp.int32 if quant else dtype)

        def write(arr, off, block, m):
            """Masked RMW block write at a dynamic row offset."""
            if arr.ndim == 2:
                z = jnp.zeros((), off.dtype)
                cur = lax.dynamic_slice(arr, (off, z),
                                        (block.shape[0], arr.shape[1]))
                out = jnp.where(m[:, None], block, cur)
                return lax.dynamic_update_slice(arr, out, (off, z))
            cur = lax.dynamic_slice(arr, (off,), (block.shape[0],))
            out = jnp.where(m, block, cur)
            return lax.dynamic_update_slice(arr, out, (off,))

        def make_body(CK, base_off):
            """Partition-chunk body over CK rows starting at window
            offset ``base_off + c*CK`` (base_off may be traced)."""
            iota_c = jnp.arange(CK)

            def body(c, carry):
                (bins2, pay2, ord2, lazy_used,
                 l_off, r_off, nlib, nib) = carry
                off = base_off + c * CK
                pos0 = src_base + off
                blk_w = _bins_slice(bins2, pos0, CK)
                blk_p = lax.dynamic_slice(
                    pay2, (pos0, jnp.zeros((), pos0.dtype)), (CK, C))
                split_col = _extract_col(blk_w,
                                         bundle_of[f] if bundled else f)
                gl = chunk_goleft(split_col, f, t, dl, isc, cm)
                valid = iota_c < jnp.clip(cnt - off, 0, CK)
                vl = valid & gl
                l_c = jnp.sum(vl, dtype=jnp.int32)
                r_c = jnp.sum(valid & ~gl, dtype=jnp.int32)
                if track:
                    blk_o = lax.dynamic_slice(ord2, (pos0,), (CK,))
                    blk_i = (blk_o & _IB_BIT) != 0
                    nlib += jnp.sum(vl & blk_i, dtype=jnp.int32)
                    nib += jnp.sum(valid & blk_i, dtype=jnp.int32)
                else:
                    # every row is in-bag: the partition counts ARE the
                    # in-bag counts
                    nlib += l_c
                    nib += l_c + r_c
                if cegb_lazy:
                    rows = (blk_o & ~_IB_BIT).astype(jnp.int32)
                    # the split acquires feature f for every in-bag row
                    # in the leaf (UpdateLeafBestSplits' InsertBitset
                    # loop over the bagged partition)
                    lazy_used = lazy_used.at[rows, f].max(valid & blk_i)
                # the sort/route move the PACKED u32 word columns;
                # children are written back packed too — bins only ever
                # unpack transiently for goleft/histogram (bins2 stays
                # u32-tiled, avoiding the u8 (4,1) sub-byte layout tax
                # on every slice/RMW write)
                cols = tuple(blk_w[:, i] for i in range(NW)) \
                    + _pack_pay(blk_p) + ((blk_o,) if track else ())
                ml = iota_c < l_c
                o_r = dst_base + cnt - r_off - CK
                mr = iota_c >= (CK - r_c)
                if route:
                    # two butterfly concentrations: lefts compact to the
                    # block FRONT, rights directly to the block END (no
                    # rotate needed — the offset is part of the route).
                    lops = route_concentrate(cols, vl, jnp.int32(0))
                    rops = route_concentrate(cols, valid & ~gl, CK - r_c)
                    lb = jnp.stack(lops[:NW], axis=1)
                    lp = _unpack_pay(lops[NW:NW + NPAY])
                    rb = jnp.stack(rops[:NW], axis=1)
                    rp = _unpack_pay(rops[NW:NW + NPAY])
                    if track:
                        lo = lops[NW + NPAY]
                        ro = rops[NW + NPAY]
                elif wide_part:
                    # WIDE partition (round 5): a variadic sort moves
                    # every operand through every bitonic stage, so at
                    # EFB width (Allstate: NW=167 word columns) the sort
                    # alone measured 0.77 ms/chunk vs 35 us at Higgs
                    # width. Instead sort ONLY (key, iota) to get the
                    # permutation, then apply it with row GATHERS of the
                    # packed [CK, ~NW] word block — one pass of traffic
                    # instead of O(log^2 CK) stage passes. Rows here are
                    # NW*4-byte contiguous runs, wide enough to gather
                    # at vector width (at Higgs width rows are ~28 B and
                    # the payload-carrying sort wins — hence the gate).
                    side = jnp.where(vl, 0, jnp.where(valid, 1, 2))
                    key = side * CK + iota_c
                    perm = lax.sort((key, iota_c.astype(jnp.int32)),
                                    num_keys=1)[1]
                    s_r = lax.rem(l_c + r_c, jnp.asarray(CK, jnp.int32))
                    perm_r = rot(perm, s_r)
                    # fold the payload (and ord) into the word block so
                    # ONE row gather moves everything; the (g, h) pair
                    # is already a single u32 word on the TPU paths
                    # (bf16 pair / quant int8 pair), and the f32 CPU
                    # pair bitcasts to two u32 words
                    if quant:
                        pw = _pack_pay(blk_p)[0].astype(jnp.uint32)[:, None]
                    elif bf16_pay:
                        pw = _pack_pay(blk_p)[0][:, None]
                    else:
                        pw = None                  # separate-gather pay
                    parts = [blk_w] + ([pw] if pw is not None else [])
                    if track:
                        parts.append(blk_o[:, None])
                    blk_all = parts[0] if len(parts) == 1 \
                        else jnp.concatenate(parts, axis=1)
                    la = jnp.take(blk_all, perm, axis=0)
                    ra = jnp.take(blk_all, perm_r, axis=0)
                    PW = 0 if pw is None else 1
                    lb, rb = la[:, :NW], ra[:, :NW]
                    if quant:
                        lp = _unpack_pay((la[:, NW].astype(jnp.uint16),))
                        rp = _unpack_pay((ra[:, NW].astype(jnp.uint16),))
                    elif bf16_pay:
                        lp = _unpack_pay((la[:, NW],))
                        rp = _unpack_pay((ra[:, NW],))
                    else:
                        lp = jnp.take(blk_p, perm, axis=0)
                        rp = jnp.take(blk_p, perm_r, axis=0)
                    if track:
                        lo = la[:, NW + PW]
                        ro = ra[:, NW + PW]
                else:
                    # stable in-chunk partition: variadic sort moving
                    # all row data by a (side, position) key
                    side = jnp.where(vl, 0, jnp.where(valid, 1, 2))
                    key = side * CK + iota_c
                    ops = _sort_by_key(key, cols)
                    lb = jnp.stack(ops[1:1 + NW], axis=1)
                    lp = _unpack_pay(ops[1 + NW:1 + NW + NPAY])
                    # rights [l_c, l_c+r_c) rotated to the block END
                    s_r = lax.rem(l_c + r_c, jnp.asarray(CK, jnp.int32))
                    rb, rp = rot(lb, s_r), rot(lp, s_r)
                    if track:
                        lo = ops[1 + NW + NPAY]
                        ro = rot(lo, s_r)
                # lefts [0, l_c) forward in place; rights packed
                # backward from the window end in the other half
                bins2 = _bins_write(bins2, src_base + l_off, lb, ml)
                pay2 = write(pay2, src_base + l_off, lp, ml)
                bins2 = _bins_write(bins2, o_r, rb, mr)
                pay2 = write(pay2, o_r, rp, mr)
                if track:
                    ord2 = write(ord2, src_base + l_off, lo, ml)
                    ord2 = write(ord2, o_r, ro, mr)
                return (bins2, pay2, ord2, lazy_used,
                        l_off + l_c, r_off + r_c, nlib, nib)

            return body

        # the window's bulk streams in BK-row bodies (8x fewer
        # serialized op chains than K-row bodies — the round-3 verdict's
        # "kill the chunk serialization" item); the remainder streams in
        # K-row bodies so small leaves never pay a BK-sized op
        carry = (bins2, pay2, ord2, lazy_used, zero, zero, zero, zero)
        if use_big:
            nb_big = lax.div(cnt, jnp.asarray(BK, jnp.int32))
            carry = lax.fori_loop(0, nb_big, make_body(BK, zero), carry)
            tail_off = nb_big * BK
        else:
            tail_off = zero
        carry = lax.fori_loop(0, window_chunks(cnt - tail_off),
                              make_body(K, tail_off), carry)
        (bins2, pay2, ord2, lazy_used, n_left, _,
         n_left_ib, n_ib) = carry

        # -- second streaming pass: histogram of the estimated-smaller
        # child over its NOW-CONTIGUOUS rows only. Histogram work drops
        # from Sum(parent) to Sum(min-child) rows per tree (~0.42x
        # empirically), which the one extra read of the small side's
        # rows does not come close to cancelling. The side is chosen by
        # the search-time count ESTIMATES (deterministic, replicated
        # across shards), like the reference's smaller-leaf choice
        # (serial_tree_learner.cpp:473-520); the sibling follows by
        # subtraction. --
        est_start = jnp.where(est_left_small, start, start + n_left)
        est_cnt = jnp.where(est_left_small, n_left, cnt - n_left)
        est_half = jnp.where(est_left_small, src, 1 - src)
        est_base = est_half * SEG + PAD + est_start

        def make_hist_body(CK, base_off):
            def hist_body(c, carry):
                hist, nu = carry
                off = base_off + c * CK
                h, valid = chunk_hist(bins2, pay2, est_base + off,
                                      est_cnt - off, CK)
                hist = hist + h
                if cegb_lazy:
                    blk_o = lax.dynamic_slice(ord2, (est_base + off,),
                                              (CK,))
                    blk_i = (blk_o & _IB_BIT) != 0
                    rows = (blk_o & ~_IB_BIT).astype(jnp.int32)
                    used_rows = jnp.take(lazy_used, rows,
                                         axis=0)          # [CK, F]
                    # lazy_used already acquired feature f during the
                    # partition pass, so column f over-counts as "used"
                    # — harmless: the caller zeroes est_nu[f] regardless
                    # (do_split's est_nu_z)
                    nu = nu + jnp.sum(
                        (valid & blk_i)[:, None] & ~used_rows,
                        axis=0).astype(dtype)
                return hist, nu

            return hist_body

        carry_h = (acc0, jnp.zeros((F_orig,), dtype))
        if use_big:
            nh_big = lax.div(est_cnt, jnp.asarray(BK, jnp.int32))
            carry_h = lax.fori_loop(0, nh_big, make_hist_body(BK, zero),
                                    carry_h)
            h_off = nh_big * BK
        else:
            h_off = zero
        est_hist, est_nu = lax.fori_loop(
            0, window_chunks(est_cnt - h_off), make_hist_body(K, h_off),
            carry_h)

        # exact global in-bag child counts replace the search-time
        # hessian-ratio estimates (SplitInner update_cnt,
        # serial_tree_learner.cpp:789-791)
        nl_ex = psum(n_left_ib).astype(dtype)
        nr_ex = psum(n_ib - n_left_ib).astype(dtype)
        est_hist, comm_ef = hist_psum_ef(est_hist, comm_ef)
        return (bins2, pay2, ord2, lazy_used, n_left, nl_ex, nr_ex,
                est_hist, est_nu, comm_ef)

    def window_hist(bins2, pay2, src, start, cnt):
        """Recompute one leaf's full histogram from its contiguous row
        window — the pool-miss path (the reference recomputes evicted
        histograms the same way, HistogramPool::Get on a miss).
        Out-of-bag rows carry zero payload (w folded into pay2), so no
        extra masking beyond the window tail is needed."""
        src_base = src * SEG + PAD + start
        acc0 = jnp.zeros((FB, B, C), jnp.int32 if quant else dtype)

        def make_body(CK, base_off):
            def body(c, acc):
                off = base_off + c * CK
                return acc + chunk_hist(bins2, pay2, src_base + off,
                                        cnt - off, CK)[0]

            return body

        if use_big:
            nb = lax.div(cnt, jnp.asarray(BK, jnp.int32))
            acc0 = lax.fori_loop(0, nb, make_body(BK, 0), acc0)
            b_off = nb * BK
        else:
            b_off = jnp.asarray(0, jnp.int32)
        return hist_psum(lax.fori_loop(0, window_chunks(cnt - b_off),
                                       make_body(K, b_off), acc0))

    # the streamed copy of the bin matrix lives PACKED: u32 words of
    # pack_w bin columns each (u8 arrays carry a (4,1) sub-byte tiling
    # that taxes every dynamic slice / masked RMW ~2-4x)
    bins_pk = bins_rm if Fp == F \
        else jnp.pad(bins_rm, ((0, 0), (0, Fp - F)))
    if nibble_bins:
        nib = bins_pk.reshape(n, NW, 8).astype(jnp.uint32)
        bins_pk = sum(nib[:, :, k] << (4 * k) for k in range(8))
    else:
        bins_pk = lax.bitcast_convert_type(
            bins_pk.reshape(n, NW, pack_w), jnp.uint32)    # [n, NW]

    # ---- root ----
    # feature-parallel devices histogram only their own feature block
    root_rows = _local_hist_rows(bins_pk, jnp.asarray(0, jnp.int32),
                                 n) if fp else bins_rm
    total_c = psum(jnp.sum(inbag.astype(dtype)))
    comm_ef0 = jnp.zeros((Fsp if sharded else FB, B, C),
                         dtype) if use_ef else ()
    if quant:
        root_hist = hist_psum(hist_from_rows_int(root_rows, gw2_q, B,
                                                 hmethod))
        if sharded:
            # the GLOBAL feature-0 row lives on device 0's chunk only;
            # broadcast it (exact int32 psum of one contributor) and
            # sum the same bin sequence the gathered path sums
            row0 = lax.psum(
                jnp.where(dev_idx == 0, root_hist[0],
                          jnp.zeros_like(root_hist[0])), cfg.axis_name)
            sums = (row0.astype(dtype) * scale2[None, :]).sum(axis=0)
        else:
            sums = hist_f(root_hist)[0].sum(axis=0)  # row hits feature 0
        if vp:
            # voting keeps the cache local; the root tuple is global
            sums = lax.psum(sums, cfg.axis_name)
        total_g, total_h = sums[0], sums[1]
    else:
        total_g = psum(jnp.sum(gw2[:, 0]))
        total_h = psum(jnp.sum(gw2[:, 1]))
        root_hist, comm_ef0 = hist_psum_ef(
            hist_from_rows(root_rows, gw2, B, hmethod,
                           cfg.hist_precision), comm_ef0)

    tree = _init_tree(L, B, dtype)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(leaf_output(total_g, total_h, p)),
        leaf_weight=tree.leaf_weight.at[0].set(total_h),
        leaf_count=tree.leaf_count.at[0].set(total_c),
    )
    best = _BestSplits.init(L, B, dtype)
    root_mask = None if interaction_groups is None \
        else allowed_features(jnp.zeros((F_orig,), jnp.bool_))
    cegb_state = ()
    root_pen = None
    if cegb:
        coupled_used = coupled_used0
        if cegb_lazy:
            lazy_used = lazy_used0
            root_nu = jnp.sum(~lazy_used & inbag[:, None],
                              axis=0).astype(dtype)               # [F]
        else:
            lazy_used = jnp.zeros((1, 1), jnp.bool_)
            root_nu = jnp.zeros((F_orig,), dtype)
        lazy_nu = jnp.zeros((L, F_orig), dtype).at[0].set(root_nu)
        cegb_state = (coupled_used, lazy_used, lazy_nu)
        root_pen = cegb_penalty(total_c, coupled_used, root_nu)
    mono_state = ()
    root_bounds = None
    if has_mono:
        leaf_min0 = jnp.full((L,), -jnp.inf, dtype)
        leaf_max0 = jnp.full((L,), jnp.inf, dtype)
        mono_state = (leaf_min0, leaf_max0)
        if intermediate:
            mono_state = mono_state + (jnp.zeros((L, L - 1), jnp.int8),)
        root_bounds = (leaf_min0[0], leaf_max0[0])
        if advanced:
            # per-leaf bin-space boxes [lo, hi) per feature; the root
            # covers everything
            box_lo0 = jnp.zeros((L, F_orig), jnp.int32)
            box_hi0 = jnp.full((L, F_orig), B, jnp.int32)
            mono_state = mono_state + (box_lo0, box_hi0)
            root_bounds = advanced_bounds(box_lo0, box_hi0,
                                          tree.leaf_value,
                                          tree.num_leaves,
                                          box_lo0[0], box_hi0[0])
    nmask_state = ()
    root_node_mask = None
    if use_bynode:
        root_node_mask = node_feature_mask(0)
        nmask_state = (jnp.zeros((L, F_orig), jnp.bool_)
                       .at[0].set(root_node_mask),)
        root_mask = root_node_mask if root_mask is None \
            else root_mask & root_node_mask
    # the root's "parent output" is its own unsmoothed output
    # (GetParentOutput, serial_tree_learner.cpp:1005-1012)
    root_out = tree.leaf_value[0]
    best = best.store(0, best_for(hist_f(root_hist), total_g, total_h,
                                  total_c, root_mask, root_pen,
                                  root_out, jnp.asarray(0, jnp.int32),
                                  root_bounds),
                      jnp.asarray(True))
    # histogram cache: full per-leaf [L, F, B, 2], or a bounded slot
    # pool [PS, F, B, 2] with recompute-on-miss (HistogramPool analog,
    # feature_histogram.hpp; budget from histogram_pool_size)
    pooled = 0 < cfg.hist_pool_slots < L
    PS = cfg.hist_pool_slots if pooled else L
    hists = jnp.zeros((PS, FH, B, 2),
                      jnp.int32 if quant else dtype).at[0].set(root_hist)
    pool_state = ()
    if pooled:
        pool_state = (
            jnp.full((L,), -1, jnp.int32).at[0].set(0),   # leaf2slot
            jnp.full((PS,), -1, jnp.int32).at[0].set(0),  # slot2leaf
            jnp.zeros((PS,), jnp.int32),                  # lru tick
        )
    pay0 = gw2_q if quant \
        else (gw2.astype(jnp.bfloat16) if bf16_pay else gw2)
    ord0 = (jnp.arange(n, dtype=jnp.uint32)
            | jnp.where(inbag, _IB_BIT, jnp.uint32(0))) if track \
        else jnp.zeros((2,), jnp.uint32)
    bins2_0 = jnp.pad(bins_pk, ((PAD, PAD + SEG), (0, 0)))
    state = _CompactState(
        tree=tree, best=best, hists=hists,
        # the wide partition stores the words FLAT (see wide_part)
        bins2=bins2_0.reshape(-1) if wide_part else bins2_0,
        pay2=jnp.pad(pay0, ((PAD, PAD + SEG), (0, 0))),
        ord2=jnp.pad(ord0, (PAD, PAD + SEG)) if track else ord0,
        leaf_buf=jnp.zeros((L,), jnp.int32),
        leaf_begin=jnp.zeros((L,), jnp.int32),
        leaf_count=jnp.zeros((L,), jnp.int32).at[0].set(n),
        branch=jnp.zeros((L, F_orig), jnp.bool_),
        num_splits=jnp.asarray(0, jnp.int32),
        cegb=cegb_state, mono=mono_state, node_masks=nmask_state,
        pool=pool_state, comm_ef=comm_ef0,
        # the first split's leaf is 0 (only the root has a stored
        # candidate), so the prefetched parent is the root histogram
        pcache=(jnp.zeros((1,), hists.dtype) if pooled else root_hist))

    def depth_ok(d):
        if cfg.max_depth <= 0:
            return jnp.asarray(True)
        return d < cfg.max_depth

    def _leaf_mask_pen_bounds(tree, branch, cegb_st, mono_st, nmask_st,
                              l):
        """One leaf's (mask, penalty, bounds) under the CURRENT state —
        the per-leaf body shared by the pooled re-search."""
        mask_l = None
        if interaction_groups is not None:
            mask_l = allowed_features(branch[l])
        if use_bynode:
            nm = nmask_st[0][l]
            mask_l = nm if mask_l is None else mask_l & nm
        pen_l = None
        if cegb:
            coupled_used, _, lazy_nu = cegb_st
            pen_l = cegb_penalty(tree.leaf_count[l], coupled_used,
                                 lazy_nu[l])
        bounds_l = None
        if has_mono:
            if advanced:
                bounds_l = advanced_bounds(mono_st[3], mono_st[4],
                                           tree.leaf_value,
                                           tree.num_leaves,
                                           mono_st[3][l], mono_st[4][l])
            else:
                bounds_l = (mono_st[0][l], mono_st[1][l])
        return mask_l, pen_l, bounds_l

    def _research_leafwise(tree, hists, branch, cegb_st, mono_st,
                           nmask_st, pool_ctx) -> _BestSplits:
        """Leaf-walking re-search (lax.fori_loop over leaf slots).

        Used (a) under the histogram pool: each leaf's histogram comes
        from its slot or a window recompute — the reference pool's
        recompute-on-miss (HistogramPool::Get, feature_histogram.hpp)
        feeding the stored-candidate patching consumers; and (b) under
        advanced monotone even unpooled: the per-leaf bound tensors are
        [L, F, B] each, so vmapping them over leaves would materialize
        O(L^2*F*B) intermediates (~GBs at 255 leaves x 28 x 256) where
        this walk peaks at O(L*F*B) like the reference's per-leaf
        traversal."""

        def body(l, best):
            if pool_ctx is not None:
                bins2, pay2, leaf_buf, lbegin, lcount, leaf2slot = \
                    pool_ctx
                slot = leaf2slot[l]
                # COLLECTIVE-IN-COND INVARIANT (data-parallel): the
                # miss branch's window_hist ends in hist_psum, i.e. a
                # collective inside lax.cond. This is deadlock-free
                # iff the predicate is bit-identical on every device —
                # which holds because leaf2slot is pool state derived
                # ONLY from the replicated tree/argmax sequence (the
                # hit branch's cached hists are likewise already
                # globally reduced). Never feed device-dependent
                # inputs into the pool bookkeeping: a divergent
                # predicate would hang all hosts, not raise. TPL010
                # holds this invariant at review time.
                # tpulint: replicated-cond leaf2slot is pool state derived only from the replicated tree/argmax sequence
                hist = lax.cond(
                    slot >= 0,
                    lambda: lax.dynamic_index_in_dim(
                        hists, jnp.maximum(slot, 0), keepdims=False),
                    lambda: window_hist(bins2, pay2, leaf_buf[l],
                                        lbegin[l], lcount[l]))
            else:
                hist = lax.dynamic_index_in_dim(hists, l,
                                                keepdims=False)
            hf = hist_f(hist)
            if sharded:
                # leaf totals from the GLOBAL feature-0 row (device
                # 0's chunk), broadcast with one [B, 2] psum so every
                # device sums the bit-identical bin sequence the
                # gathered path sums (hf[0] on a chunk is a different
                # feature per device — same total, different last-ulp)
                row0 = lax.psum(
                    jnp.where(dev_idx == 0, hf[0], jnp.zeros_like(hf[0])),
                    cfg.axis_name)
                sums = row0.sum(axis=0)
            else:
                sums = hf[0].sum(axis=0)
            mask_l, pen_l, bounds_l = _leaf_mask_pen_bounds(
                tree, branch, cegb_st, mono_st, nmask_st, l)
            r = best_for(hf, sums[0], sums[1], tree.leaf_count[l],
                         mask_l, pen_l, tree.leaf_value[l],
                         tree.leaf_depth[l], bounds_l)
            active = (l < tree.num_leaves) \
                & depth_ok(tree.leaf_depth[l])
            return best.store(l, r, active)

        return lax.fori_loop(0, L, body, _BestSplits.init(L, B, dtype))

    def research_all(tree, hists, branch, cegb_st, mono_st, nmask_st,
                     pool_ctx=None) -> _BestSplits:
        """Re-search every leaf's best split from the cached histograms
        under the CURRENT penalties / interaction masks / monotone
        bounds. Exact replacement for the reference's stored-candidate
        patching (CEGB UpdateLeafBestSplits,
        cost_effective_gradient_boosting.hpp:100-124; intermediate
        monotone leaves_to_update, monotone_constraints.hpp:560+)."""
        if pooled or advanced:
            return _research_leafwise(tree, hists, branch, cegb_st,
                                      mono_st, nmask_st, pool_ctx)
        hf = jax.vmap(hist_f)(hists)              # [L, F, B, 2]
        if sharded:
            # global feature-0 rows via device 0 (see _research_leafwise)
            row0 = lax.psum(
                jnp.where(dev_idx == 0, hf[:, 0],
                          jnp.zeros_like(hf[:, 0])), cfg.axis_name)
            sums = row0.sum(axis=1)               # [L, 2]
        else:
            sums = hf[:, 0].sum(axis=1)           # [L, 2]
        in_axes = [0, 0, 0, 0]
        args = [hf, sums[:, 0], sums[:, 1], tree.leaf_count]
        masks = None if interaction_groups is None \
            else jax.vmap(allowed_features)(branch)
        if use_bynode:
            masks = nmask_st[0] if masks is None else masks & nmask_st[0]
        in_axes.append(None if masks is None else 0)
        args.append(masks)
        if cegb:
            coupled_used, _, lazy_nu = cegb_st
            pens = jax.vmap(cegb_penalty,
                            in_axes=(0, None, 0))(tree.leaf_count,
                                                  coupled_used, lazy_nu)
        else:
            pens = None
        in_axes.append(None if pens is None else 0)
        args.append(pens)
        # per-leaf parent_output / depth / bounds
        in_axes.extend([0, 0])
        args.extend([tree.leaf_value, tree.leaf_depth])
        if has_mono:
            # (advanced never reaches here — it re-searches leaf-wise)
            in_axes.append((0, 0))
            args.append((mono_st[0], mono_st[1]))
        else:
            in_axes.append(None)
            args.append(None)
        r = jax.vmap(best_for, in_axes=tuple(in_axes))(*args)
        if cfg.max_depth > 0:
            allowed = tree.leaf_depth < cfg.max_depth
        else:
            allowed = jnp.ones((L,), jnp.bool_)
        # SplitResult and _BestSplits share field order; re-wrap so the
        # while-loop carry keeps a consistent pytree type
        return _BestSplits(jnp.where(allowed, r.gain, NEG_INF),
                           *tuple(r)[1:])

    def do_split(state: _CompactState,
                 leaf_override=None) -> _CompactState:
        (tree, best, hists, bins2, pay2, ord2, leaf_buf,
         lbegin, lcount, branch, ns, cegb_st, mono_st, nmask_st,
         pool_st, comm_ef, pcache) = state
        leaf = jnp.argmax(best.gain).astype(jnp.int32) \
            if leaf_override is None else leaf_override
        R = ns + 1
        start = lbegin[leaf]
        cnt = lcount[leaf]
        src = leaf_buf[leaf]
        f_split = best.feature[leaf]
        t_bin = best.threshold_bin[leaf]
        dl = best.default_left[leaf]
        isc = best.is_cat[leaf]
        cm = best.cat_mask[leaf]
        est_left_small = best.left_count[leaf] <= best.right_count[leaf]
        lazy_arr = cegb_st[1] if cegb else jnp.zeros((1, 1), jnp.bool_)

        # parent histogram BEFORE the partition reorders the window:
        # from the cache (full mode / pool hit) or recomputed from the
        # still-contiguous parent window (pool miss)
        if pooled:
            leaf2slot, slot2leaf, lru = pool_st
            slot_l = leaf2slot[leaf]
            # tpulint: replicated-cond leaf2slot derives only from the replicated tree/argmax sequence (see _research_leafwise)
            parent_hist = lax.cond(
                slot_l >= 0,
                lambda: lax.dynamic_index_in_dim(
                    hists, jnp.maximum(slot_l, 0), keepdims=False),
                lambda: window_hist(bins2, pay2, src, start, cnt))
        elif leaf_override is None:
            # the prefetched parent (see _CompactState.pcache): the
            # only read of `hists` in the main-loop body now happens
            # AFTER the child updates, so they alias in place
            parent_hist = pcache
        else:
            # forced splits run OUTSIDE the while loop (Python
            # unrolled), where the direct read costs one copy at most
            # M times
            parent_hist = hists[leaf]

        # -- partition the leaf's range (DataPartition::Split analog) +
        # child histogram, fused into one streaming pass --
        (bins2, pay2, ord2, lazy_arr, n_left, nl_ex, nr_ex, est_hist,
         est_nu, comm_ef) = part_apply(bins2, pay2, ord2, lazy_arr,
                                       src, start, cnt, f_split, t_bin,
                                       dl, isc, cm, est_left_small,
                                       comm_ef)
        # left child stays in the parent's half; right child was packed
        # into the opposite half
        leaf_buf = leaf_buf.at[R].set(1 - src)
        lbegin = lbegin.at[R].set(start + n_left)
        lcount = lcount.at[leaf].set(n_left).at[R].set(cnt - n_left)

        new_depth = tree.leaf_depth[leaf] + 1
        tree = _apply_split_to_tree(tree, best, leaf, R, ns, p,
                                    nl_ex, nr_ex)

        other_hist = subtract_histogram(parent_hist, est_hist)
        left_hist = jnp.where(est_left_small, est_hist, other_hist)
        right_hist = jnp.where(est_left_small, other_hist, est_hist)
        if pooled:
            # store the children: the left child inherits the parent's
            # slot when cached; otherwise (and for the right child) the
            # least-recently-used slot is evicted (HistogramPool LRU)
            tick = R

            def alloc(leaf2slot, slot2leaf, lru, forbid, take):
                """Pick the LRU victim slot (skipping ``forbid``) and —
                only when ``take`` — unmap its previous leaf."""
                score = jnp.where(jnp.arange(PS) == forbid,
                                  jnp.int32(2 ** 30), lru)
                victim = jnp.argmin(score).astype(jnp.int32)
                old = slot2leaf[victim]
                oldc = jnp.clip(old, 0, L - 1)
                leaf2slot = leaf2slot.at[oldc].set(
                    jnp.where(take & (old >= 0), -1, leaf2slot[oldc]))
                return leaf2slot, victim

            leaf2slot, victim1 = alloc(leaf2slot, slot2leaf, lru,
                                       jnp.int32(-2), slot_l < 0)
            s_l = jnp.where(slot_l >= 0, slot_l, victim1)
            slot2leaf = slot2leaf.at[s_l].set(leaf)
            lru = lru.at[s_l].set(tick)
            leaf2slot, s_r = alloc(leaf2slot, slot2leaf, lru, s_l,
                                   jnp.asarray(True))
            slot2leaf = slot2leaf.at[s_r].set(R)
            lru = lru.at[s_r].set(tick)
            leaf2slot = leaf2slot.at[leaf].set(s_l).at[R].set(s_r)
            hists = hists.at[s_l].set(left_hist).at[s_r].set(right_hist)
            pool_st = (leaf2slot, slot2leaf, lru)
        else:
            hists = hists.at[leaf].set(left_hist).at[R].set(right_hist)

        # context for the pooled re-search paths (hist per leaf from
        # slot or window recompute)
        pool_ctx = (bins2, pay2, leaf_buf, lbegin, lcount,
                    pool_st[0]) if pooled else None

        # -- monotone output-bound entries (BasicLeafConstraints::Update /
        # IntermediateLeafConstraints::UpdateConstraintsWithOutputs) --
        wl_out = best.left_output[leaf]
        wr_out = best.right_output[leaf]
        bounds_l = bounds_r = None
        if has_mono:
            lmin, lmax = mono_st[0], mono_st[1]
            pmin, pmax = lmin[leaf], lmax[leaf]
            mc_f = monotone_constraints[f_split].astype(jnp.int32)
            is_num = ~isc
            inc = is_num & (mc_f > 0)
            dec = is_num & (mc_f < 0)
            if intermediate:
                val_left, val_right = wr_out, wl_out
            else:
                val_left = val_right = (wl_out + wr_out) * 0.5
            new_min_l = jnp.where(dec, jnp.maximum(pmin, val_left), pmin)
            new_max_l = jnp.where(inc, jnp.minimum(pmax, val_left), pmax)
            new_min_r = jnp.where(inc, jnp.maximum(pmin, val_right), pmin)
            new_max_r = jnp.where(dec, jnp.minimum(pmax, val_right), pmax)
            lmin = lmin.at[leaf].set(new_min_l).at[R].set(new_min_r)
            lmax = lmax.at[leaf].set(new_max_l).at[R].set(new_max_r)
            mono_st = (lmin, lmax) + mono_st[2:]
            if intermediate:
                anc = mono_st[2]
                anc = anc.at[R].set(anc[leaf])
                anc = anc.at[leaf, ns].set(1).at[R, ns].set(2)
                mono_st = (lmin, lmax, anc) + mono_st[3:]
            bounds_l = (new_min_l, new_max_l)
            bounds_r = (new_min_r, new_max_r)
            if advanced:
                # split the parent's bin-space box between the children
                # (categorical splits leave both boxes = parent's) and
                # compute each child's per-threshold bounds from the
                # post-split leaf set
                blo, bhi = mono_st[3], mono_st[4]
                fsel = jnp.arange(F_orig) == f_split
                cut_num = fsel & is_num
                l_hi = jnp.where(cut_num,
                                 jnp.minimum(bhi[leaf], t_bin + 1),
                                 bhi[leaf])
                r_lo = jnp.where(cut_num,
                                 jnp.maximum(blo[leaf], t_bin + 1),
                                 blo[leaf])
                blo = blo.at[R].set(r_lo)
                bhi = bhi.at[R].set(bhi[leaf])
                bhi = bhi.at[leaf].set(l_hi)
                mono_st = mono_st[:3] + (blo, bhi)
                bounds_l = advanced_bounds(blo, bhi, tree.leaf_value,
                                           tree.num_leaves,
                                           blo[leaf], bhi[leaf])
                bounds_r = advanced_bounds(blo, bhi, tree.leaf_value,
                                           tree.num_leaves,
                                           blo[R], bhi[R])

        # -- child best splits --
        can_go_deeper = depth_ok(new_depth)
        child_mask = None
        if interaction_groups is not None:
            nb = branch[leaf] | (jnp.arange(F_orig) == f_split)
            branch = branch.at[leaf].set(nb).at[R].set(nb)
            child_mask = allowed_features(nb)
        mask_l = mask_r = child_mask
        if use_bynode:
            nm_l = node_feature_mask(2 * ns + 1)
            nm_r = node_feature_mask(2 * ns + 2)
            nmask_st = (nmask_st[0].at[leaf].set(nm_l).at[R].set(nm_r),)
            mask_l = nm_l if child_mask is None else child_mask & nm_l
            mask_r = nm_r if child_mask is None else child_mask & nm_r
        pen_l = pen_r = None
        if cegb:
            coupled_used, _, lazy_nu = cegb_st
            first_use = ~coupled_used[f_split] & (pen_coupled[f_split] > 0)
            coupled_used = coupled_used | (jnp.arange(F_orig) == f_split)
            # parent rows acquired f_split during the partition pass
            # (before the hist/nu pass read lazy_used), so est_nu[f]
            # is post-acquisition garbage; zero it, and zero the
            # parent's column too so the children's counts follow by
            # subtraction on acquisition-consistent vectors
            est_nu_z = est_nu.at[f_split].set(0.0)
            parent_nu = lazy_nu[leaf].at[f_split].set(0.0)
            big_nu = jnp.maximum(parent_nu - est_nu_z, 0.0)
            left_nu = jnp.where(est_left_small, est_nu_z, big_nu)
            right_nu = jnp.where(est_left_small, big_nu, est_nu_z)
            lazy_nu = lazy_nu.at[leaf].set(left_nu).at[R].set(right_nu)
            cegb_st = (coupled_used, lazy_arr, lazy_nu)
            pen_l = cegb_penalty(nl_ex, coupled_used, left_nu)
            pen_r = cegb_penalty(nr_ex, coupled_used, right_nu)
        # both children search in ONE vmapped scan (halves the
        # per-split dispatch/fusion count inside the growth loop)
        def stack2(a, b):
            return jnp.stack([a, b])

        mask2 = None if mask_l is None else stack2(mask_l, mask_r)
        pen2 = None if pen_l is None else stack2(pen_l, pen_r)
        bounds2 = None if bounds_l is None else tuple(
            stack2(a, b) for a, b in zip(bounds_l, bounds_r))
        r2 = jax.vmap(
            best_for,
            in_axes=(0, 0, 0, 0,
                     None if mask2 is None else 0,
                     None if pen2 is None else 0,
                     0, None,
                     None if bounds2 is None
                     else tuple(0 for _ in bounds2)))(
            stack2(hist_f(left_hist), hist_f(right_hist)),
            stack2(best.left_sum_g[leaf], best.right_sum_g[leaf]),
            stack2(best.left_sum_h[leaf], best.right_sum_h[leaf]),
            stack2(nl_ex, nr_ex), mask2, pen2,
            stack2(wl_out, wr_out), new_depth, bounds2)
        rl = jax.tree.map(lambda a: a[0], r2)
        rr = jax.tree.map(lambda a: a[1], r2)
        best = best.store(leaf, rl, can_go_deeper)
        best = best.store(R, rr, can_go_deeper)

        if intermediate:
            # refresh every leaf's bounds to the batch fixed point of
            # the reference's cross-leaf propagation
            # (GoUpToFindLeavesToUpdate): a leaf under a monotone
            # ancestor is bounded by the extreme CURRENT outputs of the
            # sibling subtree — then re-search all stored candidates.
            lmin, lmax, anc = mono_st[:3]
            v = tree.leaf_value
            active = jnp.arange(L) < tree.num_leaves
            node_mc = monotone_constraints[tree.split_feature] \
                .astype(jnp.int32)                          # [L-1]
            node_on = (jnp.arange(L - 1) < ns + 1) \
                & ~tree.split_is_cat & (node_mc != 0)
            in_l = (anc == 1) & active[:, None] & node_on[None, :]
            in_r = (anc == 2) & active[:, None] & node_on[None, :]
            inf_ = jnp.asarray(jnp.inf, dtype)
            lmax_sub = jnp.max(jnp.where(in_l, v[:, None], -inf_), axis=0)
            lmin_sub = jnp.min(jnp.where(in_l, v[:, None], inf_), axis=0)
            rmax_sub = jnp.max(jnp.where(in_r, v[:, None], -inf_), axis=0)
            rmin_sub = jnp.min(jnp.where(in_r, v[:, None], inf_), axis=0)
            inc_n = (node_mc > 0)[None, :]
            # leaf's max bound: right-subtree min (if left of an
            # increasing node) / left-subtree min (if right of a
            # decreasing node); min bound symmetric
            ub = jnp.minimum(
                jnp.min(jnp.where(in_l & inc_n, rmin_sub[None, :], inf_),
                        axis=1),
                jnp.min(jnp.where(in_r & ~inc_n, lmin_sub[None, :], inf_),
                        axis=1))
            lb = jnp.maximum(
                jnp.max(jnp.where(in_r & inc_n, lmax_sub[None, :], -inf_),
                        axis=1),
                jnp.max(jnp.where(in_l & ~inc_n, rmax_sub[None, :], -inf_),
                        axis=1))
            mono_st = (lb, ub, anc) + mono_st[3:]
            best = research_all(tree, hists, branch, cegb_st, mono_st,
                                nmask_st, pool_ctx)

        if cegb_coupled and not intermediate:
            # (when intermediate monotone is on, the unconditional
            # research_all above already re-searched under the updated
            # coupled_used — a second pass would be identical work)
            # First use of a coupled-penalized feature erases its penalty
            # everywhere, which can promote another leaf's non-best
            # candidate to best. The reference patches the stored
            # per-(leaf, feature) candidates (UpdateLeafBestSplits,
            # cost_effective_gradient_boosting.hpp:100-124); we hold the
            # per-leaf histograms in HBM, so an exact re-search of every
            # leaf under the updated penalty is the same result.
            # tpulint: replicated-cond first_use derives from the replicated best-split record on globally-reduced histograms
            best = lax.cond(
                first_use,
                lambda b: research_all(tree, hists, branch, cegb_st,
                                       mono_st, nmask_st, pool_ctx),
                lambda b: b, best)

        if pooled:
            new_pcache = pcache
        else:
            # prefetch the NEXT split's parent from the updated buffer
            # (the argmax here is exactly the next iteration's leaf
            # choice — best is final at this point)
            nl_next = jnp.argmax(best.gain).astype(jnp.int32)
            new_pcache = lax.dynamic_index_in_dim(hists, nl_next,
                                                  keepdims=False)
        return _CompactState(tree=tree, best=best, hists=hists,
                             bins2=bins2, pay2=pay2, ord2=ord2,
                             leaf_buf=leaf_buf,
                             leaf_begin=lbegin, leaf_count=lcount,
                             branch=branch, num_splits=ns + 1,
                             cegb=cegb_st, mono=mono_st,
                             node_masks=nmask_st, pool=pool_st,
                             comm_ef=comm_ef, pcache=new_pcache)

    def forced_result(hist, tc, f, t, p_out, bnds) -> SplitResult:
        """Fixed (feature, bin) split record from a leaf's histogram
        (SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:620 via
        GatherInfoForThresholdNumerical, feature_histogram.hpp:486).
        Missing values route right (default_left=False). ``tc`` is the
        leaf's exact count; child counts are hessian-ratio estimates
        like the regular search (feature_histogram.hpp:528)."""
        if sharded:
            # the GLOBAL feature-0 row lives on device 0's chunk only
            # (see _research_leafwise) — broadcast, then sum the same
            # bin sequence the gathered path sums
            row0 = lax.psum(
                jnp.where(dev_idx == 0, hist[0], jnp.zeros_like(hist[0])),
                cfg.axis_name)
            totals = jnp.sum(row0, axis=0)
        else:
            totals = jnp.sum(hist[0], axis=0)      # every row hits feat 0
        tg, th = totals[0], totals[1]
        # the histogram COLUMN the forced feature lives in: its own
        # column when plain, its bundle column under EFB
        fcol = bundle_of[f] if bundled else f
        if fp or sharded:
            # the forced column's histogram lives on its owner device
            # only; route it to everyone with one [B, 2] psum
            own = (_fp_owner(fcol) == dev_idx) if fp else \
                (fcol >= f_start) & (fcol < f_start + Fl)
            lf = jnp.clip(fcol - f_start, 0, Fl - 1)
            h_loc = lax.dynamic_index_in_dim(hist, lf, keepdims=False)
            h = lax.psum(jnp.where(own, h_loc, 0.0), cfg.axis_name)
        elif vp:
            # voting keeps per-device caches local; a forced (feature,
            # bin) needs the GLOBAL row — one [B, 2] psum
            h = lax.psum(hist[fcol], cfg.axis_name)
            tg = lax.psum(tg, cfg.axis_name)
            th = lax.psum(th, cfg.axis_name)
        else:
            h = hist[fcol]                         # [B, 2]
        binsb = jnp.arange(B)
        nanb = feat_nan_bin[f]
        sel = (binsb <= t) & ~((binsb == nanb) & (nanb >= 0))
        left = jnp.sum(h * sel[:, None].astype(h.dtype), axis=0)
        if bundled:
            # multi-member reconstruction (FixHistogram algebra): the
            # member's right side for threshold t is its positions
            # [off+t, off+nb-2] — the NaN position (off+nanb-1) sits
            # inside and routes right, like the plain sel excluding
            # the NaN bin from the left
            off = offset_of[f]
            nb = feat_num_bins[f]
            rsel = (binsb >= off + t) & (binsb <= off + nb - 2)
            right_m = jnp.sum(h * rsel[:, None].astype(h.dtype),
                              axis=0)
            left_m = jnp.stack([tg, th]) - right_m
            left = jnp.where(bundle_is_direct[f], left, left_m)
        lg, lh = left[0], left[1]
        lc = jnp.round(lh * tc / jnp.maximum(th, 1e-15))
        rg, rh, rc = tg - lg, th - lh, tc - lc
        if smoothing or has_mono:
            wl = constrained_output(lg, lh, lc, p_out, bnds, p)
            wr = constrained_output(rg, rh, rc, p_out, bnds, p)
            # GatherInfo evaluates the parent at its stored output
            gain = gain_at_output(lg, lh, wl, p) \
                + gain_at_output(rg, rh, wr, p) \
                - gain_at_output(tg, th, p_out, p)
        else:
            wl = leaf_output(lg, lh, p)
            wr = leaf_output(rg, rh, p)
            gain = leaf_gain(lg, lh, p) + leaf_gain(rg, rh, p) \
                - leaf_gain(tg, th, p)
        false_ = jnp.asarray(False)
        return SplitResult(
            gain=gain.astype(dtype), feature=f, threshold_bin=t,
            default_left=false_, is_cat=false_,
            cat_mask=jnp.zeros((B,), jnp.bool_),
            left_sum_g=lg, left_sum_h=lh, left_count=lc,
            right_sum_g=rg, right_sum_h=rh, right_count=rc,
            left_output=wl, right_output=wr)

    def forced_step(state: _CompactState, ok, leaf, f, t):
        """One forced split. An invalid forced split aborts ALL
        remaining ones (abort_last_forced_split,
        serial_tree_learner.cpp:695-699), not just itself."""
        bnds = None if not has_mono \
            else (state.mono[0][leaf], state.mono[1][leaf])
        if pooled:
            slot = state.pool[0][leaf]
            # tpulint: replicated-cond leaf2slot derives only from the replicated tree/argmax sequence (see _research_leafwise)
            hist_l = lax.cond(
                slot >= 0,
                lambda: lax.dynamic_index_in_dim(
                    state.hists, jnp.maximum(slot, 0), keepdims=False),
                lambda: window_hist(state.bins2, state.pay2,
                                    state.leaf_buf[leaf],
                                    state.leaf_begin[leaf],
                                    state.leaf_count[leaf]))
        else:
            hist_l = state.hists[leaf]
        r = forced_result(hist_f(hist_l),
                          state.tree.leaf_count[leaf], f, t,
                          state.tree.leaf_value[leaf], bnds)
        valid = ok & (r.left_count > 0) & (r.right_count > 0)
        forced_state = state._replace(best=state.best.store(leaf, r,
                                                            jnp.asarray(True)))
        # tpulint: replicated-cond `valid` derives from the forced-split record on globally-reduced histograms
        return lax.cond(valid,
                        lambda s: do_split(s, leaf_override=leaf),
                        lambda _: state, forced_state), valid

    M = 0
    if forced is not None:
        f_leaf, f_feat, f_bin = forced
        M = min(int(f_leaf.shape[0]), L - 1)
        forced_ok = jnp.asarray(True)
        for i in range(M):
            state, forced_ok = forced_step(state, forced_ok, f_leaf[i],
                                           f_feat[i], f_bin[i])

    # growth loop: a while_loop with the stop condition in cond_fn (the
    # reference's early break, serial_tree_learner.cpp:225) — unlike a
    # fori_loop of lax.conds, the body always does real work and XLA
    # aliases the carried buffers in place instead of copying them
    # through conditional branches.
    def can_grow(state: _CompactState):
        return (state.num_splits < L - 1) \
            & (jnp.max(state.best.gain) > 0.0)

    state = lax.while_loop(can_grow, do_split, state)
    if bundled:
        # bundle columns can't be re-routed by the predictor (the tree
        # references ORIGINAL features); merge the per-leaf windows
        # (each living in one ping-pong half) into one coherent order
        # vector, then invert
        leaf_of_pos = _leaf_of_positions(state.leaf_begin,
                                         state.leaf_count, n, L)
        in_b1 = _leaf_values_at_positions(
            state.leaf_begin, state.leaf_count, state.leaf_buf, n) == 1
        order_m = jnp.where(in_b1, state.ord2[SEG + PAD: SEG + PAD + n],
                            state.ord2[PAD: PAD + n])
        order_ids = (order_m & ~_IB_BIT).astype(jnp.int32)
        row_leaf = _row_leaf_from_order(order_ids, leaf_of_pos)
    else:
        # re-route rows through the finished tree with the in-order
        # node sweep (ops/predict.py) instead of inverting ord2 with
        # two FULL-LENGTH variadic sorts: the sweep is nn sequential
        # [n] column selects, while an n-row bitonic sort moves
        # ~log^2(n) passes of row data through HBM — at 10.5M rows the
        # sorts dwarf the sweep. Routing semantics are identical to
        # chunk_goleft (same thresholds, NaN bins, cat masks).
        t = state.tree
        row_leaf = predict_leaf_binned(
            t.split_feature, t.threshold_bin, t.default_left,
            t.left_child, t.right_child, feat_nan_bin, bins_T,
            t.split_is_cat if has_cat else None,
            t.split_cat_mask if has_cat else None)
        # an ungrown tree has no internal node 0 to route through
        row_leaf = jnp.where(t.num_leaves > 1, row_leaf, 0)
    tree = state.tree
    if quant and cfg.renew_leaf:
        # RenewIntGradTreeOutput (gradient_discretizer.hpp): replace the
        # quantized leaf outputs with exact float sums per leaf.
        sg = psum(jax.ops.segment_sum(gw2[:, 0], row_leaf, num_segments=L))
        sh = psum(jax.ops.segment_sum(gw2[:, 1], row_leaf, num_segments=L))
        newv = leaf_output(sg, sh, p)
        lv = jnp.where(jnp.arange(L) < tree.num_leaves, newv,
                       tree.leaf_value)
        tree = tree._replace(leaf_value=lv)
    if cegb:
        return tree, row_leaf, state.cegb[0], state.cegb[1]
    return tree, row_leaf


grow_tree = jax.jit(grow_tree_impl, static_argnames=("cfg",))

# recompile telemetry + XLA cost attribution: growth is the hot path
# whose silent recompiles telemetry exists to catch (obs/jit_tracker.py);
# rebinding routes calls through the CostTracked wrapper so each first
# compile per signature emits a {"event": "compile"} record (obs/cost.py)
from ..obs import register_jit  # noqa: E402  (after grow_tree exists)

grow_tree = register_jit("ops/grow_tree", grow_tree, max_signatures=8)
