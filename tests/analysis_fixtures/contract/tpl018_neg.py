"""TPL018 negatives: tuples and call sites that match the registry."""

_KNOWN_KINDS = ("ping_kill", "ping_slow")

_ONE_SHOT_KINDS = ("ping_kill",)


def trip(plan, log):
    append_fault_event(log, "ping_seen", 0, "", "observed")
    record_fault_event("ping_slow", 3, "sleep", "slowdown")
    if plan.fires("ping_kill", 0):
        pass
    n = plan.take("ping_slow")
    return n


def append_fault_event(log, kind, iteration, action, detail):
    pass


def record_fault_event(kind, iteration, action, detail):
    pass
