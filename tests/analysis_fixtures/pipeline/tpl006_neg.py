# tpulint fixture: TPL006 negative — the same generation-scoring
# helper with the dispatch outside the lock; only pure-python
# bookkeeping runs under it. No EXPECT lines.
import threading

import jax.numpy as jnp

_lock = threading.Lock()
_summary = {"auc_sum": 0.0}


def record_generation_auc(scores):
    auc = float(jnp.mean(scores))     # dispatch FIRST, lock-free
    with _lock:
        _summary["auc_sum"] += auc
