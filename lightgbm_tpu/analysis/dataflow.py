"""Dataflow lattices for the distributed-safety rules. Pure stdlib.

Three small analyses, consumed by :mod:`~lightgbm_tpu.analysis
.rules_flow` on top of the per-function CFGs:

**Rank taint** (TPL007) — which expressions derive from the *process
rank*? Sources: ``jax.process_index()`` (any spelling that resolves to
a ``process_index`` basename), reads of rank-carrying environment
variables (``LIGHTGBM_TPU_RANK`` and anything else containing
``RANK``), and calls to package functions whose *return value* is
rank-derived (a cross-module fixed point over the call graph, so
``faults.FaultPlan._rank_selected`` taints its callers). Taint
propagates through local assignments — including tuple unpacking, so
``nproc, rank = jax.process_count(), jax.process_index()`` taints only
``rank`` — and through any containing expression. ``process_count()``
is deliberately *not* a source: the world size is rank-invariant.

**Thread-side closure** (TPL008) — which functions run on a thread
other than the caller's? Seeds: ``threading.Thread(target=f)``,
``threading.Timer(t, f)``, and the ``fn`` argument of
``watchdog.guarded(name, fn, ...)`` (the collective watchdog runs it
on a fresh daemon worker). Closed transitively over the call graph, so
a helper called from a guarded collective body is thread-side too.
Method calls on *constructor-typed* receivers are followed as well:
when thread-side code calls ``obj.m(...)`` and ``obj`` was assigned
from ``SomeClass(...)`` in the scope or its enclosing function chain
(the supervisor's ``policy = AutoscalePolicy(...)`` consumed by the
nested scrape loop), ``SomeClass.m`` joins the closure — the plain
reference graph cannot see through a method call on a local.

**float64 producers** (TPL009) — numpy expressions whose value is
float64: explicit ``np.float64`` / ``dtype=np.float64`` /
``.astype("float64")``, and the float64-by-default constructors
(``np.zeros``/``ones``/``empty``/``arange``/``linspace`` with no dtype
argument).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .astscan import ModuleScan, dotted_of
from .callgraph import CallGraph, Key

__all__ = ["RankTaint", "rank_tainted_returns", "thread_side_functions",
           "resolve_fn_arg", "is_float64_expr", "MUTATOR_METHODS",
           "SYNC_PRIMITIVE_CTORS"]

#: callables whose result is this process's rank
_RANK_BASENAMES = {"process_index"}

#: list/dict/set mutators: calling one of these on a shared object is a
#: write for the race analysis
MUTATOR_METHODS = {"append", "extend", "insert", "remove", "pop",
                   "clear", "update", "setdefault", "add", "discard",
                   "popitem", "appendleft", "popleft", "sort",
                   "reverse"}

#: constructors of objects that synchronize internally — accesses to
#: them are exempt from the race analysis
SYNC_PRIMITIVE_CTORS = {"Event", "Condition", "Semaphore",
                        "BoundedSemaphore", "Barrier", "Queue",
                        "SimpleQueue", "LifoQueue", "PriorityQueue",
                        "Lock", "RLock", "local", "deque", "count"}


def _env_name_of(node: ast.AST) -> Optional[str]:
    """The environment-variable name read by this expression, if any:
    ``os.environ["X"]`` / ``os.environ.get("X", ...)`` /
    ``os.getenv("X")``."""
    if isinstance(node, ast.Subscript):
        base = dotted_of(node.value)
        if base and base.endswith("environ"):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return sl.value
    if isinstance(node, ast.Call) and node.args:
        f = dotted_of(node.func) or ""
        base = f.rsplit(".", 1)[-1]
        env_read = base == "getenv" or (
            base == "get" and isinstance(node.func, ast.Attribute)
            and (dotted_of(node.func.value) or "").endswith("environ"))
        if env_read:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
    return None


def _is_rank_env(name: Optional[str]) -> bool:
    return bool(name) and "RANK" in name.upper()


class RankTaint:
    """Per-function rank-taint facts. ``seed_names`` lets callers feed
    tainted names from enclosing scopes (closures) in; ``tainted_fns``
    is the cross-module returns-rank set from
    :func:`rank_tainted_returns`."""

    def __init__(self, fn_node: ast.AST,
                 seed_names: Iterable[str] = (),
                 tainted_fns: Optional[Set[str]] = None):
        self.fn_node = fn_node
        self._tainted_fns = tainted_fns or set()
        self.names: Set[str] = set(seed_names)
        self._solve()

    def _own_statements(self):
        """Statements of this function, not descending into nested
        function/class definitions (their bindings are their own)."""
        stack = list(getattr(self.fn_node, "body", []))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    stack.append(child)
                elif isinstance(child, (ast.excepthandler,)):
                    stack.append(child)

    def _solve(self) -> None:
        assigns: List[ast.stmt] = [
            s for s in self._own_statements()
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign))]
        for _ in range(len(assigns) + 1):
            changed = False
            for s in assigns:
                if isinstance(s, ast.Assign):
                    targets, value = s.targets, s.value
                elif isinstance(s, ast.AnnAssign):
                    if s.value is None:
                        continue
                    targets, value = [s.target], s.value
                else:  # AugAssign: x += rank keeps/adds taint
                    targets, value = [s.target], s.value
                changed |= self._bind(targets, value)
            if not changed:
                break

    def _bind(self, targets, value) -> bool:
        changed = False
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                if isinstance(value, (ast.Tuple, ast.List)) \
                        and len(t.elts) == len(value.elts):
                    # element-wise: `nproc, rank = count(), index()`
                    # taints only `rank`
                    for te, ve in zip(t.elts, value.elts):
                        changed |= self._bind([te], ve)
                elif self.is_tainted(value):
                    for te in t.elts:
                        changed |= self._bind([te], value)
                continue
            name = self._target_name(t)
            if name is None:
                continue
            if self.is_tainted(value) and name not in self.names:
                self.names.add(name)
                changed = True
        return changed

    @staticmethod
    def _target_name(t: ast.AST) -> Optional[str]:
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute) \
                and isinstance(t.value, ast.Name) \
                and t.value.id in ("self", "cls"):
            return f"{t.value.id}.{t.attr}"
        if isinstance(t, ast.Starred):
            return RankTaint._target_name(t.value)
        return None

    def is_tainted(self, expr: Optional[ast.AST]) -> bool:
        """Does any sub-expression derive from the process rank?"""
        if expr is None:
            return False
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in self.names:
                return True
            if isinstance(sub, ast.Attribute):
                d = dotted_of(sub)
                if d in self.names:
                    return True
            if isinstance(sub, ast.Call):
                f = dotted_of(sub.func) or ""
                base = f.rsplit(".", 1)[-1]
                if base in _RANK_BASENAMES:
                    return True
                if base in self._tainted_fns \
                        or f in self._tainted_fns:
                    return True
            if _is_rank_env(_env_name_of(sub)):
                return True
        return False


def _fn_summary(fn_node):
    """One own-statement walk (nested defs excluded — their returns
    must not taint the outer name): (return value exprs, called
    basenames, has a direct rank source)."""
    returns: List[ast.expr] = []
    calls: Set[str] = set()
    direct = False
    stack = list(getattr(fn_node, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            returns.append(node.value)
        if isinstance(node, ast.Call):
            f = dotted_of(node.func) or ""
            base = f.rsplit(".", 1)[-1]
            calls.add(base)
            if base in _RANK_BASENAMES:
                direct = True
        if not direct and _is_rank_env(_env_name_of(node)):
            direct = True
        stack.extend(ast.iter_child_nodes(node))
    return returns, calls, direct


def rank_tainted_returns(graph: CallGraph) -> Set[str]:
    """Basenames of package functions whose return value derives from
    the rank — fixed point: a function returning a tainted expression
    taints every caller that uses its result in a condition. Cheap
    summaries gate the expensive per-function taint solve to actual
    candidates (functions touching a rank source, or calling an
    already-tainted name)."""
    summaries = {key: _fn_summary(info.node)
                 for key, info in graph.funcs.items()}
    tainted: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for key, info in graph.funcs.items():
            name = info.name
            if name in tainted:
                continue
            returns, calls, direct = summaries[key]
            if not returns or not (direct or calls & tainted):
                continue
            taint = RankTaint(info.node, tainted_fns=tainted)
            if any(taint.is_tainted(r) for r in returns):
                tainted.add(name)
                changed = True
    return tainted


# ---------------------------------------------------------------------
# thread-side closure
# ---------------------------------------------------------------------

_THREAD_CTORS = {"Thread", "Timer"}

#: request-handler base classes whose methods run on serving-stack
#: threads (socketserver.ThreadingMixIn servers spawn one per
#: connection; http.server.ThreadingHTTPServer likewise)
_HANDLER_BASES = {"BaseRequestHandler", "StreamRequestHandler",
                  "DatagramRequestHandler", "BaseHTTPRequestHandler",
                  "SimpleHTTPRequestHandler", "CGIHTTPRequestHandler"}
#: package-specific: watchdog.guarded(name, fn, ...) runs fn on a fresh
#: daemon worker thread (resilience/watchdog.py)
_GUARDED_BASENAMES = {"guarded"}


def resolve_fn_arg(graph: CallGraph, scan: ModuleScan,
                   scope: Optional[Key],
                   node: ast.AST) -> Optional[Key]:
    """Resolve a function-valued argument (``target=_run`` /
    ``guarded(name, _run)``) to a known function key: nested defs of
    the calling scope (walking the enclosing chain), module-level
    functions, and ``self.method``."""
    if isinstance(node, ast.Name):
        qual = scope[1] if scope else None
        while qual:
            info = scan.funcs.get(f"{qual}.{node.id}")
            if info is not None:
                return info.key
            info = scan.funcs.get(qual)
            qual = info.parent_qual if info is not None else None
        info = scan.funcs.get(node.id)
        if info is not None:
            return info.key
        alias = scan.aliases.get(node.id)
        if alias is not None and alias[0] == "func":
            info = scan.funcs.get(alias[1])
            if info is not None:
                return info.key
        return None
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls") and scope:
        info = graph.funcs.get(scope)
        cls = info.class_name if info is not None else None
        if cls:
            target = scan.funcs.get(f"{cls}.{node.attr}")
            if target is not None:
                return target.key
    return None


def _ctor_class_of(scan: ModuleScan, qual: Optional[str],
                   var: str) -> Optional[str]:
    """The class name ``var`` was constructed from, when a
    ``var = SomeClass(...)`` assignment is visible in the function
    ``qual`` or its enclosing chain (closure variables: the
    supervisor assigns ``policy = AutoscalePolicy(...)`` and the
    nested scrape loop calls ``policy.observe(...)``). Only
    ``Name(...)`` constructor calls count, and only names that look
    like classes (leading capital or underscore-prefixed CapWords) —
    a ``rows = load(...)`` assignment must not type ``rows``."""
    while qual:
        info = scan.funcs.get(qual)
        if info is None:
            return None
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None:
                targets = [node.target]
            else:
                continue
            val = node.value
            if not (isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Name)):
                continue
            name = val.func.id
            if not name.lstrip("_")[:1].isupper():
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == var:
                    return name
        qual = info.parent_qual
    return None


def _method_call_targets(graph: CallGraph, key: Key,
                         methods_by_qual: Dict[str, Set[Key]]
                         ) -> Set[Key]:
    """Class methods a scope invokes through ``obj.m(...)`` where
    ``obj``'s class is recoverable via :func:`_ctor_class_of`.
    Matching is by ``Class.method`` qualname across every scanned
    module (the class is usually imported from a sibling module, so
    the receiver's scan does not hold its def)."""
    out: Set[Key] = set()
    facts = graph.facts.get(key)
    scan = graph.scans.get(key[0])
    if facts is None or scan is None:
        return out
    for rec in facts.records:
        if rec.kind != "method" or not rec.attr or rec.node is None:
            continue
        fn = rec.node.func
        if not (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)):
            continue
        cls = _ctor_class_of(scan, key[1], fn.value.id)
        if cls is None:
            continue
        out |= methods_by_qual.get(f"{cls}.{rec.attr}", set())
    return out


def thread_side_functions(graph: CallGraph) -> Dict[Key, Tuple[str, int]]:
    """Every function that runs on a spawned thread, mapped to
    ``(how, seed lineno)`` where ``how`` names the spawn site
    (``threading.Thread`` / ``threading.Timer`` /
    ``watchdog.guarded``). Methods of socketserver / http.server
    request-handler subclasses are seeded too: the serving stack
    (ThreadingTCPServer, ThreadingHTTPServer — the serve daemon's
    protocol handler, the /metrics scrape endpoint in obs/export.py)
    invokes ``do_*``/``handle`` on per-connection daemon threads the
    call graph cannot otherwise see. Seeds are closed transitively
    over the call graph: helpers called from thread-side code are
    thread-side."""
    seeds: Dict[Key, Tuple[str, int]] = {}
    for relpath, scan in graph.scans.items():
        handler_classes = {
            cls for cls, bases in scan.class_bases.items()
            if any(base.rsplit(".", 1)[-1] in _HANDLER_BASES
                   for base in bases)}
        if not handler_classes:
            continue
        for info in scan.funcs.values():
            if info.class_name in handler_classes:
                seeds.setdefault(
                    info.key, ("request-handler thread", info.lineno))
    for scope, facts in graph.facts.items():
        for rec in facts.records:
            if rec.node is None:
                continue
            basename = None
            if rec.dotted:
                basename = rec.dotted.rsplit(".", 1)[-1]
            elif rec.kind == "known" and rec.target is not None:
                basename = rec.target[1].rsplit(".", 1)[-1]
            elif rec.kind == "method":
                basename = rec.attr
            if basename is None:
                continue
            scan = graph.scans.get(rec.relpath)
            if scan is None:
                continue
            fn_node = None
            how = None
            if basename in _THREAD_CTORS:
                for kw in rec.node.keywords:
                    if kw.arg == "target":
                        fn_node = kw.value
                if fn_node is None and basename == "Timer" \
                        and len(rec.node.args) >= 2:
                    fn_node = rec.node.args[1]
                how = f"threading.{basename}"
            elif basename in _GUARDED_BASENAMES \
                    and len(rec.node.args) >= 2:
                fn_node = rec.node.args[1]
                how = "watchdog.guarded"
            if fn_node is None:
                continue
            key = resolve_fn_arg(graph, scan, rec.scope, fn_node)
            if key is not None:
                seeds.setdefault(key, (how, rec.node.lineno))
    # transitive closure over the reference graph, plus
    # constructor-typed method calls (refs cannot see through
    # ``policy.observe(...)`` on a closure variable)
    out_edges: Dict[Optional[Key], Set[Key]] = {}
    for r in graph.refs:
        out_edges.setdefault(r.scope, set()).add(r.target)
    methods_by_qual: Dict[str, Set[Key]] = {}
    for scan in graph.scans.values():
        for info in scan.funcs.values():
            if info.class_name:
                methods_by_qual.setdefault(
                    info.key[1], set()).add(info.key)
    result = dict(seeds)
    frontier = list(seeds)
    while frontier:
        k = frontier.pop()
        how, ln = result[k]
        callees = set(out_edges.get(k, ()))
        callees |= _method_call_targets(graph, k, methods_by_qual)
        for callee in callees:
            if callee not in result:
                result[callee] = (how, ln)
                frontier.append(callee)
    return result


# ---------------------------------------------------------------------
# float64 producers
# ---------------------------------------------------------------------

_F64_DEFAULT_CTORS = {"zeros", "ones", "empty", "arange", "linspace",
                      "full"}
_NUMPY_ROOTS = {"numpy", "np"}


def _numpy_rooted(dotted: Optional[str],
                  imports: Dict[str, str]) -> bool:
    if not dotted:
        return False
    root = dotted.split(".", 1)[0]
    resolved = imports.get(root, root)
    return resolved.split(".", 1)[0] in _NUMPY_ROOTS


def _is_f64_dtype(node: ast.AST, imports: Dict[str, str]) -> bool:
    if isinstance(node, ast.Constant):
        return node.value in ("float64", "f8", "double")
    if isinstance(node, ast.Name):
        return node.id == "float"  # np dtype `float` == float64
    d = dotted_of(node)
    if d is None:
        return False
    base = d.rsplit(".", 1)[-1]
    return base in ("float64", "double") and (
        _numpy_rooted(d, imports) or "." not in d)


def is_float64_expr(expr: ast.AST, imports: Dict[str, str],
                    assigns: Optional[Dict[str, List[Tuple[int, bool]]]]
                    = None) -> bool:
    """Is this expression a float64-producing numpy value?

    ``assigns`` (optional) maps local names to an assignment history of
    ``(lineno, was_f64)`` pairs so one level of local propagation works
    (``thr = np.zeros(n); jitted(thr)``).
    """
    if isinstance(expr, ast.Name) and assigns is not None:
        last: Optional[bool] = None
        for lineno, was in assigns.get(expr.id, ()):
            if lineno >= getattr(expr, "lineno", 10 ** 9):
                break
            last = was
        return bool(last)
    if isinstance(expr, ast.BinOp):
        return is_float64_expr(expr.left, imports, assigns) \
            or is_float64_expr(expr.right, imports, assigns)
    if not isinstance(expr, ast.Call):
        return False
    # X.astype(np.float64) / X.astype("float64")
    if isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "astype" and expr.args:
        return _is_f64_dtype(expr.args[0], imports)
    f = dotted_of(expr.func)
    if f is None:
        return False
    base = f.rsplit(".", 1)[-1]
    if base == "float64" and _numpy_rooted(f, imports):
        return True
    if not _numpy_rooted(f, imports):
        return False
    dtype_args = [kw.value for kw in expr.keywords
                  if kw.arg == "dtype"]
    if base in ("asarray", "array", "full") and len(expr.args) >= 2 \
            and not dtype_args:
        # positional dtype (np.asarray(x, np.float64)) / fill value
        if base == "full":
            pass  # full(shape, fill): dtype is the 3rd positional
        else:
            dtype_args = [expr.args[1]]
    if base in ("zeros", "ones", "empty") and len(expr.args) >= 2 \
            and not dtype_args:
        dtype_args = [expr.args[1]]
    if dtype_args:
        return _is_f64_dtype(dtype_args[0], imports)
    if base in _F64_DEFAULT_CTORS:
        if base == "full":
            # dtype follows the fill value: float fill -> float64
            if len(expr.args) >= 3:
                return _is_f64_dtype(expr.args[2], imports)
            return (len(expr.args) >= 2
                    and isinstance(expr.args[1], ast.Constant)
                    and isinstance(expr.args[1].value, float))
        if base == "arange":
            # int-stepped arange is int64; flag only float arguments
            return any(isinstance(a, ast.Constant)
                       and isinstance(a.value, float)
                       for a in expr.args)
        return True
    return False
