"""Thin compatibility wrapper — the hot-path lint grew into tpulint.

The ad-hoc AST guard that lived here (an eager-``lax``-loop check over
``models/gbdt.py`` + ``ops/`` gated by a hand-maintained
``KNOWN_JITTED`` allowlist) became a real analyzer:
``lightgbm_tpu/analysis/`` — a cross-module call graph that DERIVES
the jit-reachable set, plus the TPL001-TPL006 hazard catalog
(docs/STATIC_ANALYSIS.md), run via ``python -m lightgbm_tpu lint``.

This file stays so history/docs links keep working; the tests live in
``tests/test_static_analysis.py``. ``KNOWN_JITTED`` is now an
ASSERTION over the derived set (catching both stale and missing
entries), not an input to the lint. Migration notes:

- the old allowlist's ``predict_forest_raw`` entry was STALE: nothing
  ever jitted that function (dead since prediction.py's vmapped
  ``_forest_leaves``), and its eager-scope references silently demoted
  ``predict_leaf_raw``/``_traverse`` too. tpulint TPL001 caught it;
  the dead function was removed.
- the ``_train_one_iter_fused`` host-fetch guard is now rule TPL002
  driven by the ``# tpulint: hot`` marker on the function.
"""

from test_static_analysis import (  # noqa: F401
    KNOWN_JITTED,
    test_every_hot_path_lax_loop_is_jit_reachable,
    test_known_jitted_covered_by_derived_set,
    test_known_jitted_entries_exist,
    test_nonfinite_guard_stays_inside_jitted_step,
)
