"""Row-chunk sources: the raw-data side of out-of-core ingestion.

A :class:`RowChunkSource` is a RE-ITERABLE producer of bounded row
chunks — ``chunks()`` can be called twice, because construction is a
two-pass pipeline (:mod:`~lightgbm_tpu.data.ingest`): pass 1 streams to
count rows and reservoir-sample the bin-finding sample, pass 2 streams
again to bin every chunk straight into the preallocated binned matrix.
The dense float matrix therefore never exists anywhere; peak host
memory is one chunk plus the (bounded) bin-construction sample plus the
binned product itself (1-2 bytes per value).

This mirrors the reference DatasetLoader's two-round text load
(dataset_loader.cpp:299,960 — sample pass, then a streaming binning
pass) generalized from "a CSV file" to any chunked producer: numpy
arrays, ``lightgbm_tpu.Sequence`` objects, generator factories,
CSV/TSV files, and (import-guarded) Arrow tables / parquet files.

Everything here is host-side numpy and must stay jax-import-lazy:
sources are built and iterated before any accelerator state exists,
and ``python -m lightgbm_tpu lint`` runs where no jax backend can
initialize at all (tpulint covers ``data/``).
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, List, NamedTuple, Optional

import numpy as np

__all__ = ["RowChunk", "RowChunkSource", "ArrayChunkSource",
           "GeneratorChunkSource", "SequenceChunkSource",
           "CSVChunkSource", "ArrowChunkSource", "coerce_chunk_source",
           "DEFAULT_CHUNK_ROWS"]

#: chunk size when neither ``ingest_chunk_rows`` nor the source pins one
DEFAULT_CHUNK_ROWS = 65536


def _err(msg: str) -> Exception:
    """A ``LightGBMError`` imported lazily AT RAISE TIME: ``basic``
    transitively imports jax at module level, and the happy path of
    this package must stay jax-free (docs/DATA.md)."""
    from ..basic import LightGBMError
    return LightGBMError(msg)


class RowChunk(NamedTuple):
    """One bounded batch of raw rows (+ optional per-row metadata)."""

    X: np.ndarray                       # [c, F] float
    label: Optional[np.ndarray] = None  # [c]
    weight: Optional[np.ndarray] = None  # [c]


def _as_chunk(obj) -> RowChunk:
    """Normalize what an adapter yielded into a :class:`RowChunk`:
    a bare array, an ``(X,)`` / ``(X, y)`` / ``(X, y, w)`` tuple, or an
    already-built RowChunk."""
    if isinstance(obj, RowChunk):
        X, y, w = obj
    elif isinstance(obj, np.ndarray):
        X, y, w = obj, None, None
    elif isinstance(obj, (tuple, list)):
        if not 1 <= len(obj) <= 3:
            raise _err(
                f"chunk tuples must be (X[, label[, weight]]), got "
                f"{len(obj)} elements")
        X = obj[0]
        y = obj[1] if len(obj) > 1 else None
        w = obj[2] if len(obj) > 2 else None
    else:
        raise _err(f"cannot interpret chunk of type {type(obj)}")
    X = np.asarray(X)
    if X.dtype not in (np.float32, np.float64):
        X = X.astype(np.float64)
    if X.ndim == 1:
        X = X[:, None]
    if y is not None:
        y = np.asarray(y, np.float64).ravel()
        if len(y) != X.shape[0]:
            raise _err(
                f"chunk label length {len(y)} != chunk rows {X.shape[0]}")
    if w is not None:
        w = np.asarray(w, np.float64).ravel()
        if len(w) != X.shape[0]:
            raise _err(
                f"chunk weight length {len(w)} != chunk rows {X.shape[0]}")
    return RowChunk(X, y, w)


class RowChunkSource:
    """Protocol for chunked row producers.

    Subclasses implement :meth:`chunks`; every call must start a FRESH
    iteration over the same data (the ingest pipeline streams twice).
    ``num_rows`` / ``num_features`` return ``None`` when unknown ahead
    of the first pass — the pipeline then counts during pass 1 and
    falls back from deterministic row-index sampling to reservoir
    sampling (docs/DATA.md)."""

    #: advisory chunk size; ``ingest_chunk_rows`` overrides when set
    chunk_rows: int = DEFAULT_CHUNK_ROWS

    def num_rows(self) -> Optional[int]:
        return None

    def num_features(self) -> Optional[int]:
        return None

    def feature_names(self) -> Optional[List[str]]:
        return None

    def chunks(self) -> Iterator[RowChunk]:  # pragma: no cover - abstract
        raise NotImplementedError("RowChunkSource.chunks")


class ArrayChunkSource(RowChunkSource):
    """Slice an in-memory ``[n, F]`` array into row-chunk views (no
    copies): the adapter that lets one ingest pipeline serve both the
    streaming and the already-materialized case."""

    def __init__(self, X, label=None, weight=None,
                 chunk_rows: Optional[int] = None):
        self._X = np.asarray(X)
        if self._X.ndim == 1:
            self._X = self._X[:, None]
        self._label = None if label is None else \
            np.asarray(label, np.float64).ravel()
        self._weight = None if weight is None else \
            np.asarray(weight, np.float64).ravel()
        # validate up front: per-chunk slices of a LONGER metadata
        # vector all match their X slice, so truncation would
        # otherwise pass silently (the eager constructor raises)
        n = self._X.shape[0]
        if self._label is not None and len(self._label) != n:
            raise _err(f"Length of label ({len(self._label)}) != "
                       f"number of rows ({n})")
        if self._weight is not None and len(self._weight) != n:
            raise _err(f"Length of weight ({len(self._weight)}) != "
                       f"number of rows ({n})")
        if chunk_rows is not None:
            self.chunk_rows = int(chunk_rows)

    def num_rows(self) -> int:
        return int(self._X.shape[0])

    def num_features(self) -> int:
        return int(self._X.shape[1])

    def chunks(self) -> Iterator[RowChunk]:
        n = self._X.shape[0]
        step = max(1, int(self.chunk_rows))
        for lo in range(0, n, step):
            hi = min(lo + step, n)
            yield _as_chunk((
                self._X[lo:hi],
                None if self._label is None else self._label[lo:hi],
                None if self._weight is None else self._weight[lo:hi]))


class GeneratorChunkSource(RowChunkSource):
    """Wrap a zero-argument factory returning a fresh chunk iterator
    per call — the shape synthetic generators and custom loaders take.
    Items may be arrays, ``(X[, y[, w]])`` tuples, or RowChunks."""

    def __init__(self, factory: Callable[[], Iterator],
                 num_rows: Optional[int] = None,
                 num_features: Optional[int] = None,
                 feature_names: Optional[List[str]] = None,
                 chunk_rows: Optional[int] = None):
        if not callable(factory):
            raise _err(
                "GeneratorChunkSource needs a zero-argument factory "
                "returning a fresh chunk iterator per call (a generator "
                "OBJECT can only be consumed once, and ingestion "
                "streams twice)")
        self._factory = factory
        self._n = None if num_rows is None else int(num_rows)
        self._F = None if num_features is None else int(num_features)
        self._names = list(feature_names) if feature_names else None
        if chunk_rows is not None:
            self.chunk_rows = int(chunk_rows)

    def num_rows(self) -> Optional[int]:
        return self._n

    def num_features(self) -> Optional[int]:
        return self._F

    def feature_names(self) -> Optional[List[str]]:
        return self._names

    def chunks(self) -> Iterator[RowChunk]:
        for obj in self._factory():
            yield _as_chunk(obj)


class SequenceChunkSource(RowChunkSource):
    """Adapter over ``lightgbm_tpu.Sequence`` objects (or a list of
    them): batches are pulled ``batch_size`` rows at a time, so the
    caller-side source never needs to be materialized at once."""

    def __init__(self, seqs, chunk_rows: Optional[int] = None):
        self._seqs = list(seqs)
        if chunk_rows is not None:
            self.chunk_rows = int(chunk_rows)
        else:
            self.chunk_rows = max(
                int(getattr(s, "batch_size", 0) or 0)
                for s in self._seqs) or DEFAULT_CHUNK_ROWS

    def num_rows(self) -> int:
        return int(sum(len(s) for s in self._seqs))

    def chunks(self) -> Iterator[RowChunk]:
        for s in self._seqs:
            n = len(s)
            bs = max(1, int(getattr(s, "batch_size", 0) or 0)
                     or self.chunk_rows)
            for lo in range(0, n, bs):
                yield _as_chunk(np.atleast_2d(np.asarray(
                    s[lo:min(lo + bs, n)], dtype=np.float64)))


class CSVChunkSource(RowChunkSource):
    """Stream a dense CSV/TSV/whitespace text file in row chunks; the
    label column is split out per chunk (``label_column`` index or
    ``name:<col>`` against the header). LibSVM files are ragged and
    not supported here (the eager loader handles them)."""

    def __init__(self, path: str, chunk_rows: int = DEFAULT_CHUNK_ROWS,
                 header: bool = False, label_column: str = ""):
        self.path = os.fspath(path)
        self.chunk_rows = max(1, int(chunk_rows))
        self.header = bool(header)
        with open(self.path, "r") as f:
            first = f.readline().strip()
        if not first:
            raise _err(f"empty data file {self.path}")
        self._sep = "\t" if "\t" in first else \
            ("," if "," in first else None)
        tokens = first.replace(",", " ").replace("\t", " ").split()
        if any(":" in t for t in tokens[1:]):
            raise _err(
                "chunked ingestion does not support LibSVM files "
                "(ragged rows); drop ingest_chunk_rows to use the "
                "eager loader")
        self._header_names = None
        if self.header:
            self._header_names = [
                t.strip() for t in (first.split(self._sep) if self._sep
                                    else first.split())]
        self.label_col = self._resolve_label_col(str(label_column))

    def _resolve_label_col(self, lc: str) -> int:
        if lc.startswith("name:"):
            want = lc[len("name:"):]
            if not self._header_names:
                raise _err(
                    "label_column='name:...' requires header=true")
            if want not in self._header_names:
                raise _err(
                    f"label column '{want}' not found in header: "
                    f"{self._header_names}")
            return self._header_names.index(want)
        return int(lc) if lc else 0

    def feature_names(self) -> Optional[List[str]]:
        if not self._header_names:
            return None
        return [c for i, c in enumerate(self._header_names)
                if i != self.label_col]

    def _parse(self, lines: List[str]) -> np.ndarray:
        try:
            arr = np.loadtxt(lines, delimiter=self._sep, ndmin=2)
        except ValueError:
            arr = np.genfromtxt(lines, delimiter=self._sep)
            if arr.ndim == 1:
                arr = arr[None, :] if len(lines) == 1 else arr[:, None]
        return arr

    def chunks(self) -> Iterator[RowChunk]:
        with open(self.path, "r") as f:
            if self.header:
                f.readline()
            buf: List[str] = []
            for line in f:
                if not line.strip():
                    continue
                buf.append(line)
                if len(buf) == self.chunk_rows:
                    yield self._emit(buf)
                    buf = []
            if buf:
                yield self._emit(buf)

    def _emit(self, buf: List[str]) -> RowChunk:
        arr = self._parse(buf)
        y = arr[:, self.label_col].copy()
        X = np.delete(arr, self.label_col, axis=1)
        return RowChunk(X, y, None)


class ArrowChunkSource(RowChunkSource):
    """Optional pyarrow adapter: an in-memory ``pyarrow.Table`` /
    ``RecordBatch`` or a parquet file path, streamed as record
    batches. Import-guarded — constructing one without pyarrow raises
    a clear :class:`LightGBMError`, nothing else in the package ever
    imports pyarrow."""

    def __init__(self, data, chunk_rows: int = DEFAULT_CHUNK_ROWS,
                 label_column: Optional[str] = None):
        try:
            import pyarrow as pa  # noqa: F401
        except ImportError as e:
            raise _err(
                "ArrowChunkSource requires pyarrow, which is not "
                "installed") from e
        self.chunk_rows = max(1, int(chunk_rows))
        self.label_column = label_column
        self._path = None
        self._table = None
        if isinstance(data, (str, os.PathLike)):
            self._path = os.fspath(data)
        else:
            import pyarrow as pa
            if isinstance(data, pa.RecordBatch):
                data = pa.Table.from_batches([data])
            if not isinstance(data, pa.Table):
                raise _err(
                    f"ArrowChunkSource needs a pyarrow Table/"
                    f"RecordBatch or a parquet path, got {type(data)}")
            self._table = data

    def _schema_names(self) -> List[str]:
        if self._table is not None:
            return list(self._table.column_names)
        import pyarrow.parquet as pq
        return list(pq.ParquetFile(self._path).schema_arrow.names)

    def num_rows(self) -> Optional[int]:
        if self._table is not None:
            return int(self._table.num_rows)
        import pyarrow.parquet as pq
        return int(pq.ParquetFile(self._path).metadata.num_rows)

    def num_features(self) -> int:
        names = self._schema_names()
        return len(names) - (1 if self.label_column in names else 0)

    def feature_names(self) -> List[str]:
        return [c for c in self._schema_names() if c != self.label_column]

    def _batches(self):
        if self._table is not None:
            yield from self._table.to_batches(
                max_chunksize=self.chunk_rows)
            return
        import pyarrow.parquet as pq
        yield from pq.ParquetFile(self._path).iter_batches(
            batch_size=self.chunk_rows)

    def chunks(self) -> Iterator[RowChunk]:
        for batch in self._batches():
            cols, y = [], None
            for name in batch.schema.names:
                np_col = np.asarray(batch.column(name).to_numpy(
                    zero_copy_only=False), dtype=np.float64)
                if name == self.label_column:
                    y = np_col
                else:
                    cols.append(np_col)
            X = np.column_stack(cols) if cols else \
                np.zeros((batch.num_rows, 0))
            yield _as_chunk((X, y))


def _resolve_arrow_label(src: "ArrowChunkSource",
                         lc: str) -> Optional[str]:
    """Map ``cfg.label_column`` (``name:<col>`` or an index; the same
    spec the text loaders honor) onto an Arrow schema column name —
    silently ignoring it would train on the label as a feature."""
    names = src._schema_names()
    if lc.startswith("name:"):
        want = lc[len("name:"):]
        if want not in names:
            raise _err(f"label column '{want}' not found in the "
                       f"Arrow schema: {names}")
        return want
    idx = int(lc) if lc else 0
    if not 0 <= idx < len(names):
        raise _err(f"label_column index {idx} out of range for the "
                   f"{len(names)}-column Arrow schema")
    return names[idx]


def coerce_chunk_source(data, cfg) -> Optional[RowChunkSource]:
    """Map ``Dataset(data=...)`` inputs onto a chunk source, or return
    None for inputs the eager constructor should keep handling.

    Streams unconditionally: RowChunkSource instances, zero-arg chunk
    factories (callables), and ``Sequence`` objects / lists of them.
    Streams when ``ingest_chunk_rows > 0``: text-file paths (CSV/TSV;
    the dedicated ``two_round`` loader and LibSVM keep the legacy
    path) and parquet paths / pyarrow tables.
    """
    chunk_rows = int(getattr(cfg, "ingest_chunk_rows", 0) or 0)

    if isinstance(data, RowChunkSource):
        if chunk_rows > 0:
            data.chunk_rows = chunk_rows
        return data
    # late import: basic.py imports this module, so the Sequence class
    # is looked up through the package attribute at call time
    from ..basic import Sequence
    if isinstance(data, Sequence):
        return SequenceChunkSource([data],
                                   chunk_rows=chunk_rows or None)
    if isinstance(data, (list, tuple)) and data \
            and all(isinstance(s, Sequence) for s in data):
        return SequenceChunkSource(list(data),
                                   chunk_rows=chunk_rows or None)
    if callable(data) and not isinstance(data, type):
        return GeneratorChunkSource(data,
                                    chunk_rows=chunk_rows or None)
    if chunk_rows <= 0:
        return None
    if isinstance(data, (str, os.PathLike)):
        path = os.fspath(data)
        if path.endswith((".parquet", ".pq")):
            src = ArrowChunkSource(path, chunk_rows=chunk_rows)
            src.label_column = _resolve_arrow_label(
                src, str(cfg.label_column))
            return src
        try:
            with open(path, "r") as f:
                first = f.readline().strip()
        except OSError:
            # missing/unreadable file: fall through so the eager
            # loader raises its usual error regardless of
            # ingest_chunk_rows
            return None
        tokens = first.replace(",", " ").replace("\t", " ").split()
        if any(":" in t for t in tokens[1:]):
            return None  # LibSVM rows are ragged; eager loader handles
        if getattr(cfg, "two_round", False):
            from ..utils.log import log_warning
            log_warning(
                "ingest_chunk_rows > 0 streams this file through the "
                "chunked two-pass pipeline; two_round=true is "
                "superseded (the loaders sample differently, so bin "
                "boundaries may differ from previous two_round runs)")
        return CSVChunkSource(path, chunk_rows=chunk_rows,
                              header=bool(cfg.header),
                              label_column=str(cfg.label_column))
    if type(data).__module__.split(".")[0] == "pyarrow":
        src = ArrowChunkSource(data, chunk_rows=chunk_rows)
        if cfg.label_column:
            src.label_column = _resolve_arrow_label(
                src, str(cfg.label_column))
        return src
    return None
