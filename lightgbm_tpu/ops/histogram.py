"""Histogram construction: the GBDT hot loop, TPU-style.

Re-design of the reference's histogram kernels
(/root/reference/src/io/dense_bin.hpp:99 ``ConstructHistogramInner``,
src/treelearner/cuda/cuda_histogram_constructor.cu:18): per-row (grad, hess,
count) scatter-add into ``[num_features, num_bins, 3]`` accumulators.

Design notes (TPU-first):
- The bin matrix is stored transposed ``[F, n]`` (column-major, like the
  reference's DenseBin) so one feature's bins are a contiguous vector.
- The fast path is the *nibble decomposition*: a bin index b = 16*hi + lo
  turns the histogram into HI^T @ (LO * payload) — dense batched matmuls
  that ride the MXU instead of scatter hardware (which XLA serializes on
  TPU). Float payloads accumulate in f32 at HIGHEST precision; quantized
  int8 payloads accumulate exactly in int32 on the int MXU.
- There is no most-frequent-bin omission / ``FixHistogram`` reconstruction
  (dataset.h:760): every bin is accumulated directly, which on TPU costs
  nothing extra and removes a cross-rank reconstruction step.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["build_histogram", "subtract_histogram", "hist_from_rows",
           "hist_from_rows_int", "PACK"]

PACK = 8          # features per MXU pack (PACK * 16 = 128 lanes)
ROW_BLOCK = 8192  # rows per accumulation block (bounds one-hot residency)


def _nibble_hist_block(rows: jnp.ndarray, payload: jnp.ndarray,
                       s_hi: int, accum_dtype) -> jnp.ndarray:
    """One row-block of the nibble-decomposed MXU histogram.

    ``hist[f, b] = sum_r [bins[r,f]==b] * payload[r]`` with ``b = 16*hi+lo``
    factors into ``sum_r HI[r, f*s_hi+hi] * LO[r, f*16+lo] * payload[r]``:
    a dense [x, S] x [S, y*c] batched matmul over PACK-feature groups —
    the MXU replacement for the CUDA shared-memory scatter-add
    (/root/reference/src/treelearner/cuda/cuda_histogram_constructor.cu:18).
    Cross-feature (p != q) blocks of the product are computed and
    discarded; the MXU does them for free within the 128-lane tile.

    Float payloads run at HIGHEST precision (true f32 accumulation; the
    bf16 MXU default would corrupt the count channel). int8 payloads
    accumulate exactly in int32 — the quantized-gradient path
    (gradient_discretizer.hpp; cuda_histogram_constructor.cu:250-448).

    Args:
      rows: ``[S, npacks, PACK]`` int32 bin values.
      payload: ``[S, C]`` float or int8 channels (g, h, count-weight).
    Returns:
      ``[npacks, PACK, s_hi * 16, C]`` partial histograms.
    """
    S, npacks, P = rows.shape
    C = payload.shape[-1]
    onehot_dtype = payload.dtype
    is_int = jnp.issubdtype(accum_dtype, jnp.integer)
    hi = rows // 16
    lo = rows & 15
    HI = (hi[..., None] == jnp.arange(s_hi)).astype(onehot_dtype)
    LO = (lo[..., None] == jnp.arange(16)).astype(onehot_dtype)
    LOC = LO[..., None] * payload[:, None, None, None, :]  # [S,np,P,16,C]
    out = jnp.einsum(
        "snx,snyc->nxyc",
        HI.reshape(S, npacks, P * s_hi),
        LOC.reshape(S, npacks, P * 16, C),
        preferred_element_type=accum_dtype,
        precision=None if is_int else lax.Precision.HIGHEST)
    d = jnp.diagonal(out.reshape(npacks, P, s_hi, P, 16, C),
                     axis1=1, axis2=3)                    # [np,hi,16,C,P]
    return d.transpose(0, 4, 1, 2, 3).reshape(npacks, P, s_hi * 16, C)


def _hist_from_rows_impl(rows: jnp.ndarray, payload: jnp.ndarray,
                         num_bins: int, method: str,
                         accum_dtype) -> jnp.ndarray:
    if method == "scatter":
        return _hist_scatter(rows.T, payload.astype(accum_dtype), num_bins)
    S, F = rows.shape
    C = payload.shape[-1]
    s_hi = -(-num_bins // 16)
    f_pad = (-F) % PACK
    if f_pad:
        rows = jnp.pad(rows, ((0, 0), (0, f_pad)))
    Fp = F + f_pad
    npacks = Fp // PACK
    rows = rows.astype(jnp.int32).reshape(S, npacks, PACK)

    if S <= ROW_BLOCK:
        h = _nibble_hist_block(rows, payload, s_hi, accum_dtype)
    else:
        nblk = -(-S // ROW_BLOCK)
        pad = nblk * ROW_BLOCK - S
        if pad:
            rows = jnp.pad(rows, ((0, pad), (0, 0), (0, 0)))
            payload = jnp.pad(payload, ((0, pad), (0, 0)))
        rows_b = rows.reshape(nblk, ROW_BLOCK, npacks, PACK)
        pay_b = payload.reshape(nblk, ROW_BLOCK, C)

        def body(acc, xs):
            r, p = xs
            return acc + _nibble_hist_block(r, p, s_hi, accum_dtype), None

        init = jnp.zeros((npacks, PACK, s_hi * 16, C), accum_dtype)
        h, _ = lax.scan(body, init, (rows_b, pay_b))
    h = h.reshape(Fp, s_hi * 16, C)
    return h[:F, :num_bins, :]


def hist_from_rows(rows: jnp.ndarray, payload: jnp.ndarray,
                   num_bins: int, method: str = "mxu") -> jnp.ndarray:
    """Float histogram over a row-block matrix.

    Args:
      rows: ``[S, F]`` integer bin matrix (row-major).
      payload: ``[S, C]`` float per-row channels.
      num_bins: B.
      method: "mxu" (nibble matmul) or "scatter" (CPU-friendly).
    Returns:
      ``[F, B, C]`` histograms (padding features report zeros only if the
      caller masked their payload; callers crop to the true F).
    """
    return _hist_from_rows_impl(rows, payload, num_bins, method,
                                payload.dtype)


def hist_from_rows_int(rows: jnp.ndarray, payload: jnp.ndarray,
                       num_bins: int, method: str = "mxu") -> jnp.ndarray:
    """Quantized histogram: int8 payload, exact int32 accumulation
    (subtraction-safe)."""
    return _hist_from_rows_impl(rows, payload, num_bins, method, jnp.int32)


def _hist_mxu(bins_T: jnp.ndarray, gh: jnp.ndarray,
              num_bins: int) -> jnp.ndarray:
    """Full-pass MXU histogram from the feature-major bin matrix."""
    return hist_from_rows(bins_T.T, gh, num_bins)


def _hist_scatter(bins_T: jnp.ndarray, gh: jnp.ndarray, num_bins: int,
                  unroll: int = 1) -> jnp.ndarray:
    """Scatter-add path: lax.scan over features, one scatter per feature."""

    def body(carry, bins_f):
        hist = jnp.zeros((num_bins, gh.shape[-1]), dtype=gh.dtype)
        hist = hist.at[bins_f].add(gh, mode="drop")
        return carry, hist

    _, hists = lax.scan(body, None, bins_T, unroll=unroll)
    return hists


def _hist_onehot(bins_T: jnp.ndarray, gh: jnp.ndarray,
                 num_bins: int, block: int = 8192) -> jnp.ndarray:
    """One-hot matmul path: rides the MXU instead of scatter hardware.

    hist[f, b, c] = sum_r onehot(bins[f, r], b) * gh[r, c], computed in
    row blocks so the one-hot tensor stays small. Superseded by the
    nibble decomposition (16x fewer padded FLOPs at 256 bins); kept as a
    cross-check reference.
    """
    F, n = bins_T.shape
    C = gh.shape[-1]
    pad = (-n) % block
    if pad:
        bins_T = jnp.pad(bins_T, ((0, 0), (0, pad)), constant_values=0)
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    nblk = bins_T.shape[1] // block
    bins_blk = bins_T.reshape(F, nblk, block).transpose(1, 0, 2)
    gh_blk = gh.reshape(nblk, block, C)

    def body(acc, xs):
        b, g = xs
        onehot = jax.nn.one_hot(b, num_bins, dtype=gh.dtype)  # [F, blk, B]
        acc = acc + jnp.einsum(
            "frb,rc->fbc", onehot, g,
            preferred_element_type=gh.dtype,
            precision=lax.Precision.HIGHEST)
        return acc, None

    init = jnp.zeros((F, num_bins, C), dtype=gh.dtype)
    hists, _ = lax.scan(body, init, (bins_blk, gh_blk))
    return hists


def build_histogram(bins_T: jnp.ndarray,
                    grad: jnp.ndarray,
                    hess: jnp.ndarray,
                    row_weight: jnp.ndarray,
                    mask: jnp.ndarray,
                    num_bins: int,
                    method: str = "scatter") -> jnp.ndarray:
    """Build per-feature histograms for the rows selected by ``mask``.

    Args:
      bins_T: ``[F, n]`` integer bin matrix (feature-major).
      grad, hess: ``[n]`` float gradients/hessians.
      row_weight: ``[n]`` sampling weight (bagging mask / GOSS amplification);
        contributes the histogram's count channel.
      mask: ``[n]`` bool leaf-membership mask.
      num_bins: global max number of bins B.

    Returns:
      ``[F, B, 3]`` float array of (sum_grad, sum_hess, count).
    """
    m = mask.astype(grad.dtype) * row_weight.astype(grad.dtype)
    gh = jnp.stack([grad * m, hess * m, m], axis=-1)  # [n, 3]
    if method == "onehot":
        return _hist_onehot(bins_T, gh, num_bins)
    if method == "mxu":
        return _hist_mxu(bins_T, gh, num_bins)
    return _hist_scatter(bins_T, gh, num_bins)


def subtract_histogram(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """The histogram-subtraction trick: sibling = parent - child
    (serial_tree_learner.cpp:473-520)."""
    return parent - child
