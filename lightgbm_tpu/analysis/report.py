"""Finding renderers: human text, machine JSON, and SARIF 2.1.0.

SARIF (``lint --format sarif``) is the exchange format code-review
tooling ingests (GitHub code scanning, VS Code SARIF viewers): one
``run`` with the tpulint driver + rule catalog, one ``result`` per
non-baselined finding, with the stable line-number-free finding id in
``partialFingerprints`` so review systems track findings across code
motion exactly like the baseline does.
"""

from __future__ import annotations

import json
from typing import List

__all__ = ["render_text", "render_json", "render_sarif"]

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def render_text(result) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f"{f.relpath}:{f.lineno}:{f.col + 1}: "
                     f"{f.rule} [{f.fid}]")
        lines.append(f"    {f.message}")
    if result.stale_baseline:
        lines.append("")
        lines.append("stale baseline entries (finding no longer "
                     "occurs — delete them):")
        for e in result.stale_baseline:
            lines.append(f"    {e.fid}")
    if getattr(result, "stale_budget", None):
        lines.append("")
        lines.append("stale ir_budgets.json entries (no spec lowers "
                     "this entry point — delete them):")
        for e in result.stale_budget:
            lines.append(f"    {e.fid}")
    if getattr(result, "unjustified_budget", None):
        lines.append("")
        lines.append("unjustified ir_budgets.json entries (every "
                     "budget needs a real justification):")
        for e in result.unjustified_budget:
            lines.append(f"    {e.fid}")
    n = len(result.findings)
    b = len(result.baselined)
    ir = getattr(result, "ir_entries", None)
    lines.append("")
    lines.append(
        f"tpulint: {n} finding{'s' if n != 1 else ''}"
        + (f" ({b} baselined and suppressed)" if b else "")
        + f", {len(result.files)} files, "
        f"{len(result.graph.jit_reachable)} jit-reachable functions"
        + (f", {len(ir)} IR entries lowered" if ir else "")
        + f", {result.elapsed:.2f}s")
    return "\n".join(lines)


def render_json(result) -> str:
    def fdict(f):
        return {"id": f.fid, "rule": f.rule, "path": f.relpath,
                "line": f.lineno, "col": f.col + 1, "function": f.func,
                "symbol": f.symbol, "message": f.message}

    return json.dumps({
        "findings": [fdict(f) for f in result.findings],
        "baselined": [fdict(f) for f in result.baselined],
        "stale_baseline": [e.fid for e in result.stale_baseline],
        "stale_budget": [e.fid for e in
                         getattr(result, "stale_budget", [])],
        "unjustified_budget": [e.fid for e in
                               getattr(result, "unjustified_budget",
                                       [])],
        "ir_entries": list(getattr(result, "ir_entries", [])),
        "files": sorted(result.files),
        "jit_reachable": sorted(
            f"{p}:{q}" for (p, q) in result.graph.jit_reachable),
        "elapsed_seconds": result.elapsed,
    }, indent=2, sort_keys=False)


def render_sarif(result) -> str:
    """SARIF 2.1.0 — attachable to code-review tooling. Non-baselined
    findings become ``results``; baselined ones ride along with a
    ``suppressions`` entry so reviewers see the accepted set too."""
    from .rules import ALL_RULES, IR_RULES

    pkg = ""
    for s in result.graph.scans.values():
        pkg = s.module.split(".", 1)[0]
        break

    def _result(f, suppressed: bool):
        out = {
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f"{pkg}/{f.relpath}" if pkg
                               else f.relpath,
                        "uriBaseId": "SRCROOT",
                    },
                    # IR findings without a source anchor carry line
                    # 0; SARIF requires startLine >= 1
                    "region": {"startLine": max(f.lineno, 1),
                               "startColumn": f.col + 1},
                },
                "logicalLocations": [{
                    "name": f.func,
                    "kind": "function",
                }],
            }],
            "partialFingerprints": {"tpulintFindingId/v1": f.fid},
        }
        if suppressed:
            out["suppressions"] = [{
                "kind": "external",
                "justification": "accepted in tools/"
                                 "tpulint_baseline.txt",
            }]
        return out

    payload = {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri":
                    "docs/STATIC_ANALYSIS.md",
                "rules": [{
                    "id": r.id,
                    "shortDescription": {"text": r.title},
                    "helpUri": "docs/STATIC_ANALYSIS.md",
                } for r in ALL_RULES + IR_RULES],
            }},
            "results": [_result(f, False) for f in result.findings]
            + [_result(f, True) for f in result.baselined],
        }],
    }
    return json.dumps(payload, indent=2)
