# tpulint fixture: TPL008 negative — the scrape endpoint of
# tpl008_export_pos.py with the shared bookkeeping correctly guarded:
# every handler-thread mutation and every main-path read goes through
# the one module lock, so the rule's lock-acquisition proof discharges
# all of them.
import http.server
import socketserver
import threading

_scrape_lock = threading.Lock()
_scrapes = {}          # port -> scrape count, shared with readers


class ScrapeHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        with _scrape_lock:
            port = self.server.server_address[1]
            _scrapes[port] = _scrapes.get(port, 0) + 1
        self.send_response(200)
        self.end_headers()


class ProtocolHandler(socketserver.StreamRequestHandler):
    def handle(self):
        with _scrape_lock:
            _scrapes["protocol"] = _scrapes.get("protocol", 0) + 1


def scrape_count(port):
    with _scrape_lock:
        return _scrapes.get(port, 0)


def start(port):
    server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                             ScrapeHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
