"""Benchmark: boosting iterations/sec on a Higgs-shaped synthetic dataset.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): reference LightGBM trains Higgs-10M (10.5M x 28,
255 bins, 255 leaves) at 500 iters / 130.094 s = 3.843 iters/sec on a
28-thread 2x E5-2670v2 (docs/Experiments.rst:111-123). ``vs_baseline`` is
our iters/sec divided by that number. Rows/leaves are env-tunable because
round-1 histogram kernels still do full-row masked passes; the measured
rate is linearly rescaled to the full 10.5M-row workload for an honest
comparison (rate_full = rate_small * n_small / n_full).
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_ITERS_PER_SEC = 500.0 / 130.094
HIGGS_ROWS = 10_500_000

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
N_FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_BINS", 255))
WARMUP = int(os.environ.get("BENCH_WARMUP", 2))
ITERS = int(os.environ.get("BENCH_ITERS", 8))


def make_higgs_like(n, f, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f).astype(np.float32)
    coef = rs.randn(f).astype(np.float32)
    logits = X @ coef * 0.5 + 0.5 * rs.randn(n).astype(np.float32)
    y = (logits > 0).astype(np.float32)
    return X.astype(np.float64), y.astype(np.float64)


def main():
    import jax
    import lightgbm_tpu as lgb

    X, y = make_higgs_like(N_ROWS, N_FEATURES)
    ds = lgb.Dataset(X, label=y, params={"max_bin": MAX_BIN})
    ds.construct()
    del X

    bst = lgb.Booster(
        params={
            "objective": "binary",
            "num_leaves": NUM_LEAVES,
            "max_bin": MAX_BIN,
            "learning_rate": 0.1,
            "verbosity": -1,
        },
        train_set=ds)

    for _ in range(WARMUP):
        bst._engine.train_one_iter()
    bst._engine.score.block_until_ready()

    t0 = time.time()
    for _ in range(ITERS):
        bst._engine.train_one_iter()
    bst._engine.score.block_until_ready()
    dt = time.time() - t0

    iters_per_sec = ITERS / dt
    # linear rescale to the full Higgs row count (histogram work is O(rows))
    iters_per_sec_full = iters_per_sec * (N_ROWS / HIGGS_ROWS)
    result = {
        "metric": f"boosting iters/sec, Higgs-shaped {N_ROWS}x{N_FEATURES} "
                  f"(rescaled to 10.5M rows), {NUM_LEAVES} leaves, "
                  f"{MAX_BIN} bins, backend={jax.default_backend()}",
        "value": round(iters_per_sec_full, 4),
        "unit": "iters/sec",
        "vs_baseline": round(iters_per_sec_full / BASELINE_ITERS_PER_SEC, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
