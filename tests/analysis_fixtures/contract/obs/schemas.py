"""Mini single-source registry for the contract-pass fixtures
(TPL015-TPL018). Same literal-dict shape as the real
lightgbm_tpu/obs/schemas.py — the rules literal-eval THIS tree's copy,
so fixture findings never depend on the installed package."""

EVENTS = {
    "ping": {
        "doc": "one line per ping",
        "required": ("event", "seq"),
        "optional": ("note",),
    },
    "pong": {
        "doc": "one line per pong",
        "required": ("event",),
        "optional": ("latency",),
    },
}

METRICS = {
    "pings": {"kind": "counter", "labels": (), "doc": "pings sent"},
    "ping_depth": {"kind": "gauge", "labels": ("lane",),
                   "doc": "queue depth per lane"},
    "ping_ms": {"kind": "histogram", "labels": (),
                "doc": "ping latency"},
}

EXPORT_FAMILIES = {}

ENV_VARS = {
    "LIGHTGBM_TPU_PING": {"default": "1", "kind": "str",
                          "doc": "ping cadence"},
    "LIGHTGBM_TPU_PONG": {"default": None, "kind": "str",
                          "doc": "pong path (unset: disabled)"},
}

FAULT_KINDS = {
    "ping_kill": {"one_shot": True, "doc": "kill the pinger once"},
    "ping_slow": {"one_shot": False, "doc": "slow every ping"},
}

FAULT_EVENT_KINDS = {
    "ping_seen": {"doc": "observational: a ping was observed"},
}
