"""Generate docs/PARAMETERS.md from the Config dataclass.

The reference generates docs/Parameters.rst from config.h's annotated
struct via .ci/parameter-generator.py (config.h:1-10 header comment) —
the single-source-of-truth pattern this framework keeps: the dataclass
in ``lightgbm_tpu/config.py`` is the one place parameter names,
defaults, aliases and bounds live, and this script renders them.

Usage:  python tools/gen_parameters_doc.py [--check]
  --check: exit 1 if docs/PARAMETERS.md is out of sync (the
           tests/test_new_params.py sync test runs this).
"""

from __future__ import annotations

import dataclasses
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from lightgbm_tpu.config import ALIASES, Config  # noqa: E402

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
OUT = os.path.join(REPO, "docs", "PARAMETERS.md")

_SECTION_RE = re.compile(r"^\s*#\s*----\s*(.+?)\s*----\s*$")
_FIELD_RE = re.compile(r"^\s{4}(\w+)\s*:\s*[\w\[\]\., ]+\s*(?:=|$)")


def _field_sections():
    """Map field -> section title by scanning the dataclass source for
    ``# ---- section ----`` markers (comments aren't in the AST)."""
    src_path = os.path.join(REPO, "lightgbm_tpu", "config.py")
    with open(src_path) as f:
        lines = f.readlines()
    sections = {}
    current = "core"
    in_class = False
    for ln in lines:
        if ln.startswith("class Config"):
            in_class = True
            continue
        if not in_class:
            continue
        if ln.startswith("    _BOUNDS"):
            break
        m = _SECTION_RE.match(ln)
        if m:
            current = m.group(1)
            continue
        m = _FIELD_RE.match(ln)
        if m and not ln.strip().startswith("#"):
            sections[m.group(1)] = current
    return sections


def _fmt_default(v):
    if isinstance(v, str):
        return f'`"{v}"`'
    if isinstance(v, bool):
        return f"`{str(v).lower()}`"
    if isinstance(v, (list, dict)):
        return "`[]`" if v == [] else f"`{v}`"
    return f"`{v}`"


def _fmt_bounds(spec):
    lo, hi = spec[0], spec[1]
    strict = len(spec) > 2 and spec[2] == "gt"
    parts = []
    if lo is not None:
        parts.append(f"{'>' if strict else '>='} {lo}")
    if hi is not None:
        parts.append(f"<= {hi}")
    return ", ".join(parts) if parts else ""


def render() -> str:
    sections = _field_sections()
    rev_alias = {}
    for alias, canon in ALIASES.items():
        rev_alias.setdefault(canon, []).append(alias)

    by_section = {}
    for f in dataclasses.fields(Config):
        if f.name == "extra":
            continue  # internal catch-all, not a parameter
        sec = sections.get(f.name, "core")
        by_section.setdefault(sec, []).append(f)

    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` (the single source of",
        "truth — the reference generates docs/Parameters.rst from",
        "config.h the same way, via `.ci/parameter-generator.py`).",
        "",
        "Do NOT edit by hand; run `python tools/gen_parameters_doc.py`.",
        "",
    ]
    bounds = Config._BOUNDS
    for sec, fields_ in by_section.items():
        lines.append(f"## {sec}")
        lines.append("")
        lines.append("| parameter | default | constraints | aliases |")
        lines.append("|---|---|---|---|")
        for f in fields_:
            if f.default is not dataclasses.MISSING:
                dflt = _fmt_default(f.default)
            elif f.default_factory is not dataclasses.MISSING:
                dflt = _fmt_default(f.default_factory())
            else:
                dflt = ""
            b = _fmt_bounds(bounds[f.name]) if f.name in bounds else ""
            al = ", ".join(f"`{a}`" for a in
                           sorted(rev_alias.get(f.name, [])))
            lines.append(f"| `{f.name}` | {dflt} | {b} | {al} |")
        lines.append("")
    lines.append(f"Total: {sum(len(v) for v in by_section.values())} "
                 f"parameters, {len(ALIASES)} aliases.")
    lines.append("")
    return "\n".join(lines)


def main():
    text = render()
    if "--check" in sys.argv:
        try:
            with open(OUT) as f:
                on_disk = f.read()
        except OSError:
            print(f"{OUT} missing; run tools/gen_parameters_doc.py")
            sys.exit(1)
        if on_disk != text:
            print(f"{OUT} is OUT OF SYNC with config.py; "
                  "run tools/gen_parameters_doc.py")
            sys.exit(1)
        print("PARAMETERS.md in sync")
        return
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write(text)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
