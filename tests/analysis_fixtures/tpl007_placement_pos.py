# tpulint fixture: TPL007 positive — the parallel/placement.py
# host-sync sites (docs/SHARDING.md). The per-rank upload barrier and
# the sharded-checkpoint gather are world-joining collectives one
# level above hostsync: rank-guarding a call site skips a world join
# exactly like skipping the underlying allgather.
import jax

from lightgbm_tpu.parallel.placement import fetch_global, upload_barrier


def rank_gated_upload_barrier(shards):
    """Only rank 0 joins the post-placement barrier: every other rank
    sails into the first training collective while rank 0 waits."""
    if jax.process_index() == 0:
        # EXPECT: TPL007
        upload_barrier("bad/rank_gated_upload")
    return shards


def early_return_before_checkpoint_gather(score):
    """The PR 2 checkpoint shape done WRONG: the rank gate placed
    above the sharded-score assembly instead of below it — rank 0
    hangs alone in the gather."""
    if jax.process_index() != 0:
        return None
    # EXPECT: TPL007
    return fetch_global(score)


def gather_in_recovery_handler(score):
    """Only the ranks that hit the exception join the re-assembly."""
    try:
        out = fetch_global(score)
    except RuntimeError:
        # EXPECT: TPL007
        out = fetch_global(score)
    return out
