"""Per-component decomposition of one 10.5M-row boosting iteration on
the REAL booster state (the bench's exact data/config): full
train_one_iter vs gradients / grow_tree / gather_small contrib /
score add / pack_tree_device in isolation. Run on TPU:
    python benchmarks/decompose_iter.py
(Needs ~25 min: 10.5M construct + first compiles.)"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import time, numpy as np, jax, jax.numpy as jnp
import lightgbm_tpu as lgb
from lightgbm_tpu.ops.gather import gather_small

N, F = 10_500_000, 28
rs = np.random.RandomState(0)
X = rs.randn(N, F).astype(np.float32)
coef = rs.randn(F).astype(np.float32)
y = ((X @ coef) > 0).astype(np.float64)
t0=time.perf_counter()
ds = lgb.Dataset(X.astype(np.float64), label=y, params={"max_bin": 255})
ds.construct()
print(f"construct: {time.perf_counter()-t0:.1f} s", flush=True)
del X
bst = lgb.Booster(params={"objective": "binary", "num_leaves": 255,
                          "max_bin": 255, "learning_rate": 0.1,
                          "verbosity": -1}, train_set=ds)
eng = bst._engine
t0=time.perf_counter()
eng.train_one_iter(); eng.score.block_until_ready()
print(f"warmup iter (incl compile): {time.perf_counter()-t0:.1f} s", flush=True)

t0 = time.perf_counter()
for _ in range(5):
    eng.train_one_iter()
eng.score.block_until_ready()
full = (time.perf_counter() - t0) / 5
print(f"full train_one_iter: {full*1e3:.1f} ms", flush=True)

grad, hess = eng._gradients(eng.score)
jax.block_until_ready((grad, hess))
t0 = time.perf_counter()
for _ in range(5):
    g, h = eng._gradients(eng.score)
jax.block_until_ready((g, h))
print(f"gradients: {(time.perf_counter()-t0)/5*1e3:.1f} ms", flush=True)

row_w = eng._row_weights(0, grad[0], hess[0])
fmask = eng._feature_mask()
args = (eng.bins_T, grad[0], hess[0], row_w, fmask,
        eng.feat_num_bins, eng.feat_nan_bin)
from lightgbm_tpu.ops.grow import grow_tree
out = grow_tree(eng.grow_cfg, *args)
jax.block_until_ready(out)
t0 = time.perf_counter()
for _ in range(3):
    dev_tree, row_leaf = grow_tree(eng.grow_cfg, *args)
jax.block_until_ready((dev_tree, row_leaf))
print(f"grow_tree: {(time.perf_counter()-t0)/3*1e3:.1f} ms", flush=True)

lv = dev_tree.leaf_value
c = gather_small(lv, row_leaf)
jax.block_until_ready(c)
t0 = time.perf_counter()
for _ in range(5):
    c = gather_small(lv, row_leaf)
jax.block_until_ready(c)
print(f"gather_small contrib: {(time.perf_counter()-t0)/5*1e3:.1f} ms", flush=True)

s = eng.score
s2 = s.at[0].add(c * 0.1)
jax.block_until_ready(s2)
t0 = time.perf_counter()
for _ in range(5):
    s2 = s.at[0].add(c * 0.1)
jax.block_until_ready(s2)
print(f"score add: {(time.perf_counter()-t0)/5*1e3:.1f} ms", flush=True)

from lightgbm_tpu.models.tree import pack_tree_device
v, m = pack_tree_device(dev_tree)
jax.block_until_ready((v, m))
t0 = time.perf_counter()
for _ in range(5):
    v, m = pack_tree_device(dev_tree)
jax.block_until_ready((v, m))
print(f"pack_tree_device: {(time.perf_counter()-t0)/5*1e3:.1f} ms", flush=True)

# bagging/_row_weights and feature mask
t0 = time.perf_counter()
for _ in range(5):
    rw = eng._row_weights(3, grad[0], hess[0])
jax.block_until_ready(rw)
print(f"row_weights: {(time.perf_counter()-t0)/5*1e3:.1f} ms", flush=True)
