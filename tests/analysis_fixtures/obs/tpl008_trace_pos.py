# tpulint fixture: TPL008 positive — a span recorder (the obs/trace.py
# shape) whose buffer is appended from request/trainer threads and
# snapshot-and-cleared from a recorder drain thread, with NO lock on
# either side. This is the strip-the-span-lock acceptance shape:
# obs/tpl008_trace_neg.py is the same recorder WITH _spans_lock, and
# removing it must re-surface these findings.
import threading

_spans = []           # span buffer, shared with the drain thread
_spans_dropped = 0
_SPANS_CAP = 4096


def record_span(name, dur):
    global _spans_dropped
    ev = {"event": "span", "name": name, "dur": dur}
    if len(_spans) < _SPANS_CAP:
        # EXPECT: TPL008
        _spans.append(ev)
    else:
        # EXPECT: TPL008
        _spans_dropped += 1
    return ev


def _drain_loop(sink):
    while True:
        out = list(_spans)
        # EXPECT: TPL008
        _spans.clear()
        for ev in out:
            sink(ev)


def start(sink):
    threading.Thread(target=_drain_loop, args=(sink,),
                     daemon=True).start()
    threading.Thread(target=record_span, args=("serve/request", 0.01),
                     daemon=True).start()
    return record_span("train/iteration", 0.1)
