"""path_smooth, feature_fraction_bynode, monotone_penalty,
monotone_constraints_method=intermediate, auc_mu — the parameters the
reference implements in feature_histogram.hpp (smoothing),
col_sampler.hpp (GetByNode), monotone_constraints.hpp (penalty /
IntermediateLeafConstraints) and multiclass_metric.hpp (AucMuMetric)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from conftest import make_synthetic_binary


def _max_abs_leaf(bst):
    return max(float(np.max(np.abs(t.leaf_value[: t.num_leaves])))
               for t in bst._models)


def _train_reg(params, X, y, rounds=5):
    d = lgb.Dataset(X, label=y)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5, "learning_rate": 1.0}
    base.update(params)
    return lgb.train(base, d, num_boost_round=rounds)


def test_path_smooth_shrinks_leaf_outputs():
    rs = np.random.RandomState(3)
    X = rs.randn(1200, 4)
    y = X[:, 0] * 2.0 + 0.3 * rs.randn(1200)
    plain = _train_reg({}, X, y)
    smooth = _train_reg({"path_smooth": 200.0}, X, y)
    very = _train_reg({"path_smooth": 1e6}, X, y)
    m0, m1, m2 = (_max_abs_leaf(b) for b in (plain, smooth, very))
    # outputs shrink toward the parent chain as smoothing grows
    assert m1 < m0
    assert m2 < m1
    p = smooth.predict(X)
    assert np.all(np.isfinite(p))
    # still learns the signal
    assert np.corrcoef(p, y)[0, 1] > 0.8


def test_feature_fraction_bynode_diversifies_roots():
    rs = np.random.RandomState(5)
    X = rs.randn(3000, 8)
    # feature 0 dominates; with per-node sampling at 0.25 the root
    # frequently has to split elsewhere
    y = (X[:, 0] + 0.1 * X[:, 1] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    base = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
            "min_data_in_leaf": 5, "learning_rate": 0.9}
    full = lgb.train(base, d, num_boost_round=12)
    sub = lgb.train({**base, "feature_fraction_bynode": 0.25},
                    lgb.Dataset(X, label=y), num_boost_round=12)
    roots_full = {int(t.split_feature[0]) for t in full._models
                  if t.num_nodes}
    roots_sub = {int(t.split_feature[0]) for t in sub._models
                 if t.num_nodes}
    # the dominant feature owns the first root unconstrained; per-node
    # sampling at 0.25 forces other features into root position
    assert int(full._models[0].split_feature[0]) == 0
    assert len(roots_sub) > max(1, len(roots_full) - 1) \
        or not (roots_sub <= roots_full)
    assert len(roots_sub) > 1
    assert np.all(np.isfinite(sub.predict(X)))


def _is_monotone(bst, X, fidx, direction, grid=9):
    lo, hi = X[:, fidx].min(), X[:, fidx].max()
    probe = X[:200].copy()
    prev = None
    for v in np.linspace(lo, hi, grid):
        probe[:, fidx] = v
        pred = bst.predict(probe, raw_score=True)
        if prev is not None:
            diff = pred - prev
            if direction > 0 and np.min(diff) < -1e-6:
                return False
            if direction < 0 and np.max(diff) > 1e-6:
                return False
        prev = pred
    return True


@pytest.mark.parametrize("method", ["basic", "intermediate",
                                    "advanced"])
def test_monotone_methods_enforce_monotonicity(method):
    rs = np.random.RandomState(11)
    X = rs.randn(2500, 4)
    y = (X[:, 0] + np.sin(X[:, 1] * 2) + 0.2 * rs.randn(2500) > 0) \
        .astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "monotone_constraints": [1, 0, 0, 0],
                     "monotone_constraints_method": method}, d,
                    num_boost_round=20)
    assert _is_monotone(bst, X, 0, +1)


def test_monotone_advanced_multi_feature_and_quality():
    """Round 4: advanced (monotone precise mode,
    AdvancedLeafConstraints, monotone_constraints.hpp:858) no longer
    raises; it enforces monotonicity on BOTH an increasing and a
    decreasing feature simultaneously, and its per-threshold bounds
    should fit at least as well as basic's blunt midpoint bounds."""
    rs = np.random.RandomState(19)
    X = rs.randn(3000, 4)
    y = (X[:, 0] - 0.8 * X[:, 1] + np.sin(X[:, 2] * 2)
         + 0.2 * rs.randn(3000) > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 31,
              "verbosity": -1, "min_data_in_leaf": 5,
              "monotone_constraints": [1, -1, 0, 0]}

    def logloss(bst):
        p = np.clip(bst.predict(X), 1e-7, 1 - 1e-7)
        return -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))

    adv = lgb.train({**params, "monotone_constraints_method":
                     "advanced"}, lgb.Dataset(X, label=y),
                    num_boost_round=20)
    assert _is_monotone(adv, X, 0, +1)
    assert _is_monotone(adv, X, 1, -1)
    basic = lgb.train({**params, "monotone_constraints_method":
                       "basic"}, lgb.Dataset(X, label=y),
                      num_boost_round=20)
    assert logloss(adv) <= logloss(basic) * 1.05


def test_monotone_penalty_defers_constrained_feature():
    rs = np.random.RandomState(7)
    X = rs.randn(3000, 2)
    # f0 strongly informative (and constrained), f1 weakly informative
    y = (X[:, 0] + 0.25 * X[:, 1] + 0.1 * rs.randn(3000) > 0).astype(float)
    base = {"objective": "binary", "num_leaves": 4, "verbosity": -1,
            "min_data_in_leaf": 5, "monotone_constraints": [1, 0]}
    free = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=1)
    pen = lgb.train({**base, "monotone_penalty": 2.0},
                    lgb.Dataset(X, label=y), num_boost_round=1)
    assert int(free._models[0].split_feature[0]) == 0
    # a depth-0 monotone split is multiplied by ~kEpsilon, so the
    # weak unconstrained feature wins the root
    assert int(pen._models[0].split_feature[0]) == 1


def test_auc_mu_matches_binary_auc_for_two_classes():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import AucMu, auc_jnp
    rs = np.random.RandomState(0)
    n = 500
    y = (rs.rand(n) > 0.6).astype(np.float64)
    s1 = rs.randn(n) + y * 1.2
    score = np.stack([-s1 / 2, s1 / 2])  # [K=2, n]
    cfg = Config(objective="multiclass", num_class=2)
    m = AucMu(cfg)
    got = float(m.eval(score, y, None, None))
    want = float(auc_jnp(np.asarray(s1), np.asarray(y)))
    assert abs(got - want) < 1e-6


def test_auc_mu_perfect_and_random():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import AucMu
    rs = np.random.RandomState(1)
    n, K = 600, 3
    y = rs.randint(0, K, n).astype(np.float64)
    perfect = np.zeros((K, n))
    perfect[y.astype(int), np.arange(n)] = 5.0
    cfg = Config(objective="multiclass", num_class=K)
    m = AucMu(cfg)
    assert float(m.eval(perfect, y, None, None)) == pytest.approx(1.0)
    noise = rs.randn(K, n)
    val = float(m.eval(noise, y, None, None))
    assert 0.4 < val < 0.6


def test_auc_mu_through_train_metric():
    rs = np.random.RandomState(4)
    X = rs.randn(900, 5)
    y = np.argmax(X[:, :3] + 0.3 * rs.randn(900, 3), axis=1)
    d = lgb.Dataset(X, label=y)
    evals = {}
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "metric": "auc_mu", "verbosity": -1,
                     "num_leaves": 8},
                    d, num_boost_round=8, valid_sets=[d],
                    valid_names=["train"],
                    callbacks=[lgb.record_evaluation(evals)])
    curve = evals["train"]["auc_mu"]
    assert curve[-1] > 0.8
    assert curve[-1] >= curve[0] - 1e-9


def test_parameters_doc_in_sync():
    """docs/PARAMETERS.md is generated from config.py (the reference's
    parameter-generator.py pattern); it must never drift."""
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "gen_parameters_doc.py"),
         "--check"], cwd=repo, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
