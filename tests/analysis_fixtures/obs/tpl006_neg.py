# tpulint fixture: TPL006 negative — dispatch outside the lock.
import threading

import jax
import jax.numpy as jnp

_lock = threading.Lock()
_state = {"total": 0.0}


def record(values):
    total = float(jnp.sum(values))    # dispatch FIRST, lock-free
    with _lock:
        _state["total"] += total      # pure python under the lock


class Recorder:
    def __init__(self):
        self._lock = threading.RLock()
        self.snapshots = []

    def observe(self, x):
        y = jax.device_put(x)         # dispatch outside
        with self._lock:
            self.snapshots.append(y)  # bookkeeping inside
