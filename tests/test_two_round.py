"""Two-round / out-of-core text loading (two_round=true;
dataset_loader.cpp:299,960): mappers from a sampled first pass, binning
streamed chunk-by-chunk in the second — the raw float matrix is never
materialized."""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

_DIR = os.path.dirname(os.path.abspath(__file__))


def _write_csv(path, n, f, seed=0, chunk=50000):
    rs = np.random.RandomState(seed)
    coef = rs.randn(f)
    with open(path, "w") as fh:
        done = 0
        while done < n:
            c = min(chunk, n - done)
            X = rs.randn(c, f)
            y = ((X @ coef) > 0).astype(float)
            block = np.column_stack([y, X])
            np.savetxt(fh, block, delimiter=",", fmt="%.6g")
            done += c
    return coef


def test_two_round_matches_eager_loading(tmp_path):
    """Same file loaded eagerly vs two-round with a full sample: the
    binned matrices, mappers and labels must be identical, and the
    trained models equal."""
    path = str(tmp_path / "train.csv")
    _write_csv(path, 4000, 8, seed=3)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "max_bin": 63, "bin_construct_sample_cnt": 10_000}
    d_eager = lgb.Dataset(path, params=dict(params))
    d_eager.construct()
    d_two = lgb.Dataset(path, params=dict(params, two_round=True))
    d_two.construct()
    np.testing.assert_array_equal(d_eager.host_bins(),
                                  d_two.host_bins())
    np.testing.assert_allclose(np.asarray(d_eager.get_label()),
                               np.asarray(d_two.get_label()))
    b1 = lgb.train(dict(params), lgb.Dataset(path, params=dict(params)),
                   num_boost_round=3)
    b2 = lgb.train(dict(params, two_round=True),
                   lgb.Dataset(path, params=dict(params,
                                                 two_round=True)),
                   num_boost_round=3)
    for ta, tb in zip(b1._models, b2._models):
        np.testing.assert_array_equal(ta.split_feature, tb.split_feature)
        np.testing.assert_array_equal(ta.threshold_bin,
                                      tb.threshold_bin)


def test_two_round_name_label_column_resolves_header(tmp_path):
    """A name-based label_column must resolve against the header in
    the two-round path itself (ADVICE r4: it used to silently train on
    column 0 as the label). The label here is the LAST column, so any
    column-0 fallback flips every label and the eager/two-round parity
    below fails loudly."""
    path = str(tmp_path / "train_named.csv")
    rs = np.random.RandomState(5)
    X = rs.randn(1500, 4)
    y = ((X @ rs.randn(4)) > 0).astype(float)
    cols = np.column_stack([X, y])          # label LAST
    with open(path, "w") as fh:
        fh.write("f0,f1,f2,f3,target\n")
        np.savetxt(fh, cols, delimiter=",", fmt="%.6g")
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "max_bin": 31, "header": True,
              "label_column": "name:target",
              "bin_construct_sample_cnt": 10_000}
    d_two = lgb.Dataset(path, params=dict(params, two_round=True))
    d_two.construct()
    np.testing.assert_allclose(np.asarray(d_two.get_label()), y)
    d_eager = lgb.Dataset(path, params=dict(params))
    d_eager.construct()
    np.testing.assert_array_equal(d_eager.host_bins(),
                                  d_two.host_bins())


def test_two_round_name_label_without_header_raises(tmp_path):
    """name:... without header=true cannot be resolved — the loader
    must refuse, never assume column 0."""
    from lightgbm_tpu.basic import LightGBMError
    path = str(tmp_path / "noheader.csv")
    _write_csv(path, 200, 3, seed=6)
    with pytest.raises(LightGBMError, match="header"):
        ds = lgb.Dataset(path, params={
            "two_round": True, "label_column": "name:target",
            "verbosity": -1})
        ds.construct()


def test_two_round_sampled_mappers_close(tmp_path):
    """With a sub-full sample the mappers come from the sample only
    (reference semantics); training must still work well."""
    path = str(tmp_path / "train.csv")
    _write_csv(path, 20000, 6, seed=5)
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "two_round": True, "bin_construct_sample_cnt": 2000}
    bst = lgb.train(dict(params), lgb.Dataset(path, params=params),
                    num_boost_round=10)
    d = lgb.Dataset(path, params=params)
    d.construct()
    X = np.genfromtxt(path, delimiter=",")[:, 1:]
    y = np.genfromtxt(path, delimiter=",")[:, 0]
    acc = np.mean((bst.predict(X) > 0.5) == (y > 0.5))
    assert acc > 0.9, acc


_RSS_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb

d = lgb.Dataset({path!r},
                params={{"objective": "binary", "max_bin": 63,
                         "bin_construct_sample_cnt": 20000,
                         "two_round": {two_round}}})
d.construct()
assert d.num_data() == {n}
# VmHWM, NOT getrusage(ru_maxrss): ru_maxrss is per-TASK accounting
# that survives execve, so a child forked from a fat parent (a pytest
# worker late in the full suite carries ~3.6 GB of jax state) reports
# the parent's RSS as its own floor — both loads then measure
# identical peaks and the test sees zero savings (the real mechanism
# of this test's long flake history). VmHWM belongs to the mm and
# resets with the fresh address space at exec.
with open("/proc/self/status") as f:
    for line in f:
        if line.startswith("VmHWM:"):
            print(int(line.split()[1]))
            break
"""


def _measure_load_peak_kb(repo, path, n, two_round):
    """Lifetime peak RSS (KB) of ONE loader run in its own subprocess.

    The env is scrubbed to a fixed minimal set: the parent xdist
    worker exports an 8-virtual-device XLA_FLAGS (conftest) that
    balloons the jax baseline, and under ``-n 4`` the inherited env
    differs run-to-run — the round-4 'clear XLA_FLAGS' fix was not
    enough (VERDICT r4 weak #4). One load per process also makes the
    comparison a difference of lifetime peaks, with the interpreter
    baseline cancelling, instead of the old increment-above-peak
    measurement inside one process."""
    env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.environ.get("HOME", "/root"),
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": ""}
    script = _RSS_SCRIPT.format(repo=repo, path=path, n=n,
                                two_round=two_round)
    # under a loaded machine (parallel xdist workers) the subprocess
    # can be slow or OOM-killed; retry once before judging
    for _ in range(2):
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             capture_output=True, text=True,
                             timeout=1500)
        if out.returncode == 0:
            return int(out.stdout.strip())
    raise AssertionError(out.stderr[-2000:])


def _proc_has_vmhwm() -> bool:
    """Sandboxed kernels (gVisor-style /proc, e.g. this CI container's
    4.4.0) omit the VmHWM line entirely — the subprocess then prints
    nothing and the test failed on an int('') parse since seed. No
    VmHWM means this environment cannot measure lifetime peak RSS
    (ru_maxrss is no substitute: it survives execve here, so the
    child inherits the parent's floor — the measurement this test
    exists to avoid)."""
    try:
        with open("/proc/self/status") as f:
            return any(line.startswith("VmHWM:") for line in f)
    except OSError:
        return False


@pytest.mark.skipif(sys.platform != "linux" or not _proc_has_vmhwm(),
                    reason="peak measurement needs VmHWM in "
                           "/proc/self/status")
def test_two_round_peak_memory_below_eager(tmp_path):
    """The two-round load's lifetime peak RSS must sit at least half
    the raw float64 matrix BELOW the eager load's (one load per
    scrubbed-env subprocess; the eager path holds [n, F+1] float64
    plus copies, two-round holds u8 bins + one streaming chunk)."""
    n, f = 300_000, 50
    path = str(tmp_path / "big.csv")
    _write_csv(path, n, f, seed=7)
    repo = os.path.dirname(_DIR)
    p1 = _measure_load_peak_kb(repo, path, n, two_round=True)
    p2 = _measure_load_peak_kb(repo, path, n, two_round=False)
    raw_mb = n * (f + 1) * 8 / 2 ** 20      # ~117 MB
    saved_mb = (p2 - p1) / 1024             # VmHWM is kB
    assert saved_mb > raw_mb / 2, (p1, p2, raw_mb)
