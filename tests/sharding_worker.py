"""Worker for the 2-process kv-world sharded-training tests
(test_sharding.py).

Run as one rank of a 2-process world wired through the environment
(``LIGHTGBM_TPU_COORDINATOR`` / ``LIGHTGBM_TPU_NUM_PROCS`` /
``LIGHTGBM_TPU_RANK`` — the elastic_worker.py convention); on CPU the
host transport resolves to kv, so each process runs the identical
replicated program over its own 2 virtual devices and the cross-rank
surface is exactly the host-level sync points.

Modes (argv[2]):

- ``equiv`` — for each data-parallel grower (compact, masked, level),
  train the gathered/host baseline and the ``shard_residency=device``
  + ``split_search=sharded`` variant through ``distributed_dataset``;
  the worker asserts the device run freed its host binned matrix, and
  rank 0 writes every model string to ``<outdir>/models.json`` for the
  byte-identity comparison in the test.
- ``unequal_rows`` — rank 1 drops one row; ``distributed_dataset``
  must raise a LightGBMError naming both ranks and their row counts
  BEFORE any bulk collective (the old failure was an opaque allgather
  shape error).
- ``unequal_meta`` — rank 0 passes ``weight``, rank 1 does not; the
  metadata pre-check must name the field and the ranks on both sides.

Usage: python sharding_worker.py <outdir> <mode>
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

outdir = sys.argv[1]
mode = sys.argv[2]

from lightgbm_tpu.parallel.distributed import init_distributed  # noqa: E402

init_distributed()

import jax  # noqa: E402
import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.basic import LightGBMError  # noqa: E402
from lightgbm_tpu.parallel import spmd  # noqa: E402

rank = jax.process_index()
assert jax.process_count() == 2

rs = np.random.RandomState(11)
n, f = 800, 11                    # f=11 over 2 devices: uneven chunks
X = rs.randn(n, f)
y = ((X[:, 0] + 0.5 * X[:, 1] - 0.2 * X[:, 2]
      + 0.1 * rs.randn(n)) > 0).astype(np.float64)
half = n // 2
lo, hi = rank * half, (rank + 1) * half


def shard_ds(**kwargs):
    return spmd.distributed_dataset(X[lo:hi], label=y[lo:hi],
                                    params={"verbosity": -1}, **kwargs)


def _done_barrier(tag):
    """Both ranks raise the expected error, but rank 0 hosts the
    coordination service — an os._exit leaves the peer's error-poll
    thread mid-RPC and the 'Socket closed' poll result is FATAL
    (SIGABRT). shutdown() has barrier semantics AND stops the poll
    thread; both ranks reach it here (same teardown as the healthy
    path below)."""
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


if mode == "unequal_rows":
    take = hi - (1 if rank == 1 else 0)
    try:
        spmd.distributed_dataset(X[lo:take], label=y[lo:take],
                                 params={"verbosity": -1})
    except LightGBMError as e:
        msg = str(e)
        assert "rank 0: 400 rows" in msg, msg
        assert "rank 1: 399 rows" in msg, msg
        print(f"rank {rank} UNEQUAL_ROWS_OK", flush=True)
        _done_barrier("test/unequal_rows_done")
        os._exit(0)
    print(f"rank {rank} NO ERROR RAISED", flush=True)
    os._exit(1)

if mode == "unequal_meta":
    w = np.ones(hi - lo) if rank == 0 else None
    try:
        spmd.distributed_dataset(X[lo:hi], label=y[lo:hi], weight=w,
                                 params={"verbosity": -1})
    except LightGBMError as e:
        msg = str(e)
        assert "'weight'" in msg, msg
        assert "ranks [0]" in msg and "ranks [1]" in msg, msg
        print(f"rank {rank} UNEQUAL_META_OK", flush=True)
        _done_barrier("test/unequal_meta_done")
        os._exit(0)
    print(f"rank {rank} NO ERROR RAISED", flush=True)
    os._exit(1)

assert mode == "equiv", mode
base = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
        "tree_learner": "data", "num_devices": 2, "seed": 5,
        "deterministic": True, "verbosity": -1}
models = {}
for grower in ("compact", "masked", "level"):
    p = dict(base, grower=grower)
    models[f"{grower}/gathered"] = lgb.train(
        p, shard_ds(), num_boost_round=5).model_to_string()

    p2 = dict(base, grower=grower, shard_residency="device",
              split_search="sharded")
    ds2 = shard_ds()
    models[f"{grower}/sharded"] = lgb.train(
        p2, ds2, num_boost_round=5).model_to_string()
    # device residency freed the host binned matrix after the upload
    assert ds2._bins is None, grower
    try:
        ds2.host_bins()
    except LightGBMError:
        pass
    else:
        raise AssertionError("host_bins() must raise after free")

if rank == 0:
    with open(os.path.join(outdir, "models.json"), "w") as fh:
        json.dump(models, fh)

# graceful world teardown: without it the faster rank tears the
# coordination service down while the other is still mid-training and
# the survivor's error-poll thread aborts the process (SIGABRT).
# shutdown() has barrier semantics — every healthy rank reaches it
# before the service stops (peers are alive here, unlike the chaos
# workers that must skip teardown).
print(f"rank {rank} DONE", flush=True)
sys.stdout.flush()
try:
    jax.distributed.shutdown()
except Exception:
    pass
os._exit(0)
