# tpulint fixture: TPL010 negatives — justified replicated-predicate
# sites, collectives outside conditionals, and collective-free branches.
# No EXPECT lines: the engine must report nothing here.
import jax
import jax.numpy as jnp
from jax import lax


def _window_reduce(x, axis):
    return lax.psum(jnp.sum(x), axis)


def justified_pool_miss(slot, hists, x, axis):
    """The ops/grow.py histogram-pool shape, with the invariant
    named: the pragma's why documents the predicate's replication."""
    # tpulint: replicated-cond slot derives only from the replicated tree/argmax sequence
    return lax.cond(slot >= 0,
                    lambda: hists[jnp.maximum(slot, 0)],
                    lambda: _window_reduce(x, axis))


def collective_outside_cond(pred, x, axis):
    """Every rank joins the psum; only local work branches."""
    g = lax.psum(x, axis)
    return lax.cond(pred, lambda: g * 2.0, lambda: g)


def collective_free_branches(pred, x):
    return lax.cond(pred, lambda: jnp.sum(x), lambda: jnp.max(x))


def _local_stat(x):
    """Same call-shape as a collective-reaching helper, but pure."""
    return jnp.sum(x) * 0.5


def branch_calls_pure_helper(pred, x):
    return lax.cond(pred, lambda: _local_stat(x), lambda: x[0])
