"""Benchmark: boosting iterations/sec + held-out AUC on a Higgs-shaped
synthetic dataset.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.

Baseline (BASELINE.md): reference LightGBM trains Higgs-10M (10.5M x 28,
255 bins, 255 leaves) at 500 iters / 130.094 s = 3.843 iters/sec on a
28-thread 2x E5-2670v2 (docs/Experiments.rst:111-123). ``vs_baseline`` is
our iters/sec divided by that number, linearly rescaled to the 10.5M-row
workload when BENCH_ROWS is smaller (histogram work is O(rows); the
rescale factor is 1 at the full shape).

Accuracy: ``auc`` is the held-out AUC after BENCH_AUC_ITERS boosting
rounds, and ``auc_ref`` is the reference implementation's AUC trained on
the byte-identical dataset/params (measured once with an oracle build of
/root/reference at v4.6.0.99, 50 rounds, lr 0.1, 255 leaves/bins; the
synthetic task is separable so both sit near 0.97 — parity, not the
absolute Higgs 0.8457, is the check).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_ITERS_PER_SEC = 500.0 / 130.094
HIGGS_ROWS = 10_500_000

# Resilience: the driver runs this through a TPU tunnel that has died
# mid-round in rounds 1/3/4 (r04: rc=124 — the old 10x(180s+30s) probe
# loop outlived the driver's own timeout, so not even the failure JSON
# got out). Round-5 rule: ONE global deadline covers everything.
# BENCH_DEADLINE bounds probe+run; on expiry the jax-free supervisor
# parent prints the failure JSON and exits 0. Probing is bounded much
# tighter (PROBE_* below, worst case ~3.5 min) so a dead tunnel still
# leaves the line on stdout well inside the driver's budget.
BENCH_DEADLINE = float(os.environ.get("BENCH_DEADLINE", 1200.0))
_T0 = time.time()
PROBE_RETRIES = int(os.environ.get("BENCH_PROBE_RETRIES", 3))
PROBE_BACKOFF_S = float(os.environ.get("BENCH_PROBE_BACKOFF", 10.0))
# a half-dead tunnel can make backend init HANG rather than raise;
# each probe attempt runs in a subprocess bounded by this timeout
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 60))
# last full-scale number measured by the builder on a real chip
# (10.5M x 28, 255 leaves/bins; see benchmarks/PROFILE.md)
LAST_MEASURED = {"value": 1.545, "unit": "iters/sec",
                 "vs_baseline": 0.402, "commit": "6d0db35"}


class _RetryableInitError(Exception):
    """Backend init failed in-process after a successful probe.

    jax caches the failed init for the life of the interpreter, so the
    only useful recovery is a FRESH worker process — the worker exits
    rc=1 without printing, and the supervisor relaunches while the
    deadline allows."""


def _git_head():
    try:
        return subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10).stdout.strip()
    except Exception:
        return "unknown"


def _probe_backend():
    """Wait for a usable JAX backend; returns jax or raises last error.

    The probe runs in a SUBPROCESS with a hard timeout: a dead tunnel
    can make backend init either raise (caught) or HANG in native code
    holding the GIL (where in-process SIGALRM never fires — observed
    round 4). The parent only imports jax once a probe succeeded.
    Total probe time is additionally bounded by the global deadline:
    never probe past _T0 + BENCH_DEADLINE/2, so at least half the
    budget is left for the run (or for the supervisor to emit)."""
    last = None
    probe_cutoff = _T0 + BENCH_DEADLINE / 2
    # BENCH_PLATFORM=cpu forces the host backend for CI smoke runs.
    # The env var alone is NOT enough: the tunnel's sitecustomize
    # re-overrides jax_platforms at interpreter start (see
    # tests/conftest.py), so the config must be re-set after import.
    plat = os.environ.get("BENCH_PLATFORM", "")
    force = (f"jax.config.update('jax_platforms', {plat!r}); "
             if plat else "")
    for attempt in range(PROBE_RETRIES):
        budget = min(PROBE_TIMEOUT_S, probe_cutoff - time.time())
        if budget <= 1:
            break
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 f"import jax; {force}jax.devices(); "
                 "print('BENCH_PROBE_OK')"],
                capture_output=True, text=True, timeout=budget)
            if r.returncode == 0 and "BENCH_PROBE_OK" in r.stdout:
                # If the tunnel dies in the probe->init window, this
                # import raises and MUST propagate: jax caches the
                # failed backend init in-process, so looping here
                # would burn every retry on guaranteed-futile
                # attempts. The worker exits rc=1; the supervisor
                # relaunches a fresh interpreter while the deadline
                # allows (replaces the round-4 os.execve, which reset
                # the supervisor's timeout accounting — ADVICE r4).
                try:
                    import jax
                    if plat:
                        jax.config.update("jax_platforms", plat)
                    jax.devices()
                    return jax
                except Exception as e:
                    raise _RetryableInitError(
                        f"backend init failed after successful probe: "
                        f"{e}") from e
            tail = (r.stderr or r.stdout).strip().splitlines()
            last = RuntimeError(tail[-1] if tail else
                                f"probe rc={r.returncode}")
        except subprocess.TimeoutExpired:
            last = TimeoutError(
                f"backend init hung > {budget:.0f}s "
                "(tunnel half-dead)")
        except _RetryableInitError:
            raise  # fresh-interpreter territory — supervisor's job
        except Exception as e:
            # e.g. fork/exec OSError under memory pressure — exactly
            # the conditions this harness exists for; keep retrying
            last = e
        sys.stderr.write(
            f"bench: backend probe {attempt + 1}/{PROBE_RETRIES} "
            f"failed: {last}\n")
        if attempt + 1 < PROBE_RETRIES and \
                time.time() + PROBE_BACKOFF_S < probe_cutoff:
            time.sleep(PROBE_BACKOFF_S)
    raise last if last is not None else TimeoutError(
        "probe budget exhausted before any attempt")


def _emit_line(line):
    """Emit the ONE result line.

    In the worker (BENCH_RESULT_FILE set) the line goes to a file,
    atomically, and the supervisor prints it after the child exits —
    the supervisor alone owns stdout, so a worker killed in the
    timeout window can never race a second line onto it."""
    path = os.environ.get("BENCH_RESULT_FILE")
    if path:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, path)
    else:
        print(line)


def _emit_failure(err):
    """One JSON line recording the failure.

    ``value`` is null — consumers keying on value must not attribute a
    stale commit's performance to HEAD (ADVICE r4); the last clean
    builder-measured number rides along in ``last_measured``."""
    shape = "Allstate-shaped" if _ALLSTATE else "Higgs-shaped"
    result = {
        "metric": f"boosting iters/sec, {shape} "
                  f"{N_ROWS}x{N_FEATURES}, {NUM_LEAVES} leaves, "
                  f"{MAX_BIN} bins (BENCH FAILED)",
        "value": None,
        "unit": LAST_MEASURED["unit"],
        "vs_baseline": None,
        "error": f"{type(err).__name__}: {err}"[:500],
        "last_measured": LAST_MEASURED,
        "failed_at_commit": _git_head(),
    }
    _emit_line(json.dumps(result))

# BENCH_PRESET=allstate: the wide-sparse EFB path (4228 one-hot-ish
# features w/ NaN, docs/Experiments.rst:121 Allstate shape; reference
# trains 13.2M rows in 148.231 s / 500 iters = 3.373 iters/sec). The
# full 13.2M x 4228 float32 matrix is ~223 GB — beyond host RAM — so
# the eager preset defaults to 2M rows; BENCH_STREAMING=1 (or a
# --streaming argv flag) instead ingests through the chunked two-pass
# pipeline (lightgbm_tpu/data/, docs/DATA.md), where peak host RSS is
# the BINNED matrix plus one generator chunk — the full-scale
# 13.2M-row shape becomes constructible on an ordinary host.
# Default preset: the REAL Higgs shape — measured, not extrapolated.
PRESET = os.environ.get("BENCH_PRESET", "higgs")
_ALLSTATE = PRESET == "allstate"
_STREAMING = (os.environ.get("BENCH_STREAMING", "") == "1"
              or "--streaming" in sys.argv)
# BENCH_SERVE=1 / --serve: after the training legs, benchmark the
# production inference path (lightgbm_tpu/serve/, docs/SERVING.md) —
# compiled shape-bucketed predict vs the eager Booster.predict CPU
# baseline over a mix of ad-hoc batch sizes; rows/sec, p50/p99 request
# latency and the recompile count after warmup ride along in a
# "serve" block of the one JSON line.
_SERVE = (os.environ.get("BENCH_SERVE", "") == "1"
          or "--serve" in sys.argv)
SERVE_REPEAT = int(os.environ.get("BENCH_SERVE_REPEAT", 3))
# rows per ingest chunk in streaming mode (the peak-RSS knob)
INGEST_CHUNK = int(os.environ.get("BENCH_INGEST_CHUNK", 262_144))
ALLSTATE_ROWS = 13_184_290
ALLSTATE_BASELINE_ITERS_PER_SEC = 500.0 / 148.231
N_ROWS = int(os.environ.get(
    "BENCH_ROWS", 2_097_152 if _ALLSTATE else HIGGS_ROWS))
N_FEATURES = int(os.environ.get("BENCH_FEATURES",
                                4228 if _ALLSTATE else 28))
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_BINS", 255))
WARMUP = int(os.environ.get("BENCH_WARMUP", 1))
ITERS = int(os.environ.get("BENCH_ITERS", 5))
AUC_ITERS = int(os.environ.get("BENCH_AUC_ITERS", 50))
N_VALID = int(os.environ.get("BENCH_VALID", 524_288))

# oracle (reference build, v4.6.0.99) held-out AUC on the identical
# seed-0 dataset, 50 rounds: measured via /tmp oracle runs of
# /root/reference with the same make_higgs_like generator
ORACLE_AUC = {1_048_576: 0.967940, 10_500_000: 0.967607}


def make_higgs_like(n, f, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f).astype(np.float32)
    coef = rs.randn(f).astype(np.float32)
    logits = X @ coef * 0.5 + 0.5 * rs.randn(n).astype(np.float32)
    y = (logits > 0).astype(np.float32)
    # float32 on purpose: binning casts per-column to float64 itself
    # (ops/binning.py), and a whole-matrix float64 copy doubles peak
    # host RSS for nothing
    return X, y.astype(np.float64)


def higgs_chunks(n, f, seed=0, chunk_rows=None):
    """Chunked Higgs-shaped generator for --streaming mode. Each chunk
    is drawn from a per-chunk RandomState (seeded by start row), so
    pass 1 and pass 2 of the ingest pipeline see identical data
    without the generator ever holding more than one chunk. NOTE: the
    row stream differs from make_higgs_like's single-stream layout, so
    streaming runs carry no ``auc_ref`` oracle."""
    chunk_rows = chunk_rows or INGEST_CHUNK
    coef = np.random.RandomState(987).randn(f).astype(np.float32)
    start = 0
    while start < n:
        c = min(chunk_rows, n - start)
        rs = np.random.RandomState(
            (seed * 1_000_003 + start) % (2 ** 31 - 1))
        X = rs.randn(c, f).astype(np.float32)
        logits = X @ coef * 0.5 + 0.5 * rs.randn(c).astype(np.float32)
        yield X, (logits > 0).astype(np.float64)
        start += c


def allstate_chunks(n, f, seed=0, per_group=128, chunk_rows=None):
    """Chunked Allstate-shaped generator: wide sparse one-hot blocks +
    NaN (the shape EFB exists for), emitted ``chunk_rows`` rows at a
    time so no [n, f] matrix is ever held. Values per position come
    from a FIXED stream (seed 12345) so train (seed=0) and valid
    (seed=1) sample the same underlying task; per-chunk RandomStates
    keyed on the start row make the stream re-iterable for the
    two-pass ingest. Labels threshold the signal at its expectation
    (``groups``; vals ~ U(0,2)) instead of the global median, which a
    chunked generator cannot know."""
    chunk_rows = chunk_rows or INGEST_CHUNK
    groups = f // per_group
    vals = np.random.RandomState(12345).rand(
        groups, per_group).astype(np.float32) * 2
    thresh = np.float32(groups)  # E[signal] = groups * E[U(0,2)]
    start = 0
    while start < n:
        c = min(chunk_rows, n - start)
        rs = np.random.RandomState(
            (seed * 1_000_003 + start) % (2 ** 31 - 1))
        X = np.zeros((c, f), np.float32)
        signal = np.zeros(c, np.float32)
        rows = np.arange(c)
        for g in range(groups):
            pick = rs.randint(0, per_group, c)
            X[rows, g * per_group + pick] = vals[g, pick]
            signal += vals[g, pick]
        X[rs.rand(c) < 0.1, 0] = np.nan
        yield X, (signal > thresh).astype(np.float64)
        start += c


def make_allstate_like(n, f, seed=0, per_group=128):
    """Eager wrapper over :func:`allstate_chunks`: fills ONE
    preallocated [n, f] float32 matrix chunk by chunk (transient
    overhead = one chunk, no float64 copy anywhere — the old
    whole-matrix construction loop plus label astype is gone,
    ADVICE.md medium). Peak host RSS across main() is
    (BENCH_ROWS + BENCH_VALID) * BENCH_FEATURES * 4 bytes; the
    --streaming mode drops even that by never materializing X."""
    X = np.empty((n, f), np.float32)
    y = np.empty(n, np.float64)
    row = 0
    for Xc, yc in allstate_chunks(n, f, seed=seed, per_group=per_group):
        X[row:row + len(yc)] = Xc
        y[row:row + len(yc)] = yc
        row += len(yc)
    return X, y


def _serve_bench(bst, lgb_obs, n_features):
    """The serving leg: compiled shape-bucketed prediction vs the
    eager ``Booster.predict`` baseline, over a mix of ad-hoc batch
    sizes (the daemon's actual workload shape).

    Both sides are measured steady-state: the eager baseline gets one
    untimed pass to populate its per-shape jit caches (so the compiled
    win measures the re-stack + bucketing advantage, not first-call
    compiles), and the compiled side is warmed through its power-of-two
    buckets — after which its recompile counter must stay flat (the
    TPL003 serving invariant; reported for the record)."""
    import lightgbm_tpu as lgb
    rs = np.random.RandomState(99)
    sizes = [1, 3, 17, 33, 100, 257, 512, 777, 1024, 2000]
    reqs = [rs.randn(s, n_features).astype(np.float32) for s in sizes]
    rows = sum(sizes) * SERVE_REPEAT

    eager = lgb.Booster(model_str=bst.model_to_string())
    for X in reqs:
        eager.predict(X)                      # untimed warm pass
    t0 = time.time()
    for _ in range(SERVE_REPEAT):
        for X in reqs:
            eager.predict(X)
    dt_eager = time.time() - t0

    cf = bst.compile(max_batch_rows=4096)
    cf.warmup()
    watch = lgb_obs.RecompileWatcher()
    lat = []
    t0 = time.time()
    for _ in range(SERVE_REPEAT):
        for X in reqs:
            t = time.perf_counter()
            bst.predict(X)                    # routed through cf
            lat.append(time.perf_counter() - t)
    dt_compiled = time.time() - t0
    lat_ms = np.asarray(lat) * 1e3
    return {
        "batch_sizes": sizes,
        "repeat": SERVE_REPEAT,
        "rows_per_sec_compiled": round(rows / dt_compiled, 1),
        "rows_per_sec_eager": round(rows / dt_eager, 1),
        "speedup_vs_eager": round(dt_eager / dt_compiled, 3),
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
        "recompiles_after_warmup": watch.delta(),
    }


def _peak_rss_bytes():
    """Linux ru_maxrss is KiB; the one number the streaming-ingest
    memory claim is checked against."""
    import resource
    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024


def auc(y, p):
    o = np.argsort(p)
    r = np.empty(len(p))
    r[o] = np.arange(1, len(p) + 1)
    npos = y.sum()
    nneg = len(y) - npos
    return (r[y == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def main():
    # persistent XLA compilation cache: the grower compiles once per
    # (shape, config); repeated bench runs skip the 20-40s TPU compile
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.expanduser("~/.cache/lightgbm_tpu/xla"))
    jax = _probe_backend()
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs as lgb_obs
    from lightgbm_tpu.utils.timer import Timer as _PhaseTimer

    # stdout belongs to the ONE JSON result line (driver contract,
    # tests/test_bench_contract.py). The package logger defaults to
    # stdout, and e.g. the native fastparse build-failure warning would
    # land there — route all library logging to stderr for the run.
    import logging
    _blog = logging.getLogger("lightgbm_tpu_bench")
    if not _blog.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(message)s"))
        _blog.addHandler(h)
        _blog.setLevel(logging.INFO)
    lgb.register_logger(_blog)

    # run telemetry rides along in the one JSON line: phase wall times
    # (host-side Timer, ~µs/phase against ~100ms iterations), jit
    # recompile count and HBM gauges — the numbers the perf ROADMAP
    # items report against (docs/OBSERVABILITY.md)
    _PhaseTimer.enable()
    recompile_watch = lgb_obs.RecompileWatcher()

    valid_chunks = None
    Xv = yv = None
    if _STREAMING:
        # chunked two-pass ingestion (lightgbm_tpu/data/): the dense
        # float train matrix never exists; the valid set is predicted
        # chunk-by-chunk below, so it is never materialized either
        from lightgbm_tpu.data import GeneratorChunkSource
        gen = allstate_chunks if _ALLSTATE else higgs_chunks

        def train_chunks():
            return gen(N_ROWS, N_FEATURES, seed=0,
                       chunk_rows=INGEST_CHUNK)

        def valid_chunks():
            return gen(N_VALID, N_FEATURES, seed=1,
                       chunk_rows=INGEST_CHUNK)

        src = GeneratorChunkSource(train_chunks, num_rows=N_ROWS,
                                   num_features=N_FEATURES)
        ds = lgb.Dataset(src, params={"max_bin": MAX_BIN,
                                      "ingest_chunk_rows": INGEST_CHUNK})
        ds.construct()
    elif _ALLSTATE:
        # train/valid generated separately so peak host RSS is
        # (N_ROWS + N_VALID)·f·4 bytes — the slice-copy pattern below
        # would transiently hold ~2.6x that (X + Xtr + Xv), ~89 GB at
        # the default preset
        Xtr, ytr = make_allstate_like(N_ROWS, N_FEATURES, seed=0)
        Xv, yv = make_allstate_like(N_VALID, N_FEATURES, seed=1)
        ds = lgb.Dataset(Xtr, label=ytr, params={"max_bin": MAX_BIN})
        ds.construct()
        del Xtr
    else:
        # single generation + split: this exact layout is what
        # ORACLE_AUC was measured against — don't change it
        X, y = make_higgs_like(N_ROWS + N_VALID, N_FEATURES)
        # slice-copies so `del X` actually frees the big base array
        Xv, yv = X[N_ROWS:].copy(), y[N_ROWS:].copy()
        Xtr, ytr = X[:N_ROWS].copy(), y[:N_ROWS]
        del X
        ds = lgb.Dataset(Xtr, label=ytr, params={"max_bin": MAX_BIN})
        ds.construct()
        del Xtr

    bst = lgb.Booster(
        params={
            "objective": "binary",
            "num_leaves": NUM_LEAVES,
            "max_bin": MAX_BIN,
            "learning_rate": 0.1,
            "verbosity": -1,
            # BENCH_RESIDENCY=device: lay the binned rows directly
            # into their mesh slices and free the host copy
            # (parallel/placement.py, docs/SHARDING.md); the
            # host_binned_bytes fields below measure the claim
            "shard_residency": os.environ.get("BENCH_RESIDENCY",
                                              "auto"),
            # BENCH_SPLIT_SEARCH=sharded: reduce-scatter split search
            "split_search": os.environ.get("BENCH_SPLIT_SEARCH",
                                           "gathered"),
        },
        train_set=ds)

    for _ in range(WARMUP):
        bst._engine.train_one_iter()
    bst._engine.score.block_until_ready()

    t0 = time.time()
    for _ in range(ITERS):
        bst._engine.train_one_iter()
    bst._engine.score.block_until_ready()
    dt = time.time() - t0

    # accuracy leg: continue to AUC_ITERS rounds, then held-out AUC
    result_auc = None
    trained = WARMUP + ITERS
    if AUC_ITERS > trained:
        for _ in range(AUC_ITERS - trained):
            bst._engine.train_one_iter()
        if _STREAMING:
            # valid set predicted chunk-by-chunk: only predictions and
            # labels (8 bytes/row each) are ever held, never the rows
            preds, labels = [], []
            for Xc, yc in valid_chunks():
                preds.append(bst.predict(Xc))
                labels.append(yc)
            result_auc = float(auc(np.concatenate(labels),
                                   np.concatenate(preds)))
        else:
            result_auc = float(auc(yv, bst.predict(Xv)))

    iters_per_sec = ITERS / dt
    # linear rescale to the preset's full row count (histogram work is
    # O(rows); the factor is 1 at the default shape, so normally this
    # is a direct measurement)
    full_rows = ALLSTATE_ROWS if _ALLSTATE else HIGGS_ROWS
    base = ALLSTATE_BASELINE_ITERS_PER_SEC if _ALLSTATE \
        else BASELINE_ITERS_PER_SEC
    iters_per_sec_full = iters_per_sec * (N_ROWS / full_rows)
    scale_note = "" if N_ROWS == full_rows \
        else f" (rescaled to {full_rows} rows)"
    shape_name = "Allstate-shaped" if _ALLSTATE else "Higgs-shaped"
    result = {
        "metric": f"boosting iters/sec, {shape_name} "
                  f"{N_ROWS}x{N_FEATURES}"
                  f"{scale_note}, {NUM_LEAVES} leaves, "
                  f"{MAX_BIN} bins, backend={jax.default_backend()}"
                  + (", streaming-ingest" if _STREAMING else ""),
        "value": round(iters_per_sec_full, 4),
        "unit": "iters/sec",
        "vs_baseline": round(iters_per_sec_full / base, 4),
        "peak_rss_bytes": _peak_rss_bytes(),
    }
    if _STREAMING:
        result["ingest"] = dict(ds._ingest_stats)
    # per-host resident binned bytes, measured AFTER construct+train:
    # the ingest stats record the shard's footprint at construct time;
    # this is what is still host-resident now — 0 under
    # shard_residency=device (the host copy was freed after the mesh
    # upload), so the "no host holds the global binned matrix" claim
    # is a measured number, not an assertion
    result["shard_residency"] = getattr(bst._engine, "_residency",
                                        "host")
    # the engine-kept gauge, not ds._bins: an EFB run under device
    # residency frees the Dataset copy but keeps the bundled host
    # matrix resident, and the gauge tracks THAT (gbdt.py publishes it
    # in every residency branch)
    try:
        from lightgbm_tpu.obs.registry import registry
        result["host_binned_bytes"] = int(
            registry.gauge("host_binned_bytes").value)
    except Exception:
        result["host_binned_bytes"] = int(
            0 if ds._bins is None else ds._bins.nbytes)
    if bst._engine.bundle is not None:
        b = bst._engine.bundle
        result["efb_bundles"] = len(b.groups)
        result["hbm_bin_bytes"] = int(bst._engine.bins_T.size
                                      * bst._engine.bins_T.dtype.itemsize)
    phases = _PhaseTimer.snapshot()
    top_phases = sorted(phases.items(), key=lambda kv: -kv[1]["total"])[:8]
    result["telemetry"] = {
        "recompiles": recompile_watch.delta(),
        "phases": {label: {"total": round(v["total"], 4),
                           "count": int(v["count"])}
                   for label, v in top_phases},
        "hbm": lgb_obs.device_memory_stats(),
    }
    # in-band XLA cost attribution (obs/cost.py; docs/ROOFLINE.md):
    # every first compile per signature recorded flops/bytes and the
    # cost-model-optimal ms at the device peaks, so each bench run
    # carries its own roofline denominators
    try:
        from lightgbm_tpu.obs.cost import drain_compile_events
        result["telemetry"]["xla_cost"] = [
            {k: ev.get(k) for k in ("entry", "flops",
                                    "bytes_accessed", "wall_ms",
                                    "optimal_ms", "device_kind")}
            for ev in drain_compile_events()]
    except Exception:
        result["telemetry"]["xla_cost"] = []
    if _SERVE:
        result["serve"] = _serve_bench(bst, lgb_obs, N_FEATURES)
    if result_auc is not None:
        result["auc"] = round(result_auc, 6)
        # the oracle was measured against the exact eager single-stream
        # layout; streaming draws a different (per-chunk-seeded) stream
        oracle_config = (not _STREAMING and N_FEATURES == 28
                         and NUM_LEAVES == 255
                         and MAX_BIN == 255 and N_VALID == 524_288
                         and AUC_ITERS == 50)
        if oracle_config and N_ROWS in ORACLE_AUC:
            result["auc_ref"] = ORACLE_AUC[N_ROWS]
    _emit_line(json.dumps(result))


def _supervise():
    """Run the real bench in a child process under the global deadline.

    The parent holds no jax state, so it can ALWAYS emit the one-line
    JSON record even when the child hangs in native backend-init code
    (the half-dead-tunnel mode where no in-process mechanism fires).
    Whatever happens, the parent prints one JSON line and exits 0
    within BENCH_DEADLINE seconds of process start."""
    import tempfile
    fd, result_file = tempfile.mkstemp(prefix="bench_result_")
    os.close(fd)
    os.unlink(result_file)  # worker recreates it atomically
    env = dict(os.environ, BENCH_WORKER="1",
               BENCH_RESULT_FILE=result_file)

    def _take_result():
        try:
            with open(result_file) as f:
                line = f.read().strip()
            return line or None
        except OSError:
            return None

    try:
        _supervise_loop(env, _take_result)
    finally:
        for leftover in (result_file, result_file + ".tmp"):
            try:
                os.unlink(leftover)
            except OSError:
                pass
    sys.exit(0)


def _supervise_loop(env, _take_result):
    while True:
        try:
            r = subprocess.run(
                [sys.executable] + sys.argv, env=env,
                timeout=max(BENCH_DEADLINE - (time.time() - _T0), 5))
            line = _take_result()
            if line:
                # measured (rc=0) or worker-side failure record (rc=3)
                print(line)
                break
            # rc=1 is the ONLY retryable worker outcome (init flap
            # after a successful probe — needs a fresh interpreter);
            # deterministic crashes (SIGSEGV/OOM-kill/negative rc)
            # must not crash-loop for half the deadline
            if r.returncode == 1 and \
                    BENCH_DEADLINE - (time.time() - _T0) > BENCH_DEADLINE / 2:
                sys.stderr.write("bench: worker init flap, relaunching\n")
                time.sleep(PROBE_BACKOFF_S)
                continue
            _emit_failure(RuntimeError(
                f"bench worker exited rc={r.returncode} "
                "without a result"))
        except subprocess.TimeoutExpired:
            line = _take_result()
            if line:
                print(line)
            else:
                _emit_failure(TimeoutError(
                    f"bench exceeded BENCH_DEADLINE="
                    f"{BENCH_DEADLINE:.0f}s (hung backend init or run)"))
        except Exception as err:
            _emit_failure(err)
        break


if __name__ == "__main__":
    if os.environ.get("BENCH_WORKER") != "1":
        _supervise()
    else:
        try:
            main()
        except _RetryableInitError:
            # no line printed: rc=1 tells the supervisor to relaunch
            import traceback
            traceback.print_exc(file=sys.stderr)
            sys.exit(1)
        except Exception as err:  # emit data, never a bare stack trace
            import traceback
            traceback.print_exc(file=sys.stderr)
            _emit_failure(err)
            sys.exit(3)
