"""Static guard against the eager-loop regression class.

PROFILE.md (round 5) records a 530 ms/iter regression whose root cause
was a ``lax`` loop dispatching eagerly — op-by-op through the device
tunnel — instead of inside one jitted program. Op-level timing looks
fine in microbenchmarks, so nothing catches it at runtime; this lint
catches it at review time instead: every ``lax.fori_loop`` /
``lax.scan`` / ``lax.while_loop`` call in the boosting path
(``models/gbdt.py`` + ``ops/``) must live inside a function on the
KNOWN_JITTED allowlist — functions whose only entry is through a
``jax.jit`` wrapper (``grow_tree``, the fused-iteration program, the
prediction jits).

Adding a new device loop? Put it behind a jitted entry point, register
that entry point with ``obs.register_jit`` (so recompiles are counted),
and add the enclosing function here.
"""

import ast
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "lightgbm_tpu")

LOOP_NAMES = {"fori_loop", "scan", "while_loop"}

# root-level functions whose bodies are only ever traced (verified:
# every call path enters through a jax.jit wrapper)
KNOWN_JITTED = {
    ("ops/gather.py", "_gather_small"),      # gather_small jit
    ("ops/grow.py", "_grow_masked_impl"),    # grow_tree jit
    ("ops/grow.py", "_grow_compact_impl"),   # grow_tree jit
    ("ops/histogram.py", "_hist_from_rows_impl"),
    ("ops/histogram.py", "_hist_scatter"),
    ("ops/predict.py", "_traverse"),         # predict jits
    ("ops/predict.py", "predict_forest_raw"),
}


def _hot_path_files():
    out = [os.path.join(PKG, "models", "gbdt.py")]
    ops = os.path.join(PKG, "ops")
    out.extend(os.path.join(ops, f) for f in sorted(os.listdir(ops))
               if f.endswith(".py"))
    return out


def _loop_sites(path):
    """(lineno, loop_name, root_function) of every lax loop call."""
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    sites = []

    def visit(node, stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack = stack + [node.name]
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in LOOP_NAMES:
                root = stack[0] if stack else "<module>"
                sites.append((node.lineno, fn.attr, root))
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    visit(tree, [])
    return sites


def test_no_eager_lax_loops_in_boosting_path():
    offenders = []
    for path in _hot_path_files():
        rel = os.path.relpath(path, PKG).replace(os.sep, "/")
        for lineno, loop, root in _loop_sites(path):
            if (rel, root) not in KNOWN_JITTED:
                offenders.append(f"{rel}:{lineno}: lax.{loop} in "
                                 f"{root}() is not on the KNOWN_JITTED "
                                 "allowlist")
    assert not offenders, (
        "eager-dispatch risk (PROFILE.md 530 ms/iter class):\n  "
        + "\n  ".join(offenders))


def test_allowlist_entries_still_exist():
    """A renamed/deleted function must be pruned from the allowlist —
    stale entries would silently stop guarding anything."""
    live = set()
    for path in _hot_path_files():
        rel = os.path.relpath(path, PKG).replace(os.sep, "/")
        for _, _, root in _loop_sites(path):
            live.add((rel, root))
    stale = KNOWN_JITTED - live
    assert not stale, f"prune stale allowlist entries: {sorted(stale)}"
