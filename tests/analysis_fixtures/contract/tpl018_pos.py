"""TPL018 positives: fault-kind drift from the registry."""

# EXPECT: TPL018
_KNOWN_KINDS = ("ping_kill",)

# EXPECT: TPL018
_ONE_SHOT_KINDS = ("ping_slow",)


def trip(plan, log):
    # EXPECT: TPL018
    record_fault_event("ping_oops", 0, "raise", "bad kind")
    # observational kinds are legal for writers, not for plan gates
    # EXPECT: TPL018
    if plan.fires("ping_seen", 0):
        pass


def record_fault_event(kind, iteration, action, detail):
    pass
