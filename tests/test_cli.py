"""CLI application tests (the reference's examples/*/train.conf pattern,
tests/python_package_test/test_consistency.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import make_synthetic_binary

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import main, parse_args, load_config_file


@pytest.fixture(scope="module")
def train_csv(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    X, y = make_synthetic_binary(n=500, f=5)
    arr = np.column_stack([y, X])
    path = d / "train.csv"
    np.savetxt(path, arr, delimiter=",", fmt="%.8g")
    return str(path), X, y


def test_config_file_parsing(tmp_path):
    conf = tmp_path / "train.conf"
    conf.write_text(
        "# comment line\n"
        "task = train\n"
        "objective=binary  # trailing comment\n"
        "num_trees = 7\n"
        "\n")
    kv = load_config_file(str(conf))
    assert kv == {"task": "train", "objective": "binary", "num_trees": "7"}
    params = parse_args([f"config={conf}", "num_iterations=9"])
    # CLI pair wins over config-file pair, alias resolved
    assert params["num_iterations"] == "9"
    assert params["objective"] == "binary"


def test_cli_train_predict_roundtrip(train_csv, tmp_path):
    path, X, y = train_csv
    model_out = str(tmp_path / "model.txt")
    rc = main([
        "task=train", f"data={path}", "objective=binary",
        "num_iterations=8", "num_leaves=7", "min_data_in_leaf=5",
        f"output_model={model_out}", "verbosity=-1",
    ])
    assert rc == 0
    assert os.path.exists(model_out)

    pred_out = str(tmp_path / "preds.txt")
    rc = main([
        "task=predict", f"data={path}", f"input_model={model_out}",
        f"output_result={pred_out}", "verbosity=-1",
    ])
    assert rc == 0
    preds = np.loadtxt(pred_out)
    assert preds.shape[0] == len(y)
    acc = ((preds > 0.5) == y).mean()
    assert acc > 0.8


def test_cli_snapshot_and_continue(train_csv, tmp_path):
    path, X, y = train_csv
    model_out = str(tmp_path / "model.txt")
    rc = main([
        "task=train", f"data={path}", "objective=binary",
        "num_iterations=4", "num_leaves=7", "min_data_in_leaf=5",
        "snapshot_freq=2", f"output_model={model_out}", "verbosity=-1",
    ])
    assert rc == 0
    assert os.path.exists(model_out + ".snapshot_iter_2")
    # continued training from the saved model
    model2 = str(tmp_path / "model2.txt")
    rc = main([
        "task=train", f"data={path}", "objective=binary",
        "num_iterations=2", "num_leaves=7", "min_data_in_leaf=5",
        f"input_model={model_out}", f"output_model={model2}",
        "verbosity=-1",
    ])
    assert rc == 0
    bst = lgb.Booster(model_file=model2)
    assert bst.num_trees() == 6


def test_cli_convert_model_compiles_and_matches(train_csv, tmp_path):
    path, X, y = train_csv
    model_out = str(tmp_path / "model.txt")
    main(["task=train", f"data={path}", "objective=binary",
          "num_iterations=5", "num_leaves=7", "min_data_in_leaf=5",
          f"output_model={model_out}", "verbosity=-1"])
    cpp_out = str(tmp_path / "model.cpp")
    rc = main(["task=convert_model", f"input_model={model_out}",
               f"convert_model={cpp_out}", "verbosity=-1"])
    assert rc == 0
    src = open(cpp_out).read()
    assert "PredictTree0" in src and "void Predict(" in src

    # compile + run the generated code against the python predictions
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    harness = tmp_path / "harness.cpp"
    harness.write_text(
        '#include <cstdio>\n#include "model.cpp"\n'
        "int main(){double fval[%d]; double out[1];\n"
        "  while (scanf(\"%%lf %%lf %%lf %%lf %%lf\", &fval[0],&fval[1],"
        "&fval[2],&fval[3],&fval[4])==5){\n"
        "    lightgbm_tpu_model::Predict(fval,out);"
        "printf(\"%%.10f\\n\",out[0]);}\n  return 0;}\n" % X.shape[1])
    exe = str(tmp_path / "model_exe")
    subprocess.run(["g++", "-O1", "-o", exe, str(harness)],
                   check=True, cwd=tmp_path)
    inp = "\n".join(" ".join(f"{v:.10g}" for v in row) for row in X[:50])
    res = subprocess.run([exe], input=inp, capture_output=True, text=True,
                         check=True)
    cpp_preds = np.array([float(s) for s in res.stdout.split()])
    bst = lgb.Booster(model_file=model_out)
    py_preds = bst.predict(X[:50])
    np.testing.assert_allclose(cpp_preds, py_preds, rtol=1e-6, atol=1e-6)


def test_cli_refit(train_csv, tmp_path):
    path, X, y = train_csv
    model_out = str(tmp_path / "model.txt")
    main(["task=train", f"data={path}", "objective=binary",
          "num_iterations=5", "num_leaves=7", "min_data_in_leaf=5",
          f"output_model={model_out}", "verbosity=-1"])
    refit_out = str(tmp_path / "refit.txt")
    rc = main(["task=refit", f"data={path}", f"input_model={model_out}",
               f"output_model={refit_out}", "verbosity=-1"])
    assert rc == 0
    bst = lgb.Booster(model_file=refit_out)
    assert bst.num_trees() == 5


def test_save_binary_roundtrip(train_csv, tmp_path):
    path, X, y = train_csv
    rc = main(["task=save_binary", f"data={path}", "verbosity=-1"])
    assert rc == 0
    bin_path = path + ".bin"
    assert os.path.exists(bin_path)

    # binary load must give identical bins + metadata and train fine
    ds_txt = lgb.Dataset(path).construct()
    ds_bin = lgb.Dataset(bin_path).construct()
    np.testing.assert_array_equal(ds_txt.host_bins(), ds_bin.host_bins())
    np.testing.assert_array_equal(ds_txt.get_label(), ds_bin.get_label())
    assert ds_txt.get_feature_name() == ds_bin.get_feature_name()

    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds_bin, num_boost_round=5)
    pred = bst.predict(X)
    assert (((pred > 0.5) == y).mean()) > 0.8
    os.remove(bin_path)
