"""Dataset and Booster — the user-facing core API.

Re-design of the reference python-package surface
(/root/reference/python-package/lightgbm/basic.py: Dataset :1744, Booster
:3539) fused with the C++ layers it fronts (src/io/dataset.cpp,
dataset_loader.cpp, metadata.cpp, src/c_api.cpp): there is no C API /
ctypes boundary here — binning is host numpy, training state is JAX arrays
in HBM, and the model is numpy trees (models/tree.py).
"""

from __future__ import annotations

import io
import os
import threading
import warnings
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .config import ALIASES, Config, resolve_params
from .metrics import create_metrics
from .objectives import create_objective
from .ops.binning import BinMapper, BinType, MissingType, bin_values, find_bin

__all__ = ["Dataset", "Booster", "LightGBMError", "Sequence"]


class LightGBMError(Exception):
    """Error class (matches the reference package's exception name)."""


def _is_1d(a) -> bool:
    return hasattr(a, "ndim") and a.ndim == 1


def _load_text_file(path: str, cfg: Config
                    ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray],
                               Optional[np.ndarray]]:
    """Parse CSV/TSV/LibSVM into (X, label, weight, group).

    Format auto-detection follows Parser::CreateParser
    (/root/reference/src/io/parser.cpp): sniff the first lines for tabs,
    commas, or 'idx:value' pairs. Companion ``<file>.weight`` /
    ``<file>.query`` files are honored like Metadata::Init
    (src/io/metadata.cpp).
    """
    with open(path, "r") as f:
        first = f.readline().strip()
    header = cfg.header
    sep = None
    if "\t" in first:
        sep = "\t"
    elif "," in first:
        sep = ","
    tokens = first.replace(",", " ").replace("\t", " ").split()
    is_libsvm = any(":" in t for t in tokens[1:])

    label_col = 0
    lc = str(cfg.label_column)
    if lc.startswith("name:"):
        # resolve against the header line (Config::label_column name:
        # form, config.h; DataLoader maps it through the header)
        want = lc[len("name:"):]
        if not header:
            raise LightGBMError(
                "label_column='name:...' requires header=true")
        names = [t.strip() for t in
                 (first.split(sep) if sep else first.split())]
        if want not in names:
            raise LightGBMError(
                f"label column '{want}' not found in header: {names}")
        label_col = names.index(want)
    elif lc != "":
        label_col = int(lc)

    if is_libsvm:
        labels, rows = [], []
        max_idx = -1
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = {}
                for tok in parts[1:]:
                    if ":" not in tok:
                        continue
                    i, v = tok.split(":")
                    i = int(i)
                    row[i] = float(v)
                    max_idx = max(max_idx, i)
                rows.append(row)
        X = np.zeros((len(rows), max_idx + 1))
        for r, row in enumerate(rows):
            for i, v in row.items():
                X[r, i] = v
        y = np.asarray(labels)
    else:
        # native OpenMP parser (src/io/parser.cpp analog); numpy is the
        # no-compiler fallback
        from .utils.native import parse_dense_text
        raw = parse_dense_text(path, bool(header))
        if raw is None:
            raw = np.genfromtxt(path, delimiter=sep,
                                skip_header=1 if header else 0)
        if raw.ndim == 1:
            raw = raw[:, None]
        y = raw[:, label_col].copy()
        X = np.delete(raw, label_col, axis=1)

    weight = None
    group = None
    wfile = path + ".weight"
    if os.path.exists(wfile):
        weight = np.loadtxt(wfile)
    qfile = path + ".query"
    if os.path.exists(qfile):
        group = np.loadtxt(qfile).astype(np.int64)
    return X, y, weight, group


def _two_round_load(path: str, cfg: Config, cat_idx_set,
                    feature_name):
    """Two-round / out-of-core text loading (``two_round=true``;
    dataset_loader.cpp:299,960 LoadFromFile's two-pass path).

    Round 1 streams the file once: counts rows and reservoir-samples up
    to ``bin_construct_sample_cnt`` raw lines; BinMappers are built from
    the sample only (the reference's SampleTextDataFromFile +
    ConstructBinMappersFromTextData). Round 2 streams again in bounded
    chunks, parsing and binning each chunk straight into the
    preallocated u8/u16 matrix — the raw float matrix is NEVER
    materialized, so peak memory is the BINNED matrix (1-2 bytes/value)
    plus one chunk, not 8 bytes/value.

    Returns (bins [n, F_used], mappers, used, full_mappers, n, F,
    label, weight, group).
    """
    from .ops.binning import BinType, bin_values, find_bin

    with open(path, "r") as f:
        first = f.readline().strip()
    sep = "\t" if "\t" in first else ("," if "," in first else None)
    tokens = first.replace(",", " ").replace("\t", " ").split()
    if any(":" in t for t in tokens[1:]):
        return None  # libsvm rows are ragged; eager loader handles them
    header = bool(cfg.header)
    label_col = 0
    lc = str(cfg.label_column)
    if lc.startswith("name:"):
        # resolve against the header HERE rather than deferring to the
        # eager loader: a user sets two_round precisely because the
        # file dwarfs host RAM, so falling back to the full-matrix
        # loader would defeat the mode on exactly its target input.
        # Silently assuming column 0 trained on a feature as the
        # label (ADVICE r4).
        want = lc[len("name:"):]
        if not header:
            raise LightGBMError(
                "label_column='name:...' requires header=true")
        names = [t.strip() for t in
                 (first.split(sep) if sep else first.split())]
        if want not in names:
            raise LightGBMError(
                f"label column '{want}' not found in header: {names}")
        label_col = names.index(want)
    elif lc:
        label_col = int(lc)

    # ---- round 1: count + reservoir sample ----
    rs = np.random.RandomState(cfg.data_random_seed)
    cap = max(int(cfg.bin_construct_sample_cnt), 2)
    sample_lines: List[str] = []
    n = 0
    with open(path, "r") as f:
        if header:
            f.readline()
        for line in f:
            if not line.strip():
                continue
            if n < cap:
                sample_lines.append(line)
            else:
                j = int(rs.randint(0, n + 1))
                if j < cap:
                    sample_lines[j] = line
            n += 1
    if n == 0:
        raise LightGBMError(f"empty data file {path}")

    def parse_lines(lines):
        try:
            # np.loadtxt's C tokenizer: fast and allocation-light (the
            # python-object row lists genfromtxt builds would dominate
            # the loader's peak memory)
            arr = np.loadtxt(lines, delimiter=sep, ndmin=2)
        except ValueError:
            arr = np.genfromtxt(lines, delimiter=sep)
            if arr.ndim == 1:
                arr = arr[None, :] if len(lines) == 1 else arr[:, None]
        return arr

    sample = parse_lines(sample_lines)
    del sample_lines
    F = sample.shape[1] - 1
    Xs = np.delete(sample, label_col, axis=1)
    del sample

    # ---- mappers from the sample only ----
    full_mappers = []
    for j in range(F):
        mb = cfg.max_bin
        if cfg.max_bin_by_feature and j < len(cfg.max_bin_by_feature):
            mb = cfg.max_bin_by_feature[j]
        m = find_bin(
            Xs[:, j], mb,
            min_data_in_bin=cfg.min_data_in_bin,
            bin_type=(BinType.CATEGORICAL if j in cat_idx_set
                      else BinType.NUMERICAL),
            use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing)
        full_mappers.append(m)
    del Xs
    used = [j for j, m in enumerate(full_mappers) if not m.is_trivial]
    mappers = [full_mappers[j] for j in used]
    max_bins = max((m.num_bins for m in mappers), default=2)
    bdtype = np.uint8 if max_bins <= 256 else np.uint16

    # ---- round 2: chunked parse -> bin in place ----
    CHUNK = 16384
    bins = np.zeros((n, len(used)), bdtype)
    label = np.zeros(n, np.float64)
    row = 0
    with open(path, "r") as f:
        if header:
            f.readline()
        buf: List[str] = []
        for line in f:
            if not line.strip():
                continue
            buf.append(line)
            if len(buf) == CHUNK:
                arr = parse_lines(buf)
                label[row:row + len(buf)] = arr[:, label_col]
                Xc = np.delete(arr, label_col, axis=1)
                bins[row:row + len(buf)] = bin_values(
                    [Xc[:, j] for j in used], mappers, bdtype)
                row += len(buf)
                buf = []
        if buf:
            arr = parse_lines(buf)
            label[row:row + len(buf)] = arr[:, label_col]
            Xc = np.delete(arr, label_col, axis=1)
            bins[row:row + len(buf)] = bin_values(
                [Xc[:, j] for j in used], mappers, bdtype)
            row += len(buf)
    if row != n:
        raise LightGBMError(
            f"two_round: second pass read {row} rows, first pass {n}")

    weight = None
    group = None
    if os.path.exists(path + ".weight"):
        weight = np.loadtxt(path + ".weight")
    if os.path.exists(path + ".query"):
        group = np.loadtxt(path + ".query").astype(np.int64)
    return (bins, mappers, np.asarray(used, np.int32), full_mappers,
            n, F, label, weight, group)


def _extract_pandas(data, categorical_feature):
    """Pandas ingestion: category dtypes -> integer codes (the
    pandas_categorical path of basic.py _data_from_pandas)."""
    import pandas as pd
    feature_name = [str(c) for c in data.columns]
    cat_cols = []
    pandas_categorical = []
    arrs = []
    for i, col in enumerate(data.columns):
        s = data[col]
        if isinstance(s.dtype, pd.CategoricalDtype):
            cat_cols.append(i)
            pandas_categorical.append(list(s.cat.categories))
            codes = s.cat.codes.to_numpy().astype(np.float64)
            codes[codes < 0] = np.nan
            arrs.append(codes)
        else:
            arrs.append(s.to_numpy(dtype=np.float64, na_value=np.nan))
    X = np.column_stack(arrs) if arrs else np.zeros((len(data), 0))
    if categorical_feature in ("auto", None, ""):
        cat_idx = cat_cols
    else:
        cat_idx = _resolve_cat_indices(categorical_feature, feature_name)
    return X, feature_name, cat_idx, pandas_categorical


def _resolve_cat_indices(categorical_feature, feature_name) -> List[int]:
    out = []
    for c in categorical_feature or []:
        if isinstance(c, str):
            if c in feature_name:
                out.append(feature_name.index(c))
            else:
                raise LightGBMError(f"Unknown categorical feature {c}")
        else:
            out.append(int(c))
    return sorted(set(out))


class Sequence:
    """Generic chunked data source (the reference's abstract streaming
    Sequence, python-package/lightgbm/basic.py:903): subclass with
    ``__getitem__`` (row index or slice -> numpy rows), ``__len__``,
    and optionally ``batch_size``. A Sequence (or list of Sequences) is
    a valid ``Dataset(data=...)`` — rows are pulled batch by batch, so
    the raw source never needs to be materialized at once by the
    caller."""

    batch_size = 4096

    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError("Sequence.__getitem__")

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError("Sequence.__len__")


def _extract_arrow(data):
    """pyarrow Table / RecordBatch -> [n, F] float64 + column names
    (the reference's Arrow C-data-interface ingest, arrow.h)."""
    import pyarrow as pa

    if isinstance(data, pa.RecordBatch):
        data = pa.Table.from_batches([data])
    if isinstance(data, (pa.ChunkedArray, pa.Array)):
        col = data.combine_chunks() if isinstance(data, pa.ChunkedArray) \
            else data
        return np.asarray(col, dtype=np.float64)[:, None], []
    if not isinstance(data, pa.Table):
        raise LightGBMError(
            f"Unsupported pyarrow input {type(data)}; pass a Table, "
            "RecordBatch or Array")
    cols = []
    for name in data.column_names:
        col = data.column(name)
        np_col = col.to_numpy(zero_copy_only=False)
        cols.append(np.asarray(np_col, dtype=np.float64))
    X = np.column_stack(cols) if cols else np.zeros((data.num_rows, 0))
    return X, list(data.column_names)


class Dataset:
    """Binned training data container (Dataset + Metadata + DatasetLoader
    analog: dataset.h:48-555, dataset_loader.cpp)."""

    _construct_tl = threading.local()

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, position=None):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.position = position
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = resolve_params(params)
        self.free_raw_data = free_raw_data
        self._handle = None  # "constructed" flag
        # constructed state
        self.mappers: List[BinMapper] = []
        self._bins: Optional[np.ndarray] = None       # [n, F_used]
        self._used_features: Optional[np.ndarray] = None
        self._device_bins = None
        self._data_digest: Optional[str] = None
        self._host_bins_freed = False
        self._feature_names: List[str] = []
        self._pandas_categorical = None
        self._n: int = 0
        self._F: int = 0
        self._query_boundaries: Optional[np.ndarray] = None
        self.used_indices = None

    # -- streaming push ingest (LGBM_DatasetInitStreaming /
    # PushRows[WithMetadata] / MarkFinished, c_api.h:177-323): rows and
    # their metadata arrive in arbitrary-order batches into a
    # preallocated host staging area; construction (binning + device
    # upload) happens once at mark_finished ----------------------------
    @classmethod
    def init_streaming(cls, num_rows: int, num_features: int,
                       **dataset_kwargs) -> "Dataset":
        ds = cls(data=np.zeros((0, num_features)), **dataset_kwargs)
        ds.data = np.full((num_rows, num_features), np.nan, np.float64)
        ds._stream_label = np.zeros(num_rows, np.float64)
        ds._stream_weight = None
        ds._stream_filled = np.zeros(num_rows, bool)
        ds._stream_total = num_rows
        return ds

    def push_rows(self, mat, start_row: int = None, label=None,
                  weight=None) -> "Dataset":
        """Append (or place, with ``start_row``) a batch of raw rows;
        the WithMetadata variant is the optional label/weight args."""
        if getattr(self, "_stream_filled", None) is None:
            raise LightGBMError(
                "push_rows requires a Dataset.init_streaming dataset")
        mat = np.atleast_2d(np.asarray(mat, np.float64))
        if start_row is None:
            filled = np.flatnonzero(~self._stream_filled)
            start_row = int(filled[0]) if len(filled) else \
                self._stream_total
        end = start_row + mat.shape[0]
        if end > self._stream_total:
            raise LightGBMError("push_rows beyond the declared num_rows")
        self.data[start_row:end] = mat
        self._stream_filled[start_row:end] = True
        if label is not None:
            self._stream_label[start_row:end] = np.asarray(label).ravel()
        if weight is not None:
            if self._stream_weight is None:
                self._stream_weight = np.ones(self._stream_total,
                                              np.float64)
            self._stream_weight[start_row:end] = \
                np.asarray(weight).ravel()
        return self

    def mark_finished(self) -> "Dataset":
        """All pushes done -> bin and construct (MarkFinished)."""
        if getattr(self, "_stream_filled", None) is None:
            raise LightGBMError(
                "mark_finished requires a Dataset.init_streaming dataset")
        if not self._stream_filled.all():
            missing = int((~self._stream_filled).sum())
            raise LightGBMError(
                f"streaming dataset has {missing} unpushed rows")
        if self.label is None:
            self.label = self._stream_label
        if self.weight is None and self._stream_weight is not None:
            self.weight = self._stream_weight
        self._stream_filled = None
        return self.construct()

    # -- binary serialization (save_binary, dataset.h:692 /
    # dataset_loader.cpp:417 LoadFromBinFile analog: the binned matrix +
    # mappers + metadata round-trip so re-runs skip parsing and binning) --
    _BIN_MAGIC = "lightgbm_tpu.dataset.v1"

    def save_binary(self, filename) -> "Dataset":
        self.construct()
        import json
        meta = {
            "magic": self._BIN_MAGIC,
            "mappers": [m.to_dict() for m in self.mappers],
            "full_mappers": [m.to_dict() if m is not None else None
                             for m in self._full_mappers],
            "feature_names": self._feature_names,
            "F_total": int(self._F_total),
            "cat_idx": sorted(int(c) for c in self._cat_idx),
        }
        arrays = {
            "bins": self._bins,
            "used_features": self._used_features,
            "meta_json": np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8),
        }
        if self.label is not None:
            arrays["label"] = np.asarray(self.label, np.float64)
        if self.weight is not None:
            arrays["weight"] = np.asarray(self.weight, np.float64)
        if self._query_boundaries is not None:
            arrays["query_boundaries"] = self._query_boundaries
        if self.init_score is not None:
            arrays["init_score"] = np.asarray(self.init_score, np.float64)
        with open(filename, "wb") as f:
            np.savez(f, **arrays)
        return self

    @staticmethod
    def _is_binary_file(path: str) -> bool:
        """Probe for our npz container: zip magic + the meta_json member.
        A text file that merely starts with 'PK' falls through to the
        text parser."""
        try:
            with open(path, "rb") as f:
                if f.read(4) != b"PK\x03\x04":
                    return False
            with np.load(path, allow_pickle=False) as z:
                return "meta_json" in z.files
        except (OSError, ValueError, KeyError):
            return False

    def _construct_from_binary(self, path: str) -> "Dataset":
        import json
        from .ops.binning import BinMapper
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(bytes(z["meta_json"]).decode())
            if meta.get("magic") != self._BIN_MAGIC:
                raise LightGBMError(f"{path} is not a lightgbm_tpu "
                                    "binary dataset")
            self._bins = z["bins"]
            self._used_features = z["used_features"].astype(np.int32)
            if "label" in z.files and self.label is None:
                self.label = z["label"]
            if "weight" in z.files and self.weight is None:
                self.weight = z["weight"]
            if "query_boundaries" in z.files:
                self._query_boundaries = z["query_boundaries"]
            if "init_score" in z.files and self.init_score is None:
                self.init_score = z["init_score"]
        self.mappers = [BinMapper.from_dict(d) for d in meta["mappers"]]
        self._feature_names = meta["feature_names"]
        self._F_total = meta["F_total"]
        self._cat_idx = set(meta["cat_idx"])
        self._full_mappers = [None if d is None else BinMapper.from_dict(d)
                              for d in meta["full_mappers"]]
        self._n = self._bins.shape[0]
        self._F = len(self.mappers)

        # a valid set loaded from binary must share the reference's bin
        # mappers (LoadFromBinFile alignment checks, dataset_loader.cpp)
        if self.reference is not None:
            ref = self.reference.construct()
            ref_dicts = [m.to_dict() for m in ref.mappers]
            own_dicts = [m.to_dict() for m in self.mappers]
            if ref_dicts != own_dicts:
                raise LightGBMError(
                    f"Binary dataset {path} was binned differently from "
                    "its reference dataset; rebuild it from text against "
                    "the same training data")

        # metadata supplied by the caller wins over the stored copies and
        # gets the same normalization/validation as the text path
        if self.label is not None:
            self.label = np.asarray(self.label, np.float64).ravel()
            if len(self.label) != self._n:
                raise LightGBMError(
                    f"Length of label ({len(self.label)}) != number of "
                    f"rows ({self._n})")
        if self.weight is not None:
            self.weight = np.asarray(self.weight, np.float64).ravel()
        if self.group is not None:
            g = np.asarray(self.group, np.int64).ravel()
            self._query_boundaries = np.concatenate(
                [[0], np.cumsum(g)]).astype(np.int64)
            if self._query_boundaries[-1] != self._n:
                raise LightGBMError("Sum of group sizes != number of rows")
        if self.init_score is not None:
            self.init_score = np.asarray(self.init_score, np.float64)
        self._handle = True
        if self.free_raw_data:
            self.data = None
        return self

    # -- construction ---------------------------------------------------
    def construct(self) -> "Dataset":
        if self._handle is not None:
            return self
        # time only the OUTERMOST construct: an unconstructed
        # `reference` chain re-enters here and would double-count the
        # inner duration under the same label
        tl = Dataset._construct_tl
        if getattr(tl, "depth", 0):
            return self._construct_impl()
        from .utils.timer import timed
        tl.depth = 1
        try:
            with timed("dataset/construct"):
                return self._construct_impl()
        finally:
            tl.depth = 0

    def _construct_impl(self) -> "Dataset":
        cfg = Config.from_params(self.params)
        data = self.data
        label = self.label
        weight = self.weight
        group = self.group

        cat_idx: List[int] = []
        feature_name = self.feature_name
        if isinstance(data, (str, Path)) and self._is_binary_file(str(data)):
            return self._construct_from_binary(str(data))
        # out-of-core streaming construct (lightgbm_tpu/data/): chunk
        # sources always stream; text/parquet paths stream when
        # ingest_chunk_rows > 0 (docs/DATA.md). The dense float matrix
        # never exists on this path.
        from .data.sources import coerce_chunk_source
        chunk_src = coerce_chunk_source(data, cfg)
        if chunk_src is not None:
            return self._construct_streaming(cfg, chunk_src, label,
                                             weight, group)
        if isinstance(data, (str, Path)):
            if cfg.two_round and self.reference is None:
                cat_set = set()
                cat_ok = True
                for src in (self.categorical_feature,
                            cfg.categorical_feature):
                    if src in ("auto", "", None):
                        continue
                    if isinstance(src, str):
                        src = [c for c in src.split(",") if c]
                    if isinstance(src, (list, tuple)):
                        try:
                            cat_set |= {int(c) for c in src}
                            continue
                        except (TypeError, ValueError):
                            pass
                    # name-based spec needs the parsed header; the
                    # eager loader resolves it
                    cat_ok = False
                out = _two_round_load(str(data), cfg, cat_set,
                                      feature_name) if cat_ok else None
                if out is not None:
                    return self._finish_two_round(cfg, out, label,
                                                  weight, group,
                                                  cat_set)
            X, y, w, q = _load_text_file(str(data), cfg)
            if label is None:
                label = y
            if weight is None and w is not None:
                weight = w
            if group is None and q is not None:
                group = q
        else:
            try:
                import pandas as pd
                is_pandas = isinstance(data, pd.DataFrame)
            except ImportError:
                is_pandas = False
            if is_pandas:
                X, names, cat_idx, self._pandas_categorical = _extract_pandas(
                    data, self.categorical_feature)
                if feature_name == "auto":
                    feature_name = names
                try:
                    import pandas as pd
                    if isinstance(label, (pd.Series, pd.DataFrame)):
                        label = label.to_numpy().ravel()
                except ImportError:
                    pass
            elif type(data).__module__.split(".")[0] == "pyarrow":
                # Arrow ingest (the C-data-interface path of the
                # reference, include/LightGBM/arrow.h): Tables /
                # RecordBatches column-by-column, chunked arrays
                # concatenated; per-column to_numpy is zero-copy for
                # non-null numeric chunks
                X, names = _extract_arrow(data)
                if feature_name == "auto" and names:
                    feature_name = names
            elif hasattr(data, "tocsr") or hasattr(data, "toarray"):
                X = np.asarray(data.todense(), dtype=np.float64)
            elif isinstance(data, np.ndarray):
                # float32 is kept WITHOUT a whole-matrix float64 copy:
                # every consumer (find_bin, bin_values, _raw_numeric)
                # casts per column, so upcasting here would only
                # double peak host RSS — at Allstate-bench scale
                # (2M x 4228) that is the difference between ~44 GB
                # and OOM. Mirrors the reference accepting float32
                # buffers (C_API_DTYPE_FLOAT32, c_api.h).
                X = data if data.dtype == np.float32 \
                    else np.asarray(data, dtype=np.float64)
                if X.ndim == 1:
                    X = X[:, None]
            elif isinstance(data, (list, tuple)):
                X = np.asarray(data, dtype=np.float64)
            else:
                raise LightGBMError(
                    f"Cannot construct Dataset from {type(data)}")

        if label is None:
            raise LightGBMError("Label should not be None")
        y = np.asarray(label, dtype=np.float64).ravel()
        n, F = X.shape
        if len(y) != n:
            raise LightGBMError(
                f"Length of label ({len(y)}) != number of rows ({n})")
        self._n, self._F_total = n, F

        if not isinstance(feature_name, list) or feature_name == "auto":
            feature_name = [f"Column_{i}" for i in range(F)]
        self._feature_names = list(feature_name)

        if not cat_idx and self.categorical_feature not in ("auto", None, ""):
            cat_idx = _resolve_cat_indices(self.categorical_feature,
                                           self._feature_names)
        cat_param = cfg.categorical_feature
        if not cat_idx and cat_param not in ("auto", "", None):
            if isinstance(cat_param, str):
                cat_param = [c for c in cat_param.split(",") if c]
            cat_idx = _resolve_cat_indices(cat_param, self._feature_names)
        self._cat_idx = set(cat_idx)

        # -- binning: reuse the reference dataset's mappers for alignment
        # (LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:299) --
        if self.reference is not None:
            ref = self.reference.construct()
            self.mappers = ref.mappers
            self._used_features = ref._used_features
            self._feature_names = ref._feature_names
            full_mappers = ref._full_mappers
        else:
            max_bin = cfg.max_bin
            sample_cnt = min(cfg.bin_construct_sample_cnt, n)
            if sample_cnt < n:
                rng = np.random.RandomState(cfg.data_random_seed)
                sample_rows = rng.choice(n, size=sample_cnt, replace=False)
            else:
                sample_rows = slice(None)
            full_mappers = []
            for j in range(F):
                mb = max_bin
                if cfg.max_bin_by_feature and j < len(cfg.max_bin_by_feature):
                    mb = cfg.max_bin_by_feature[j]
                m = find_bin(
                    X[sample_rows, j], mb,
                    min_data_in_bin=cfg.min_data_in_bin,
                    bin_type=(BinType.CATEGORICAL if j in self._cat_idx
                              else BinType.NUMERICAL),
                    use_missing=cfg.use_missing,
                    zero_as_missing=cfg.zero_as_missing)
                full_mappers.append(m)
            used = [j for j, m in enumerate(full_mappers) if not m.is_trivial]
            self._used_features = np.asarray(used, dtype=np.int32)
            self.mappers = [full_mappers[j] for j in used]
        self._full_mappers = full_mappers

        from .ops.binning import bin_matrix
        self._bins = bin_matrix(X, self._used_features, self.mappers)
        self._F = len(self.mappers)
        # linear trees fit on raw numerical values (the reference keeps
        # raw data when linear_tree is set — Dataset raw_data_, dataset.h).
        # Datasets aligned to a reference inherit its retention so valid
        # sets of a linear model can be scored.
        if cfg.linear_tree or (self.reference is not None
                               and self.reference.raw_numeric() is not None):
            self._raw_numeric = (
                np.asarray(X)[:, self._used_features].astype(
                    np.float32, copy=False)
                if len(self._used_features)
                else np.zeros((n, 0), np.float32))
        else:
            self._raw_numeric = None

        self.label = y
        self.weight = None if weight is None else \
            np.asarray(weight, np.float64).ravel()
        if group is not None:
            g = np.asarray(group, np.int64).ravel()
            self._query_boundaries = np.concatenate(
                [[0], np.cumsum(g)]).astype(np.int64)
            if self._query_boundaries[-1] != n:
                raise LightGBMError(
                    "Sum of group sizes != number of rows")
        if self.init_score is not None:
            self.init_score = np.asarray(self.init_score,
                                         np.float64)
        self._handle = True
        if self.free_raw_data:
            self.data = None
        return self

    def _resolve_streaming_cats(self, cfg, src) -> set:
        """Categorical-feature resolution for chunk sources: integer
        indices always work; names resolve through the source's column
        names (CSV header, Arrow schema) when it has any. Precedence
        matches the eager constructor: the ``categorical_feature``
        argument wins outright, the params spec is only a fallback
        when the argument resolved to nothing."""
        cat_set = set()
        names = src.feature_names()
        for spec in (self.categorical_feature, cfg.categorical_feature):
            if cat_set:
                break
            if spec in ("auto", "", None):
                continue
            if isinstance(spec, str):
                spec = [c for c in spec.split(",") if c]
            for c in spec or []:
                try:
                    cat_set.add(int(c))
                    continue
                except (TypeError, ValueError):
                    pass
                if names and str(c) in names:
                    cat_set.add(names.index(str(c)))
                else:
                    raise LightGBMError(
                        f"categorical feature {c!r} cannot be resolved "
                        "for a chunked source without column names; "
                        "pass integer indices (or a header/Arrow "
                        "schema)")
        return cat_set

    def _construct_streaming(self, cfg, src, label, weight,
                             group) -> "Dataset":
        """Out-of-core construct (lightgbm_tpu/data/, docs/DATA.md):
        two-pass chunk ingestion — sample -> host-synced BinMappers ->
        chunk-by-chunk binning into the preallocated shard. The dense
        float matrix never exists; peak host memory scales with
        ``ingest_chunk_rows x n_features``, not dataset rows."""
        from .data.ingest import dataset_digest, ingest_dataset
        cat_set = self._resolve_streaming_cats(cfg, src)
        ref = None
        if self.reference is not None:
            ref = self.reference.construct()
        # linear trees fit on raw numerical values: pass 2 retains the
        # used-column f32 matrix — the eager path's exact retention
        # cost — instead of refusing the mode (valid sets inherit the
        # reference's retention so they can be scored)
        keep_raw = bool(cfg.linear_tree) or (
            ref is not None and ref.raw_numeric() is not None)
        res = ingest_dataset(src, cfg, cat_set, reference=ref,
                             keep_raw=keep_raw)
        y = res.label
        if label is not None:
            y = np.asarray(label, np.float64).ravel()
        if y is None:
            raise LightGBMError("Label should not be None")
        if len(y) != res.n:
            raise LightGBMError(
                f"Length of label ({len(y)}) != number of rows "
                f"({res.n})")
        if weight is None and res.weight is not None:
            weight = res.weight
        # companion metadata files of a streamed text path (Metadata::
        # Init semantics, like the eager and two-round loaders)
        path = getattr(src, "path", None)
        if path is not None:
            if weight is None and os.path.exists(path + ".weight"):
                weight = np.loadtxt(path + ".weight")
            if group is None and os.path.exists(path + ".query"):
                group = np.loadtxt(path + ".query").astype(np.int64)
        self._n, self._F_total = res.n, res.F
        fn = self.feature_name
        names = src.feature_names()
        if ref is not None:
            self._feature_names = list(ref._feature_names)
            self._cat_idx = set(ref._cat_idx)
        else:
            if isinstance(fn, list) and len(fn) == res.F:
                self._feature_names = list(fn)
            elif names and len(names) == res.F:
                self._feature_names = [str(c) for c in names]
            else:
                self._feature_names = [f"Column_{i}"
                                       for i in range(res.F)]
            self._cat_idx = set(cat_set)
        self.mappers = res.mappers
        self._used_features = res.used
        self._full_mappers = res.full_mappers
        self._bins = res.bins
        self._F = len(res.mappers)
        self._raw_numeric = res.raw
        # checkpoint data fingerprint: accumulated incrementally over
        # the pass-2 label/bin chunks; only an explicit label override
        # forces a recompute of the label leg
        if label is not None or res.digest is None:
            self._data_digest = dataset_digest(y, res.bins)
        else:
            self._data_digest = res.digest
        self._ingest_stats = res.stats
        return self._install_metadata(y, weight, group, res.n)

    def _finish_two_round(self, cfg, out, label, weight, group,
                          cat_set) -> "Dataset":
        """Install the out-of-core loader's pre-binned result (the tail
        of construct() without a raw float matrix ever existing)."""
        (bins, mappers, used, full_mappers, n, F, y, w, q) = out
        if label is not None:
            y = np.asarray(label, np.float64).ravel()
        if weight is None and w is not None:
            weight = w
        if group is None and q is not None:
            group = q
        if len(y) != n:
            raise LightGBMError(
                f"Length of label ({len(y)}) != number of rows ({n})")
        if cfg.linear_tree:
            raise LightGBMError(
                "two_round loading cannot retain raw data for "
                "linear_tree (the reference's two-pass loader has the "
                "same restriction on raw-data consumers)")
        self._n, self._F_total = n, F
        fn = self.feature_name
        if isinstance(fn, list) and len(fn) == F:
            self._feature_names = list(fn)
        else:
            self._feature_names = [f"Column_{i}" for i in range(F)]
        self._cat_idx = set(cat_set)
        self.mappers = mappers
        self._used_features = used
        self._full_mappers = full_mappers
        self._bins = bins
        self._F = len(mappers)
        self._raw_numeric = None
        return self._install_metadata(y, weight, group, n)

    def _install_metadata(self, y, weight, group, n) -> "Dataset":
        """Shared construct() tail: metadata coercion + validation +
        handle flip (used by the eager and two-round paths)."""
        self.label = y
        self.weight = None if weight is None else \
            np.asarray(weight, np.float64).ravel()
        if group is not None:
            g = np.asarray(group, np.int64).ravel()
            self._query_boundaries = np.concatenate(
                [[0], np.cumsum(g)]).astype(np.int64)
            if self._query_boundaries[-1] != n:
                raise LightGBMError(
                    "Sum of group sizes != number of rows")
        if self.init_score is not None:
            self.init_score = np.asarray(self.init_score, np.float64)
        self._handle = True
        if self.free_raw_data:
            self.data = None
        return self

    # -- introspection ---------------------------------------------------
    def num_data(self) -> int:
        self.construct()
        return self._n

    def num_features(self) -> int:
        """Number of *usable* (non-trivial) features."""
        self.construct()
        return self._F

    def num_total_features(self) -> int:
        self.construct()
        return self._F_total

    def num_total_bins(self) -> int:
        self.construct()
        return max((m.num_bins for m in self.mappers), default=2)

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._feature_names)

    def get_label(self):
        return self.label

    def get_weight(self):
        return self.weight

    def get_init_score(self):
        return self.init_score

    def get_group(self):
        if self._query_boundaries is None:
            return None
        return np.diff(self._query_boundaries)

    def get_position(self):
        """Per-row result-list positions for position-debiased LTR
        (Metadata::positions, dataset.h:48-398)."""
        return self.position

    def raw_numeric(self) -> Optional[np.ndarray]:
        """[n, F_used] float32 raw values (NaN preserved) — retained only
        when linear_tree is set (the reference's Dataset raw_data_)."""
        return getattr(self, "_raw_numeric", None)

    def set_position(self, position) -> "Dataset":
        self.position = None if position is None else \
            np.asarray(position).ravel()
        return self

    def query_boundaries(self) -> Optional[np.ndarray]:
        self.construct()
        return self._query_boundaries

    def set_label(self, label) -> "Dataset":
        self.label = np.asarray(label, np.float64).ravel()
        # a streaming construct's precomputed checkpoint fingerprint
        # covered the OLD labels; drop it so the checkpoint layer
        # rehashes the current ones (different-data refusal stays sound)
        self._data_digest = None
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = None if weight is None else \
            np.asarray(weight, np.float64).ravel()
        return self

    def set_group(self, group) -> "Dataset":
        g = np.asarray(group, np.int64).ravel()
        self._query_boundaries = np.concatenate(
            [[0], np.cumsum(g)]).astype(np.int64)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = None if init_score is None else \
            np.asarray(init_score, np.float64)
        return self

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None, position=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params, position=position)

    # -- reference-parity accessors (python-package basic.py Dataset) ----
    _FIELD_GETTERS = {"label": "get_label", "weight": "get_weight",
                      "init_score": "get_init_score",
                      "position": "get_position", "group": "get_group"}

    def get_field(self, field_name: str):
        """Generic field accessor (Dataset.get_field)."""
        getter = self._FIELD_GETTERS.get(field_name)
        if getter is None:
            raise LightGBMError(f"Unknown field {field_name}")
        return getattr(self, getter)()

    def set_field(self, field_name: str, data) -> "Dataset":
        """Generic field setter (Dataset.set_field)."""
        setter = {"label": self.set_label, "weight": self.set_weight,
                  "init_score": self.set_init_score,
                  "position": self.set_position,
                  "group": self.set_group}.get(field_name)
        if setter is None:
            raise LightGBMError(f"Unknown field {field_name}")
        return setter(data)

    def get_data(self):
        """The raw data this Dataset was built from (row-subset for
        subset Datasets; None once freed via free_raw_data)."""
        if self.data is not None and self.used_indices is not None:
            idx = np.asarray(self.used_indices)
            if hasattr(self.data, "iloc"):
                return self.data.iloc[idx]
            return np.asarray(self.data)[idx]
        return self.data

    def get_ref_chain(self, ref_limit: int = 100):
        """Set of Datasets along the reference chain."""
        chain = set()
        node, hops = self, 0
        while node is not None and hops < ref_limit:
            chain.add(node)
            node = node.reference
            hops += 1
        return chain

    def set_reference(self, reference: "Dataset") -> "Dataset":
        if self._handle is not None:
            raise LightGBMError(
                "Cannot set reference after the Dataset is constructed")
        self.reference = reference
        return self

    def set_feature_name(self, feature_name: List[str]) -> "Dataset":
        if feature_name == "auto":
            # the documented default sentinel: keep current names
            # (python-package Dataset.set_feature_name semantics)
            return self
        if self._handle is not None and feature_name is not None:
            if len(feature_name) != self._F_total:
                raise LightGBMError(
                    f"Expected {self._F_total} feature names, got "
                    f"{len(feature_name)}")
            self._feature_names = [str(f) for f in feature_name]
        else:
            self.feature_name = feature_name
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if self._handle is not None:
            raise LightGBMError(
                "Cannot set categorical feature after the Dataset is "
                "constructed")
        self.categorical_feature = categorical_feature
        return self

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row-subset view constructed against this Dataset's bin
        mappers (Dataset::CopySubrow analog; the cv() fold path)."""
        from .engine import _subset_dataset
        self.construct()
        return _subset_dataset(self, np.asarray(used_indices, np.int64),
                               params or self.params)

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Stack another constructed Dataset's features onto this one
        (Dataset::AddFeaturesFrom, src/io/dataset.cpp)."""
        self.construct()
        other.construct()
        if other._n != self._n:
            raise LightGBMError(
                "Cannot add features from a Dataset with a different "
                "number of rows")
        self._bins = np.hstack([self._bins, other._bins])
        self.mappers = list(self.mappers) + list(other.mappers)
        self._full_mappers = list(self._full_mappers) \
            + list(other._full_mappers)
        self._used_features = np.concatenate(
            [self._used_features,
             other._used_features + self._F_total]).astype(np.int32)
        self._feature_names = list(self._feature_names) \
            + list(other._feature_names)
        self._F += other._F
        self._F_total += other._F_total
        self._cat_idx = set(self._cat_idx) | {
            c + self._F_total - other._F_total for c in other._cat_idx}
        self._device_bins = None
        self._bundle_info = None
        self._device_raw = None
        if self._raw_numeric is not None \
                and other._raw_numeric is not None:
            self._raw_numeric = np.hstack([self._raw_numeric,
                                           other._raw_numeric])
        else:
            self._raw_numeric = None
        return self

    # -- device views ----------------------------------------------------
    def device_bins(self):
        """[F, n] bin matrix on device (feature-major; HBM-resident)."""
        import jax.numpy as jnp
        self.construct()
        if self._device_bins is None:
            if self._bins is None and getattr(self, "_host_bins_freed",
                                              False):
                raise LightGBMError(
                    "the host binned matrix was freed after device "
                    "placement and no device view was registered "
                    "(shard_residency=device; docs/SHARDING.md)")
            self._device_bins = jnp.asarray(self._bins.T)
        return self._device_bins

    def host_bins(self) -> np.ndarray:
        self.construct()
        if self._bins is None and getattr(self, "_host_bins_freed",
                                          False):
            raise LightGBMError(
                "the host binned matrix was freed after device "
                "placement (shard_residency=device; docs/SHARDING.md) "
                "— construct the Dataset with shard_residency=host if "
                "a host copy is required")
        return self._bins

    def free_host_bins(self) -> None:
        """Release the host binned matrix after device placement
        (shard_residency=device, parallel/placement.py). The checkpoint
        data fingerprint is computed FIRST and cached on the Dataset
        (``_data_digest``) so resume validation keeps working without
        the bins; subsequent ``host_bins()`` calls raise a clear error
        instead of returning None."""
        if self._bins is None:
            return
        if self._data_digest is None and self.label is not None:
            from .data.ingest import dataset_digest
            self._data_digest = dataset_digest(
                np.asarray(self.label, np.float64), self._bins)
        try:
            from .obs.registry import registry
            registry.gauge("host_binned_bytes").set(0.0)
        except Exception:
            pass
        self._bins = None
        self._device_bins = None
        self._bundle_info = None
        self._host_bins_freed = True

    def bundles(self, cfg):
        """Exclusive-feature-bundling info (ops/bundling.py), or None
        when bundling is off / not profitable. Cached per bin matrix
        (subset copies recompute — the shapes differ)."""
        self.construct()
        if not getattr(cfg, "enable_bundle", True):
            return None
        cap = getattr(cfg, "max_cat_to_onehot", 4)
        cached = getattr(self, "_bundle_info", None)
        # cache key includes the one-hot cap: it gates cat-member
        # ELIGIBILITY, so a stale bundle under a different cap would
        # leave wide cat members with zero split candidates
        if cached is not None and \
                cached.bins_bundled.shape[0] == self._n \
                and getattr(self, "_bundle_cat_cap", None) == cap:
            return cached
        if self._bins is None and getattr(self, "_host_bins_freed",
                                          False):
            raise LightGBMError(
                "the host binned matrix was freed after device "
                "placement (shard_residency=device; docs/SHARDING.md) "
                "— bundles cannot be rebuilt; reconstruct the Dataset "
                "to retrain with EFB")
        from .ops.bundling import build_bundles
        self._bundle_info = build_bundles(
            self._bins, self.mappers, max_cat_onehot=cap)
        self._bundle_cat_cap = cap
        return self._bundle_info

    def device_raw(self):
        """[n, F_used] raw float32 values on device (linear trees)."""
        import jax.numpy as jnp
        self.construct()
        if getattr(self, "_device_raw", None) is None:
            rn = self.raw_numeric()
            if rn is None:
                raise LightGBMError(
                    "linear tree evaluation needs raw data; construct the "
                    "Dataset with the linear_tree parameter")
            self._device_raw = jnp.asarray(rn)
        return self._device_raw

    def device_feat_num_bins(self):
        import jax.numpy as jnp
        self.construct()
        return jnp.asarray([m.num_bins for m in self.mappers], jnp.int32)

    def device_feat_nan_bin(self):
        import jax.numpy as jnp
        self.construct()
        # The "missing bin" per feature: rows landing in it are routed by
        # the learned default direction, not the threshold. NaN features
        # keep it as the last bin; zero_as_missing features use the zero
        # bin (which may sit mid-range).
        nb = []
        for m in self.mappers:
            if m.bin_type != BinType.NUMERICAL:
                nb.append(-1)
            elif m.missing_type == MissingType.NAN:
                nb.append(m.num_bins - 1)
            elif m.missing_type == MissingType.ZERO:
                nb.append(m.default_bin)
            else:
                nb.append(-1)
        return jnp.asarray(nb, jnp.int32)

    def device_feat_is_cat(self):
        """[F] bool categorical-feature mask, or None if all numerical."""
        import jax.numpy as jnp
        self.construct()
        arr = np.asarray([m.bin_type == BinType.CATEGORICAL
                          for m in self.mappers], bool)
        return jnp.asarray(arr) if arr.any() else None

    def used_feature_indices(self) -> np.ndarray:
        self.construct()
        return self._used_features

    def usable_feature_mask(self) -> np.ndarray:
        self.construct()
        return np.ones((self._F,), bool)

    def inner_feature_index(self, real_idx: np.ndarray) -> np.ndarray:
        """Map real feature indices to positions in the used-feature set."""
        self.construct()
        lut = np.full((self._F_total,), -1, np.int32)
        lut[self._used_features] = np.arange(self._F, dtype=np.int32)
        return lut[np.asarray(real_idx, np.int64)]

    def thresholds_to_bins(self, real_feat: np.ndarray,
                           thresholds: np.ndarray) -> np.ndarray:
        self.construct()
        inner = self.inner_feature_index(real_feat)
        out = np.zeros(len(thresholds), np.int32)
        for i, (f, t) in enumerate(zip(inner, thresholds)):
            m = self.mappers[f]
            out[i] = int(np.searchsorted(m.upper_bounds, t, side="left"))
        return out

    def monotone_array(self, cfg: Config) -> Optional[np.ndarray]:
        mc = cfg.monotone_constraints
        if not mc:
            return None
        self.construct()
        full = np.zeros((self._F_total,), np.int8)
        full[: len(mc)] = mc
        return full[self._used_features]

    def feature_infos(self) -> List[str]:
        self.construct()
        out = []
        lut = {int(j): m for j, m in zip(self._used_features, self.mappers)}
        for j in range(self._F_total):
            m = lut.get(j)
            if m is None:
                out.append("none")
            elif m.bin_type == BinType.CATEGORICAL:
                out.append(":".join(str(int(c)) for c in m.bin_to_cat))
            else:
                out.append(f"[{m.min_value:g}:{m.max_value:g}]")
        return out


class _EvalResultTuple(tuple):
    pass


class Booster:
    """User-facing booster (basic.py:3539 Booster analog)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._train_data_name = "training"
        self._attrs: Dict[str, str] = {}
        self.params = params or {}
        self._engine = None
        self._metrics = []
        self._valid_names: List[str] = []
        self.pandas_categorical = None
        self._trees: List = []
        self._cfg: Optional[Config] = None
        self._num_class = 1
        self._feature_names: List[str] = []
        self._feature_infos: List[str] = []
        self._objective_str = "none"
        self._avg_output = False
        self._compiled_forest = None

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be a Dataset instance")
            cfg = Config.from_params(params)
            from .utils.log import scoped_verbosity
            with scoped_verbosity(cfg.verbosity):
                train_set.params = {**resolve_params(train_set.params),
                                    **resolve_params(params)}
                train_set.construct()
                self._cfg = cfg
                objective = create_objective(cfg)
                if objective is not None and hasattr(objective,
                                                     "set_dataset"):
                    objective.set_dataset(train_set)
                from .models.gbdt import GBDTBooster
                self._engine = GBDTBooster(cfg, train_set, objective)
                self._metrics = create_metrics(cfg)
                self._num_class = cfg.num_class
                self._feature_names = train_set.get_feature_name()
                self._feature_infos = train_set.feature_infos()
                self._objective_str = self._objective_repr(cfg)
                self._avg_output = cfg.boosting == "rf"
            self.train_set = train_set
        elif model_file is not None:
            with open(model_file) as f:
                self._load_model_string(f.read())
        elif model_str is not None:
            self._load_model_string(model_str)
        else:
            raise TypeError(
                "At least one of train_set, model_file or model_str "
                "should be not None")

    # -- training --------------------------------------------------------
    @property
    def _models(self) -> List:
        return self._engine.models if self._engine is not None \
            else self._trees

    def _objective_repr(self, cfg: Config) -> str:
        """Objective line of the model text (matches the reference's
        ObjectiveFunction::ToString tokens, e.g. ``binary sigmoid:1``,
        ``multiclassova num_class:3 sigmoid:1``, ``regression sqrt``)."""
        o = cfg.objective
        if o == "binary":
            return f"binary sigmoid:{cfg.sigmoid:g}"
        if o == "multiclass":
            return f"multiclass num_class:{cfg.num_class}"
        if o == "multiclassova":
            return (f"multiclassova num_class:{cfg.num_class} "
                    f"sigmoid:{cfg.sigmoid:g}")
        if o in ("regression", "regression_l2") and cfg.reg_sqrt:
            return "regression sqrt"
        if o == "lambdarank":
            return "lambdarank"
        return o

    def _preload(self, base: "Booster") -> None:
        """Adopt an existing model's trees for continued training
        (init_model semantics, reference engine.py/basic.py).

        The trees are adopted through a model-text round trip rather
        than a deepcopy: a live Booster's trees carry ``threshold_bin``
        indices in the bin space of the dataset they were GROWN
        against, and continued training on FRESH data (the
        warm-start retrain loop, docs/PIPELINE.md) bins this train set
        with its own mappers — stale bin indices would silently
        mis-route rows. Parsed trees carry ``threshold_bin = -1``, so
        the binned traversal maps the real-valued thresholds onto the
        current mappers (``_binned_node_arrays``), exactly like the
        init_model-from-file and checkpoint-restore paths (whose
        byte-exact resume proves the round trip lossless)."""
        parsed = Booster(model_str=base.model_to_string())
        self._engine.preload_models(parsed._trees)
        # continued training adds num_boost_round NEW iterations on
        # top of the adopted ones (reference: init_iteration +
        # num_boost_round); the engine loop needs the offset
        self._engine.init_iteration = int(self._engine.iter_)

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        self._engine.add_valid(data, name)
        self._valid_names.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; True means training should stop
        (no further splits possible)."""
        if train_set is not None:
            raise LightGBMError(
                "Resetting train_set mid-training is not supported yet")
        if fobj is not None:
            import numpy as _np
            score = self._engine.current_score(0)
            K = self._engine.K
            grad, hess = fobj(score[0] if K == 1 else score,
                              self._engine.train_set)
            return self._engine.train_one_iter(
                _np.asarray(grad), _np.asarray(hess))
        return self._engine.train_one_iter()

    def rollback_one_iter(self) -> "Booster":
        self._engine.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return len(self._models) // self.num_model_per_iteration()

    def num_trees(self) -> int:
        return len(self._models)

    def num_model_per_iteration(self) -> int:
        if self._engine is not None:
            return self._engine.K
        return max(1, self._num_class)

    def num_feature(self) -> int:
        if self._engine is not None:
            return self._engine.train_set.num_total_features()
        return len(self._feature_names)

    def feature_name(self) -> List[str]:
        return list(self._feature_names)

    # -- evaluation -------------------------------------------------------
    def eval_train(self, feval=None) -> List[Tuple]:
        return self._eval(0, self._train_data_name, feval)

    def eval_valid(self, feval=None) -> List[Tuple]:
        out = []
        for i, name in enumerate(self._valid_names):
            out.extend(self._eval(i + 1, name, feval))
        return out

    def eval(self, data, name: str, feval=None) -> List[Tuple]:
        if data is self.train_set:
            return self._eval(0, self._train_data_name, feval)
        for i, v in enumerate(self._engine.valid_sets):
            if v.dataset is data:
                return self._eval(i + 1, name, feval)
        raise LightGBMError("Data should be added with add_valid first")

    def _eval(self, data_idx: int, name: str, feval=None) -> List[Tuple]:
        res = self._engine.eval_metrics(self._metrics, data_idx)
        out = [(name, mname, val, self._metric_higher_better(mname))
               for mname, val in res.items()]
        if feval is not None:
            fevals = feval if isinstance(feval, (list, tuple)) else [feval]
            score = self._engine.current_score(data_idx)
            ds = self._engine.train_set if data_idx == 0 else \
                self._engine.valid_sets[data_idx - 1].dataset
            for f in fevals:
                ret = f(score[0] if self._engine.K == 1 else score, ds)
                if isinstance(ret, list):
                    for (mn, v, hb) in ret:
                        out.append((name, mn, v, hb))
                else:
                    mn, v, hb = ret
                    out.append((name, mn, v, hb))
        return out

    def _metric_higher_better(self, mname: str) -> bool:
        for m in self._metrics:
            if m.name == mname:
                return m.higher_better
        return False

    # -- prediction --------------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                **kwargs) -> np.ndarray:
        from .prediction import predict_any
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 \
                else -1
        return predict_any(
            self, data, start_iteration, num_iteration,
            raw_score, pred_leaf, pred_contrib,
            pred_early_stop=bool(kwargs.get("pred_early_stop", False)),
            pred_early_stop_freq=int(kwargs.get("pred_early_stop_freq", 10)),
            pred_early_stop_margin=float(
                kwargs.get("pred_early_stop_margin", 10.0)))

    def compile(self, num_iteration: Optional[int] = None,
                start_iteration: int = 0, **kwargs):
        """Compile the forest once into tensorized device arrays
        (serve/compile.py): the returned
        :class:`~lightgbm_tpu.serve.compile.CompiledForest` predicts
        through ONE jitted program with power-of-two row bucketing,
        and subsequent :meth:`predict` calls over the same iteration
        range ride it too — ad-hoc batch sizes stop triggering
        per-shape recompiles. The cached compilation is bypassed
        automatically when the booster trains further or a different
        iteration range is requested. ``kwargs``:
        ``min_bucket`` / ``max_batch_rows`` (powers of two)."""
        if num_iteration is None:
            num_iteration = self.best_iteration \
                if self.best_iteration > 0 else -1
        from .serve.compile import compile_forest
        cf = compile_forest(self, num_iteration=num_iteration,
                            start_iteration=start_iteration, **kwargs)
        self._compiled_forest = cf
        return cf

    # -- model io ----------------------------------------------------------
    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        from .models.model_io import model_to_string
        return model_to_string(self, num_iteration, start_iteration,
                               importance_type)

    def save_model(self, filename, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        s = self.model_to_string(num_iteration, start_iteration,
                                 importance_type)
        # crash-safe write (same-directory tmp + os.replace, like the
        # native-lib build and checkpoint snapshots): a killed process
        # never leaves a truncated model file behind
        from .utils.atomic import atomic_write_text
        atomic_write_text(filename, s)
        return self

    def _load_model_string(self, s: str) -> None:
        from .models.model_io import load_model_string
        load_model_string(self, s)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> Dict:
        from .models.model_io import dump_model_dict
        return dump_model_dict(self, num_iteration, start_iteration,
                               importance_type)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        nf = self.num_feature()
        imp = np.zeros((nf,), np.float64)
        trees = self._models
        if iteration is not None and iteration > 0:
            trees = trees[: iteration * self.num_model_per_iteration()]
        for t in trees:
            for i in range(t.num_nodes):
                f = int(t.split_feature[i])
                if importance_type == "split":
                    imp[f] += 1
                else:
                    imp[f] += max(0.0, float(t.split_gain[i]))
        if importance_type == "split":
            return imp.astype(np.int64 if True else np.float64)
        return imp

    def trees_to_dataframe(self):
        from .models.model_io import trees_to_dataframe
        return trees_to_dataframe(self)

    # -- misc reference-API methods ---------------------------------------
    # -- reference-parity surface (python-package basic.py Booster) -----
    @classmethod
    def model_from_string(cls, model_str: str) -> "Booster":
        """Load a Booster from a model-format string."""
        return cls(model_str=model_str)

    def attr(self, key: str) -> Optional[str]:
        """Free-form string attribute (Booster::GetAttr analog)."""
        return self._attrs.get(key)

    def set_attr(self, **kwargs) -> "Booster":
        """Set (value) or delete (None) string attributes."""
        for k, v in kwargs.items():
            if v is None:
                self._attrs.pop(k, None)
            else:
                self._attrs[k] = str(v)
        return self

    def lower_bound(self) -> float:
        """Smallest reachable raw score: sum over trees of each tree's
        minimum leaf value (Booster::LowerBoundValue)."""
        return float(sum(np.min(t.leaf_value[: t.num_leaves])
                         for t in self._models) or 0.0)

    def upper_bound(self) -> float:
        """Largest reachable raw score (Booster::UpperBoundValue)."""
        return float(sum(np.max(t.leaf_value[: t.num_leaves])
                         for t in self._models) or 0.0)

    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120,
                    num_machines: int = 1) -> "Booster":
        """Wire the multi-controller runtime (LGBM_NetworkInit analog;
        on TPU the 'network' is the jax.distributed world)."""
        from .parallel.distributed import init_distributed
        if num_machines > 1:
            init_distributed(machines=machines if isinstance(machines, str)
                             else ",".join(machines))
        return self

    def free_network(self) -> "Booster":
        """Tear the multi-controller runtime down (LGBM_NetworkFree)."""
        from .parallel.distributed import shutdown_distributed
        shutdown_distributed()
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Re-apply tunable params mid-training (LGBM_BoosterResetParameter;
        learning_rate takes effect on the next iteration)."""
        self.params = {**self.params, **params}
        if self._engine is not None:
            if "learning_rate" in params:
                self._engine._shrinkage = float(params["learning_rate"])
                # the new rate must take effect on the NEXT iteration
                # (reference semantics) — discard any precomputed
                # lookahead still scored at the old rate
                self._engine._abort_scan_window()
            for k in ("bagging_fraction", "bagging_freq",
                      "feature_fraction", "feature_fraction_bynode"):
                if k in params:
                    setattr(self._engine.cfg, k, params[k])
                    # the scan-window programs BAKE the bagging
                    # fractions/freq and key schedules into their
                    # traced bodies (gbdt._get_scan_fn fresh_bag /
                    # _StepCtx), unlike the per-iteration fused fn
                    # whose row weights arrive as operands — drop the
                    # cache (and any precomputed lookahead) so the
                    # next window re-traces with the new cfg
                    self._engine._scan_fns = {}
                    self._engine._abort_scan_window()
            if "feature_fraction_bynode" in params:
                # bynode is baked into the traced grow programs (the
                # per-node key schedule): refresh the static grow
                # config and drop/rebuild every cached program —
                # fused, eager (reads grow_cfg per call), and the
                # distributed grow fn — so all three re-trace with
                # the new setting
                eng = self._engine
                bynode = float(params["feature_fraction_bynode"])
                gcfg = eng.grow_cfg._replace(bynode=bynode)
                if bynode < 1.0 and gcfg.grower != "compact":
                    # same coercion as engine init: per-node column
                    # sampling lives on the compact grower only
                    gcfg = gcfg._replace(grower="compact")
                eng.grow_cfg = gcfg
                eng._fused_fn = None
                eng._scan_fns = {}
                if eng._grow_fn is not None:
                    eng._grow_fn = eng._build_grow_fn()
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        return float(self._models[tree_id].leaf_value[leaf_id])

    def set_leaf_output(self, tree_id: int, leaf_id: int,
                        value: float) -> "Booster":
        self._models[tree_id].leaf_value[leaf_id] = value
        return self

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """Randomly permute tree order in [start, end) iterations
        (LGBM_BoosterShuffleModels)."""
        models = self._models
        K = self.num_model_per_iteration()
        n_iters = len(models) // K
        end = n_iters if end_iteration < 0 else min(end_iteration, n_iters)
        idx = np.arange(start_iteration, end)
        np.random.shuffle(idx)
        order = list(range(n_iters))
        order[start_iteration:end] = idx.tolist()
        reordered = []
        for it in order:
            reordered.extend(models[it * K: (it + 1) * K])
        models[:] = reordered
        return self

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of split thresholds used for a feature
        (basic.py get_split_value_histogram analog)."""
        if isinstance(feature, str):
            fidx = self.feature_name().index(feature)
        else:
            fidx = int(feature)
        values = []
        for t in self._models:
            for i in range(t.num_nodes):
                if int(t.split_feature[i]) == fidx \
                        and not t.is_categorical_node(i):
                    values.append(float(t.threshold[i]))
        hist, bin_edges = np.histogram(values, bins=bins or "auto")
        if xgboost_style:
            import pandas as pd
            ret = np.column_stack((bin_edges[1:], hist))
            ret = ret[ret[:, 1] > 0]
            return pd.DataFrame(ret, columns=["SplitValue", "Count"])
        return hist, bin_edges

    def refit(self, data, label, decay_rate: float = 0.9, weight=None,
              **kwargs) -> "Booster":
        """Refit leaf values on new data keeping tree structures
        (reference basic.py refit -> LGBM_BoosterRefit / GBDT::RefitTree:
        new_leaf = decay*old + (1-decay)*fit, trees processed in boosting
        order so later trees see refreshed scores).

        The warm-start edge of the continuous retrain loop
        (docs/PIPELINE.md): fresh production data is rarely clean, so
        per-tree gradients/hessians and the fitted leaf values run
        through the same non-finite guard as training
        (``nonfinite_policy``: raise | skip_tree — the tree keeps its
        old leaf values | clamp), and the ``refit_nan@T`` chaos kind
        (resilience/faults.py) poisons tree ``T``'s gradients to prove
        it. Guard trips surface as ``refit_nan`` fault events."""
        if not self._models:
            raise LightGBMError("Cannot refit an empty model")
        if any(t.is_linear and t.leaf_coeff and any(
                len(c) for c in t.leaf_coeff) for t in self._models):
            raise LightGBMError(
                "refit is not yet supported for linear trees (the "
                "reference's is_refit CalculateLinear path)")
        new_bst = self.__deepcopy__(None)
        X = np.asarray(data, np.float64)
        y = np.asarray(label, np.float64).ravel()
        w = None if weight is None else np.asarray(weight, np.float64)
        leaves = self.predict(X, pred_leaf=True)  # [n, T]
        if leaves.ndim == 1:
            leaves = leaves[:, None]
        cfg = self._cfg or Config.from_params(self.params)
        from .objectives import create_objective
        obj_cfg = Config.from_params(
            {**self.params, "objective": (self._objective_str or
                                          "regression").split()[0]})
        objective = create_objective(obj_cfg)
        if objective is None:
            raise LightGBMError("Cannot refit without a built-in objective")
        if hasattr(objective, "init_label_weights"):
            objective.init_label_weights(y, w)
        K = self.num_model_per_iteration()
        n = len(y)
        score = np.zeros((K, n), np.float64)
        lam = cfg.lambda_l2
        shrink = cfg.learning_rate
        from .resilience.faults import FaultPlan, append_fault_event
        fault_plan = FaultPlan.from_env()
        policy = cfg.nonfinite_policy
        fault_log: List[Dict] = []
        for ti, tree in enumerate(new_bst._models):
            k = ti % K
            g, h = objective.grad_hess(
                np.asarray(score[0] if K == 1 else score, np.float32),
                np.asarray(y, np.float32),
                None if w is None else np.asarray(w, np.float32))
            g = np.asarray(g, np.float64).reshape(K, n)[k] if K > 1 \
                else np.asarray(g, np.float64).ravel()
            h = np.asarray(h, np.float64).reshape(K, n)[k] if K > 1 \
                else np.asarray(h, np.float64).ravel()
            if fault_plan.take("refit_nan", ti):
                g = np.where(np.arange(n) % 7 == 0, np.nan, g)
            lv = leaves[:, ti]
            L = tree.num_leaves
            sg = np.bincount(lv, weights=g, minlength=L)
            sh = np.bincount(lv, weights=h, minlength=L)
            fit = -sg / (sh + lam)
            fit = fit * shrink
            # non-finite guard (same policy surface as training): bad
            # labels / poisoned gradients in the fresh data must not
            # publish a NaN forest into the serve fleet
            if not np.all(np.isfinite(fit)):
                if policy == "raise":
                    raise LightGBMError(
                        f"refit: non-finite leaf values fitted for "
                        f"tree {ti} (nonfinite_policy=raise)")
                if policy == "skip_tree":
                    append_fault_event(
                        fault_log, "refit_nan", ti, "skip_tree",
                        f"non-finite refit values for tree {ti}; "
                        "keeping its existing leaf values")
                    score[k] += tree.leaf_value[lv]
                    continue
                append_fault_event(
                    fault_log, "refit_nan", ti, "clamp",
                    f"non-finite refit values for tree {ti} clamped")
                fit = np.nan_to_num(fit, nan=0.0,
                                    posinf=1e30, neginf=-1e30)
            tree.leaf_value = decay_rate * tree.leaf_value \
                + (1.0 - decay_rate) * fit
            score[k] += tree.leaf_value[lv]
        new_bst._refit_fault_log = fault_log
        return new_bst

    def free_dataset(self) -> "Booster":
        self.train_set = None
        return self

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        model_str = self.model_to_string()
        return Booster(model_str=model_str)
