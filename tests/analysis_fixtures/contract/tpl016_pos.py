"""TPL016 positives: metric bumps that drift from the registry."""


def feed(registry, name):
    # EXPECT: TPL016
    registry.counter("pigns").inc()
    # EXPECT: TPL016
    registry.gauge("pings").set(1)
    # EXPECT: TPL016
    registry.counter("pings", lane="a").inc()
    # EXPECT: TPL016
    registry.gauge("ping_depth").set(2)
    # EXPECT: TPL016
    registry.counter(name).inc()
