"""Butterfly-route partition: the compact grower's in-chunk primitive.

A split streams each leaf window in K-row chunks; every chunk must be
stably two-way partitioned (lefts to the block front, rights packed to
the block end) before the masked window writes (ops/grow.py
``part_apply``). The reference's GPU learner does this with a warp
prefix-scan + scatter (/root/reference/src/treelearner/cuda/
cuda_data_partition.cu: GenDataToLeftBitVector + SplitInner); TPUs have
no per-lane scatter, so the redesign routes rows through a butterfly:

- each marked row's destination is ``offset + stable rank`` (one prefix
  sum);
- stage ``s`` exchanges partners at stride ``2^s`` (LSB-first); a pair
  swaps when the low slot's row needs destination bit ``s`` set or the
  high slot's row needs it clear; don't-care rows yield. An
  order-preserving partial route is congestion-free on the butterfly
  (the classic SIMD concentrator-routing result), so ``log2(K)`` stages
  of vector selects replace an ``O(log^2 K)``-stage variadic
  ``lax.sort`` — ~14 vs ~196 stages at K=16384.

Two implementations:

- :func:`route_pair` — a Pallas TPU kernel that runs BOTH concentration
  passes (lefts, rights) over the stacked column matrix in one VMEM
  residency: loads the [NC, K] chunk once, does all stages on-chip, and
  writes the two routed copies. This is the TPU analog of the CUDA
  split kernel's shared-memory residency.
- :func:`route_concentrate` — the same routing as plain XLA ops (flat
  rolls + selects), used on CPU (tests, virtual-mesh dryruns) and as
  the reference implementation the kernel is tested against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = ["route_concentrate", "route_pair", "stack_cols", "unstack_cols"]


def _prefix_inclusive(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive prefix sum along the last axis of a [1, K] int32 array
    via log-step shifts (Pallas TPU has no cumsum primitive)."""
    k = x.shape[-1]
    sh = 1
    while sh < k:
        x = x + jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (sh,), x.dtype), x[..., :-sh]],
            axis=-1)
        sh *= 2
    return x


def _roll_last(x: jnp.ndarray, m: int) -> jnp.ndarray:
    """Circular roll by +m along the last axis (out[i] = x[i - m])."""
    return jnp.concatenate([x[..., -m:], x[..., :-m]], axis=-1)


def _route_stages(dst: jnp.ndarray, A: jnp.ndarray, k: int):
    """Shared stage loop: route (dst, A) through the LSB-first butterfly.

    dst: [1, K] int32 destinations (-1 = don't care); A: [NC, K]."""
    iota = lax.broadcasted_iota(jnp.int32, (1, k), 1)
    s = 1
    while s < k:
        m = s
        hib = (iota & m) != 0
        dp = jnp.where(hib, _roll_last(dst, m), _roll_last(dst, -m))
        swap = (((dst >= 0) & (((dst & m) != 0) != hib))
                | ((dp >= 0) & (((dp & m) != 0) == hib)))
        Ap = jnp.where(hib, _roll_last(A, m), _roll_last(A, -m))
        A = jnp.where(swap, Ap, A)
        dst = jnp.where(swap, dp, dst)
        s *= 2
    return A


def _route_pair_kernel(a_ref, marks_ref, l_ref, r_ref):
    A = a_ref[...]
    k = A.shape[-1]
    ml = marks_ref[0:1, :]
    mr = marks_ref[1:2, :]
    pfl = _prefix_inclusive(ml)
    pfr = _prefix_inclusive(mr)
    rc = pfr[:, -1:]                                   # [1, 1] total rights
    dst_l = jnp.where(ml != 0, pfl - 1, -1)
    dst_r = jnp.where(mr != 0, (k - rc) + pfr - 1, -1)
    l_ref[...] = _route_stages(dst_l, A, k)
    r_ref[...] = _route_stages(dst_r, A, k)


@functools.partial(jax.jit, static_argnames=("interpret",))
def route_pair(A: jnp.ndarray, mark_left: jnp.ndarray,
               mark_right: jnp.ndarray, interpret: bool = False):
    """Both concentration passes in one Pallas kernel.

    Args:
      A: ``[NC, K]`` int32 stacked row columns (K a power of two).
      mark_left / mark_right: ``[K]`` bool, disjoint row classes
        (unmarked rows are padding don't-cares).
    Returns:
      ``(L, R)``: lefts stably compacted to ``[0, n_left)`` of L,
      rights to ``[K - n_right, K)`` of R.
    """
    nc, k = A.shape
    marks = jnp.stack([mark_left.astype(jnp.int32),
                       mark_right.astype(jnp.int32)])
    out = jax.ShapeDtypeStruct((nc, k), A.dtype)
    return pl.pallas_call(
        _route_pair_kernel,
        out_shape=(out, out),
        interpret=interpret,
    )(A, marks)


def route_concentrate(cols, mark, offset):
    """XLA reference implementation: stable compaction of the
    ``mark``ed rows to positions [offset, offset + popcount(mark)),
    unmarked rows being don't-cares (see module docstring).

    Args:
      cols: tuple of ``[K]`` arrays to move (any dtypes, K power of 2).
      mark: ``[K]`` bool; offset: scalar int32 first destination slot.
    Returns:
      tuple of routed ``[K]`` arrays.
    """
    stacked, spec = stack_cols(cols)
    k = stacked.shape[-1]
    rank = jnp.cumsum(mark.astype(jnp.int32)) - 1
    dst = jnp.where(mark, offset + rank, -1)[None, :]
    routed = _route_stages(dst, stacked, k)
    return unstack_cols(routed, spec)


def stack_cols(cols):
    """Bitcast a tuple of [K] columns (u8/u16/u32/i32/f32) into one
    [NC, K] int32 matrix + a spec to undo it."""
    rows, spec = [], []
    for c in cols:
        if c.dtype == jnp.int32:
            rows.append(c)
        elif c.dtype in (jnp.uint32, jnp.float32):
            rows.append(lax.bitcast_convert_type(c, jnp.int32))
        else:
            rows.append(c.astype(jnp.int32))
        spec.append(c.dtype)
    return jnp.stack(rows), tuple(spec)


def unstack_cols(A, spec):
    out = []
    for i, dt in enumerate(spec):
        c = A[i]
        if dt == jnp.int32:
            out.append(c)
        elif dt in (jnp.uint32, jnp.float32):
            out.append(lax.bitcast_convert_type(c, dt))
        else:
            out.append(c.astype(dt))
    return tuple(out)
