"""Collective watchdog: a deadline + heartbeat around every host-level
sync point of a multi-process run.

The failure mode this exists for: one rank dies (preemption, OOM kill,
segfault) or stalls (swap storm, hung I/O) while the others are already
inside — or about to enter — a host-level collective
(``process_allgather`` / ``broadcast_one_to_all`` in parallel/spmd.py).
The survivors then wait forever: the reference's socket linker would
eventually hit its socket timeout (src/network/linkers_socket.cpp:169
retries with ``time_out``), but JAX's multihost helpers happily block
until the heat death of the pod. Every cross-host call site therefore
runs through :func:`guarded`, which converts both an infinite hang and
a transport error into a ``LightGBMError`` naming the collective, the
iteration, and the last sync every rank was heard from — the signal a
supervisor (``python -m lightgbm_tpu launch``, resilience/elastic.py)
needs to restart the world from the newest checkpoint.

Deadline resolution (first hit wins):

1. ``LIGHTGBM_TPU_COLLECTIVE_TIMEOUT`` environment variable (seconds;
   ``0`` disables the watchdog),
2. :func:`configure`, called by ``train()`` with
   ``Config.collective_timeout_sec``,
3. the 300 s default.

Mechanics: the collective runs on a fresh *daemon* thread while the
caller waits on an event with a timeout. On expiry the caller raises
and the stuck thread is abandoned — it can never be unblocked anyway,
and being a daemon it cannot keep the aborting process alive. After a
timeout the world must be restarted; this module makes no attempt to
resume collectives. The bookkeeping lock below is only ever held
around dict updates, never across a collective (tpulint TPL006 now
watches this file for exactly that).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..utils.log import log_warning

__all__ = ["guarded", "configure", "deadline_seconds", "last_heard",
           "DEFAULT_DEADLINE_SECONDS"]

DEFAULT_DEADLINE_SECONDS = 300.0

_ENV_DEADLINE = "LIGHTGBM_TPU_COLLECTIVE_TIMEOUT"

#: bookkeeping only — guards _last_ok; NEVER held across a collective
_state_lock = threading.Lock()
_configured: Optional[float] = None
_last_ok: Optional[Dict[str, Any]] = None


def configure(deadline: Optional[float]) -> None:
    """Set the process-wide collective deadline (seconds). ``train()``
    calls this with ``Config.collective_timeout_sec``; the environment
    variable still overrides. ``0`` disables, ``None`` resets to the
    default."""
    global _configured
    with _state_lock:
        _configured = None if deadline is None else float(deadline)


def deadline_seconds() -> float:
    """The effective deadline: env var > configure() > default."""
    env = os.environ.get(_ENV_DEADLINE)
    if env:
        try:
            return float(env)
        except ValueError:
            log_warning(f"{_ENV_DEADLINE}={env!r} is not a number; "
                        "using the configured deadline")
    with _state_lock:
        if _configured is not None:
            return _configured
    return DEFAULT_DEADLINE_SECONDS


def last_heard() -> Optional[Dict[str, Any]]:
    """The most recent completed guarded collective:
    ``{"name", "iteration", "time", "world"}`` — the heartbeat the
    timeout error reports. None before the first sync."""
    with _state_lock:
        return None if _last_ok is None else dict(_last_ok)


def _record_ok(name: str, iteration: Optional[int],
               world: Optional[int]) -> None:
    global _last_ok
    with _state_lock:
        _last_ok = {"name": name,
                    "iteration": None if iteration is None
                    else int(iteration),
                    "time": time.monotonic(),
                    "world": None if world is None else int(world)}


def _heartbeat_clause() -> str:
    heard = last_heard()
    if heard is None:
        return ("no collective has completed yet in this process — the "
                "peers may never have come up")
    ago = time.monotonic() - heard["time"]
    ranks = (f"all {heard['world']} ranks were heard from"
             if heard["world"] else "every rank was heard from")
    at_it = ("" if heard["iteration"] is None
             else f" at iteration {heard['iteration']}")
    return (f"last successful sync was '{heard['name']}'{at_it}, "
            f"{ago:.1f}s ago, when {ranks}")


def _fault(kind: str, iteration: Optional[int], detail: str) -> None:
    from .faults import record_fault_event
    record_fault_event(kind, iteration=-1 if iteration is None
                       else int(iteration),
                       action="raise", detail=detail)


def guarded(name: str, fn: Callable, *args,
            iteration: Optional[int] = None,
            world: Optional[int] = None,
            deadline: Optional[float] = None) -> Any:
    """Run one host-level collective ``fn(*args)`` under the watchdog.

    Returns ``fn``'s result. Raises ``LightGBMError`` when the
    collective exceeds the deadline (a peer died or stalled mid-sync)
    or fails with a transport error — in both cases naming ``name``,
    ``iteration`` and the last completed sync. A ``LightGBMError``
    raised by ``fn`` itself (e.g. a divergence check) passes through
    untouched. Callers gate on ``jax.process_count() > 1``; this
    module itself never imports jax.
    """
    from ..basic import LightGBMError

    limit = deadline_seconds() if deadline is None else float(deadline)
    if limit <= 0:
        out = fn(*args)
        _record_ok(name, iteration, world)
        return out

    box: Dict[str, Any] = {}
    done = threading.Event()

    # box is written only before done.set() and read only after
    # done.wait() returned True, so the Event establishes the
    # happens-before; on a timeout the abandoned worker's late write is
    # never read (box is per-call and unreachable after the raise).
    # tpulint: threadsafe Event handshake (write, set, wait, read)
    def _run() -> None:
        try:
            box["value"] = fn(*args)
        except BaseException as e:  # noqa: BLE001 — ferried to caller
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(target=_run, daemon=True,
                              name=f"collective[{name}]")
    worker.start()
    at_it = "" if iteration is None else f" at iteration {iteration}"
    if not done.wait(limit):
        detail = (f"collective '{name}'{at_it} exceeded the "
                  f"{limit:g}s watchdog deadline")
        try:
            from ..obs.registry import registry
            registry.counter("collective_timeouts").inc()
        except Exception:
            pass
        _fault("collective_timeout", iteration, detail)
        raise LightGBMError(
            f"{detail}: a peer process likely died or stalled before "
            f"joining ({_heartbeat_clause()}). The world must be "
            "restarted — `python -m lightgbm_tpu launch` supervises "
            "exactly this, resuming from the newest checkpoint "
            "(docs/RESILIENCE.md). Deadline knob: "
            f"{_ENV_DEADLINE} / collective_timeout_sec.")
    err = box.get("error")
    if err is not None:
        if isinstance(err, LightGBMError):
            raise err
        # the kv transport surfaces a stalled peer as its own timeout
        # (DEADLINE_EXCEEDED / _StalledRank) before the outer deadline,
        # with per-rank attribution; classify it as the same event
        is_timeout = (getattr(err, "is_timeout", False)
                      or "DEADLINE_EXCEEDED" in str(err))
        detail = (f"collective '{name}'{at_it} "
                  + ("timed out" if is_timeout else "failed")
                  + f" ({type(err).__name__}: {err})")
        if is_timeout:
            try:
                from ..obs.registry import registry
                registry.counter("collective_timeouts").inc()
            except Exception:
                pass
        _fault("collective_timeout" if is_timeout else "collective_error",
               iteration, detail)
        raise LightGBMError(
            f"{detail}: a peer process likely died or stalled "
            f"mid-collective ({_heartbeat_clause()}). Restart the "
            "world from the newest checkpoint — `python -m "
            "lightgbm_tpu launch` supervises exactly this "
            "(docs/RESILIENCE.md).") from err
    _record_ok(name, iteration, world)
    return box.get("value")
