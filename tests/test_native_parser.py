"""Native C++ text parser (src/io/parser.cpp analog) vs numpy parity."""

import numpy as np
import pytest

from lightgbm_tpu.utils.native import parse_dense_text


@pytest.mark.parametrize("delim", ["\t", ",", " "])
def test_native_matches_numpy(tmp_path, delim):
    rs = np.random.RandomState(0)
    M = rs.randn(500, 7)
    M[rs.rand(500, 7) < 0.05] = np.nan
    path = tmp_path / "data.txt"
    # empty cells only make sense for single-char delimiters; runs of
    # whitespace collapse, so spell missing as "nan" there
    empty = "nan" if delim == " " else ""
    with open(path, "w") as fh:
        for row in M:
            fh.write(delim.join(empty if np.isnan(v) else f"{v:.10g}"
                                for v in row) + "\n")
    got = parse_dense_text(str(path), False)
    if got is None:
        pytest.skip("native parser unavailable (no compiler)")
    want = np.genfromtxt(path, delimiter=None if delim == " " else delim)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-12, equal_nan=True)


@pytest.mark.skipif(
    not __import__("os").path.isdir("/root/reference/examples"),
    reason="reference examples not mounted")
def test_native_used_for_reference_example():
    import lightgbm_tpu as lgb
    d = lgb.Dataset(
        "/root/reference/examples/binary_classification/binary.train",
        params={"verbosity": -1})
    d.construct()
    assert d.num_data() == 7000
    assert d.num_total_features() == 28


def test_native_bin_matrix_bit_identical_to_numpy():
    """ltpu_bin_columns vs the numpy value_to_bin path: bit-identical
    bins across NaN-bearing, zero-heavy, f32/f64, and mixed
    numerical+categorical matrices (the native kernel is the
    construct-time hot path at EFB width; bin.h ValueToBin analog)."""
    from lightgbm_tpu.ops.binning import (BinType, bin_matrix, bin_values,
                                          find_bin)

    rs = np.random.RandomState(3)
    n, f = 30_000, 37
    X = rs.randn(n, f).astype(np.float32)
    X[:, 5] = np.where(rs.rand(n) < 0.6, 0.0, X[:, 5])   # zero-heavy
    X[rs.rand(n) < 0.1, 0] = np.nan                       # NaN bin
    X[rs.rand(n) < 0.05, 5] = np.nan                      # NaN + zeros
    cats = np.zeros(n); cats[::3] = 5; cats[1::7] = 9
    X[:, 3] = cats                                        # categorical

    mappers = [find_bin(np.ascontiguousarray(X[:10_000, j]), 255,
                        bin_type=(BinType.CATEGORICAL if j == 3
                                  else BinType.NUMERICAL))
               for j in range(f)]
    idx = np.arange(f)
    for M in (X, X.astype(np.float64)):
        a = bin_matrix(M, idx, mappers)
        b = bin_values([M[:, j] for j in range(f)], mappers)
        assert np.array_equal(a, b), M.dtype

    # u16 bins (>256): parity on a high-cardinality column set
    Xw = rs.randn(20_000, 4).astype(np.float32)
    mw = [find_bin(np.ascontiguousarray(Xw[:, j]), 1023)
          for j in range(4)]
    a = bin_matrix(Xw, np.arange(4), mw)
    b = bin_values([Xw[:, j] for j in range(4)], mw)
    assert a.dtype == b.dtype and np.array_equal(a, b)

    # non-contiguous / unsupported dtype falls back, same result
    Xnc = np.asfortranarray(X)
    a = bin_matrix(Xnc, idx, mappers)
    assert np.array_equal(a, bin_values([X[:, j] for j in range(f)],
                                        mappers))
