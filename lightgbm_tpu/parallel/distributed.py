"""Multi-host (multi-controller) initialization.

The reference reaches multi-machine training through ``Network::Init``
(/root/reference/src/network/linkers_socket.cpp:169 TCP mesh handshake /
linkers_mpi.cpp:16 MPI world) configured by ``machines``/``mlist`` +
``local_listen_port`` + ``num_machines``
(/root/reference/src/application/application.cpp:168-176; the Dask layer
assembles the same params, python-package/lightgbm/dask.py:495-520).

The TPU-native replacement is JAX's multi-controller runtime: every host
runs the same program, ``jax.distributed.initialize`` wires the
processes, and ``jax.devices()`` then spans all hosts so the ordinary
data-parallel Mesh (parallel/mesh.py) covers the pod — ICI inside a
slice, DCN across slices — with no linker layer at all.

``init_distributed`` accepts BOTH the native JAX arguments and the
reference's machine-list vocabulary so a LightGBM-style launch config
ports directly:

    # reference-style (mlist.txt holds "host:port" lines, rank inferred)
    init_distributed(machine_list_file="mlist.txt", local_rank=0)
    # or explicit
    init_distributed(machines="10.0.0.1:12400,10.0.0.2:12400",
                     local_rank=1)
    # or native
    init_distributed(coordinator_address="10.0.0.1:12400",
                     num_processes=2, process_id=1)
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

__all__ = ["init_distributed", "shutdown_distributed", "parse_machines"]

_INITIALIZED = False


def parse_machines(machines: Optional[str] = None,
                   machine_list_file: Optional[str] = None
                   ) -> List[Tuple[str, int]]:
    """Parse the reference's machine-list formats: a comma/newline
    separated ``host:port`` string (config ``machines``) or a file with
    one ``host port`` / ``host:port`` per line (``machine_list_file``,
    tests/distributed/_test_distributed.py:23-38)."""
    entries: List[str] = []
    if machines:
        entries = [m for m in machines.replace("\n", ",").split(",") if m]
    elif machine_list_file:
        with open(machine_list_file) as fh:
            entries = [ln.strip() for ln in fh if ln.strip()]
    out = []
    for e in entries:
        host, _, port = e.replace(" ", ":").partition(":")
        out.append((host, int(port or 0)))
    return out


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     machines: Optional[str] = None,
                     machine_list_file: Optional[str] = None,
                     local_rank: Optional[int] = None) -> None:
    """Wire this process into a multi-host JAX runtime (the
    ``LGBM_NetworkInit`` / ``Network::Init`` analog).

    With reference-style arguments, the first machine in the list is
    the coordinator and ``local_rank`` (or env ``LIGHTGBM_TPU_RANK``)
    selects this process's slot. A single-entry machine list is a
    no-op, matching ``num_machines=1``. Under standard TPU pod
    launchers (GKE/queued resources) the arguments can all be omitted —
    ``jax.distributed.initialize()`` discovers the topology itself.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    import jax

    if coordinator_address is None and (machines or machine_list_file):
        mlist = parse_machines(machines, machine_list_file)
        if len(mlist) <= 1:
            return  # num_machines=1: nothing to wire
        host, port = mlist[0]
        coordinator_address = f"{host}:{port}"
        num_processes = len(mlist)
        if process_id is None:
            rank = local_rank if local_rank is not None else int(
                os.environ.get("LIGHTGBM_TPU_RANK", "-1"))
            if rank < 0:
                raise ValueError(
                    "machine-list initialization needs local_rank (or "
                    "env LIGHTGBM_TPU_RANK) to identify this process")
            process_id = rank

    if coordinator_address is None and num_processes is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    _INITIALIZED = True


def shutdown_distributed() -> None:
    """Tear the multi-controller runtime down (MPI_Finalize analog)."""
    global _INITIALIZED
    if not _INITIALIZED:
        return
    import jax

    jax.distributed.shutdown()
    _INITIALIZED = False
