"""Artifact store transport: the publish/serve handoff over ANY target.

PR 12's publication protocol (resilience/publisher.py) assumed the
trainer and the serving fleet share a filesystem — the publisher wrote
``os.replace``-atomic files into a directory the serve watcher polled.
ROADMAP 3(c) removes that assumption: the manifest-first protocol,
sha256 validation, retry/backoff and trace stamping all ride THIS
interface instead, so the same publisher/watcher code publishes into a
local directory today and an object store / rsync / KV target
tomorrow.

The interface is deliberately object-store-shaped (whole-blob
put/get/list/delete, no rename, no partial writes): every real
cross-machine transport — S3/GCS-style buckets, an rsync'd spool, a KV
service — offers exactly these verbs, and the ONE atomicity property
the publication protocol needs is "a put is all-or-nothing", which
object PUTs give natively and :class:`LocalDirStore` implements with
the same-dir-tmp + ``os.replace`` convention (utils/atomic.py).

Failure contract (what the publisher's retry loop and the serve
watcher's skip-and-retry path key on):

- a transient transport failure (outage, timeout) raises
  :class:`StoreError` — an ``OSError`` subclass, so the publisher's
  jittered-backoff retry loop and the watcher's skip paths catch it
  without learning a new exception type;
- an absent blob raises ``FileNotFoundError`` (also ``OSError``);
- a TORN blob (a crashed non-atomic writer) never comes from the
  store itself — it is modeled by the chaos kinds (``publish_torn`` /
  ``store_outage``, resilience/faults.py) and caught by the manifest
  sha256 validation, exactly as on a shared filesystem.

:class:`MemoryBackend` is the test double: an in-memory blob map with
injectable latency / outage / torn-write faults, reachable through
``store_for("mem://<name>")`` so any component that accepts a store
spec can be pointed at a faulted transport without touching a disk.

Threading contract (tpulint TPL008 over resilience/): the serve
watcher thread, the supervisor's scrape thread and test threads all
touch one store concurrently, so :class:`MemoryBackend` guards its
blob map and fault knobs with one lock; :class:`LocalDirStore` is
stateless over the filesystem. This module never imports jax — the
publisher, the pipeline supervisor and the serve watcher all consume
it on jax-free paths.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.atomic import atomic_write_bytes

__all__ = ["StoreError", "ArtifactStore", "LocalDirStore",
           "ObjectStore", "MemoryBackend", "store_for"]


class StoreError(OSError):
    """A transient artifact-store transport failure (outage, timeout).

    Subclasses ``OSError`` on purpose: the publisher's retry loop and
    the serve watcher's skip-and-retry path already handle ``OSError``
    — a new transport must not need new handling."""


class ArtifactStore:
    """Blob-store verbs the publication protocol rides.

    Names are flat (no directories); a put is all-or-nothing — a
    reader never observes a partial blob from the store itself."""

    #: human-readable target for log lines and error messages
    url: str = "store://"

    def put_bytes(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, name: str) -> bytes:
        raise NotImplementedError

    def list_names(self) -> List[str]:
        """All blob names; ``[]`` when the target does not exist yet
        (a publisher creates it on first put)."""
        raise NotImplementedError

    def stat(self, name: str) -> Optional[Tuple[float, int]]:
        """``(mtime, size)`` of a blob, None when absent/unreadable —
        the serve watcher's newest-artifact ordering key."""
        raise NotImplementedError

    def delete(self, name: str) -> None:
        """Remove a blob; idempotent (an absent name is a no-op)."""
        raise NotImplementedError


class LocalDirStore(ArtifactStore):
    """The shared-filesystem transport: one directory, atomic puts via
    the same-dir-tmp + ``os.replace`` convention."""

    def __init__(self, directory):
        self.directory = os.fspath(directory)
        self.url = self.directory

    def put_bytes(self, name: str, data: bytes) -> None:
        atomic_write_bytes(os.path.join(self.directory, name), data)

    def get_bytes(self, name: str) -> bytes:
        with open(os.path.join(self.directory, name), "rb") as fh:
            return fh.read()

    def list_names(self) -> List[str]:
        try:
            return sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return []

    def stat(self, name: str) -> Optional[Tuple[float, int]]:
        try:
            st = os.stat(os.path.join(self.directory, name))
        except OSError:
            return None
        return (st.st_mtime, st.st_size)

    def delete(self, name: str) -> None:
        try:
            os.remove(os.path.join(self.directory, name))
        except FileNotFoundError:
            pass


class MemoryBackend:
    """In-memory blob map with injectable transport faults (tests).

    Fault knobs (all settable at any time, from any thread):

    - ``latency_sec``: every verb sleeps this long first (a slow NFS
      rename / cross-region put);
    - ``set_outage(n)``: the next ``n`` mutating/reading verbs raise
      :class:`StoreError` (n < 0 = outage until cleared with 0);
    - ``tear_next_put()``: the next put stores only a prefix of the
      payload and then raises — the torn-write shape a crashed
      non-atomic writer leaves, which the manifest validation must
      catch downstream.
    """

    def __init__(self, latency_sec: float = 0.0):
        self._lock = threading.Lock()
        # ---- guarded by self._lock ----
        self._blobs: Dict[str, Tuple[float, bytes]] = {}
        self._outage = 0
        self._torn_puts = 0
        self._clock = 0.0           # monotonic per-backend mtime
        self.latency_sec = float(latency_sec)
        self.puts = 0
        self.gets = 0
        self.faults_injected = 0

    # -- fault injection ----------------------------------------------
    def set_outage(self, n: int) -> None:
        with self._lock:
            self._outage = int(n)

    def tear_next_put(self, n: int = 1) -> None:
        with self._lock:
            self._torn_puts = int(n)

    def _enter(self, verb: str) -> None:
        if self.latency_sec > 0:
            time.sleep(self.latency_sec)
        with self._lock:
            if self._outage != 0:
                if self._outage > 0:
                    self._outage -= 1
                self.faults_injected += 1
                raise StoreError(f"injected store outage ({verb})")

    # -- blob verbs ----------------------------------------------------
    def put(self, name: str, data: bytes) -> None:
        self._enter("put")
        with self._lock:
            self._clock += 1.0
            self.puts += 1
            if self._torn_puts > 0:
                self._torn_puts -= 1
                self.faults_injected += 1
                self._blobs[name] = (self._clock,
                                     data[: max(1, len(data) // 3)])
                raise StoreError(f"injected torn put of {name!r}")
            self._blobs[name] = (self._clock, bytes(data))

    def get(self, name: str) -> bytes:
        self._enter("get")
        with self._lock:
            self.gets += 1
            entry = self._blobs.get(name)
        if entry is None:
            raise FileNotFoundError(name)
        return entry[1]

    def list(self) -> List[str]:
        self._enter("list")
        with self._lock:
            return sorted(self._blobs)

    def stat(self, name: str) -> Optional[Tuple[float, int]]:
        with self._lock:
            entry = self._blobs.get(name)
        if entry is None:
            return None
        return (entry[0], len(entry[1]))

    def delete(self, name: str) -> None:
        self._enter("delete")
        with self._lock:
            self._blobs.pop(name, None)


class ObjectStore(ArtifactStore):
    """The object-store-shaped transport: whole-blob verbs delegated
    to a pluggable ``backend`` (a :class:`MemoryBackend` in tests; an
    rsync spool / KV / bucket client in a real deployment). Atomicity
    comes from the backend's all-or-nothing put."""

    def __init__(self, backend, url: str = "object://"):
        self.backend = backend
        self.url = url

    def put_bytes(self, name: str, data: bytes) -> None:
        self.backend.put(name, data)

    def get_bytes(self, name: str) -> bytes:
        return self.backend.get(name)

    def list_names(self) -> List[str]:
        return self.backend.list()

    def stat(self, name: str) -> Optional[Tuple[float, int]]:
        return self.backend.stat(name)

    def delete(self, name: str) -> None:
        self.backend.delete(name)


# process-wide mem:// registry so every component given the same spec
# (publisher, watcher, tests) lands on ONE faultable backend
_mem_lock = threading.Lock()
# ---- guarded by _mem_lock ----
_mem_backends: Dict[str, MemoryBackend] = {}


def store_for(target) -> ArtifactStore:
    """An :class:`ArtifactStore` from a target spec.

    - an ``ArtifactStore`` passes through unchanged;
    - ``mem://<name>`` names a process-shared :class:`MemoryBackend`
      (created on first use — the faultable test transport);
    - anything else (a str / path-like) is a :class:`LocalDirStore`.
    """
    if isinstance(target, ArtifactStore):
        return target
    spec = os.fspath(target)
    if spec.startswith("mem://"):
        with _mem_lock:
            backend = _mem_backends.get(spec)
            if backend is None:
                backend = _mem_backends[spec] = MemoryBackend()
        return ObjectStore(backend, url=spec)
    return LocalDirStore(spec)
