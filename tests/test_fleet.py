"""Self-healing serving fleet (ISSUE 17, docs/RESILIENCE.md).

Layers under test:

1. Artifact-store transport (resilience/store.py): local-dir and
   object-store-shaped backends behind one interface, with injectable
   latency / outage / torn-write faults on the memory backend, and
   the process-shared ``mem://`` registry.
2. Publisher over stores (resilience/publisher.py): manifest-first
   publication through any store, store_outage retry/backoff,
   publish_poison (byte-valid, canary-garbage), publish_keep
   retention with protected shas, and rollback republication.
3. Autoscaling + rollback policy (resilience/autoscale.py):
   hysteresis scaling decisions from the fleet scrape signal, and the
   watching -> adopted | rolled-back publication state machine.
4. Canary gate (serve/daemon.py): a poisoned publication is refused
   BEFORE the swap with a canary_refused fault event; a valid canary
   passes and the validated forest is the one installed.
5. Drain + scrape robustness: a connection parked in the TCP accept
   backlog across a SIGTERM drain gets a typed {"error": "draining"}
   reply (never a hang), and a wedged replica (accepts TCP, never
   replies) is marked dead without stalling the scrape round.
6. (slow) The ISSUE 17 chaos e2e: load-spike autoscaling up AND back
   down, a store outage mid-publish carried by retry/backoff, and a
   poisoned generation refused by every canary gate and rolled back
   to last-known-good by the fleet supervisor.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.resilience.autoscale import (  # noqa: E402
    AutoscalePolicy, RollbackGuard)
from lightgbm_tpu.resilience.publisher import (  # noqa: E402
    MANIFEST_SUFFIX, PublishError, latest_manifest, latest_manifest_in,
    load_manifest_in, prune_publications, publish_model,
    rollback_publication, validate_artifact_in)
from lightgbm_tpu.resilience.store import (  # noqa: E402
    LocalDirStore, MemoryBackend, ObjectStore, StoreError, store_for)

from tests._mp_utils import REPO_DIR, kill_group  # noqa: E402
from tests.conftest import make_synthetic_binary  # noqa: E402


def _train(params, X, y, rounds=5, **kwargs):
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    return lgb.train({"verbosity": -1, **params}, ds,
                     num_boost_round=rounds, **kwargs)


@pytest.fixture(scope="module")
def binary_model():
    X, y = make_synthetic_binary(n=900, f=8)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    return bst, X, y


def _canary_for(bst, X, rows=4, tol=1e-3):
    """The publisher-side canary batch: float32 rows (what the serve
    path feeds the forest) scored through the reference predictor."""
    c_rows = np.asarray(X[:rows], np.float32)
    scores = bst.predict(c_rows.astype(np.float64),
                         raw_score=True).reshape(-1)
    return {"rows": c_rows.tolist(),
            "scores": [float(s) for s in scores], "tol": tol}


# ---------------------------------------------------------------------
# 1. artifact-store transport
# ---------------------------------------------------------------------

def test_local_dir_store_roundtrip(tmp_path):
    store = LocalDirStore(str(tmp_path / "pub"))
    assert store.list_names() == []          # missing dir: empty, no raise
    store.put_bytes("a.txt", b"hello")
    store.put_bytes("b.txt", b"world!!")
    assert store.get_bytes("a.txt") == b"hello"
    assert sorted(store.list_names()) == ["a.txt", "b.txt"]
    mtime, size = store.stat("b.txt")
    assert size == 7 and mtime > 0
    assert store.stat("missing.txt") is None
    with pytest.raises(FileNotFoundError):
        store.get_bytes("missing.txt")
    store.delete("a.txt")
    store.delete("a.txt")                    # idempotent
    assert store.list_names() == ["b.txt"]


def test_memory_backend_outage_and_torn_put():
    backend = MemoryBackend()
    store = ObjectStore(backend, url="object://t")
    store.put_bytes("m.txt", b"x" * 90)
    backend.set_outage(2)
    with pytest.raises(StoreError):
        store.get_bytes("m.txt")
    with pytest.raises(StoreError):
        store.put_bytes("m.txt", b"y")
    # outage over: verbs work again
    assert store.get_bytes("m.txt") == b"x" * 90
    # a torn put stores a prefix THEN raises — the crashed non-atomic
    # writer shape manifest validation exists for
    backend.tear_next_put()
    with pytest.raises(StoreError):
        store.put_bytes("m.txt", b"z" * 90)
    torn = store.get_bytes("m.txt")
    assert torn == b"z" * 30 and len(torn) < 90
    assert backend.faults_injected == 3


def test_store_for_registry_and_passthrough(tmp_path):
    a = store_for("mem://registry-test")
    b = store_for("mem://registry-test")
    a.put_bytes("k", b"v")
    assert b.get_bytes("k") == b"v"          # same process-shared backend
    assert a.backend is b.backend
    local = store_for(str(tmp_path))
    assert isinstance(local, LocalDirStore)
    assert store_for(local) is local         # ArtifactStore passthrough


# ---------------------------------------------------------------------
# 2. publisher over stores
# ---------------------------------------------------------------------

def test_publish_through_object_store(binary_model):
    bst, X, _ = binary_model
    store = ObjectStore(MemoryBackend(), url="object://pub")
    manifest = publish_model(bst, store, "model_g0000.txt",
                             metadata={"generation": 0},
                             canary=_canary_for(bst, X))
    assert validate_artifact_in(store, "model_g0000.txt")["sha256"] \
        == manifest["sha256"]
    got = latest_manifest_in(store)
    assert got is not None and got[0] == "model_g0000.txt"
    assert got[1]["canary"]["tol"] == 1e-3
    # store targets report member NAMES; dir targets joined paths
    assert latest_manifest(store)[0] == "model_g0000.txt"


def test_store_outage_publish_retries_to_success(binary_model,
                                                 monkeypatch):
    """store_outage@G: the transport is down for the first attempt;
    the jittered-backoff retry carries the publication through and the
    outage is a telemetry event, never a crash."""
    bst, _, _ = binary_model
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS, drain_events
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "store_outage@4")
    drain_events(FAULT_EVENTS)
    store = ObjectStore(MemoryBackend(), url="object://outage")
    sleeps = []
    manifest = publish_model(bst, store, "model_g0004.txt",
                             fault_iteration=4, backoff_base_sec=0.01,
                             _sleep=sleeps.append)
    assert len(sleeps) == 1 and sleeps[0] > 0
    assert validate_artifact_in(store, "model_g0004.txt")["sha256"] \
        == manifest["sha256"]
    events = drain_events(FAULT_EVENTS)
    assert any(e["kind"] == "store_outage" and e["action"] == "retry"
               for e in events)


def test_real_store_outage_also_retries(binary_model):
    """Not just the injected kind: a StoreError raised by the backend
    itself rides the same retry loop."""
    bst, _, _ = binary_model
    backend = MemoryBackend()
    store = ObjectStore(backend, url="object://flaky")
    backend.set_outage(1)
    manifest = publish_model(bst, store, "m.txt",
                             backoff_base_sec=0.001,
                             _sleep=lambda _: None)
    assert validate_artifact_in(store, "m.txt")["sha256"] \
        == manifest["sha256"]
    # exhaustion raises PublishError, never StoreError
    backend.set_outage(-1)
    with pytest.raises(PublishError, match="failed after"):
        publish_model(bst, store, "m2.txt", retries=1,
                      backoff_base_sec=0.001, _sleep=lambda _: None)
    backend.set_outage(0)


def test_publish_poison_is_byte_valid_but_canary_garbage(
        binary_model, monkeypatch):
    """publish_poison@G: the publication's sha256 validates (only the
    serve-side canary gate can catch it) but its embedded expectations
    are shifted far outside any tolerance."""
    bst, X, _ = binary_model
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS, drain_events
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "publish_poison@2")
    drain_events(FAULT_EVENTS)
    store = ObjectStore(MemoryBackend(), url="object://poison")
    canary = _canary_for(bst, X)
    manifest = publish_model(bst, store, "model_g0002.txt",
                             canary=canary, fault_iteration=2)
    # byte-valid: manifest validation accepts the poisoned publication
    assert validate_artifact_in(store, "model_g0002.txt")["sha256"] \
        == manifest["sha256"]
    want = np.asarray(canary["scores"])
    got = np.asarray(manifest["canary"]["scores"])
    assert np.all(np.abs(got - want) > 100.0)
    events = drain_events(FAULT_EVENTS)
    assert any(e["kind"] == "publish_poison"
               and e["action"] == "published_poisoned" for e in events)


def test_prune_publications_keep_and_protect(tmp_path):
    names = [f"model_g{g:04d}.txt" for g in range(4)]
    shas = []
    for g, name in enumerate(names):
        # distinct payloads: retention ranks by recency but protects
        # by sha, so every publication needs its own
        shas.append(publish_model(f"model body {g}\n", str(tmp_path),
                                  name, metadata={"v": name})["sha256"])
        time.sleep(0.02)             # distinct created_unix ordering
    # keep=0 is unbounded
    assert prune_publications(str(tmp_path), 0) == []
    # keep=2 prunes the two oldest — unless protected: g0 survives as
    # the (say) currently-served model, so only g1 goes
    pruned = prune_publications(str(tmp_path), 2,
                                protect_shas=(shas[0],))
    assert pruned == [names[1]]
    left = sorted(os.listdir(tmp_path))
    assert names[1] not in left
    assert names[1] + MANIFEST_SUFFIX not in left
    for keepname in (names[0], names[2], names[3]):
        assert keepname in left and keepname + MANIFEST_SUFFIX in left
    # newest-validated lookup still lands on g3
    assert latest_manifest(str(tmp_path))[1]["v"] == names[3]


def test_publish_with_keep_prunes_inline(tmp_path):
    """publish_model(keep=N) prunes after a successful publish, never
    pruning its own fresh publication."""
    for g in range(3):
        publish_model(f"model body {g}\n", str(tmp_path),
                      f"model_g{g:04d}.txt", keep=2)
        time.sleep(0.02)
    left = sorted(n for n in os.listdir(tmp_path)
                  if not n.endswith(MANIFEST_SUFFIX))
    assert left == ["model_g0001.txt", "model_g0002.txt"]


def test_rollback_publication_republishes_last_known_good(
        binary_model, tmp_path):
    bst, X, y = binary_model
    good = publish_model(bst, str(tmp_path), "model_g0001.txt",
                         metadata={"generation": 1},
                         canary=_canary_for(bst, X))
    time.sleep(0.02)
    bad_bst = _train({"objective": "binary", "num_leaves": 15},
                     X, (X[:, 0] > 0).astype(np.float64))
    bad = publish_model(bad_bst, str(tmp_path), "model_g0002.txt",
                        metadata={"generation": 2})
    time.sleep(0.02)
    manifest = rollback_publication(str(tmp_path), "model_g0002.txt",
                                    "model_g0001.txt")
    # the bad publication is GONE (artifact and manifest)
    left = os.listdir(tmp_path)
    assert "model_g0002.txt" not in left
    assert "model_g0002.txt" + MANIFEST_SUFFIX not in left
    # the republication carries the good bytes (same sha), provenance,
    # and the good canary — and wins newest-validated polling
    assert manifest["sha256"] == good["sha256"]
    assert manifest["rollback_of"] == bad["sha256"]
    assert manifest["generation"] == 1
    assert manifest["canary"] == good["canary"]
    newest_path, newest = latest_manifest(str(tmp_path))
    assert os.path.basename(newest_path).startswith("rollback_")
    assert newest["sha256"] == good["sha256"]


# ---------------------------------------------------------------------
# 3. autoscaling + rollback policy
# ---------------------------------------------------------------------

def _rows(qps_each, n=1, p99=10.0, shed=None):
    return [{"rank": r, "alive": True, "qps": qps_each, "p99_ms": p99,
             **({} if shed is None else {"shed_total": shed})}
            for r in range(n)]


def test_autoscale_up_signals_and_observation_consume():
    clock = [100.0]
    pol = AutoscalePolicy(1, 3, up_qps=10.0, down_qps=5.0,
                          up_p99_ms=200.0, up_cooldown_sec=5.0,
                          down_cooldown_sec=15.0,
                          _now=lambda: clock[0])
    # no observation yet -> no decision
    assert pol.decide(1) is None
    pol.observe(_rows(25.0))
    action, reason = pol.decide(1)
    assert action == "up" and "qps" in reason
    # the observation is CONSUMED: a tight supervision loop cannot
    # re-fire on the same scrape
    assert pol.decide(2) is None
    # p99 breach scales up too (after the up cooldown)
    clock[0] += 6.0
    pol.observe(_rows(1.0, n=2, p99=500.0))
    assert pol.decide(2)[0] == "up"
    # shed forward-motion scales up; a restarted replica's counter
    # RESET does not
    clock[0] += 6.0
    pol.observe(_rows(1.0, n=3, shed=50))
    assert pol.decide(3) is None             # at max_replicas: bounded
    clock[0] += 6.0
    pol.observe(_rows(1.0, n=2, shed=80))    # +30 forward
    assert pol.decide(2)[0] == "up"
    clock[0] += 6.0
    pol.observe(_rows(1.0, n=2, shed=0))     # reset, not a shed burst
    assert pol.decide(2) is None
    assert pol.scale_ups == 3


def test_autoscale_down_hysteresis_and_cooldown():
    clock = [0.0]
    pol = AutoscalePolicy(1, 3, up_qps=10.0, down_qps=5.0,
                          up_p99_ms=200.0, up_cooldown_sec=5.0,
                          down_cooldown_sec=15.0,
                          _now=lambda: clock[0])
    pol.observe(_rows(20.0))
    assert pol.decide(1)[0] == "up"          # scaled at t=0
    # calm traffic, but inside the down cooldown: hold
    clock[0] = 10.0
    pol.observe(_rows(1.0, n=2))
    assert pol.decide(2) is None
    # past the cooldown AND qps clears down_qps with one fewer replica
    clock[0] = 16.0
    pol.observe(_rows(1.0, n=2))
    action, reason = pol.decide(2)
    assert action == "down" and "qps" in reason
    # at the floor: never below min_replicas
    clock[0] = 40.0
    pol.observe(_rows(0.0))
    assert pol.decide(1) is None
    # qps in the dead band (above down threshold, below up): hold —
    # the hysteresis gap that prevents flapping
    clock[0] = 60.0
    pol.observe(_rows(4.0, n=2))             # 8 total; (2-1)*5=5 < 8
    assert pol.decide(2) is None
    assert (pol.scale_ups, pol.scale_downs) == (1, 1)


def test_rollback_guard_adopts_then_condemns():
    clock = [0.0]
    guard = RollbackGuard(refuse_sec=5.0, adopt_sec=2.0,
                          _now=lambda: clock[0])
    # publication 1: served -> adopted as last-known-good
    assert guard.note_publication("model_g0001.txt", "sha1")
    assert not guard.note_publication("model_g0001.txt", "sha1")
    guard.observe([{"rank": 0, "sha256": "sha1",
                    "swap_failures_total": 0}])
    assert guard.decide() is None            # first sighting starts clock
    clock[0] = 3.0
    assert guard.decide() is None
    assert guard.last_known_good == ("model_g0001.txt", "sha1")
    # publication 2: nobody serves it and swap failures mount (every
    # canary gate refused it) -> condemned after refuse_sec
    assert guard.note_publication("model_g0002.txt", "sha2")
    guard.observe([{"rank": 0, "sha256": "sha1",
                    "swap_failures_total": 2}])
    clock[0] = 4.0
    assert guard.decide() is None            # refuse_sec not reached
    clock[0] = 9.0
    order = guard.decide()
    assert order == {"bad_name": "model_g0002.txt", "bad_sha": "sha2",
                     "good_name": "model_g0001.txt",
                     "good_sha": "sha1"}
    # condemned shas are remembered: a rollback can never loop
    assert not guard.note_publication("model_g0002.txt", "sha2")
    assert guard.decide() is None


def test_rollback_guard_requires_swap_failures():
    """A publication nobody has swapped onto yet but with NO swap
    failures is still rolling out (slow compile, mid-restart) — the
    guard must not condemn it on a timer alone."""
    clock = [0.0]
    guard = RollbackGuard(refuse_sec=5.0, adopt_sec=2.0,
                          _now=lambda: clock[0])
    guard.note_publication("m.txt", "shaX")
    guard.observe([{"rank": 0, "sha256": "old",
                    "swap_failures_total": 0}])
    clock[0] = 60.0
    assert guard.decide() is None
    # ...until failures mount
    guard.observe([{"rank": 0, "sha256": "old",
                    "swap_failures_total": 3}])
    assert guard.decide()["bad_sha"] == "shaX"


def test_rollback_guard_post_swap_eviction_condemns():
    """The OTHER rollback trigger: a replica swapped onto the watched
    publication, then failed post-swap health checks and was evicted
    — condemned immediately, before any adopt."""
    clock = [0.0]
    guard = RollbackGuard(refuse_sec=5.0, adopt_sec=2.0,
                          _now=lambda: clock[0])
    guard.note_publication("good.txt", "g")
    guard.observe([{"rank": 0, "sha256": "g",
                    "swap_failures_total": 0}])
    guard.decide()                           # first sighting at t=0
    clock[0] = 3.0
    guard.decide()                           # adopted
    guard.note_publication("next.txt", "n")
    guard.observe([{"rank": 1, "sha256": "n",
                    "swap_failures_total": 0}])
    guard.note_eviction(1)
    order = guard.decide()
    assert order["bad_sha"] == "n" and order["good_sha"] == "g"


# ---------------------------------------------------------------------
# 4. the serve-side canary gate
# ---------------------------------------------------------------------

def test_canary_gate_refuses_poison_then_accepts_valid(
        binary_model, tmp_path):
    """A byte-valid publication whose canary scores mismatch is
    refused BEFORE swap_deferred — the old model keeps serving, a
    canary_refused fault event fires once, and the swap-failure
    counter feeds the supervisor's rollback guard. A publication with
    honest expectations swaps, and the forest installed is the one
    that scored the canary."""
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS, drain_events
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.serve.compile import compile_forest
    from lightgbm_tpu.serve.daemon import (ServeState, _artifact_key,
                                           _Watcher)
    bst, X, y = binary_model
    model_a = str(tmp_path / "a.txt")
    bst.save_model(model_a)
    cf = compile_forest(bst, max_batch_rows=256)
    mb = MicroBatcher(cf, batch_window_ms=0.5, max_batch_rows=256)
    state = ServeState(mb, cf.model_id, model_a)
    drain_events(FAULT_EVENTS)
    try:
        watcher = _Watcher(
            state, str(tmp_path), 0.1,
            dict(num_iteration=-1, min_bucket=16, max_batch_rows=256),
            _artifact_key(model_a), 64)
        bst_b = _train({"objective": "binary", "num_leaves": 15},
                       X, (X[:, 1] > 0).astype(np.float64))
        poisoned = _canary_for(bst_b, X)
        poisoned["scores"] = [s + 1e3 for s in poisoned["scores"]]
        publish_model(bst_b, str(tmp_path), "b.txt", canary=poisoned)
        target = str(tmp_path / "b.txt")
        os.utime(target, (time.time() + 2, time.time() + 2))

        assert watcher.poll_once() is False
        assert state.stats()["swap_failures"] == 1
        events = drain_events(FAULT_EVENTS)
        assert any(e["kind"] == "canary_refused"
                   and e["action"] == "refused_swap" for e in events)
        assert any(e["kind"] == "swap_failure" for e in events)
        # the old model is untouched
        assert state.stats()["model"] == cf.model_id
        # still refused next poll (counter moves; event fired once)
        assert watcher.poll_once() is False
        assert state.stats()["swap_failures"] == 2
        assert not any(e["kind"] == "canary_refused"
                       for e in drain_events(FAULT_EVENTS))

        # an honest republication swaps
        manifest = publish_model(bst_b, str(tmp_path), "b.txt",
                                 canary=_canary_for(bst_b, X))
        os.utime(target, (time.time() + 4, time.time() + 4))
        assert watcher.poll_once() is True
        st = state.stats()
        assert st["model"] == compile_forest(bst_b).model_id
        assert st["manifest"]["sha256"] == manifest["sha256"]
    finally:
        state.close()


def test_watcher_degrades_through_store_outage(binary_model, tmp_path):
    """A store outage while polling the watch target degrades to
    serving the current model with ONE store_outage fault event per
    episode — never a watcher crash — and recovers when the store
    does."""
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS, drain_events
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.serve.compile import compile_forest
    from lightgbm_tpu.serve.daemon import ServeState, _Watcher
    bst, X, y = binary_model
    backend = MemoryBackend()
    store = ObjectStore(backend, url="object://watch")
    cf = compile_forest(bst, max_batch_rows=256)
    mb = MicroBatcher(cf, batch_window_ms=0.5, max_batch_rows=256)
    state = ServeState(mb, cf.model_id, "seed")
    drain_events(FAULT_EVENTS)
    try:
        watcher = _Watcher(
            state, store, 0.1,
            dict(num_iteration=-1, min_bucket=16, max_batch_rows=256),
            None, 64)
        backend.set_outage(-1)
        assert watcher.poll_once() is False
        assert watcher.poll_once() is False
        events = drain_events(FAULT_EVENTS)
        assert sum(1 for e in events
                   if e["kind"] == "store_outage"
                   and e["action"] == "degraded") == 1
        assert state.stats()["model"] == cf.model_id
        # store recovers -> the next poll swaps onto the publication
        backend.set_outage(0)
        bst_b = _train({"objective": "binary", "num_leaves": 15},
                       X, (X[:, 1] > 0).astype(np.float64))
        manifest = publish_model(bst_b, store, "b.txt",
                                 canary=_canary_for(bst_b, X))
        assert watcher.poll_once() is True
        assert state.stats()["manifest"]["sha256"] == \
            manifest["sha256"]
    finally:
        state.close()


# ---------------------------------------------------------------------
# 5. drain + scrape robustness
# ---------------------------------------------------------------------

def _read_ready(proc, tries=400):
    for _ in range(tries):
        line = proc.stdout.readline()
        if not line:
            raise AssertionError("daemon exited before serve_ready")
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("event") == "serve_ready":
            return obj
    raise AssertionError("no serve_ready line")


@pytest.mark.slow
def test_backlogged_connection_gets_draining_reply(binary_model,
                                                   tmp_path):
    """The accept-backlog drain regression: a connection that lands in
    the TCP backlog while the daemon is busy and is only accepted
    AFTER SIGTERM must get a typed {"error": "draining"} reply, not a
    hang or a reset. SIGSTOP parks the accept loop so the kernel
    completes our handshake into the backlog; SIGCONT + the drain
    window's linger then sweeps it."""
    bst, X, _ = binary_model
    model = str(tmp_path / "model.txt")
    bst.save_model(model)
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "serve", model,
         "--port", "0", "--warmup-rows", "64",
         "--window-ms", "5", "--max-batch-rows", "256",
         "--grace", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO_DIR, start_new_session=True)
    try:
        ready = _read_ready(proc)
        port = ready["port"]
        # warm check: the daemon answers (also proves accept works
        # BEFORE the stop)
        s0 = socket.create_connection(("127.0.0.1", port), timeout=10)
        fh0 = s0.makefile("rw")
        fh0.write(json.dumps({"cmd": "ping"}) + "\n")
        fh0.flush()
        assert json.loads(fh0.readline())["ok"]
        s0.close()
        os.kill(proc.pid, signal.SIGSTOP)    # accept loop frozen
        try:
            # this handshake completes in the KERNEL's listen backlog;
            # the stopped daemon never accepts it
            s1 = socket.create_connection(("127.0.0.1", port),
                                          timeout=10)
            s1.settimeout(30)
            fh1 = s1.makefile("rw")
            os.kill(proc.pid, signal.SIGTERM)   # queued behind STOP
        finally:
            os.kill(proc.pid, signal.SIGCONT)
        # wait until the drain has provably begun (cmd verbs keep
        # answering during a drain; only predict requests flip) so the
        # backlogged request cannot race the drain flag
        deadline = time.monotonic() + 8.0
        while True:
            assert time.monotonic() < deadline, "drain never began"
            try:
                s2 = socket.create_connection(("127.0.0.1", port),
                                              timeout=5)
                fh2 = s2.makefile("rw")
                fh2.write(json.dumps({"cmd": "stats"}) + "\n")
                fh2.flush()
                st = json.loads(fh2.readline())
                s2.close()
                if st.get("draining"):
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
        # the drain sweeps the backlog: a typed reply, not a hang
        fh1.write(json.dumps({"rows": X[:4].tolist()}) + "\n")
        fh1.flush()
        line = fh1.readline()
        assert line, "backlogged connection dropped without a reply"
        reply = json.loads(line)
        assert reply.get("error") == "draining", reply
        assert reply.get("draining") is True
        s1.close()
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            kill_group(proc)


class _FakeProc:
    def poll(self):
        return None


def _bind_two_ports():
    """Two CONTIGUOUS free ports (the fleet scrape addresses replicas
    at health_port + rank)."""
    for _ in range(50):
        s0 = socket.socket()
        try:
            s0.bind(("127.0.0.1", 0))
            base = s0.getsockname()[1]
            s1 = socket.socket()
            try:
                s1.bind(("127.0.0.1", base + 1))
                return s0, s1, base
            except OSError:
                s1.close()
        except OSError:
            pass
        s0.close()
    raise AssertionError("could not find two contiguous free ports")


def test_wedged_replica_fails_scrape_without_stalling_round():
    """A wedged replica — accepts TCP, never replies — must be marked
    alive: false within one bounded health_timeout, while the healthy
    replica's row (scraped concurrently) still lands in the SAME
    round."""
    from lightgbm_tpu.obs.export import (counter_family, gauge_family,
                                         render_openmetrics)
    from lightgbm_tpu.resilience.elastic import _Replica, _scrape_fleet
    ls0, ls1, base = _bind_two_ports()
    stop = threading.Event()
    metrics_text = render_openmetrics({}, extra={
        "serve_qps": gauge_family(12.5),
        "serve_p99_ms": gauge_family(8.0),
        "serve_requests_total": counter_family(100),
        "serve_shed_total": counter_family(0),
        "serve_model_info": gauge_family(1, model="m1",
                                         sha="abc123"),
    })

    def _healthy():
        ls0.listen(8)
        ls0.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = ls0.accept()
            except socket.timeout:
                continue
            conn.recv(65536)
            conn.sendall((json.dumps(
                {"ok": True, "metrics": metrics_text}) + "\n"
            ).encode())
            conn.close()

    def _wedged():
        ls1.listen(8)
        ls1.settimeout(0.2)
        held = []
        while not stop.is_set():
            try:
                conn, _ = ls1.accept()   # accept, never reply
                held.append(conn)
            except socket.timeout:
                continue
        for c in held:
            c.close()

    threads = [threading.Thread(target=_healthy, daemon=True),
               threading.Thread(target=_wedged, daemon=True)]
    for t in threads:
        t.start()
    try:
        healthy, wedged = _Replica(0), _Replica(1)
        healthy.proc = wedged.proc = _FakeProc()
        t0 = time.monotonic()
        record = _scrape_fleet([healthy, wedged], base,
                               health_timeout=1.5)
        elapsed = time.monotonic() - t0
        rows = {r["rank"]: r for r in record["replicas"]}
        assert rows[0]["alive"] and rows[0]["qps"] == 12.5
        assert rows[0]["sha256"] == "abc123"
        assert rows[1]["alive"] is False
        assert rows[1]["responsive"] is False
        # one bounded round: the wedge cost ~one health_timeout, not
        # one per replica queued behind it
        assert elapsed < 4.0, elapsed
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        ls0.close()
        ls1.close()


# ---------------------------------------------------------------------
# 6. the ISSUE 17 chaos e2e
# ---------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(900)
def test_fleet_chaos_end_to_end(tmp_path):
    """The acceptance run: a load spike scales the fleet up and back
    down (hysteresis, no client timeouts), a store outage mid-publish
    is carried by retry/backoff while the old model keeps serving,
    and a poisoned generation is refused by the canary gate and
    rolled back to last-known-good by the fleet supervisor — all
    confirmed from the merged telemetry."""
    workdir = str(tmp_path / "pipe")
    env = {k: v for k, v in os.environ.items()
           if k not in ("LIGHTGBM_TPU_FAULT_INJECT",
                        "LIGHTGBM_TPU_CHECKPOINT",
                        "LIGHTGBM_TPU_TELEMETRY")}
    env["PYTHONPATH"] = REPO_DIR
    # store_outage@1 downs the transport for generation 1's first
    # publish attempt; publish_poison@2 poisons generation 2's canary
    env["LIGHTGBM_TPU_FAULT_INJECT"] = "store_outage@1,publish_poison@2"
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "pipeline",
         "--workdir", workdir, "--generations", "3",
         "--rounds", "5", "--rows", "900", "--features", "8",
         "--request-rate", "8", "--request-rows", "4",
         "--replicas", "1", "--max-replicas", "3",
         "--autoscale-up-qps", "15", "--autoscale-down-qps", "6",
         "--spike-rate", "60", "--spike-start", "4",
         "--spike-duration", "12",
         "--retire-grace", "15", "--rollback-grace", "8",
         "--canary-rows", "8", "--publish-keep", "4",
         "--health-interval", "0.5", "--health-grace", "25",
         "--scrape-interval", "1",
         "--swap-timeout", "240", "--grace", "10",
         "--param", "publish_backoff_sec=2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO_DIR, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=800)
    except subprocess.TimeoutExpired:
        kill_group(proc)
        out, _ = proc.communicate(timeout=30)
        pytest.fail(f"pipeline hung; partial output:\n{out[-4000:]}")
    assert proc.returncode == 0, f"pipeline failed:\n{out[-6000:]}"
    summary = None
    for line in out.splitlines():
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("event") == "pipeline_summary":
            summary = obj
    assert summary is not None, out[-4000:]
    assert summary["failures"] == []
    assert summary["generations_published"] == 3

    # --- autoscaling: the spike scaled the fleet up, the calm after
    # it scaled back down, and clients saw no timeouts throughout
    lifecycle = summary["fleet_lifecycle"]
    assert lifecycle["scale_ups"] >= 1, lifecycle
    assert lifecycle["scale_downs"] >= 1, lifecycle
    assert lifecycle["replicas_peak"] >= 2, lifecycle
    client = summary["client"]
    assert client["timeout"] == 0, client
    assert client["ok"] > 0

    # --- rollback: generation 2's poisoned publication was refused
    # and rolled back to generation 1 (same bytes -> same sha)
    rollbacks = summary["rollbacks"]
    assert len(rollbacks) == 1, rollbacks
    assert lifecycle["rollbacks"] == 1
    # the fleet converged on the rollback republication of gen 1,
    # never serving the poisoned model
    poisoned_sha = rollbacks[0]["bad_sha"]
    good_sha = rollbacks[0]["good_sha"]
    assert poisoned_sha and good_sha and poisoned_sha != good_sha
    fleet = summary["fleet"]
    assert fleet and all(st is not None for st in fleet)
    for st in fleet:
        assert st["manifest_sha256"] == good_sha
        assert st["manifest_sha256"] != poisoned_sha

    # --- the fault/refusal evidence landed in telemetry: the serve
    # side refused the canary; generation 1's trainer retried through
    # the store outage
    telem = os.path.join(workdir, "telemetry")
    serve_kinds = set()
    for suffix in ("", ".rank1", ".rank2"):
        path = os.path.join(telem, "serve.jsonl" + suffix)
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            for ln in fh:
                if not ln.strip():
                    continue
                ev = json.loads(ln)
                if ev.get("event") == "fault":
                    serve_kinds.add(ev.get("kind"))
    assert "canary_refused" in serve_kinds, serve_kinds
    train_kinds = set()
    with open(os.path.join(telem, "train_g0001.jsonl")) as fh:
        for ln in fh:
            if not ln.strip():
                continue
            ev = json.loads(ln)
            if ev.get("event") == "fault":
                train_kinds.add(ev.get("kind"))
    assert "store_outage" in train_kinds, train_kinds

    # --- `stats --fleet` merges the autoscale/rollback evidence
    st = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "stats", telem,
         "--fleet"],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=REPO_DIR)
    assert st.returncode == 0, st.stderr[-3000:]
    assert "autoscale" in st.stdout, st.stdout
    assert "rollbacks" in st.stdout, st.stdout
