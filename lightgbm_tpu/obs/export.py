"""OpenMetrics export of the MetricsRegistry — the fleet scrape plane.

Every process in the fleet (trainer ranks under ``launch``, serve
replicas, the ``pipeline`` supervisor) carries the same process-global
:class:`~lightgbm_tpu.obs.registry.MetricsRegistry`; this module turns
it into the one wire format every metrics consumer already speaks —
OpenMetrics / Prometheus text — and exposes it two ways:

- :func:`render_openmetrics` — the pure render (snapshot -> text),
  shared by the serve daemon's ``{"cmd": "metrics"}`` protocol verb and
  the HTTP endpoint below;
- :class:`MetricsHTTPServer` / :func:`ensure_metrics_server` — a
  stdlib-``http.server`` ``/metrics`` endpoint
  (``Config.metrics_port`` / ``--metrics-port``, port + rank per
  process).

Two hard constraints shape the code:

- **jax-free**: supervisors (``launch``, ``pipeline``) serve their own
  ``/metrics`` and must never pin a backend; this module imports only
  stdlib + the (equally jax-free) registry.
- **no registry lock across I/O** (tpulint TPL006 discipline): the
  render always runs on ``registry.snapshot()`` — a copy taken under
  the lock — never on live instruments, so a slow scraper can never
  stall a training iteration's counter bump.

:func:`parse_openmetrics` is the strict line-grammar counterpart (no
client library): the fleet supervisors use it to scrape trainer-rank
endpoints for iteration skew, and the tests golden-parse every
rendered byte through it.

See docs/OBSERVABILITY.md "Fleet metrics plane".
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.log import log_info, log_warning
from .registry import MetricsRegistry
from .registry import registry as _global_registry

__all__ = ["render_openmetrics", "parse_openmetrics",
           "MetricsHTTPServer", "ensure_metrics_server",
           "counter_family", "gauge_family",
           "CONTENT_TYPE", "METRIC_PREFIX"]

#: every exported family is namespaced under this prefix
METRIC_PREFIX = "lightgbm_tpu_"

#: the OpenMetrics text content type (Prometheus accepts it and falls
#: back to the 0.0.4 text parse if it must)
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; " \
               "charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(raw: str) -> str:
    """A legal OpenMetrics metric/label name from a registry name
    (phase labels carry '/', '-', etc.)."""
    name = _NAME_FIX.sub("_", str(raw))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def _escape_label_value(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


def _labels_text(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_metric_name(k)}="{_escape_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _num(value) -> str:
    """OpenMetrics sample value: integers render bare, floats via
    repr (full precision round-trips through the parser)."""
    f = float(value)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_openmetrics(snapshot: Dict[str, Dict[str, object]],
                       extra: Optional[Dict[str, Dict[str,
                                                      object]]] = None,
                       prefix: str = METRIC_PREFIX) -> str:
    """Render a ``MetricsRegistry.snapshot()`` dict as OpenMetrics
    text (terminated by ``# EOF``).

    ``extra`` merges additional families of the same snapshot shape
    (``{name: {"kind": ..., "series": [{"labels": ..., ...}]}}``) —
    the serve daemon injects its batcher/latency gauges this way
    without ever writing them into the registry twice.

    Pure function over copies: callers hand in snapshots, so no lock
    is ever held here (the TPL006 discipline for the scrape path).
    """
    families = dict(snapshot)
    if extra:
        families.update(extra)
    lines: List[str] = []
    for raw_name in sorted(families):
        fam = families[raw_name]
        kind = str(fam.get("kind", "gauge"))
        series = fam.get("series") or []
        base = prefix + _metric_name(raw_name)
        if kind == "counter":
            # registry counters named *_total (publish_total, ...)
            # already carry the OpenMetrics suffix; the family name
            # drops it so samples never read *_total_total
            if base.endswith("_total"):
                base = base[:-len("_total")]
            lines.append(f"# TYPE {base} counter")
            for row in series:
                labels = _labels_text(row.get("labels") or {})
                value = row.get("value")
                if value is None:
                    continue
                lines.append(f"{base}_total{labels} {_num(value)}")
        elif kind == "gauge":
            rows = [row for row in series
                    if row.get("value") is not None]
            if rows:
                lines.append(f"# TYPE {base} gauge")
                for row in rows:
                    labels = _labels_text(row.get("labels") or {})
                    lines.append(f"{base}{labels} "
                                 f"{_num(row['value'])}")
            max_rows = [row for row in series
                        if row.get("max") is not None]
            if max_rows:
                lines.append(f"# TYPE {base}_max gauge")
                for row in max_rows:
                    labels = _labels_text(row.get("labels") or {})
                    lines.append(f"{base}_max{labels} "
                                 f"{_num(row['max'])}")
        elif kind == "histogram":
            # the registry keeps streaming moments, not buckets: the
            # faithful OpenMetrics mapping is a summary (count + sum)
            # plus min/max gauges
            lines.append(f"# TYPE {base} summary")
            for row in series:
                labels = _labels_text(row.get("labels") or {})
                lines.append(f"{base}_count{labels} "
                             f"{_num(row.get('count', 0))}")
                lines.append(f"{base}_sum{labels} "
                             f"{_num(row.get('total', 0.0))}")
            for bound in ("min", "max"):
                rows = [row for row in series
                        if row.get(bound) is not None]
                if rows:
                    lines.append(f"# TYPE {base}_{bound} gauge")
                    for row in rows:
                        labels = _labels_text(row.get("labels") or {})
                        lines.append(f"{base}_{bound}{labels} "
                                     f"{_num(row[bound])}")
        else:  # unknown kind: degrade to untyped gauges, never drop
            lines.append(f"# TYPE {base} gauge")
            for row in series:
                labels = _labels_text(row.get("labels") or {})
                value = row.get("value")
                if value is not None:
                    lines.append(f"{base}{labels} {_num(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def counter_family(value, **labels) -> Dict[str, object]:
    """One-sample counter in the snapshot-family shape ``extra``
    providers hand :func:`render_openmetrics` (the serve daemon's
    batcher counters, the pipeline's client view)."""
    return {"kind": "counter",
            "series": [{"labels": labels, "value": value}]}


def gauge_family(value, **labels) -> Dict[str, object]:
    """One-sample gauge in the snapshot-family shape (None values are
    skipped by the render, so callers never need to branch)."""
    return {"kind": "gauge",
            "series": [{"labels": labels, "value": value}]}


# ---------------------------------------------------------------------
# strict line-grammar parser (the scraper + golden-parse side)
# ---------------------------------------------------------------------

_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_][a-zA-Z0-9_]*) "
    r"(counter|gauge|summary|histogram|untyped)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_][a-zA-Z0-9_]*)"
    r"(\{[^{}]*\})? "
    r"(NaN|[+-]Inf|-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(value: str) -> str:
    # one left-to-right scan, NOT chained str.replace: sequential
    # replaces decode the escaped form of a literal backslash
    # followed by 'n' ('\\\\n') into backslash+newline
    out = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ("\\", '"'):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_value(text: str) -> float:
    if text == "NaN":
        return float("nan")
    if text == "+Inf":
        return float("inf")
    if text == "-Inf":
        return float("-inf")
    return float(text)


def parse_openmetrics(text: str) \
        -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Parse OpenMetrics text with a strict line grammar (no client
    library): every line must be a ``# TYPE`` declaration, a sample,
    or the final ``# EOF`` — anything else raises ``ValueError``.

    Returns ``{sample_name: {sorted_label_items: value}}`` (sample
    names keep their ``_total``/``_count``/... suffixes, so the
    round-trip against :func:`render_openmetrics` is exact).
    """
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    saw_eof = False
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.rstrip("\r")
        if not line:
            continue
        if saw_eof:
            raise ValueError(f"line {lineno}: content after # EOF")
        if line == "# EOF":
            saw_eof = True
            continue
        if line.startswith("#"):
            if _TYPE_RE.match(line):
                continue
            raise ValueError(
                f"line {lineno}: not a # TYPE declaration: {line!r}")
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: not a sample: {line!r}")
        name, labels_blob, value = m.group(1), m.group(2), m.group(3)
        labels: List[Tuple[str, str]] = []
        if labels_blob:
            inner = labels_blob[1:-1]
            matched = _LABEL_RE.findall(inner)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            if rebuilt != inner:
                raise ValueError(
                    f"line {lineno}: malformed label set: "
                    f"{labels_blob!r}")
            labels = [(k, _unescape_label_value(v))
                      for k, v in matched]
        out.setdefault(name, {})[tuple(sorted(labels))] = \
            _parse_value(value)
    if not saw_eof:
        raise ValueError("missing # EOF terminator")
    return out


# ---------------------------------------------------------------------
# the /metrics endpoint
# ---------------------------------------------------------------------

# per-endpoint scrape bookkeeping, keyed by bound port: written by the
# server's request-handler threads (every GET is one scrape), read by
# scrape_count() callers on the main path and exported as
# lightgbm_tpu_metrics_scrapes_total. Module-level so the TPL008
# thread-shared-state proof covers it — every touch goes through
# _scrape_lock.
_scrape_lock = threading.Lock()
_scrape_counts: Dict[int, int] = {}


class MetricsHTTPServer:
    """Stdlib ``/metrics`` endpoint over one registry.

    A daemon thread runs a ``ThreadingHTTPServer``, which handles
    every GET on its own request thread: the handler bumps the scrape
    counter under ``_scrape_lock``, takes a registry snapshot (the
    only other locked step, inside the registry), and renders outside
    any lock. ``extra_families`` is an optional zero-arg callable
    returning additional snapshot-shaped families (the serve daemon's
    batcher stats); it runs on the scrape thread and must be cheap
    and lock-disciplined itself.
    """

    def __init__(self, port: int,
                 registry: Optional[MetricsRegistry] = None,
                 extra_families: Optional[Callable[[], Dict]] = None,
                 host: str = "127.0.0.1"):
        import http.server

        self.registry = registry if registry is not None \
            else _global_registry
        self.extra_families = extra_families

        exporter = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            # one request per connection is fine at scrape cadence
            protocol_version = "HTTP/1.0"

            def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                with _scrape_lock:
                    count = _scrape_counts.get(exporter.port, 0) + 1
                    _scrape_counts[exporter.port] = count
                try:                     # render OUTSIDE the lock
                    body = exporter.render(scrapes=count) \
                        .encode("utf-8")
                except Exception as e:   # never kill the scrape thread
                    self.send_error(500, str(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass                     # scrapes must not spam stderr

        self._server = http.server.ThreadingHTTPServer(
            (host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.5}, daemon=True,
            name="lightgbm-tpu-metrics")
        self._thread.start()
        log_info(f"metrics: /metrics endpoint on "
                 f"http://{host}:{self.port}/metrics")

    def render(self, scrapes: Optional[int] = None) -> str:
        """One scrape: snapshot (locked, inside the registry), render
        (no lock). The endpoint's own scrape count rides along as
        ``lightgbm_tpu_metrics_scrapes_total``."""
        if scrapes is None:
            scrapes = self.scrape_count()
        snapshot = self.registry.snapshot()
        extra = {"metrics_scrapes": {
            "kind": "counter",
            "series": [{"labels": {}, "value": scrapes}]}}
        if self.extra_families is not None:
            try:
                extra.update(self.extra_families() or {})
            except Exception as e:
                log_warning(f"metrics: extra families provider failed "
                            f"({e}); exporting registry only")
        return render_openmetrics(snapshot, extra=extra)

    def scrape_count(self) -> int:
        with _scrape_lock:
            return _scrape_counts.get(self.port, 0)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# one endpoint per process: repeated train() calls (cv folds, the
# pipeline's generations) must reuse the first server, not fight over
# the port. Guarded by _server_lock.
_server_lock = threading.Lock()
_server: Optional[MetricsHTTPServer] = None


def ensure_metrics_server(port: int,
                          registry: Optional[MetricsRegistry] = None,
                          extra_families: Optional[
                              Callable[[], Dict]] = None) \
        -> Optional[MetricsHTTPServer]:
    """Start the process-wide ``/metrics`` endpoint once; subsequent
    calls return the existing server (whatever port it bound). A bind
    failure warns and returns None — metrics must degrade, never take
    down training or serving."""
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        try:
            _server = MetricsHTTPServer(
                port, registry=registry, extra_families=extra_families)
        except OSError as e:
            log_warning(f"metrics: cannot bind /metrics endpoint on "
                        f"port {port} ({e}); export disabled for this "
                        "process")
            return None
        return _server
