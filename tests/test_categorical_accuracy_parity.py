"""Accuracy impact of the documented semantic relaxations, measured
against the reference on realistic categorical data.

Two places deliberately relax reference semantics (docstrings in
ops/split.py and ops/grow.py):
- categorical ``min_data_per_group`` uses hessian-ratio count
  estimates per category group instead of exact per-group counts;
- quantized training estimates per-bin data counts from the quantized
  hessian sum.

Oracle: the reference CLI (built as documented in
tests/data/README.md) trained on the byte-identical seed-42 dataset
below (3 high-cardinality categoricals with group effects + 2
numerics + 1 noise categorical; 6000 train / 2000 test rows,
categorical_feature=0,1,2,3, 60 iters, 31 leaves, lr 0.1,
min_data_in_leaf 20) reaches held-out AUC 0.925362. Both our float
and quantized paths must land within noise of that.
"""

import numpy as np

import lightgbm_tpu as lgb

REF_AUC = 0.925362


def _data():
    rs = np.random.RandomState(42)
    n = 8000
    c1 = rs.randint(0, 40, n)
    c2 = rs.randint(0, 12, n)
    c3 = rs.randint(0, 100, n)
    cnoise = rs.randint(0, 25, n)
    x1 = rs.randn(n)
    x2 = rs.randn(n)
    logit = (rs.randn(40)[c1] + rs.randn(12)[c2] * 0.7
             + rs.randn(100)[c3] * 0.5 + 0.6 * x1 - 0.4 * x2
             + 0.8 * rs.randn(n))
    y = (logit > 0).astype(float)
    X = np.column_stack([c1, c2, c3, cnoise, x1, x2]).astype(np.float64)
    return X, y


def _auc(yv, p):
    o = np.argsort(p)
    r = np.empty(len(p))
    r[o] = np.arange(1, len(p) + 1)
    npos = yv.sum()
    nneg = len(yv) - npos
    return (r[yv == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def _train_auc(extra=None):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 31,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1}
    params.update(extra or {})
    d = lgb.Dataset(X[:6000], label=y[:6000],
                    categorical_feature=[0, 1, 2, 3])
    bst = lgb.train(params, d, 60)
    return _auc(y[6000:], bst.predict(X[6000:]))


def test_categorical_float_matches_reference_auc():
    a = _train_auc()
    assert abs(a - REF_AUC) < 0.004, (a, REF_AUC)


def test_categorical_quantized_matches_reference_auc():
    a = _train_auc({"use_quantized_grad": True})
    assert abs(a - REF_AUC) < 0.006, (a, REF_AUC)
