"""Native C++ text parser (src/io/parser.cpp analog) vs numpy parity."""

import numpy as np
import pytest

from lightgbm_tpu.utils.native import parse_dense_text


@pytest.mark.parametrize("delim", ["\t", ",", " "])
def test_native_matches_numpy(tmp_path, delim):
    rs = np.random.RandomState(0)
    M = rs.randn(500, 7)
    M[rs.rand(500, 7) < 0.05] = np.nan
    path = tmp_path / "data.txt"
    # empty cells only make sense for single-char delimiters; runs of
    # whitespace collapse, so spell missing as "nan" there
    empty = "nan" if delim == " " else ""
    with open(path, "w") as fh:
        for row in M:
            fh.write(delim.join(empty if np.isnan(v) else f"{v:.10g}"
                                for v in row) + "\n")
    got = parse_dense_text(str(path), False)
    if got is None:
        pytest.skip("native parser unavailable (no compiler)")
    want = np.genfromtxt(path, delimiter=None if delim == " " else delim)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-12, equal_nan=True)


@pytest.mark.skipif(
    not __import__("os").path.isdir("/root/reference/examples"),
    reason="reference examples not mounted")
def test_native_used_for_reference_example():
    import lightgbm_tpu as lgb
    d = lgb.Dataset(
        "/root/reference/examples/binary_classification/binary.train",
        params={"verbosity": -1})
    d.construct()
    assert d.num_data() == 7000
    assert d.num_total_features() == 28
