# tpulint fixture: TPL008 positive — a lifecycle load generator whose
# worker thread mutates outcome stats no lock guards. This is the
# "strip the lock from pipeline.py's LoadGenerator" acceptance shape:
# pipeline/tpl008_neg.py is the same generator WITH the common lock,
# and stripping the real one must re-surface these findings.
import threading

_published = []     # module-global publish book the poller mutates


class LoadGenerator:
    def __init__(self):
        self.attempts = 0
        self.ok = 0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            # EXPECT: TPL008
            self.attempts += 1
            # EXPECT: TPL008
            self.ok += 1

    def snapshot(self):
        return {"attempts": self.attempts, "ok": self.ok}


def _poll_publications():
    # EXPECT: TPL008
    _published.append("model.txt")


def watch_publications():
    threading.Thread(target=_poll_publications).start()
    return list(_published)
