"""TPL014 positive: a ``register_jit`` entry point with no
``max_signatures`` declaration. AST-scanned only (never imported) by
``analysis.ircheck.register_jit_sites`` — the local stub keeps the
file import-safe without touching the real registry."""


def _identity(x):
    return x


def register_jit(name, fn, max_signatures=None):
    return fn


# EXPECT: TPL014
F = register_jit("fixture/undeclared", _identity)
