"""Accepted-findings baseline (tools/tpulint_baseline.txt).

Format — one finding id per line, a ``#`` justification REQUIRED on
every entry (the tier-1 test enforces it: an acceptance without a
reason is just a suppressed bug)::

    # tpulint baseline
    TPL002:models/gbdt.py:GBDTBooster.train_one_iter:jax.device_get#1  # non-defer path: ...

Ids are stable under line drift (rule + file + function + symbol +
ordinal — no line numbers), so refactors that merely move code never
churn the baseline. Stale entries (baselined findings that no longer
occur) are reported so the baseline only ever shrinks honestly.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Tuple

__all__ = ["BaselineEntry", "load_baseline", "format_baseline",
           "assign_ids"]


@dataclass
class BaselineEntry:
    fid: str
    justification: str
    lineno: int


def load_baseline(path: str) -> List[BaselineEntry]:
    entries: List[BaselineEntry] = []
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as fh:
        for i, raw in enumerate(fh, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            # the id itself contains '#' (the ordinal) — the id is the
            # first whitespace-delimited token, the justification is
            # everything after the following '#'
            parts = line.split(None, 1)
            fid = parts[0]
            just = ""
            if len(parts) > 1:
                just = parts[1].lstrip("#").strip()
            if just.upper().startswith("TODO"):
                # --write-baseline skeletons: a TODO placeholder is NOT
                # a justification — the gate must keep failing until a
                # real reason replaces it
                just = ""
            entries.append(BaselineEntry(fid=fid, justification=just,
                                         lineno=i))
    return entries


def assign_ids(findings) -> None:
    """Stable finding ids: ``RULE:path:func:symbol#N`` where N orders
    same-keyed findings by line (1-based)."""
    groups: Dict[Tuple[str, str, str, str], list] = {}
    for f in findings:
        groups.setdefault((f.rule, f.relpath, f.func, f.symbol),
                          []).append(f)
    for (rule, rel, func, symbol), group in groups.items():
        group.sort(key=lambda f: (f.lineno, f.col))
        for i, f in enumerate(group, start=1):
            f.fid = f"{rule}:{rel}:{func}:{symbol}#{i}"


def format_baseline(findings) -> str:
    """Render findings as a baseline file body (justifications left as
    TODO markers for the author to fill in — the test rejects them
    until a real reason is written)."""
    lines = [
        "# tpulint baseline — accepted findings "
        "(python -m lightgbm_tpu lint --baseline <this file>).",
        "# Every entry MUST carry a '#' justification; "
        "tests/test_static_analysis.py enforces it.",
        "",
    ]
    for f in sorted(findings, key=lambda f: f.fid):
        lines.append(f"{f.fid}  # TODO: justify "
                     f"({f.relpath}:{f.lineno})")
    return "\n".join(lines) + "\n"
