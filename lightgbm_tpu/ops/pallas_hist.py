"""Hand-tiled Pallas TPU histogram kernel (``hist_method="pallas"``).

The MXU nibble path (histogram.py) materializes its HI/LO one-hot
operands through HBM — the measured cost center of the whole histogram
(~25 us of one-hot broadcast/compare per 16K-row chunk on v5e against
~22 us of einsum, benchmarks/PROFILE.md). This kernel builds the
one-hot *inside* the kernel body, so it only ever exists in VMEM:

- **Grid** = ``(feature_packs, row_tiles)``. The row-tile dimension is
  innermost, so the ``[C, FPACK, B]`` output block stays VMEM-resident
  across the whole row sweep of one feature pack (initialized at tile
  0, accumulated in f32 thereafter) while Pallas double-buffers the
  ``[ROW_TILE, FPACK]`` bin-column and ``[ROW_TILE, C]`` payload blocks
  through VMEM — the bin matrix streams HBM -> VMEM exactly once per
  feature pack and nothing histogram-shaped ever goes back until the
  final ``[C, F, B]`` result (a few hundred KB).
- **Compute**: the per-tile one-hot ``[ROW_TILE, FPACK * B]`` feeds ONE
  ``dot_general`` against the ``[ROW_TILE, C]`` payload with
  f32 ``preferred_element_type`` — N = FPACK*B lanes (2048 at B=256:
  16 full lane tiles), K = ROW_TILE. Features live in the N dimension,
  so no cross-feature garbage is computed (the MXU path burns PACK x
  PACK blocks to keep a diagonal) and no sub-lane reshape/diagonal
  extraction is needed — the two Mosaic cliffs that killed the earlier
  prototype (PROFILE.md "rejected routes").
- **Tiling**: B pads up to a 128-lane multiple; ROW_TILE is sized so
  the one-hot block stays ~4 MiB of VMEM (1024 rows at B<=128, 512 at
  B=256), leaving room for Pallas' input double buffers.
- **Exactness**: float payloads accumulate in f32 (on TPU the MXU's
  default single-pass mode reads the f32 one-hot/payload as bf16 — the
  same numerics class as the mxu path's documented default). int8
  quantized payloads are EXACT int32: each <=131072-row super-block's
  f32 sums are exact integers (131072 * 127 < 2^24) and blocks are
  converted to int32 before the cross-block sum, mirroring the mxu
  path's per-ROW_BLOCK conversion.

CPU correctness (tier-1) runs the SAME kernel under
``pallas_call(..., interpret=True)``; parity with the mxu and scatter
paths is asserted by tests/test_pallas_hist.py. On-chip iters/sec on
the Higgs-shaped bench (255 leaves / 255 bins) is the gate for
flipping ``hist_method="auto"`` to pallas on TPU
(benchmarks/fused_iter_bench.py grows the pallas arm); until a
measured win lands in PROFILE.md, ``auto`` keeps the mxu path and
pallas is opt-in. docs/PALLAS.md records the tiling rationale.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pallas_available", "hist_from_rows_pallas", "FPACK",
           "INT_BLOCK"]

FPACK = 8        # feature columns per grid cell: FPACK * 128-padded-B
                 # output lanes per dot (2048 at B=256 — 16 lane tiles)
INT_BLOCK = 131072   # rows per int-exact super-block: 131072 * 127
                     # = 1.66e7 < 2^24, so every f32 partial sum of an
                     # int8 payload is an exact integer
_ONEHOT_VMEM = 4 * 2 ** 20   # one-hot block VMEM budget (bytes)

_pallas_mod = None
_pallas_checked = False


def pallas_available() -> bool:
    """Whether the Pallas kernel can be built in this environment.

    True when ``jax.experimental.pallas`` imports (the kernel runs
    natively on TPU and under ``interpret=True`` everywhere else).
    ``LIGHTGBM_TPU_DISABLE_PALLAS=1`` forces False — the operational
    kill switch the ``auto``/OOM-ladder fallback paths key on."""
    global _pallas_mod, _pallas_checked
    if os.environ.get("LIGHTGBM_TPU_DISABLE_PALLAS", "") == "1":
        return False
    if not _pallas_checked:
        _pallas_checked = True
        try:
            from jax.experimental import pallas as pl  # noqa: F401
            _pallas_mod = pl
        except Exception:  # pragma: no cover - env without pallas
            _pallas_mod = None
    return _pallas_mod is not None


def _tile_plan(bp: int):
    """(fpack, row_tile) keeping the f32 one-hot block
    [RT, fpack, BP] under the VMEM budget: shrink the feature pack
    first at very wide B (bundled bin-position counts), then the row
    tile (power of two; floor 8 = the f32 sublane minimum, reached
    only past bp = 128K where even fpack=1 rows are that wide)."""
    fp = FPACK
    while fp > 1 and 128 * fp * bp * 4 > _ONEHOT_VMEM:
        fp //= 2
    rt = _ONEHOT_VMEM // (fp * bp * 4)      # rows fitting the budget
    if rt < 8:
        return fp, 8   # bp > 128K: a >1 GB histogram; floor the tile
    return fp, min(1024, 1 << (rt.bit_length() - 1))


def _require_pallas():
    """The imported pallas module, or a clear error when the kernel
    cannot be built here (single cache: pallas_available())."""
    if not pallas_available():
        raise RuntimeError(
            "hist_method='pallas' requested but jax.experimental."
            "pallas is unavailable (or LIGHTGBM_TPU_DISABLE_PALLAS"
            "=1); use hist_method='auto'|'mxu'|'scatter'")
    return _pallas_mod


def _hist_kernel(bins_ref, pay_ref, out_ref, *, bp: int, fpack: int,
                 row_tile: int):
    """One (feature-pack, row-tile) grid cell.

    ``out_ref`` is the pack's [C, fpack, BP] f32 accumulator — the same
    block for every row tile (the grid's innermost dimension), so it
    lives in VMEM across the whole row sweep."""
    pl = _require_pallas()
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)            # [RT, fpack]
    pay = pay_ref[...]                                # [RT, C]
    c = pay.shape[-1]
    iota_b = lax.broadcasted_iota(jnp.int32, (row_tile, fpack, bp), 2)
    onehot = (bins[:, :, None] == iota_b).astype(jnp.float32)
    # [C, fpack*BP] = pay^T @ onehot, contracting the row dimension:
    # features ride the N (lane) dimension so nothing off-diagonal is
    # computed, and the one-hot never leaves VMEM
    acc = lax.dot_general(pay.astype(jnp.float32),
                          onehot.reshape(row_tile, fpack * bp),
                          (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    out_ref[...] += acc.reshape(c, fpack, bp)


def _hist_tiles(rows: jnp.ndarray, payload: jnp.ndarray, num_bins: int,
                interpret: bool) -> jnp.ndarray:
    """One pallas_call over the whole [S, F] block -> [F, B, C] f32."""
    pl = _require_pallas()
    S, F = rows.shape
    C = payload.shape[-1]
    bp = max(128, -(-num_bins // 128) * 128)
    fp, rt = _tile_plan(bp)
    Sp = -(-S // rt) * rt
    Fp = -(-F // fp) * fp
    if Sp > S:
        rows = jnp.pad(rows, ((0, Sp - S), (0, 0)))
        payload = jnp.pad(payload, ((0, Sp - S), (0, 0)))
    if Fp > F:
        # pad features' histogram rows are cropped below; their bin
        # values are irrelevant
        rows = jnp.pad(rows, ((0, 0), (0, Fp - F)))
    out = pl.pallas_call(
        functools.partial(_hist_kernel, bp=bp, fpack=fp, row_tile=rt),
        grid=(Fp // fp, Sp // rt),
        in_specs=[
            pl.BlockSpec((rt, fp), lambda i, j: (j, i)),
            pl.BlockSpec((rt, C), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((C, fp, bp), lambda i, j: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((C, Fp, bp), jnp.float32),
        interpret=interpret,
    )(rows, payload.astype(jnp.float32))
    return jnp.transpose(out, (1, 2, 0))[:F, :num_bins, :]


def hist_from_rows_pallas(rows: jnp.ndarray, payload: jnp.ndarray,
                          num_bins: int, int_exact: bool = False,
                          interpret: Optional[bool] = None) -> jnp.ndarray:
    """Histogram over a row-block matrix via the Pallas kernel.

    Args:
      rows: ``[S, F]`` integer bin matrix (row-major, u8/u16).
      payload: ``[S, C]`` float channels, or int8 when ``int_exact``.
      num_bins: B.
      int_exact: accumulate an int8 payload to an EXACT int32 result
        (subtraction-safe) via <=INT_BLOCK-row super-blocks.
      interpret: run under the Pallas interpreter; defaults to True on
        every non-TPU backend (the tier-1 CPU parity mode).

    Returns:
      ``[F, B, C]`` f32 (int32 when ``int_exact``).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    S = rows.shape[0]
    if not int_exact:
        return _hist_tiles(rows, payload, num_bins, interpret)
    if S <= INT_BLOCK:
        return _hist_tiles(rows, payload, num_bins,
                           interpret).astype(jnp.int32)
    nblk = -(-S // INT_BLOCK)
    pad = nblk * INT_BLOCK - S
    if pad:
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
        payload = jnp.pad(payload, ((0, pad), (0, 0)))
    F = rows.shape[1]
    rows_b = rows.reshape(nblk, INT_BLOCK, F)
    pay_b = payload.reshape(nblk, INT_BLOCK, payload.shape[-1])

    def body(acc, xs):
        r, p = xs
        h = _hist_tiles(r, p, num_bins, interpret)
        return acc + h.astype(jnp.int32), None

    init = jnp.zeros((F, num_bins, payload.shape[-1]), jnp.int32)
    out, _ = lax.scan(body, init, (rows_b, pay_b))
    return out


# standalone jitted entry point: benchmarks/hist_micro.py's pallas arm
# and ad-hoc kernel probes dispatch through this, and registering it
# puts the kernel under the same recompile telemetry (TPL003 /
# obs/jit_tracker.py) as the other hot-path programs
hist_from_rows_pallas_jit = jax.jit(
    hist_from_rows_pallas,
    static_argnames=("num_bins", "int_exact", "interpret"))

from ..obs import register_jit  # noqa: E402  (after the jit exists)

hist_from_rows_pallas_jit = register_jit("ops/pallas_hist",
                                         hist_from_rows_pallas_jit,
                                         max_signatures=8)
